#
# graftlint rule implementations (R1-R5) over the stdlib ast.
#
# Design notes:
#   - ModuleIndex resolves local aliases to canonical dotted names once per
#     module ("np" -> numpy, "jnp" -> jax.numpy, `from jax import lax` ->
#     jax.lax, `from jax.lax import psum` -> jax.lax.psum), so every rule
#     matches on canonical names and survives import-style drift.
#   - R1 runs a single forward dataflow pass per function (no fixpoint):
#     names assigned from jnp/jax.lax/jax.random/jitted-function results are
#     device-tainted; host materializers (jax.device_get, np.asarray, ...)
#     both SINK taint (their use in a hot context is the finding) and
#     UNTAINT their result (a fetched value is host data).
#   - Heuristics deliberately under-approximate: a rule that cries wolf gets
#     pragma'd into noise.  Every rule has fixture tests in
#     tests/test_graftlint.py proving it fires on the bad shape and stays
#     silent on the corrected one.
#

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

# R11/R12 (the concurrency pass) live in concurrency.py: they analyze a SET
# of modules as one program, unlike the per-module rules in this file.
RULES = (
    "R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9", "R10", "R11", "R12",
)
PER_MODULE_RULES = RULES[:10]
CONCURRENCY_RULES = ("R11", "R12")

FindingTuple = Tuple[str, int, str, str]  # (rule, line, message, func-qualname)

# -- canonical-name machinery -------------------------------------------------

_MODULE_CANON = {
    "numpy": "numpy",
    "jax": "jax",
    "jax.numpy": "jax.numpy",
    "jax.lax": "jax.lax",
    "jax.random": "jax.random",
    "functools": "functools",
    "time": "time",
}

# canonical prefixes whose call results live on device
_DEVICE_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.random.")
_DEVICE_CALLS = {"jax.device_put", "jax.jit", "jax.pmap", "jax.vmap"}

# host materializers: calling these ON a device value is the sync point
_HOST_FETCHERS = {"jax.device_get"}
_NUMPY_SINKS = {
    "numpy.asarray", "numpy.array", "numpy.sum", "numpy.mean", "numpy.max",
    "numpy.min", "numpy.any", "numpy.all", "numpy.isfinite", "numpy.isnan",
    "numpy.unique", "numpy.sort", "numpy.argsort", "numpy.concatenate",
}
_BUILTIN_SINKS = {"float", "int", "bool"}
_METHOD_SINKS = {"item", "tolist", "to_py"}

_LAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "axis_index",
}

_LEGACY_NP_RANDOM = {
    "seed", "rand", "randn", "randint", "random", "random_sample", "ranf",
    "sample", "uniform", "normal", "standard_normal", "choice", "shuffle",
    "permutation", "beta", "binomial", "exponential", "gamma", "poisson",
    "lognormal", "multivariate_normal", "bytes",
}

_SHAPE_PARAM_RE = re.compile(
    r"^(k|n|m|d|num_\w+|n_\w+|max_iter|max_depth|chunk|chunk_\w+|shape|"
    r"size|rounds|round_size|depth|width|n?dims?|axis)$"
)

_F64_ATTRS = {"numpy.float64", "jax.numpy.float64"}
_F64_STRINGS = {"float64", "f8", "double", ">f8", "<f8"}


class ModuleIndex:
    """Per-module alias resolution + module-level jit-function registry."""

    def __init__(self, tree: ast.Module, path: str):
        self.path = path
        self.aliases: Dict[str, str] = {}       # local name -> canonical dotted
        self.mesh_names: Set[str] = set()       # names imported from parallel/mesh
        self.str_constants: Dict[str, int] = {} # module-level NAME = "literal" lines
        self.jitted: Set[str] = set()           # module-level jit-wrapped defs
        self._collect(tree)

    def _collect(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    canon = _MODULE_CANON.get(a.name, a.name)
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        canon if a.asname else canon.split(".")[0]
                    )
                    if a.asname:
                        self.aliases[a.asname] = canon
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    local = a.asname or a.name
                    base = node.module or ""
                    canon_base = _MODULE_CANON.get(base, base)
                    self.aliases[local] = f"{canon_base}.{a.name}" if canon_base else a.name
                    if _is_mesh_module(mod):
                        self.mesh_names.add(local)
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, str):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.str_constants[t.id] = stmt.lineno
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _jit_decorator_info(stmt, self) is not None:
                    self.jitted.add(stmt.name)
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                if self.dotted(stmt.value.func) == "jax.jit":
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.jitted.add(t.id)

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, or None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))


def _is_mesh_module(mod: str) -> bool:
    m = mod.lstrip(".")
    return (
        m.endswith("parallel.mesh")
        or m == "mesh"
        or m.endswith(".mesh")
        or m.endswith("compat")
    )


def _jit_decorator_info(
    fn: ast.AST, index: "ModuleIndex"
) -> Optional[Tuple[Set[str], bool]]:
    """(static param names, has_any_statics) when `fn` is jit-decorated,
    else None.  Handles @jax.jit, @jit, and @partial(jax.jit, ...)."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = index.dotted(target)
        statics: Set[str] = set()
        has_statics = False
        if name == "jax.jit":
            if isinstance(dec, ast.Call):
                has_statics, statics = _collect_statics(dec, params)
            return statics, has_statics
        if name in ("functools.partial", "partial") and isinstance(dec, ast.Call):
            if dec.args and index.dotted(dec.args[0]) == "jax.jit":
                has_statics, statics = _collect_statics(dec, params)
                return statics, has_statics
    return None


def _collect_statics(call: ast.Call, params: List[str]) -> Tuple[bool, Set[str]]:
    statics: Set[str] = set()
    found = False
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            found = True
            for v in ast.walk(kw.value):
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    statics.add(v.value)
        elif kw.arg == "static_argnums":
            found = True
            for v in ast.walk(kw.value):
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    if 0 <= v.value < len(params):
                        statics.add(params[v.value])
    return found, statics


# -- R1: host sync in hot path ------------------------------------------------

class _R1FunctionPass:
    def __init__(self, index: ModuleIndex, fn, qualname: str, in_jit: bool):
        self.index = index
        self.fn = fn
        self.qualname = qualname
        self.in_jit = in_jit
        self.tainted: Set[str] = set()
        self.findings: List[FindingTuple] = []

    # taint evaluation ---------------------------------------------------
    def _is_host_materializer(self, call: ast.Call) -> bool:
        name = self.index.dotted(call.func)
        if name in _HOST_FETCHERS or name in _NUMPY_SINKS:
            return True
        if name in _BUILTIN_SINKS:
            return True
        f = call.func
        return isinstance(f, ast.Attribute) and f.attr in _METHOD_SINKS

    def _expr_tainted(self, node: ast.AST) -> bool:
        """Whether evaluating `node` can yield (or contain) a device value.
        Recursive so untainting boundaries cut their whole subtree: host
        materializers return host data, range/len return host ints, and
        .shape/.ndim/.dtype reads are trace-time constants."""
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        if isinstance(node, ast.Call):
            if self._is_host_materializer(node):
                return False
            name = self.index.dotted(node.func)
            if name in ("range", "len", "print", "repr", "str"):
                return False
            if name is not None and (
                name.startswith(_DEVICE_PREFIXES)
                or name in _DEVICE_CALLS
                or name in self.index.jitted
            ):
                return True
            # fall through: a call ON a tainted value (x.sum()) or WITH a
            # tainted arg conservatively stays device-valued
        return any(self._expr_tainted(c) for c in ast.iter_child_nodes(node))

    def _assign_targets(self, target: ast.AST, taint: bool) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                if taint:
                    self.tainted.add(sub.id)
                else:
                    self.tainted.discard(sub.id)

    # statement walk -----------------------------------------------------
    def run(self) -> List[FindingTuple]:
        self._walk(self.fn.body, loop_depth=0)
        return self.findings

    def _walk(self, body: List[ast.stmt], loop_depth: int) -> None:
        for stmt in body:
            self._check_stmt_exprs(stmt, loop_depth)
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = stmt.value
                if value is not None:
                    taint = self._expr_tainted(value) and not (
                        isinstance(value, ast.Call)
                        and self._is_host_materializer(value)
                    )
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for t in targets:
                        self._assign_targets(t, taint)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                if self._expr_tainted(stmt.iter):
                    self._assign_targets(stmt.target, True)
                self._walk(stmt.body, loop_depth + 1)
                self._walk(stmt.orelse, loop_depth)
            elif isinstance(stmt, ast.While):
                self._walk(stmt.body, loop_depth + 1)
                self._walk(stmt.orelse, loop_depth)
            elif isinstance(stmt, ast.If):
                self._walk(stmt.body, loop_depth)
                self._walk(stmt.orelse, loop_depth)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._walk(stmt.body, loop_depth)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, loop_depth)
                for h in stmt.handlers:
                    self._walk(h.body, loop_depth)
                self._walk(stmt.orelse, loop_depth)
                self._walk(stmt.finalbody, loop_depth)
            # nested defs get their own pass (module driver); skip here

    def _own_expr_nodes(self, stmt: ast.stmt) -> Iterator[ast.AST]:
        """The statement's OWN expressions: compound statements yield only
        their header (iter/test/items) — their bodies are checked per child
        statement by _walk, at the right loop depth — and nested function
        defs are skipped entirely (they get their own pass)."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            roots: List[ast.AST] = [stmt.iter]
        elif isinstance(stmt, (ast.While, ast.If)):
            roots = [stmt.test]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            roots = [i.context_expr for i in stmt.items]
        elif isinstance(stmt, ast.Try):
            return
        else:
            roots = [stmt]
        stack = list(roots)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_stmt_exprs(self, stmt: ast.stmt, loop_depth: int) -> None:
        hot = loop_depth > 0 or self.in_jit
        if not hot:
            return
        for node in self._own_expr_nodes(stmt):
            if not isinstance(node, ast.Call):
                continue
            name = self.index.dotted(node.func)
            is_fetch = name in _HOST_FETCHERS
            is_sink = (
                name in _NUMPY_SINKS
                or (name in _BUILTIN_SINKS and isinstance(node.func, ast.Name))
                or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHOD_SINKS
                )
            )
            if not (is_fetch or is_sink):
                continue
            # device_get outside a loop is the sanctioned batched fetch
            if is_fetch and loop_depth == 0:
                continue
            args_tainted = any(self._expr_tainted(a) for a in node.args) or (
                isinstance(node.func, ast.Attribute)
                and self._expr_tainted(node.func.value)
            )
            if args_tainted:
                where = "inside a loop" if loop_depth > 0 else "inside a jitted body"
                label = name or f".{node.func.attr}()"  # type: ignore[union-attr]
                self.findings.append(
                    (
                        "R1",
                        node.lineno,
                        f"{label} on a device-array value {where}: hidden "
                        "device->host sync per iteration — batch ONE "
                        "jax.device_get after the loop (docs/graftlint.md#r1)",
                        self.qualname,
                    )
                )


# -- R2: recompile risk -------------------------------------------------------

def _r2_check_function(
    fn: ast.FunctionDef, index: ModuleIndex, qualname: str
) -> Iterator[FindingTuple]:
    info = _jit_decorator_info(fn, index)
    if info is None:
        return
    statics, _has = info
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for p in params:
        if p in statics:
            continue
        if _SHAPE_PARAM_RE.match(p):
            yield (
                "R2",
                fn.lineno,
                f"jit param '{p}' of '{fn.name}' looks like a Python "
                "shape/config scalar: every distinct value recompiles — add "
                "it to static_argnames or hoist it out of the jitted "
                "signature (docs/graftlint.md#r2)",
                qualname,
            )
    dynamic = {p for p in params if p not in statics}
    for node in _walk_own_body(fn):
        if isinstance(node, (ast.If, ast.While)):
            test = node.test
            if _is_structural_test(test, index):
                continue
            names = _dynamic_value_names(test)
            hits = sorted(names & dynamic)
            if hits:
                kind = "while" if isinstance(node, ast.While) else "if"
                yield (
                    "R2",
                    node.lineno,
                    f"Python {kind} on non-static jit arg(s) "
                    f"{', '.join(hits)} inside '{fn.name}': the branch "
                    "traces one side only (or fails on a tracer) — use "
                    "jax.lax.cond/while_loop or mark the arg static "
                    "(docs/graftlint.md#r2)",
                    qualname,
                )


def _walk_own_body(fn: ast.FunctionDef) -> Iterator[ast.AST]:
    """Walk fn's statements without descending into nested function defs
    (nested defs are usually lax.scan/while bodies with their own rules)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            stack.append(child)


_STATIC_ATTRS = {"ndim", "shape", "dtype", "size", "sharding"}


def _dynamic_value_names(test: ast.AST) -> Set[str]:
    """Names whose VALUE the test depends on.  `x.shape`/`x.ndim`/`x.dtype`
    reads are trace-time constants of a traced arg, so their base name does
    not count."""
    static_bases: Set[int] = set()
    for node in ast.walk(test):
        if (
            isinstance(node, ast.Attribute)
            and node.attr in _STATIC_ATTRS
            and isinstance(node.value, ast.Name)
        ):
            static_bases.add(id(node.value))
    return {
        n.id
        for n in ast.walk(test)
        if isinstance(n, ast.Name) and id(n) not in static_bases
    }


def _is_structural_test(test: ast.AST, index: ModuleIndex) -> bool:
    """Tests that are static under jit: isinstance/hasattr checks, `is
    None` comparisons, attribute-only conditions (config flags)."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            name = index.dotted(node.func)
            if name in ("isinstance", "hasattr", "callable", "len"):
                return True
        if isinstance(node, ast.Compare):
            if any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                return True
    return False


# -- R3: collective axis names must be bound through parallel/mesh ------------

def _r3_axis_arg(call: ast.Call, fname: str) -> Optional[ast.AST]:
    short = fname.rsplit(".", 1)[-1]
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis_names"):
            return kw.value
    pos = 0 if short == "axis_index" else 1
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _r3_check_call(
    call: ast.Call, index: ModuleIndex, qualname: str
) -> Iterator[FindingTuple]:
    name = index.dotted(call.func)
    if name is None:
        return
    short = name.rsplit(".", 1)[-1]
    is_collective = (
        name.startswith("jax.lax.") or name == f"jax.lax.{short}"
    ) and short in _LAX_COLLECTIVES
    if not is_collective and short in _LAX_COLLECTIVES and name == short:
        # `from jax.lax import psum` resolves through aliases to jax.lax.psum
        is_collective = True
    if is_collective:
        axis = _r3_axis_arg(call, name)
        if axis is not None:
            yield from _r3_flag_literals(axis, short, index, qualname, call.lineno)
        return
    if short in ("PartitionSpec", "P", "NamedSharding") or short == "Mesh":
        source = call.args[1] if short == "Mesh" and len(call.args) > 1 else None
        nodes = [source] if source is not None else list(call.args) + [
            kw.value for kw in call.keywords
        ]
        for n in nodes:
            if n is None:
                continue
            yield from _r3_flag_literals(n, short, index, qualname, call.lineno)


_R3_CONSTRUCTORS = ("PartitionSpec", "P", "NamedSharding", "Mesh")


def _iter_pruning_nested_constructors(node: ast.AST) -> Iterator[ast.AST]:
    """Walk `node` but skip subtrees of nested PartitionSpec/Mesh/... calls:
    ast.walk visits those Call nodes in their own right, so descending into
    them here would report each literal twice (e.g. P("data") inside
    NamedSharding(mesh, P("data"))) and inflate --baseline budgets."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            f = n.func
            short = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if short in _R3_CONSTRUCTORS:
                continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _r3_flag_literals(
    node: ast.AST, context: str, index: ModuleIndex, qualname: str, line: int
) -> Iterator[FindingTuple]:
    for sub in _iter_pruning_nested_constructors(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield (
                "R3",
                getattr(sub, "lineno", line),
                f"string-literal axis name '{sub.value}' in {context}: a "
                "typo only explodes at trace time on a real mesh — bind "
                "through parallel/mesh (DATA_AXIS/MODEL_AXIS) "
                "(docs/graftlint.md#r3)",
                qualname,
            )
        elif isinstance(sub, ast.Name):
            if sub.id in index.str_constants and sub.id not in index.mesh_names:
                yield (
                    "R3",
                    getattr(sub, "lineno", line),
                    f"axis name '{sub.id}' is a module-local string, not "
                    "bound through parallel/mesh — import "
                    "DATA_AXIS/MODEL_AXIS instead (docs/graftlint.md#r3)",
                    qualname,
                )


# -- R4: nondeterminism -------------------------------------------------------

def _r4_check_call(
    call: ast.Call, index: ModuleIndex, qualname: str, at_module_scope: bool
) -> Iterator[FindingTuple]:
    name = index.dotted(call.func)
    if name is None:
        return
    if name.startswith("numpy.random."):
        short = name.rsplit(".", 1)[-1]
        if short in _LEGACY_NP_RANDOM:
            yield (
                "R4",
                call.lineno,
                f"np.random.{short} uses the hidden GLOBAL RNG: results "
                "depend on import/call order across workers — use "
                "np.random.default_rng(seed) threaded from the caller "
                "(docs/graftlint.md#r4)",
                qualname,
            )
            return
        if short == "default_rng" and not call.args and not call.keywords:
            yield (
                "R4",
                call.lineno,
                "np.random.default_rng() without a seed: every rank draws "
                "a different stream — thread an explicit seed "
                "(docs/graftlint.md#r4)",
                qualname,
            )
            return
    if at_module_scope and (
        name.startswith("numpy.random.") or name.startswith("jax.random.")
    ):
        yield (
            "R4",
            call.lineno,
            f"{name} at module scope: RNG state drawn at import time "
            "differs per process — construct RNGs inside the function "
            "that uses them (docs/graftlint.md#r4)",
            qualname,
        )


def _r4_check_for(
    node: ast.For, qualname: str, index: ModuleIndex
) -> Iterator[FindingTuple]:
    it = node.iter
    is_set = isinstance(it, (ast.Set, ast.SetComp)) or (
        isinstance(it, ast.Call)
        and isinstance(it.func, ast.Name)
        and it.func.id == "set"
    )
    if is_set:
        yield (
            "R4",
            node.lineno,
            "iterating a set: order is hash-seed dependent, so anything "
            "derived (collective payloads, encode_attrs dicts) diverges "
            "across ranks — wrap in sorted() (docs/graftlint.md#r4)",
            qualname,
        )


# -- R5: float64 discipline in solver kernels ---------------------------------

def _r5_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "/ops/" in norm or norm.startswith("ops/")


def _r5_check(
    node: ast.AST, index: ModuleIndex, qualname: str
) -> Iterator[FindingTuple]:
    if isinstance(node, ast.Attribute):
        name = index.dotted(node)
        if name in _F64_ATTRS:
            yield (
                "R5",
                node.lineno,
                f"{name.replace('numpy', 'np').replace('jax.np', 'jnp')} in a "
                "solver kernel: TPUs demote f64 to slow emulation, and numpy "
                "f64 scalars silently promote weak-typed jnp math — keep "
                "device math f32/bf16 or pragma host-side use "
                "(docs/graftlint.md#r5)",
                qualname,
            )
    elif isinstance(node, ast.keyword) and node.arg == "dtype":
        v = node.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str) \
                and v.value in _F64_STRINGS:
            yield (
                "R5",
                v.lineno,
                f"dtype='{v.value}' in a solver kernel: f64 on TPU is "
                "emulated — use float32/bfloat16 on device "
                "(docs/graftlint.md#r5)",
                qualname,
            )
        elif isinstance(v, ast.Name) and v.id == "float" \
                and "float" not in index.aliases:
            yield (
                "R5",
                v.lineno,
                "dtype=float is float64: TPUs emulate f64 — spell the "
                "intended width explicitly (docs/graftlint.md#r5)",
                qualname,
            )


# -- R6: raw wall clocks in engine/serving modules ----------------------------
# Every timestamp the framework takes must come from ONE clock so spans,
# counters, duration series, and trace exports are mutually comparable —
# srml-scope's profiling.now()/span().  A module-local time.perf_counter()
# is invisible to the telemetry snapshots and the Chrome-trace export, and
# (worse) time.time() is not even monotonic.  Scoped to the package
# (benchmark/test harness code may time however it likes); profiling.py is
# the clock's home and exempt.  time.monotonic/time.sleep stay allowed —
# deadline polling loops are control flow, not observability.

_R6_CLOCKS = {"time.time", "time.perf_counter", "time.perf_counter_ns"}


def _r6_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    if norm.endswith("/profiling.py") or norm == "profiling.py":
        return False
    return "spark_rapids_ml_tpu/" in norm or norm.startswith(
        "spark_rapids_ml_tpu"
    )


def _r6_check_call(
    call: ast.Call, index: ModuleIndex, qualname: str
) -> Iterator[FindingTuple]:
    name = index.dotted(call.func)
    if name in _R6_CLOCKS:
        yield (
            "R6",
            call.lineno,
            f"{name}() in an engine/serving module: timing outside "
            "srml-scope is invisible to spans, telemetry snapshots, and "
            "trace exports (and time.time is not monotonic) — use "
            "profiling.now() or profiling.span() (docs/observability.md#r6)",
            qualname,
        )


# -- R7: every thread must be named -------------------------------------------
# The srml-watch flight recorder, trace exports, and watchdog reports all
# attribute events to thread NAMES ("srml-serve-km", "srml-precompile-3",
# "srml-watch-hb-r0").  An unnamed threading.Thread shows up as "Thread-7" —
# useless in a hang dump and unstable across runs — so every Thread
# constructed inside the package must pass name=.  Scoped like R6 to
# spark_rapids_ml_tpu/ (tests/benchmarks may thread however they like).

_R7_THREADS = {"threading.Thread", "threading.Timer"}


def _r7_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "spark_rapids_ml_tpu/" in norm or norm.startswith(
        "spark_rapids_ml_tpu"
    )


def _r7_check_call(
    call: ast.Call, index: ModuleIndex, qualname: str
) -> Iterator[FindingTuple]:
    name = index.dotted(call.func)
    if name not in _R7_THREADS:
        return
    if any(kw.arg == "name" for kw in call.keywords):
        return
    yield (
        "R7",
        call.lineno,
        f"{name}(...) without name=: the flight recorder, trace exports, "
        "and watchdog reports attribute events by thread name — an "
        "anonymous 'Thread-N' is useless in a hang dump.  Pass "
        "name=\"srml-<subsystem>-...\" (docs/observability.md#r7)",
        qualname,
    )


# -- R8: remote-DMA confinement + paired start/wait ---------------------------
# pltpu.make_async_remote_copy is inter-chip RDMA: a wrong device_id or a
# mis-sequenced semaphore does not raise — it wedges the ring (or silently
# corrupts a neighbor's HBM).  The API therefore lives in ONE audited
# module, parallel/exchange.py (DeviceSection.ring_shift), and every other
# engine composes ring exchanges through it.  Additionally, a DMA handle
# (remote or local make_async_copy) that is .start()ed but never .wait()ed
# in the same kernel body races the output block's flush — the same
# undefined-DMA-ordering hazard the qres grid restructure fixed — so the
# pairing is checked per function body.

_R8_REMOTE = "make_async_remote_copy"
_R8_DMA_MAKERS = {"make_async_remote_copy", "make_async_copy"}


def _r8_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "spark_rapids_ml_tpu/" in norm or norm.startswith(
        "spark_rapids_ml_tpu"
    )


def _r8_confined(path: str) -> bool:
    norm = path.replace("\\", "/")
    return norm.endswith("parallel/exchange.py")


def _r8_short(func: ast.AST, index: ModuleIndex) -> Optional[str]:
    name = index.dotted(func)
    if name:
        return name.rsplit(".", 1)[-1]
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _r8_check_call(
    call: ast.Call, index: ModuleIndex, qualname: str, path: str
) -> Iterator[FindingTuple]:
    if _r8_short(call.func, index) == _R8_REMOTE and not _r8_confined(path):
        yield (
            "R8",
            call.lineno,
            "make_async_remote_copy outside parallel/exchange.py: the "
            "inter-chip DMA surface is confined to the ONE audited module "
            "— compose ring exchanges through "
            "exchange.DeviceSection.ring_shift (docs/graftlint.md#r8)",
            qualname,
        )


def _r8_check_function(
    fn: ast.FunctionDef, index: ModuleIndex, qualname: str
) -> Iterator[FindingTuple]:
    dma_vars: Dict[str, int] = {}   # local name -> assignment line
    started: Dict[str, int] = {}    # local name -> first .start() line
    waited: Set[str] = set()
    for node in _walk_own_body(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _r8_short(node.value.func, index) in _R8_DMA_MAKERS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        dma_vars[t.id] = node.lineno
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
        ):
            base = node.func.value.id
            if node.func.attr == "start":
                started.setdefault(base, node.lineno)
            elif node.func.attr == "wait":
                waited.add(base)
    for name, line in sorted(started.items(), key=lambda kv: kv[1]):
        if name in dma_vars and name not in waited:
            yield (
                "R8",
                line,
                f"DMA handle '{name}' is start()ed but never wait()ed in "
                "this kernel body: an unwaited async copy races the output "
                "block's flush (undefined ordering; can wedge the device) "
                "— pair every start() with a wait() before the body "
                "returns (docs/graftlint.md#r8)",
                qualname,
            )


# -- R9: unbounded waits + silent teardown swallows ---------------------------
# The distributed lifecycle's characteristic failure is the HANG: a dead
# peer turns every timeout-less `.result()` / `.wait()` / `.acquire()` /
# `.join()` into a forever-block that no watchdog can attribute ("hang for
# 5 minutes, then die without naming the culprit" — the srml-shield
# motivation).  Scoped to spark_rapids_ml_tpu/{parallel,serving}/ — the
# modules that wait on OTHER processes and threads; solver/engine code
# blocks only on the device runtime, whose waits jax owns.
#
# Two shapes:
#   (a) obj.result()/wait()/acquire()/join() with NO arguments at all —
#       any argument (positional deadline or timeout=) bounds the wait and
#       passes, which also keeps "".join(parts) (always has its iterable)
#       and Condition.wait(remaining) out of scope.  Deliberately
#       under-approximate: a timeout variable that is None at runtime is
#       invisible to the AST.
#   (b) `except Exception:` / `except BaseException:` / bare `except:`
#       whose body performs NO call and NO raise — a teardown error
#       swallowed without even a logged event (the TpuContext.__exit__
#       shape this PR fixed).  Any call in the handler body (logger,
#       counter, cleanup) counts as handling.

_R9_WAITERS = {"result", "wait", "acquire", "join"}
_R9_BROAD_TYPES = {"Exception", "BaseException", "builtins.Exception",
                   "builtins.BaseException"}


def _r9_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    return (
        "spark_rapids_ml_tpu/parallel/" in norm
        or "spark_rapids_ml_tpu/serving/" in norm
    )


def _r9_check_call(
    call: ast.Call, index: ModuleIndex, qualname: str
) -> Iterator[FindingTuple]:
    if not isinstance(call.func, ast.Attribute):
        return
    attr = call.func.attr
    if attr not in _R9_WAITERS:
        return
    if call.args or call.keywords:
        return  # any deadline/timeout argument bounds the wait
    yield (
        "R9",
        call.lineno,
        f".{attr}() without a timeout: a dead peer or wedged worker turns "
        "this into a forever-block no watchdog can attribute — pass a "
        "timeout (and surface the expiry as a typed error) "
        "(docs/graftlint.md#r9)",
        qualname,
    )


def _r9_check_except(
    handler: ast.ExceptHandler, index: ModuleIndex, qualname: str
) -> Iterator[FindingTuple]:
    t = handler.type
    if t is not None:
        name = index.dotted(t)
        if name not in _R9_BROAD_TYPES:
            return  # narrow handler (or a tuple of specific types): fine
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, (ast.Call, ast.Raise)):
            return  # logged / counted / re-raised: handled
    caught = index.dotted(t) if t is not None else "everything (bare except)"
    yield (
        "R9",
        handler.lineno,
        f"`except {caught}` swallows the error without a logged event: a "
        "teardown failure that vanishes here is the next silent hang's "
        "root cause — log it (or count it) before suppressing "
        "(docs/graftlint.md#r9)",
        qualname,
    )


# -- R10: raw-socket confinement + bounded socket waits -----------------------
# The srml-wire control plane (parallel/netplane.py) is the ONE audited
# home of the raw socket API inside the package: a stray socket.socket()
# elsewhere is an unbounded, un-lease-fenced, un-fault-injectable side
# channel the chaos matrix can never exercise (the R8 confinement argument,
# ported from remote-DMA to the network).  Within netplane itself, every
# blocking socket wait must be poll-bounded: a `.recv()`/`.accept()` whose
# function body has no PRECEDING `.settimeout()` is the wire analog of
# R9's timeout-less `.result()` — a dead peer turns it into a forever-block
# no watchdog can attribute.

_R10_CONSTRUCTORS = {"socket.socket", "socket.create_connection"}
_R10_WAITERS = {"recv", "accept"}


def _r10_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "spark_rapids_ml_tpu/" in norm or norm.startswith(
        "spark_rapids_ml_tpu"
    )


def _r10_confined(path: str) -> bool:
    norm = path.replace("\\", "/")
    return norm.endswith("parallel/netplane.py")


def _r10_check_call(
    call: ast.Call, index: ModuleIndex, qualname: str, path: str
) -> Iterator[FindingTuple]:
    name = index.dotted(call.func)
    if name in _R10_CONSTRUCTORS and not _r10_confined(path):
        yield (
            "R10",
            call.lineno,
            f"{name} outside parallel/netplane.py: the raw socket surface "
            "is confined to the ONE audited wire module — route control "
            "traffic through TcpControlPlane / CoordinatorServer so it is "
            "lease-fenced, fault-injectable, and bounded "
            "(docs/graftlint.md#r10)",
            qualname,
        )


def _r10_check_function(
    fn: ast.FunctionDef, index: ModuleIndex, qualname: str
) -> Iterator[FindingTuple]:
    """Within netplane.py: every recv/accept must follow a settimeout in
    the SAME function body (the local-invariant discipline — a reader
    helper enforces its own poll bound instead of trusting callers)."""
    first_settimeout: Optional[int] = None
    waits: List[Tuple[int, str]] = []
    for node in _walk_own_body(fn):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
        ):
            continue
        attr = node.func.attr
        if attr == "settimeout":
            if first_settimeout is None or node.lineno < first_settimeout:
                first_settimeout = node.lineno
        elif attr in _R10_WAITERS:
            waits.append((node.lineno, attr))
    for line, attr in sorted(waits):
        if first_settimeout is None or line < first_settimeout:
            yield (
                "R10",
                line,
                f".{attr}() with no preceding .settimeout() in this "
                "function body: a dead peer turns the read into a "
                "forever-block no watchdog can attribute — set the poll "
                "timeout where the wait happens (docs/graftlint.md#r10)",
                qualname,
            )


# -- driver -------------------------------------------------------------------

def lint_tree(
    tree: ast.Module, index: ModuleIndex, selected: Set[str]
) -> List[FindingTuple]:
    findings: List[FindingTuple] = []

    # function-scoped passes (R1 dataflow, R2 jit checks), with qualnames
    def visit_functions(body, prefix: str, enclosing_jit: bool) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{stmt.name}"
                is_jit = (
                    _jit_decorator_info(stmt, index) is not None
                    or enclosing_jit
                )
                if "R1" in selected and isinstance(stmt, ast.FunctionDef):
                    findings.extend(
                        _R1FunctionPass(index, stmt, qual, is_jit).run()
                    )
                if "R2" in selected and isinstance(stmt, ast.FunctionDef):
                    findings.extend(_r2_check_function(stmt, index, qual))
                if (
                    "R8" in selected
                    and isinstance(stmt, ast.FunctionDef)
                    and _r8_applies(index.path)
                ):
                    findings.extend(_r8_check_function(stmt, index, qual))
                if (
                    "R10" in selected
                    and isinstance(stmt, ast.FunctionDef)
                    and _r10_confined(index.path)
                ):
                    findings.extend(_r10_check_function(stmt, index, qual))
                visit_functions(stmt.body, f"{qual}.", is_jit)
            elif isinstance(stmt, ast.ClassDef):
                visit_functions(stmt.body, f"{prefix}{stmt.name}.", enclosing_jit)
            elif hasattr(stmt, "body") and isinstance(
                getattr(stmt, "body"), list
            ):
                visit_functions(stmt.body, prefix, enclosing_jit)
                for extra in ("orelse", "finalbody"):
                    b = getattr(stmt, extra, None)
                    if b:
                        visit_functions(b, prefix, enclosing_jit)
                for h in getattr(stmt, "handlers", []) or []:
                    visit_functions(h.body, prefix, enclosing_jit)

    visit_functions(tree.body, "", False)

    # module-wide single-node rules (R3/R4/R5) with module-scope detection
    module_stmts = set()
    for stmt in tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            for n in ast.walk(stmt):
                module_stmts.add(id(n))

    qual_of: Dict[int, str] = {}

    def map_quals(body, prefix: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                qual = f"{prefix}{stmt.name}"
                for n in ast.walk(stmt):
                    qual_of.setdefault(id(n), qual)
                map_quals(stmt.body, f"{qual}.")

    map_quals(tree.body, "")

    for node in ast.walk(tree):
        qual = qual_of.get(id(node), "")
        if isinstance(node, ast.Call):
            if "R3" in selected:
                findings.extend(_r3_check_call(node, index, qual))
            if "R4" in selected:
                findings.extend(
                    _r4_check_call(node, index, qual, id(node) in module_stmts)
                )
            if "R6" in selected and _r6_applies(index.path):
                findings.extend(_r6_check_call(node, index, qual))
            if "R7" in selected and _r7_applies(index.path):
                findings.extend(_r7_check_call(node, index, qual))
            if "R8" in selected and _r8_applies(index.path):
                findings.extend(_r8_check_call(node, index, qual, index.path))
            if "R9" in selected and _r9_applies(index.path):
                findings.extend(_r9_check_call(node, index, qual))
            if "R10" in selected and _r10_applies(index.path):
                findings.extend(_r10_check_call(node, index, qual, index.path))
        if (
            isinstance(node, ast.ExceptHandler)
            and "R9" in selected
            and _r9_applies(index.path)
        ):
            findings.extend(_r9_check_except(node, index, qual))
        if isinstance(node, ast.For) and "R4" in selected:
            findings.extend(_r4_check_for(node, qual, index))
        if "R5" in selected and _r5_applies(index.path):
            findings.extend(_r5_check(node, index, qual))
    return findings
