#
# graftlint concurrency pass (R11/R12): whole-program lock-order and
# shared-state analysis over the stdlib ast.
#
# Every rule before this one checks a single statement (or a single
# function body).  Concurrency bugs do not live in single statements: a
# lock-order inversion needs TWO nesting sites, usually in different
# functions, and PR 13/15's review rounds found exactly those by hand
# (the partial-sendall stream desync; the probe path stalled behind the
# repack lock).  This pass is the lockdep/ThreadSanitizer move, ported to
# review time:
#
#   R11 lock-order   (a) build the package-wide held->acquired graph from
#                    `with self._lock:` blocks, explicit .acquire()/
#                    .release() pairs, and interprocedural edges through
#                    same-module calls, then flag every edge that sits on
#                    a cycle — two threads driving the two nesting orders
#                    deadlock.  (b) flag blocking operations performed
#                    while a lock is held (socket recv/accept,
#                    Future.result, foreign Condition.wait, cached_call/
#                    AOT-compile waits, device_get/block_until_ready,
#                    subprocess/sleep): every thread contending for that
#                    lock stalls behind a wait that has nothing to do
#                    with the state the lock guards — the exact shape of
#                    PR 15's probe-stall finding.
#   R12 shared-state an instance attribute written both under a lock and
#                    with no lock held is a race against the guarded
#                    readers; container mutation (append/pop/[k]=/update)
#                    on an attribute whose writes are never guarded is
#                    non-atomic even on CPython (the lock-free discipline
#                    only covers atomic reference swaps).  Scoped to the
#                    thread-spawning modules (serving/, parallel/,
#                    ann/mutable.py, stream/session.py, watch.py).
#
# Honest limitations (documented in docs/graftlint.md#r11):
#   - NO cross-module call edges: a lock graph edge forms only when both
#     acquisitions are reachable inside one module.  Lock identities are
#     module+class scoped, so a cross-module cycle is invisible — the
#     runtime lockdep sanitizer (sanitize.lockdep_lock) covers that half.
#   - NO alias analysis: a lock reaching a function as a parameter
#     (netplane's _send_to(conn, lock, ...)) is untracked; `self._X` and
#     module-level names are the only resolvable lock references.
#   - Guardedness is lexical plus one interprocedural refinement: a
#     helper whose every same-module call site holds lock L is analyzed
#     as running under L (the `_locked` helper convention).
#
# Like every graftlint rule: deliberately under-approximate — a rule that
# cries wolf gets pragma'd into noise.
#

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .rules import ModuleIndex

# (rule, path, line, message, func-qualname) — the cross-module pass must
# carry the path per finding, unlike rules.FindingTuple.
CCFinding = Tuple[str, str, int, str, str]

_LOCK_CONSTRUCTORS = {"threading.Lock", "threading.RLock"}
_CONDITION_CONSTRUCTOR = "threading.Condition"

# methods that mutate a container in place — not an atomic reference swap
_CONTAINER_MUTATORS = {
    "append", "extend", "insert", "add", "remove", "discard", "pop",
    "popleft", "popitem", "appendleft", "clear", "update", "setdefault",
    "sort", "reverse",
}

# construction-time methods: single-threaded by contract, writes exempt
_CTOR_METHODS = {"__init__", "__new__", "__post_init__"}


def r11_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "spark_rapids_ml_tpu/" in norm


def r12_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    return (
        "spark_rapids_ml_tpu/serving/" in norm
        or "spark_rapids_ml_tpu/parallel/" in norm
        or norm.endswith("ann/mutable.py")
        or norm.endswith("stream/session.py")
        or norm.endswith("spark_rapids_ml_tpu/watch.py")
    )


# -- lock inventory -----------------------------------------------------------

@dataclass
class _LockDef:
    key: str         # globally unique node: "<path>:<Class>.<attr>" / "<path>:<name>"
    display: str     # what the message shows: "self._lock (MicroBatcher)" etc.


@dataclass
class _ClassLocks:
    locks: Dict[str, _LockDef] = field(default_factory=dict)       # attr -> lock
    conditions: Dict[str, str] = field(default_factory=dict)       # attr -> bound lock attr


def _is_lock_call(call: ast.Call, index: ModuleIndex) -> bool:
    name = index.dotted(call.func)
    if name in _LOCK_CONSTRUCTORS:
        return True
    # the runtime sanitizer's named wrapper constructs (and is) the lock
    return bool(name) and (name == "lockdep_lock" or name.endswith(".lockdep_lock"))


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ModuleCC:
    """Per-module lock inventory + per-function event summaries."""

    def __init__(self, tree: ast.Module, index: ModuleIndex, path: str):
        self.tree = tree
        self.index = index
        self.path = path
        self.module_locks: Dict[str, _LockDef] = {}
        self.module_conditions: Dict[str, str] = {}  # name -> bound module lock name
        self.class_locks: Dict[str, _ClassLocks] = {}
        self.functions: Dict[str, "_FuncSummary"] = {}
        self._collect_locks()
        self._collect_functions()

    # lock definitions --------------------------------------------------
    def _collect_locks(self) -> None:
        # module-level: NAME = threading.Lock() / Condition(NAME)
        for stmt in self.tree.body:
            if not (isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call)):
                continue
            for t in stmt.targets:
                if not isinstance(t, ast.Name):
                    continue
                if _is_lock_call(stmt.value, self.index):
                    self.module_locks[t.id] = _LockDef(
                        key=f"{self.path}:{t.id}", display=t.id
                    )
                elif self.index.dotted(stmt.value.func) == _CONDITION_CONSTRUCTOR:
                    if stmt.value.args and isinstance(stmt.value.args[0], ast.Name):
                        self.module_conditions[t.id] = stmt.value.args[0].id
                    else:
                        # condition over its own implicit lock
                        self.module_locks[t.id] = _LockDef(
                            key=f"{self.path}:{t.id}", display=t.id
                        )
        # class-level: self._x = threading.Lock() anywhere in the class body
        for cls_qual, cls in self._iter_classes(self.tree.body, ""):
            cl = _ClassLocks()
            for node in ast.walk(cls):
                if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                    continue
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if _is_lock_call(node.value, self.index):
                        cl.locks[attr] = _LockDef(
                            key=f"{self.path}:{cls_qual}.{attr}",
                            display=f"self.{attr} ({cls_qual})",
                        )
                    elif self.index.dotted(node.value.func) == _CONDITION_CONSTRUCTOR:
                        bound = (
                            _self_attr(node.value.args[0])
                            if node.value.args
                            else None
                        )
                        if bound is not None:
                            cl.conditions[attr] = bound
                        else:
                            cl.locks[attr] = _LockDef(
                                key=f"{self.path}:{cls_qual}.{attr}",
                                display=f"self.{attr} ({cls_qual})",
                            )
            if cl.locks or cl.conditions:
                self.class_locks[cls_qual] = cl

    def _iter_classes(self, body, prefix: str):
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                qual = f"{prefix}{stmt.name}"
                yield qual, stmt
                yield from self._iter_classes(stmt.body, f"{qual}.")

    # lock reference resolution -----------------------------------------
    def resolve_lock(self, node: ast.AST, cls_qual: str) -> Optional[_LockDef]:
        """LockDef a `with X:` / `X.acquire()` expression refers to, following
        condition->lock binding; None when unresolvable (no alias analysis)."""
        attr = _self_attr(node)
        if attr is not None and cls_qual:
            cl = self.class_locks.get(cls_qual)
            if cl is None:
                return None
            if attr in cl.conditions:
                attr = cl.conditions[attr]
            return cl.locks.get(attr)
        if isinstance(node, ast.Name):
            name = node.id
            if name in self.module_conditions:
                name = self.module_conditions[name]
            return self.module_locks.get(name)
        return None

    def condition_bound_lock(self, node: ast.AST, cls_qual: str) -> Optional[_LockDef]:
        """LockDef a condition attribute is bound to, or None when `node` is
        not a known condition."""
        attr = _self_attr(node)
        if attr is not None and cls_qual:
            cl = self.class_locks.get(cls_qual)
            if cl and attr in cl.conditions:
                return cl.locks.get(cl.conditions[attr])
            return None
        if isinstance(node, ast.Name) and node.id in self.module_conditions:
            return self.module_locks.get(self.module_conditions[node.id])
        return None

    # function summaries ------------------------------------------------
    def _collect_functions(self) -> None:
        # two phases: register every qualname FIRST so calls to methods
        # defined later in the class body still resolve, then walk bodies
        defs: List[Tuple[ast.AST, str, str]] = []

        def visit(body, prefix: str, cls_qual: str) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{stmt.name}"
                    defs.append((stmt, qual, cls_qual))
                    # nested defs are separate threads of control: analyzed
                    # with an empty held set of their own
                    visit(stmt.body, f"{qual}.", cls_qual)
                elif isinstance(stmt, ast.ClassDef):
                    visit(stmt.body, f"{prefix}{stmt.name}.", f"{prefix}{stmt.name}")

        visit(self.tree.body, "", "")
        self._known_quals = {qual for _stmt, qual, _cls in defs}
        for stmt, qual, cls_qual in defs:
            self.functions[qual] = _FuncSummary(self, stmt, qual, cls_qual)

    def resolve_callee(self, call: ast.Call, cls_qual: str) -> Optional[str]:
        """Same-module callee qualname for `self.m(...)` / `f(...)`, else None."""
        known = getattr(self, "_known_quals", set())
        attr = _self_attr(call.func)
        if attr is not None and cls_qual:
            qual = f"{cls_qual}.{attr}"
            return qual if qual in known else None
        if isinstance(call.func, ast.Name) and call.func.id in known:
            return call.func.id
        return None


@dataclass
class _Block:
    kind: str              # human label of the blocking class
    held: Tuple[str, ...]  # held lock keys at the site ("" when from summary)
    line: int


class _FuncSummary:
    """One pass over a function's own body (nested defs excluded), tracking
    the lexically-held lock set."""

    def __init__(self, mod: _ModuleCC, fn, qual: str, cls_qual: str):
        self.mod = mod
        self.fn = fn
        self.qual = qual
        self.cls_qual = cls_qual
        # (acquired lock key, held keys at acquisition, line)
        self.acquires: List[Tuple[str, Tuple[str, ...], int]] = []
        # (callee qual, held keys, line)
        self.calls: List[Tuple[str, Tuple[str, ...], int]] = []
        # direct blocking ops (held may be empty: feeds the may-block summary)
        self.blocks: List[_Block] = []
        # (attr, kind 'rebind'|'container', op, held keys, line)
        self.writes: List[Tuple[str, str, str, Tuple[str, ...], int]] = []
        self._held: List[str] = []
        self._explicit: List[str] = []
        self._walk_stmts(fn.body)

    # held-set helpers ---------------------------------------------------
    def _held_keys(self) -> Tuple[str, ...]:
        seen: List[str] = []
        for k in self._held:
            if k not in seen:
                seen.append(k)
        return tuple(seen)

    def _acquire(self, lock: _LockDef, line: int) -> None:
        if lock.key not in self._held:
            self.acquires.append((lock.key, self._held_keys(), line))
        self._held.append(lock.key)

    # statement walk -----------------------------------------------------
    def _walk_stmts(self, stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # separate thread of control
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in stmt.items:
                    self._scan_expr(item.context_expr)
                    lock = self.mod.resolve_lock(item.context_expr, self.cls_qual)
                    if lock is not None:
                        self._acquire(lock, item.context_expr.lineno)
                        pushed += 1
                self._walk_stmts(stmt.body)
                for _ in range(pushed):
                    self._held.pop()
                continue
            # explicit acquire()/release(): linear hold tracked to the
            # matching release (or function end) — under-approximate on
            # branches, exact on the straight-line idiom
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                call = stmt.value
                if isinstance(call.func, ast.Attribute) and call.func.attr in (
                    "acquire",
                    "release",
                ):
                    lock = self.mod.resolve_lock(call.func.value, self.cls_qual)
                    if lock is not None:
                        if call.func.attr == "acquire":
                            self._acquire(lock, call.lineno)
                            self._explicit.append(lock.key)
                        elif lock.key in self._explicit:
                            self._explicit.remove(lock.key)
                            # drop the innermost matching hold
                            for i in range(len(self._held) - 1, -1, -1):
                                if self._held[i] == lock.key:
                                    del self._held[i]
                                    break
                        continue
            # compound statements: recurse into bodies with the same held set
            for name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, name, None)
                if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
                    self._walk_stmts(sub)
            for h in getattr(stmt, "handlers", []) or []:
                self._walk_stmts(h.body)
            # expressions hanging off this statement (tests, iterables,
            # values of simple statements) — but not nested suites
            for node in ast.iter_child_nodes(stmt):
                if isinstance(node, (ast.stmt, ast.ExceptHandler)):
                    continue
                self._scan_expr(node)
            if isinstance(stmt, (ast.Assign, ast.AugAssign)):
                self._scan_write(stmt)
            if isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr is not None:
                            self.writes.append(
                                (attr, "container", "del [k]",
                                 self._held_keys(), stmt.lineno)
                            )

    # write classification (R12) ----------------------------------------
    def _scan_write(self, stmt) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                op = "=" if isinstance(stmt, ast.Assign) else "aug-assign"
                self.writes.append(
                    (attr, "rebind", op, self._held_keys(), stmt.lineno)
                )
                continue
            if isinstance(t, ast.Subscript):
                attr = _self_attr(t.value)
                if attr is not None:
                    self.writes.append(
                        (attr, "container", "[k] =",
                         self._held_keys(), stmt.lineno)
                    )
            if isinstance(t, ast.Tuple):
                for el in t.elts:
                    a = _self_attr(el)
                    if a is not None:
                        self.writes.append(
                            (a, "rebind", "=", self._held_keys(), stmt.lineno)
                        )

    # expression scan: calls (edges, blocking, container mutators) ------
    def _scan_expr(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                continue  # deferred body: not executed at this point
            if not isinstance(node, ast.Call):
                continue
            callee = self.mod.resolve_callee(node, self.cls_qual)
            if callee is not None:
                self.calls.append((callee, self._held_keys(), node.lineno))
            if isinstance(node.func, ast.Attribute):
                attr_name = node.func.attr
                recv_attr = _self_attr(node.func.value)
                if (
                    attr_name in _CONTAINER_MUTATORS
                    and recv_attr is not None
                ):
                    self.writes.append(
                        (recv_attr, "container", f".{attr_name}()",
                         self._held_keys(), node.lineno)
                    )
            blocked = self._classify_blocking(node)
            if blocked is not None:
                self.blocks.append(
                    _Block(kind=blocked, held=self._held_keys(), line=node.lineno)
                )

    def _classify_blocking(self, call: ast.Call) -> Optional[str]:
        """Label of the blocking-op class this call belongs to, or None.
        A .wait() on a condition bound to the ONLY held lock is the
        sanctioned wait-releases-the-lock idiom and is exempt."""
        name = self.mod.index.dotted(call.func)
        if name == "time.sleep":
            return "time.sleep()"
        if name == "jax.device_get":
            return "jax.device_get() (device->host sync)"
        if name and (name.startswith("subprocess.") or name == "subprocess"):
            return f"{name}() (subprocess)"
        if name and (name == "cached_call" or name.endswith(".cached_call")):
            return "cached_call() (AOT compile wait)"
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        if attr == "block_until_ready":
            return ".block_until_ready() (device sync)"
        if attr == "result":
            return ".result() (Future wait)"
        if attr in ("recv", "recv_into", "accept"):
            return f".{attr}() (socket wait)"
        if attr == "wait":
            bound = self.mod.condition_bound_lock(call.func.value, self.cls_qual)
            held = self._held_keys()
            if bound is not None and held == (bound.key,):
                return None  # cond.wait() releases the one lock it guards
            return ".wait() (blocking wait)"
        return None


# -- the package-wide pass ----------------------------------------------------

@dataclass
class ParsedModule:
    path: str
    tree: ast.Module
    index: ModuleIndex


def _display_of(mods: List[_ModuleCC]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for m in mods:
        for d in m.module_locks.values():
            out[d.key] = d.display
        for cl in m.class_locks.values():
            for d in cl.locks.values():
                out[d.key] = d.display
    return out


def _fixpoint_sets(
    functions: Dict[str, _FuncSummary],
    seed: Dict[str, Set],
) -> Dict[str, Set]:
    """Transitive closure of per-function sets through same-module calls."""
    result = {q: set(s) for q, s in seed.items()}
    changed = True
    while changed:
        changed = False
        for qual, fn in functions.items():
            acc = result[qual]
            before = len(acc)
            for callee, _held, _line in fn.calls:
                if callee != qual:
                    acc |= result.get(callee, set())
            if len(acc) != before:
                changed = True
    return result


def _context_held(functions: Dict[str, _FuncSummary]) -> Dict[str, Set[str]]:
    """Locks PROVABLY held at every same-module call site of a function
    (the `_locked` helper convention): meet-over-call-sites fixpoint with
    optimistic top; functions with no in-module callers get the empty set."""
    callers: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {q: [] for q in functions}
    for qual, fn in functions.items():
        for callee, held, _line in fn.calls:
            if callee in callers and callee != qual:
                callers[callee].append((qual, held))
    TOP = None  # lattice top: "every lock" (unknown yet)
    ctx: Dict[str, Optional[Set[str]]] = {
        q: (set() if not callers[q] else TOP) for q in functions
    }
    for _ in range(len(functions) + 1):
        changed = False
        for qual in functions:
            if not callers[qual]:
                continue
            met: Optional[Set[str]] = TOP
            for caller, held in callers[qual]:
                caller_ctx = ctx.get(caller) or set()
                site = set(held) | caller_ctx
                met = site if met is None else (met & site)
            if met is None:
                met = set()
            if ctx[qual] is None or met != ctx[qual]:
                ctx[qual] = met
                changed = True
        if not changed:
            break
    return {q: (s or set()) for q, s in ctx.items()}


def lint_concurrency(
    modules: Iterable[ParsedModule], selected: Set[str]
) -> List[CCFinding]:
    """Run R11/R12 over a set of parsed modules as ONE program: lock nodes
    are module+class scoped, edges merge into a single held->acquired graph,
    and every edge on a cycle is reported at each witness site."""
    findings: List[CCFinding] = []
    mods = [
        _ModuleCC(pm.tree, pm.index, pm.path)
        for pm in modules
        if r11_applies(pm.path) or r12_applies(pm.path)
    ]
    if not mods:
        return findings
    display = _display_of(mods)

    def show(key: str) -> str:
        return display.get(key, key)

    # -- R11(a): the held->acquired graph --------------------------------
    # edge (held, acquired) -> witness sites (path, line, func, via)
    edges: Dict[Tuple[str, str], List[Tuple[str, int, str, str]]] = {}
    if "R11" in selected:
        for m in mods:
            if not r11_applies(m.path):
                continue
            may_acquire = _fixpoint_sets(
                m.functions,
                {q: {a for a, _h, _l in fn.acquires}
                 for q, fn in m.functions.items()},
            )
            for qual, fn in m.functions.items():
                for lock, held, line in fn.acquires:
                    for h in held:
                        if h != lock:
                            edges.setdefault((h, lock), []).append(
                                (m.path, line, qual, "")
                            )
                for callee, held, line in fn.calls:
                    if not held:
                        continue
                    for lock in may_acquire.get(callee, ()):
                        for h in held:
                            if h != lock:
                                edges.setdefault((h, lock), []).append(
                                    (m.path, line, qual, callee)
                                )
        # cycle detection: an edge is an inversion witness when the
        # acquired lock can reach the held lock through other edges
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src: str, dst: str) -> bool:
            stack, seen = [src], {src}
            while stack:
                n = stack.pop()
                if n == dst:
                    return True
                for nxt in adj.get(n, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            return False

        for (a, b), sites in sorted(edges.items()):
            if not reaches(b, a):
                continue
            # name one counter-witness so the message shows both orders
            counter = None
            for (c, d), csites in edges.items():
                if c == b and reaches(d, a):
                    counter = (c, d, csites[0])
                    break
            for path, line, qual, via in sites:
                how = f" (via call to {via}())" if via else ""
                if counter:
                    # name the counter-witness by FUNCTION, not line: the
                    # message feeds the stable finding id, which must
                    # survive unrelated edits shifting code up or down
                    cpath, cqual = counter[2][0], counter[2][2]
                    other = (
                        f"{show(counter[0])} -> {show(counter[1])} in "
                        f"{cpath}::{cqual or '<module>'}"
                    )
                else:  # pragma: no cover - counter edge always exists on a cycle
                    other = "the reverse order elsewhere"
                findings.append((
                    "R11",
                    path,
                    line,
                    f"lock-order inversion: {show(a)} is held while "
                    f"acquiring {show(b)} here{how}, but {other} closes a "
                    "cycle — two threads driving both orders deadlock; "
                    "pick ONE nesting order and document it "
                    "(docs/graftlint.md#r11)",
                    qual,
                ))

    # -- R11(b): blocking ops under a held lock --------------------------
    if "R11" in selected:
        for m in mods:
            if not r11_applies(m.path):
                continue
            may_block = _fixpoint_sets(
                m.functions,
                {q: {b.kind for b in fn.blocks}
                 for q, fn in m.functions.items()},
            )
            for qual, fn in m.functions.items():
                for b in fn.blocks:
                    if not b.held:
                        continue
                    locks = ", ".join(show(k) for k in b.held)
                    findings.append((
                        "R11",
                        m.path,
                        b.line,
                        f"blocking {b.kind} while holding {locks}: every "
                        "thread contending for the lock stalls behind a "
                        "wait unrelated to the state it guards — move the "
                        "wait outside the critical section "
                        "(docs/graftlint.md#r11)",
                        qual,
                    ))
                for callee, held, line in fn.calls:
                    if not held:
                        continue
                    kinds = may_block.get(callee, set())
                    if not kinds:
                        continue
                    locks = ", ".join(show(k) for k in held)
                    findings.append((
                        "R11",
                        m.path,
                        line,
                        f"call to {callee}() while holding {locks} reaches "
                        f"a blocking {sorted(kinds)[0]} — every thread "
                        "contending for the lock stalls behind it; move "
                        "the wait outside the critical section "
                        "(docs/graftlint.md#r11)",
                        qual,
                    ))

    # -- R12: shared-state write discipline ------------------------------
    if "R12" in selected:
        for m in mods:
            if not r12_applies(m.path):
                continue
            ctx = _context_held(m.functions)
            # group writes per class attr
            per_class: Dict[str, Dict[str, List[Tuple[str, str, Tuple[str, ...], int, str, bool]]]] = {}
            for qual, fn in m.functions.items():
                if not fn.cls_qual or fn.cls_qual not in m.class_locks:
                    continue  # no lock in the class: nothing claims guarding
                if not m.class_locks[fn.cls_qual].locks:
                    continue
                method = qual.rsplit(".", 1)[-1]
                if method in _CTOR_METHODS:
                    continue  # construction is single-threaded by contract
                for attr, kind, op, held, line in fn.writes:
                    guarded = bool(held) or bool(ctx.get(qual))
                    per_class.setdefault(fn.cls_qual, {}).setdefault(
                        attr, []
                    ).append((kind, op, held, line, qual, guarded))
            for cls_qual, attrs in sorted(per_class.items()):
                lock_names = ", ".join(
                    f"self.{a}" for a in sorted(m.class_locks[cls_qual].locks)
                )
                for attr, writes in sorted(attrs.items()):
                    if attr in m.class_locks[cls_qual].locks:
                        continue  # rebinding the lock itself: not state
                    guarded_sites = [w for w in writes if w[5]]
                    unguarded = [w for w in writes if not w[5]]
                    if guarded_sites and unguarded:
                        g = guarded_sites[0]
                        for kind, op, _held, line, qual, _ in unguarded:
                            findings.append((
                                "R12",
                                m.path,
                                line,
                                f"self.{attr} is written under a lock at "
                                f"{m.path}:{g[3]} but written here with no "
                                "lock held — the unguarded write races "
                                "every reader that trusts the lock "
                                "(docs/graftlint.md#r12)",
                                qual,
                            ))
                    elif unguarded and not guarded_sites:
                        for kind, op, _held, line, qual, _ in unguarded:
                            if kind != "container":
                                continue
                            findings.append((
                                "R12",
                                m.path,
                                line,
                                f"non-atomic {op} mutation of lock-free "
                                f"attribute self.{attr} (class {cls_qual} "
                                f"owns {lock_names}): in-place container "
                                "mutation is not an atomic reference swap "
                                "— guard it, or build a fresh container "
                                "and swap the reference "
                                "(docs/graftlint.md#r12)",
                                qual,
                            ))
    findings.sort(key=lambda f: (f[1], f[2], f[0]))
    return findings
