#
# Headline benchmark.  Default: cycle EVERY arm in one run — KMeans at the
# flagship shape (k=1000, maxIter=30, initMode=random on 1M x 3000 float32;
# /root/reference/python/benchmark/databricks/run_benchmark.sh:45-55, results
# in databricks/results/running_times.png: CPU 9526 s, GPU 82 s on 2x A10G
# => ~12,195 rows/s) as the headline, the other arms at driver-capturable
# shapes so every claimed multiple has a recorded artifact.
#
# Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} for the
# headline arm (value = MEDIAN rows/sec of SRML_BENCH_REPEATS timed runs,
# default 3), plus "value_best"/"spread_pct"/"times_sec" for the protocol
# and an "arms" map carrying the same stats for every other arm (an arm
# that fails records an "error" string instead of sinking the run).
#
# SRML_BENCH_ALGO=<arm> runs that single arm (same JSON shape, no "arms"
# map).  Arms: kmeans|pca|linreg|logreg|logreg_sparse|knn|rf_clf|rf_reg|umap.
# Size knobs: SRML_BENCH_ROWS / SRML_BENCH_COLS / SRML_BENCH_K /
# SRML_BENCH_ITERS / SRML_BENCH_REPEATS.  Row counts default to a
# memory-safe fraction of the reference's 1M and are normalized to
# rows/sec, so vs_baseline stays comparable.
#

import gc
import glob
import json
import os
import statistics
import time

import numpy as np

# Persistent XLA compilation cache: heavyweight compiles are paid once per
# machine instead of once per bench run.  Env vars alone are NOT enough on
# hosts whose sitecustomize imports jax before this file runs (the axon
# image does) — jax has already read its config by then — so main() also
# sets the same values through jax.config.update.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/srml_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

REF_ROWS = 1_000_000
# reference GPU-cluster fit seconds on 1M x 3000 (running_times.png, 2x A10G)
REF_GPU_SECONDS = {
    "kmeans": 82.0,
    "pca": 37.0,
    "linreg": 32.0,   # ridge configuration (fastest GPU arm)
    "logreg": 69.0,
    "knn": 82.0,      # no published kNN bar; reuse the kmeans-scale bar as a floor
    "ann": 82.0,      # no published ANN bar either; same kmeans-scale floor
    "ann_pq": 82.0,   # the PQ tier shares the ANN floor (same workload)
    "rf_clf": 59.0,
    "rf_reg": 52.0,
    "umap": 82.0,     # no published UMAP bar; kmeans-scale floor like knn
    # no published tuning bar; scored against the linreg bar as a floor on
    # trained row-visits/sec (rows x candidates x (folds-1) per sweep)
    "tuning": 32.0,
    # BASELINE.json's "LogisticRegression multinomial on 1Bx100 sparse" has
    # no published time; scored against the dense logreg bar as a floor
    # (different shape: 100 sparse cols vs 3000 dense — see docs)
    "logreg_sparse": 69.0,
    # no published streaming bar (the reference cannot incrementally fit at
    # all); scored against the linreg bar as a conservative floor on
    # ingested rows/sec — streamed ingest re-pays chunk staging per chunk,
    # so beating the batch-fit bar at all is the story
    "streaming": 32.0,
}

# all arms, headline first; cycle-mode shape overrides keep the slower
# host-ingest arms inside a sane wall-clock (rows/sec stays comparable —
# that is the whole point of the normalized metric)
CYCLE_ARMS = [
    "kmeans", "pca", "linreg", "logreg", "logreg_sparse",
    "knn", "ann", "ann_pq", "rf_reg", "rf_clf", "umap", "tuning",
    "streaming",
]
CYCLE_OVERRIDES = {
    # 1M x 100 sparse (the BASELINE.json shape family, 4x smaller)
    "logreg_sparse": {"SRML_BENCH_ROWS": "1000000"},
}


def _sync(x) -> float:
    # np.asarray forces execution + fetch (block_until_ready alone does not
    # synchronize through the axon tunnel)
    return float(np.asarray(x).ravel()[0])


def _timed_repeats(fn, repeats: int):
    """One warmup call (compiles are cached for the timed runs), then
    `repeats` timed calls.  Returns (cold_seconds, per-run seconds,
    per-run phase-time dicts): the cold time captures the first-fit
    experience (compiles + staging) the warm numbers amortize away; the
    multi-repeat protocol exists because single timed runs on the tunneled
    device have been observed 5x apart under congestion.  The per-repeat
    phase breakdown (srml-scope) is what lets a spread be ATTRIBUTED to a
    phase instead of eyeballed (the kNN arm's standing 31% mystery)."""
    from spark_rapids_ml_tpu import profiling

    t0 = time.perf_counter()
    fn()
    cold = time.perf_counter() - t0
    times, phases = [], []
    for _ in range(repeats):
        profiling.reset_phase_times()
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
        phases.append(profiling.phase_times())
    return cold, times, phases




def _device_padded_gen(mesh, rows, gen_fn, seed=42):
    """Generate an (n_pad, D) dataset ON DEVICE, row-sharded over the mesh,
    with a weight vector masking the pad rows.  Keeps multi-GB benchmark
    inputs off the host link (uploads can take minutes when the link is
    congested and are not part of the measured fit)."""
    import jax
    import numpy as np
    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, data_sharding

    n_dev = mesh.shape[DATA_AXIS]
    n_pad = rows + (-rows) % n_dev
    Xs = jax.jit(lambda s: gen_fn(jax.random.PRNGKey(s), n_pad),
                 out_shardings=data_sharding(mesh))(seed)
    w = jax.device_put(
        np.r_[np.ones(rows, np.float32), np.zeros(n_pad - rows, np.float32)],
        data_sharding(mesh),
    )
    return Xs, w


def build_arm(algo: str, overrides):
    """Set up one benchmark arm; returns (fit_fn, label, rows) with all
    inputs staged (device-resident where the arm measures device compute).
    `overrides` shadow the SRML_BENCH_* env knobs in cycle mode."""
    import jax

    def _ov(key, default):
        return overrides.get(key) or os.environ.get(key) or default

    platform = jax.devices()[0].platform
    on_accel = platform != "cpu"
    rows = int(_ov("SRML_BENCH_ROWS", 400_000 if on_accel else 20_000))
    cols = int(_ov("SRML_BENCH_COLS", 3000 if on_accel else 256))
    iters = int(_ov("SRML_BENCH_ITERS", 30))

    from spark_rapids_ml_tpu.parallel.mesh import data_sharding, get_mesh

    rng = np.random.default_rng(42)
    mesh = get_mesh()

    if algo == "kmeans":
        k = int(_ov("SRML_BENCH_K", 1000 if on_accel else 64))
        from spark_rapids_ml_tpu import KMeans
        from spark_rapids_ml_tpu.dataframe import DataFrame

        # Unit-scale centers with unit noise: clusters overlap, so Lloyd
        # genuinely uses all maxIter iterations (wider separation converges
        # exactly in ~6 iterations and would overstate throughput vs the
        # reference's 30-iteration arm).  Data is generated on device and
        # enters through DataFrame.from_device — the timed region is the
        # PUBLIC estimator fit (validation, param translation, dispatch,
        # solver, attribute fetch), with ingest untimed the same way the
        # reference's GPU arm starts from plugin-cached device data.
        import jax.numpy as jnp

        def _gen(key, n_pad):
            kc, ka, kn = jax.random.split(key, 3)
            centers_true = jax.random.normal(kc, (k, cols), jnp.float32)
            assign = jax.random.randint(ka, (n_pad,), 0, k)
            return centers_true[assign] + jax.random.normal(
                kn, (n_pad, cols), jnp.float32
            )

        Xs, w = _device_padded_gen(mesh, rows, _gen)
        _sync(Xs.sum())
        df = DataFrame.from_device(Xs, n_rows=rows)
        est = KMeans(k=k, maxIter=iters, tol=0.0, initMode="random", seed=1)

        def fit():
            model = est.fit(df)
            return _sync(np.asarray(model.cluster_centers_))

        return fit, f"kmeans_fit_throughput_k{k}_d{cols}_iter{iters}", rows

    if algo == "pca":
        k = int(_ov("SRML_BENCH_K", 3))
        from spark_rapids_ml_tpu import PCA
        from spark_rapids_ml_tpu.dataframe import DataFrame

        # low-rank + noise generated on device (no 4.8 GB upload); timed
        # region = PCA().fit() at the public API (see kmeans arm note)
        import jax.numpy as jnp

        def _gen(key, n_pad):
            ka, kb, kn = jax.random.split(key, 3)
            A = jax.random.normal(ka, (n_pad, 32), jnp.float32)
            B = jax.random.normal(kb, (32, cols), jnp.float32)
            return A @ B + 0.1 * jax.random.normal(kn, (n_pad, cols), jnp.float32)

        Xs, w = _device_padded_gen(mesh, rows, _gen)
        _sync(Xs.sum())
        df = DataFrame.from_device(Xs, n_rows=rows)
        est = PCA(k=k)

        def fit():
            model = est.fit(df)
            return float(np.asarray(model.components_).ravel()[0])

        return fit, f"pca_fit_throughput_k{k}_d{cols}", rows

    if algo in ("linreg", "logreg"):
        # GLMs through the public estimator fit on a from_device frame —
        # data generated on device like every other arm (the old host
        # from_numpy staging uploaded 1.2 GB through the tunnel during the
        # untimed warmup, 60+ s under congestion, and forced a 100k-row
        # override; the full 400k shape now runs)
        import jax.numpy as jnp

        from spark_rapids_ml_tpu import LinearRegression, LogisticRegression
        from spark_rapids_ml_tpu.dataframe import DataFrame

        coef = rng.standard_normal(cols, dtype=np.float32)

        def _gen(key, n_pad):
            kx, kn = jax.random.split(key)
            X = jax.random.normal(kx, (n_pad, cols), jnp.float32)
            y = X @ jnp.asarray(coef) + 0.1 * jax.random.normal(kn, (n_pad,))
            if algo == "logreg":
                y = (y > 0).astype(jnp.float32)
            return X, y

        n_dev = mesh.devices.size
        n_pad = rows + (-rows) % n_dev
        Xs, ys = jax.jit(
            lambda s: _gen(jax.random.PRNGKey(s), n_pad),
            out_shardings=(data_sharding(mesh), data_sharding(mesh)),
        )(42)
        _sync(Xs.sum())
        y_host = np.asarray(ys)[:rows]  # labels are O(N) scalars
        df = DataFrame.from_device(Xs, y=y_host, n_rows=rows)
        if algo == "linreg":
            est = LinearRegression(regParam=1e-5, maxIter=iters)

            def fit():
                model = est.fit(df)
                return float(np.asarray(model.coefficients).ravel()[0])

            return fit, f"linreg_ridge_fit_throughput_d{cols}", rows
        est = LogisticRegression(regParam=1e-5, maxIter=max(iters, 200))

        def fit():
            model = est.fit(df)
            return float(np.asarray(model.coefficientMatrix).ravel()[0])

        return fit, f"logreg_fit_throughput_d{cols}_iter{max(iters, 200)}", rows

    if algo == "logreg_sparse":
        # BASELINE.json repro config scaled to one chip: multinomial logreg
        # on sparse rows (1Bx100 at 1% nnz in the reference's distributed
        # arm).  Timed region = LogisticRegression().fit() on a CSR-built
        # DataFrame — the ELL kernels underneath (ops/sparse.py) never
        # densify; the device-input cache keeps repeat ingest untimed.
        import scipy.sparse as sp

        from spark_rapids_ml_tpu import LogisticRegression
        from spark_rapids_ml_tpu.dataframe import DataFrame

        rows = int(_ov("SRML_BENCH_ROWS", 4_000_000 if on_accel else 50_000))
        cols = int(_ov("SRML_BENCH_COLS", 100))
        n_classes = 4
        density = 0.01
        nnz_per_row = max(1, int(cols * density))
        idx = rng.integers(0, cols, size=(rows, nnz_per_row), dtype=np.int32)
        val = rng.standard_normal((rows, nnz_per_row), dtype=np.float32)
        W_true = rng.standard_normal((cols, n_classes), dtype=np.float32)
        # labels from the sparse logits
        logits = np.zeros((rows, n_classes), np.float32)
        for j in range(nnz_per_row):
            logits += val[:, j : j + 1] * W_true[idx[:, j]]
        y = logits.argmax(axis=1).astype(np.float32)
        indptr = np.arange(0, (rows + 1) * nnz_per_row, nnz_per_row, dtype=np.int64)
        csr = sp.csr_matrix(
            (val.ravel(), idx.ravel().astype(np.int64), indptr),
            shape=(rows, cols),
        )
        df = DataFrame.from_numpy(csr, y, num_partitions=1)
        est = LogisticRegression(
            regParam=1e-5, maxIter=max(iters, 100), tol=1e-6
        )

        def fit():
            model = est.fit(df)
            return float(np.asarray(model.coefficientMatrix).ravel()[0])

        return (
            fit,
            f"logreg_sparse_fit_throughput_d{cols}_nnz{nnz_per_row}",
            rows,
        )

    if algo == "knn":
        k = int(_ov("SRML_BENCH_K", 200))

        # brute-force kNN is FLOP-bound: 2*n_items*d FLOP per query row
        # (2.4 GFLOP at the 400k x 3000 default), so the per-chip query
        # budget is what keeps the arm's wall-clock sane.  16384 = two
        # dispatch blocks, so result fetches overlap the next block's
        # compute (the steady state a real serving loop runs in)
        n_query = int(_ov("SRML_BENCH_QUERIES", min(rows, 16384)))
        import jax.numpy as jnp

        from spark_rapids_ml_tpu import NearestNeighbors
        from spark_rapids_ml_tpu.dataframe import DataFrame
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

        # Timed region = the PUBLIC model.kneighbors(query_df) call.  Index
        # + queries are GENERATED on device (a 4.9 GB index upload through
        # the tunnel is untimed setup that can eat 30+ min under
        # congestion) and installed in the model's own staging caches —
        # the state any user reaches after one prior kneighbors call on
        # the same model (the reference's GPU arm likewise queries data
        # already resident on the GPUs).  The host-side frames carry
        # placeholder feature blocks whose values are never read on the
        # cached path.
        n_dev = mesh.shape[DATA_AXIS]
        n_pad = rows + (-rows) % n_dev
        items_dev = jax.jit(
            lambda s: jax.random.normal(
                jax.random.PRNGKey(s), (n_pad, cols), jnp.float32
            ),
            out_shardings=data_sharding(mesh),
        )(0)
        Q_dev = jax.jit(
            lambda s: jax.random.normal(
                jax.random.PRNGKey(s), (n_query, cols), jnp.float32
            )
        )(7)
        _sync(items_dev.sum())
        _sync(Q_dev.sum())

        from spark_rapids_ml_tpu.core import extract_partition_features
        from spark_rapids_ml_tpu.ops.knn import prepare_items

        # zeros, NOT np.empty: uninitialized NaN pages fail the zero-copy
        # block guard's row equality (NaN != NaN) and would silently defeat
        # the seeded staging caches, re-uploading garbage inside the clock
        item_df = DataFrame.from_numpy(
            np.zeros((rows, cols), np.float32), num_partitions=1
        )
        query_df = DataFrame.from_numpy(
            np.zeros((n_query, cols), np.float32), num_partitions=1
        )
        est = NearestNeighbors(k=k)
        model = est.fit(item_df)
        # stage the device-resident index through prepare_items: the device
        # path tile-aligns it once, so the fused kernels never re-pad
        # (shuffle off — the data is i.i.d.-generated)
        prepared = prepare_items(
            items_dev[:rows], np.arange(rows, dtype=np.int64), mesh,
            shuffle=False,
        )
        q_block = extract_partition_features(
            query_df.partitions[0], "features", None, np.float32
        )
        model.seed_staging(
            prepared, query_blocks={0: (q_block, Q_dev)}, mesh=mesh
        )

        def fit():
            _, _, knn_df = model.kneighbors(query_df)
            d0 = knn_df.partitions[0]["distances"].iloc[0]
            return float(np.asarray(d0).ravel()[0])

        # throughput counts completed query rows
        return fit, f"knn_query_throughput_n{rows}_d{cols}_k{k}", n_query

    if algo in ("ann", "ann_pq"):
        # IVF probed query throughput (srml-ann / srml-pq).  Shape: the ANN
        # regime is many rows x embedding-scale dims (the exact arm's
        # 3000-col FLOP wall is exactly what IVF probing removes), so the
        # arm defaults to 400k x 256 clustered rows.  The timed region is
        # the PUBLIC model.kneighbors probed search with the index staged
        # and kernels warm (the warmup call); index build (quantizer +
        # assignment + layout + upload) lands in cold_sec.  recall@k vs
        # the exact path is measured by benchmark/bench_approximate_nn.py
        # on the same engine and asserted in tests (>= 0.95 flat, >= 0.9
        # refined pq) — the arms report throughput at the documented
        # operating points.  BOTH arms record index_bytes_per_item, so
        # every round's artifact carries the flat-vs-pq compression ratio.
        k = int(_ov("SRML_BENCH_K", 200))
        rows = int(_ov("SRML_BENCH_ROWS", 400_000 if on_accel else 20_000))
        cols = int(_ov("SRML_BENCH_COLS", 256 if on_accel else 64))
        n_query = int(_ov("SRML_BENCH_QUERIES", min(rows, 16384)))
        from spark_rapids_ml_tpu import ApproximateNearestNeighbors
        from spark_rapids_ml_tpu.ann.ivfflat import default_nlist, default_nprobe
        from spark_rapids_ml_tpu.dataframe import DataFrame

        nlist = int(_ov("SRML_BENCH_NLIST", default_nlist(rows)))
        nprobe = int(_ov("SRML_BENCH_NPROBE", default_nprobe(nlist)))
        # clustered items (the workload IVF exists for; uniform data would
        # spread every query's true neighbors over all lists and report a
        # recall no real embedding table exhibits)
        n_blobs = max(32, nlist)
        centers_h = 10.0 * rng.standard_normal((n_blobs, cols), dtype=np.float32)
        lab = rng.integers(0, n_blobs, size=rows)
        X_host = centers_h[lab] + rng.standard_normal(
            (rows, cols), dtype=np.float32
        )
        item_bdf = DataFrame.from_numpy(X_host)
        query_bdf = DataFrame.from_numpy(X_host[:n_query].copy())
        if algo == "ann_pq":
            from spark_rapids_ml_tpu.ann.pq import default_m_sub

            m_sub = int(_ov("SRML_BENCH_PQ_M", default_m_sub(cols)))
            est = ApproximateNearestNeighbors(
                k=k,
                algorithm="ivfpq",
                algoParams={"nlist": nlist, "nprobe": nprobe, "M": m_sub},
            ).setInputCol("features")
            label = (
                f"annpq_query_throughput_n{rows}_d{cols}_k{k}"
                f"_l{nlist}_p{nprobe}_m{m_sub}"
            )
        else:
            est = ApproximateNearestNeighbors(
                k=k, algoParams={"nlist": nlist, "nprobe": nprobe}
            ).setInputCol("features")
            label = f"ann_query_throughput_n{rows}_d{cols}_k{k}_l{nlist}_p{nprobe}"
        model = est.fit(item_bdf)  # index build: untimed setup (cold_sec
        # still captures staging + compiles via the warmup call)
        _ARM_EXTRAS[algo] = {
            "index_bytes_per_item": round(model.index_bytes_per_item(), 2)
        }

        def fit():
            _, _, knn_df = model.kneighbors(query_bdf)
            d0 = knn_df.partitions[0]["distances"].iloc[0]
            return float(np.asarray(d0).ravel()[0])

        return fit, label, n_query

    on_accel_rf = algo in ("rf_clf", "rf_reg") and on_accel
    if on_accel_rf:
        # the reference's published regressor arm: 30 trees, bins=128,
        # depth=6 on 1M x 3000 synthetic (run_benchmark.sh:113-122; GPU pair
        # 52 s).  Timed region = the PUBLIC RandomForest*.fit() on a
        # from_device frame — estimator preprocessing, device-side binning
        # sample + edges, MXU histogram growth (ops/forest_mxu), and the
        # forest-attribute fetch all inside the clock, matching what cuML's
        # fit() does after plugin-cached ingest.
        import jax.numpy as jnp

        from spark_rapids_ml_tpu import (
            RandomForestClassifier,
            RandomForestRegressor,
        )
        from spark_rapids_ml_tpu.dataframe import DataFrame

        rows = int(_ov("SRML_BENCH_ROWS", 400_000))
        if algo == "rf_reg":
            # 30 trees, depth 6, onethird feature subsets (Spark 'auto')
            est = RandomForestRegressor(
                numTrees=30, maxDepth=6, maxBins=128,
                featureSubsetStrategy="onethird", seed=3,
            )
            n_trees, depth = 30, 6
        else:
            # 50 trees, depth 13 (deep bucketed phase), sqrt subsets
            est = RandomForestClassifier(
                numTrees=50, maxDepth=13, maxBins=128,
                featureSubsetStrategy="sqrt", seed=3,
            )
            n_trees, depth = 50, 13
        n_informative = 10  # sklearn make_regression default, as the
        # reference's gen_data uses (gen_data.py)
        coef = np.zeros(cols, np.float32)
        coef[rng.choice(cols, n_informative, replace=False)] = (
            rng.standard_normal(n_informative).astype(np.float32)
        )

        def _gen(key, n_pad):
            kx, kn = jax.random.split(key)
            X = jax.random.normal(kx, (n_pad, cols), jnp.float32)
            y = X @ jnp.asarray(coef) + 0.1 * jax.random.normal(kn, (n_pad,))
            if algo == "rf_clf":
                y = (y > 0).astype(jnp.float32)
            return X, y

        Xs, ys = jax.jit(lambda s: _gen(jax.random.PRNGKey(s), rows))(42)
        _sync(Xs.sum())
        y_host = np.asarray(ys)  # labels are O(N) scalars, features stay put
        df = DataFrame.from_device(Xs, y=y_host, n_rows=rows)

        def fit():
            model = est.fit(df)
            return float(model.getNumTrees)

        return (
            fit,
            f"{algo}_fit_throughput_d{cols}_t{n_trees}_depth{depth}",
            rows,
        )

    if algo in ("rf_clf", "rf_reg"):
        # CPU smoke runs only (on accelerators both arms take the MXU branch
        # above): estimator-level fit on a small HIGGS-like shape
        from spark_rapids_ml_tpu.dataframe import DataFrame

        rows = int(_ov("SRML_BENCH_ROWS", 100_000 if on_accel else 5_000))
        cols = int(_ov("SRML_BENCH_COLS", 28 if on_accel else 16))
        X_host = rng.standard_normal((rows, cols), dtype=np.float32)
        if algo == "rf_clf":
            from spark_rapids_ml_tpu import RandomForestClassifier

            y = (
                X_host[:, :10] @ rng.standard_normal(10, dtype=np.float32) > 0
            ).astype(np.float32)
            # reference arm params on accel; scaled down for CPU smoke runs
            est = (
                RandomForestClassifier(numTrees=50, maxBins=128, maxDepth=13, seed=1)
                if on_accel
                else RandomForestClassifier(numTrees=8, maxBins=32, maxDepth=6, seed=1)
            )
        else:
            from spark_rapids_ml_tpu import RandomForestRegressor

            est = (
                RandomForestRegressor(numTrees=30, maxBins=128, maxDepth=6, seed=1)
                if on_accel
                else RandomForestRegressor(numTrees=8, maxBins=32, maxDepth=5, seed=1)
            )
            y = (X_host[:, :10] @ rng.standard_normal(10, dtype=np.float32)).astype(
                np.float32
            )
        df = DataFrame.from_numpy(X_host, y, num_partitions=8)

        def fit():
            model = est.fit(df)
            return float(model.getNumTrees)

        return fit, f"{algo}_fit_throughput_d{cols}", rows

    if algo == "tuning":
        # srml-sweep: an m-candidate x k-fold CrossValidator through the
        # batched one-dispatch engine (docs/tuning_engine.md).  Host-facade
        # frame on purpose: the sweep's scoring pass reads host partitions
        # (from_device frames are fit-input-only), and the repeat runs ride
        # the device-input cache so the staging is untimed after warm-up —
        # what the clock holds is the sweep itself (masked-fold stats,
        # lane solves, fold scoring, winner refit).  Throughput counts
        # TRAINED ROW-VISITS: rows x candidates x (folds-1)/folds x folds.
        from spark_rapids_ml_tpu import LinearRegression
        from spark_rapids_ml_tpu.dataframe import DataFrame
        from spark_rapids_ml_tpu.evaluation import RegressionEvaluator
        from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

        rows = int(_ov("SRML_BENCH_ROWS", 100_000 if on_accel else 20_000))
        cols = int(_ov("SRML_BENCH_COLS", 512 if on_accel else 128))
        m = int(_ov("SRML_BENCH_GRID", 8))
        k_folds = int(_ov("SRML_BENCH_FOLDS", 3))
        X_host = rng.standard_normal((rows, cols), dtype=np.float32)
        coef = rng.standard_normal(cols, dtype=np.float32)
        y = (X_host @ coef + 0.1 * rng.standard_normal(rows)).astype(
            np.float32
        )
        df = DataFrame.from_numpy(X_host, y=y, num_partitions=4)
        grid = ParamGridBuilder().addGrid(
            LinearRegression.regParam, np.geomspace(1e-3, 1.0, m).tolist()
        ).build()

        def fit():
            cv = CrossValidator(
                estimator=LinearRegression(standardization=False),
                estimatorParamMaps=grid,
                evaluator=RegressionEvaluator(),
                numFolds=k_folds,
                seed=7,
            )
            return float(cv.fit(df).avgMetrics[0])

        return (
            fit,
            f"tuning_sweep_throughput_n{rows}_d{cols}_m{m}_k{k_folds}",
            rows * m * (k_folds - 1),
        )

    if algo == "umap":
        from spark_rapids_ml_tpu import UMAP
        from spark_rapids_ml_tpu.dataframe import DataFrame

        rows = int(_ov("SRML_BENCH_ROWS", 50_000 if on_accel else 2_000))
        cols = int(_ov("SRML_BENCH_COLS", 128 if on_accel else 32))
        X_host = rng.standard_normal((rows, cols), dtype=np.float32)
        df = DataFrame.from_numpy(X_host, num_partitions=8)
        est = UMAP(n_components=2, n_neighbors=15, n_epochs=200, random_state=1)

        def fit():
            model = est.fit(df)
            return float(np.asarray(model.embedding_).ravel()[0])

        return fit, f"umap_fit_throughput_n{rows}_d{cols}", rows

    if algo == "streaming":
        # srml-stream: steady-state partial_fit ingest through the linreg
        # streaming engine (docs/streaming.md).  The timed region is the
        # full chunked ingest + finalize of a fresh engine per run — chunk
        # staging IS the workload here (a streaming system re-pays it per
        # chunk by construction), while the bucket compile lands in the
        # warm-up run like every other arm's cold cost.  Throughput counts
        # ingested rows/sec; benchmark/bench_streaming.py carries the
        # refresh-blip and refit-cost detail numbers.
        from spark_rapids_ml_tpu import LinearRegression

        rows = int(_ov("SRML_BENCH_ROWS", 400_000 if on_accel else 40_000))
        cols = int(_ov("SRML_BENCH_COLS", 512 if on_accel else 128))
        chunk = int(_ov("SRML_BENCH_CHUNK", 8192))
        X_host = rng.standard_normal((rows, cols), dtype=np.float32)
        coef = rng.standard_normal(cols, dtype=np.float32)
        y = (X_host @ coef + 0.1 * rng.standard_normal(rows)).astype(
            np.float64
        )
        bounds = list(range(0, rows, chunk))

        def fit():
            eng = LinearRegression(standardization=False).streaming()
            for s in bounds:
                eng.partial_fit(X_host[s : s + chunk], y=y[s : s + chunk])
            return float(eng.finalize().coef_[0])

        return (
            fit,
            f"streaming_ingest_throughput_n{rows}_d{cols}_c{chunk}",
            rows,
        )

    raise SystemExit(f"unknown SRML_BENCH_ALGO={algo}")


# measurement assumptions that must travel WITH the numbers (advisor
# round-4: the caveat lived only in comments, so cross-framework
# comparisons could silently drop it)
ARM_NOTES = {
    "ann": (
        "probed IVF-Flat search at the documented operating point "
        "(nlist/nprobe in the metric label) on clustered data; index build "
        "is untimed setup; recall@k vs the exact path is gated >= 0.95 in "
        "tests/test_ann_engine.py and reported per-run by "
        "benchmark/bench_approximate_nn.py; index_bytes_per_item in the "
        "record pairs with the ann_pq arm's for the compression ratio"
    ),
    "ann_pq": (
        "probed IVF-PQ ADC search + f32 refine at the documented operating "
        "point (nlist/nprobe/M in the metric label) on the SAME clustered "
        "shape as the ann arm; refined recall@10 >= 0.9 is gated in "
        "tests/test_pq_engine.py and reported per-run by "
        "benchmark/bench_approximate_nn.py --algorithm ivfpq; "
        "index_bytes_per_item vs the ann arm is the compression headline"
    ),
    "knn": (
        "timed region is model.kneighbors with the item index and query "
        "upload pre-seeded in the model staging caches (the steady state "
        "after one prior call on the same model); query/index ingest is "
        "NOT in the clock"
    ),
    "streaming": (
        "steady-state chunked partial_fit ingest + finalize through the "
        "linreg streaming engine; chunk staging stays IN the clock (a "
        "streaming system re-pays it per chunk by construction); the "
        "bucket compile lands in the untimed warm-up; refresh-blip and "
        "batch-refit comparison numbers come from "
        "benchmark/bench_streaming.py"
    ),
}


# Per-arm minimum timed repeats: the kNN arm's short timed region (two
# dispatch blocks) showed a 31.4% max-min spread at 3 repeats under tunnel
# congestion (BENCH_r05) — more samples tighten the median without touching
# the timed region itself.  Applied as a floor so SRML_BENCH_REPEATS can
# still raise everything globally.
ARM_MIN_REPEATS = {"knn": 7, "ann": 7, "ann_pq": 7}  # short timed regions

# per-arm extra record fields set by build_arm (e.g. the ann arms'
# index_bytes_per_item) and merged into the stats dict by run_arm — the
# timed metric stays ONE number per arm; extras ride the artifact
_ARM_EXTRAS: dict = {}


def run_arm(algo: str, overrides, repeats: int):
    """Build, warm up, and time one arm; returns its stats dict.  cold_sec
    records the first (warmup) call — compiles + device staging included —
    so the first-fit experience is a captured artifact, not a claim."""
    from spark_rapids_ml_tpu.parallel.exchange import byte_totals

    repeats = max(repeats, ARM_MIN_REPEATS.get(algo, 1))
    _x0_total, x0_per = byte_totals()
    fit, label, rows = build_arm(algo, overrides)
    cold, times, phases = _timed_repeats(fit, repeats)
    med, best = statistics.median(times), min(times)
    value = rows / med
    baseline = REF_ROWS / REF_GPU_SECONDS.get(algo, REF_GPU_SECONDS["kmeans"])
    out = {
        "metric": label,
        "value": round(value, 1),
        "unit": "rows/sec",
        "vs_baseline": round(value / baseline, 3),
        "value_best": round(rows / best, 1),
        "spread_pct": round(100.0 * (max(times) - best) / med, 1),
        "times_sec": [round(t, 3) for t in times],
        "cold_sec": round(cold, 3),
        "repeats": repeats,  # can exceed the global knob (ARM_MIN_REPEATS)
        # backend tag (standings.py): a builder round that fell back to the
        # CPU backend measures different shapes on different silicon — it
        # must never be scored against the accelerator floor or diffed
        # against an accelerator round (r06_builder_cycle.json is the
        # motivating capture)
        "backend": __import__("jax").devices()[0].platform,
    }
    # per-arm exchange byte totals (parallel/exchange section counters):
    # host sections count per call, device sections per compiled geometry
    # (trace time), so the number captures what ONE steady-state dispatch
    # set moves — which is exactly where the all-gather -> ring-permute
    # candidate-traffic reduction (~n_dev x) shows up.  standings.py
    # renders the total as the kNN arm's `bytes moved` column.
    x1_total, x1_per = byte_totals()
    sections = {
        name: v - x0_per.get(name, 0)
        for name, v in sorted(x1_per.items())
        if v - x0_per.get(name, 0) > 0
    }
    out["exchange_bytes"] = int(sum(sections.values()))
    if sections:
        out["exchange_sections"] = sections
    # per-repeat phase breakdown + the phase the spread lives in (srml-scope
    # satellites: standings.py renders the attribution next to the ⚠ flag)
    from spark_rapids_ml_tpu import profiling

    attribution = profiling.spread_attribution(phases, med)
    if attribution:
        out["spread_attribution"] = attribution
        out["spread_phase"] = next(iter(attribution))
    if phases and phases[-1]:
        out["phase_times_per_repeat"] = [
            {k: round(v, 4) for k, v in sorted(p.items())} for p in phases
        ]
    if algo in ARM_NOTES:
        out["notes"] = ARM_NOTES[algo]
    out.update(_ARM_EXTRAS.pop(algo, {}))
    return out


def _release_arm_state():
    """Free device buffers between arms (the fit closures pin the staged
    datasets; the estimator arms also pin the device-input cache slot).
    After the cache clear + gc, any still-live device array of arm scale is
    a leak — delete it outright (nothing legitimate survives between arms)
    and report it, then sync the stream so queued deallocations land before
    the next arm's multi-GB staging races them (run r4a: rf/umap arms
    RESOURCE_EXHAUSTED behind the knn arm's lingering 4.8 GB)."""
    import sys

    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.core import clear_fit_cache

    clear_fit_cache()
    gc.collect()
    leaked = [a for a in jax.live_arrays() if a.nbytes >= (64 << 20)]
    if leaked:
        total = sum(a.nbytes for a in leaked) / 2**30
        print(
            f"[bench] releasing {len(leaked)} leaked device buffers "
            f"({total:.2f} GB)",
            file=sys.stderr,
        )
        for a in leaked:
            a.delete()
    _sync(jnp.zeros(1))  # flush pending deallocations through the relay


def main() -> None:
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
    )

    repeats = max(1, int(os.environ.get("SRML_BENCH_REPEATS", "3")))
    algo = os.environ.get("SRML_BENCH_ALGO", "")

    if algo and algo != "all":
        print(json.dumps(run_arm(algo, {}, repeats)))
        return

    # cycle mode (the default): headline kmeans first, then every other arm
    # — one captured artifact per claimed multiple (a failing arm records
    # its error and the run carries on)
    results = {}
    for arm in CYCLE_ARMS:
        try:
            results[arm] = run_arm(arm, CYCLE_OVERRIDES.get(arm, {}), repeats)
        except Exception as e:  # noqa: BLE001 — any arm failure is recorded
            results[arm] = {"error": f"{type(e).__name__}: {e}"}
        _release_arm_state()
    headline = dict(results.get("kmeans") or {"error": "headline arm failed"})
    headline["repeats"] = repeats
    headline["arms"] = {a: r for a, r in results.items() if a != "kmeans"}
    # prior-round pointer: the newest BENCH_r*.json present when THIS run
    # started is what this artifact should be diffed against —
    # benchmark/standings.py renders the Δ% regression column from it, so
    # the bench trajectory is itself observable (srml-watch satellite)
    prior = sorted(
        glob.glob(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r*.json"))
    )
    headline["prev_round"] = os.path.basename(prior[-1]) if prior else None
    print(json.dumps(headline))


if __name__ == "__main__":
    main()
