#
# Headline benchmark: KMeans fit throughput, mirroring the reference's
# flagship workload (k=1000, maxIter=30, initMode=random on 1M x 3000
# float32 rows; /root/reference/python/benchmark/databricks/run_benchmark.sh:45-55,
# results in databricks/results/running_times.png: CPU 9526 s, GPU 82 s on
# 2x A10G => ~12,195 rows/s).
#
# Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where value
# is fit rows/sec on this host's devices and vs_baseline is the ratio to the
# reference GPU cluster's 12,195 rows/s.
#
# Row count is scaled to the available memory by default (full 1M x 3000 is
# 12 GB resident before solver workspace); override with env vars
# SRML_BENCH_ROWS / SRML_BENCH_COLS / SRML_BENCH_K / SRML_BENCH_ITERS.
#

import json
import os
import time

import numpy as np

REF_GPU_SECONDS = 82.0  # running_times.png, 2x g5.2xlarge (A10G)
REF_ROWS = 1_000_000
BASELINE_ROWS_PER_SEC = REF_ROWS / REF_GPU_SECONDS


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    default_rows = 400_000 if platform != "cpu" else 20_000
    default_cols = 3000 if platform != "cpu" else 256
    default_k = 1000 if platform != "cpu" else 64
    rows = int(os.environ.get("SRML_BENCH_ROWS", default_rows))
    cols = int(os.environ.get("SRML_BENCH_COLS", default_cols))
    k = int(os.environ.get("SRML_BENCH_K", default_k))
    iters = int(os.environ.get("SRML_BENCH_ITERS", 30))

    from spark_rapids_ml_tpu.ops.kmeans import lloyd_iterations, random_init
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh, shard_rows, data_sharding

    rng = np.random.default_rng(42)
    # blob-ish data so Lloyd doesn't converge degenerately in one step
    centers_true = rng.standard_normal((k, cols)).astype(np.float32) * 3.0
    assign = rng.integers(0, k, size=rows)
    X_host = centers_true[assign] + rng.standard_normal((rows, cols)).astype(np.float32)

    mesh = get_mesh()
    Xs, _ = shard_rows(X_host, mesh)
    w = jax.device_put(np.ones(Xs.shape[0], dtype=np.float32), data_sharding(mesh))
    # Force the host->device transfer to finish before timing fit (through the
    # axon dev tunnel block_until_ready is a no-op and device_put is lazy, so
    # sync via a dependent scalar fetched to host).
    float(np.asarray(Xs.sum()))
    chunk = min(32768, Xs.shape[0])

    def fit():
        c0 = random_init(Xs, w, k, seed=1)
        centers, n_iter, inertia = lloyd_iterations(
            Xs, w, c0, mesh, max_iter=iters, tol=0.0, chunk=chunk
        )
        # np.asarray forces execution + fetch (block_until_ready alone does
        # not synchronize through the tunnel)
        return np.asarray(centers)

    fit()  # compile (cached for the timed run)
    t0 = time.perf_counter()
    fit()
    elapsed = time.perf_counter() - t0

    rows_per_sec = rows / elapsed
    print(
        json.dumps(
            {
                "metric": f"kmeans_fit_throughput_k{k}_d{cols}_iter{iters}",
                "value": round(rows_per_sec, 1),
                "unit": "rows/sec",
                "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
