#!/bin/bash
# CI entry point (counterpart of the reference's ci/test.sh: lint -> unit
# tests -> benchmark smoke on tiny data).
set -ex

cd "$(dirname "$0")/.."

# 1. lint / static checks: byte-compile everything (mypy/black optional in
#    this image), then graftlint — the JAX/TPU invariant checker (R1-R12:
#    hidden host syncs, recompile risk, unbound collective axis names,
#    nondeterministic RNG/set-order, float64 in solver kernels, raw clocks
#    outside srml-scope, unnamed threads, remote-DMA confinement, unbounded
#    waits, raw-socket confinement, lock-order/blocking-under-lock,
#    shared-state write discipline; see docs/graftlint.md).  This is the
#    ONE whole-package gate: R11/R12 need every module parsed together for
#    the package-wide lock graph, and --fail-on-new vs the committed
#    baseline makes any NEW finding a build error while audited debt stays
#    visible as warnings (the per-PR per-module re-runs that used to ride
#    each focused step below are consolidated here — same files, one
#    program, no drift between the module lists and the tree).
python -m compileall -q spark_rapids_ml_tpu benchmark tests bench.py __graft_entry__.py
python -m tools.graftlint spark_rapids_ml_tpu benchmark \
    --baseline ci/graftlint-baseline.json --fail-on-new

# 2. native runtime build
make -C native

# 3. unit tests on the virtual 8-device CPU mesh.  Default budget: the fast
#    suite (heavy multi-process / deep-forest paths are @slow-tagged, like the
#    reference's --runslow gate, conftest.py:96-116).  SRML_CI_FULL=1 adds the
#    full --runslow pass (nightly budget).  Both wall-clocks are printed so the
#    two CI budgets stay measured.
#    --durations keeps the top time sinks visible so the default budget
#    cannot quietly creep (round-4 judge: 338 s -> 492 s unnoticed).
t0=$SECONDS
python -m pytest tests/ -x -q --durations=10
echo "CI budget: default suite took $((SECONDS - t0))s"
if [ "${SRML_CI_FULL:-0}" = "1" ]; then
    t1=$SECONDS
    python -m pytest tests/ -x -q --runslow -m slow
    echo "CI budget: slow-marked remainder took $((SECONDS - t1))s"
    # srml-shield slow gates, re-asserted by name: the 3- and 4-process
    # multicontroller fit + kneighbors parity variants (uneven partitions,
    # one empty rank — rank-indexing bugs cannot hide at nranks=2) and the
    # hardware kNN audit (TPU-gated; skips cleanly on CPU)
    python -m pytest tests/test_multicontroller.py -q --runslow \
        -k "three_plus or multirank"
    python -m pytest tests/test_knn_audit.py -q --runslow
    # srml-wire slow gates by name: the FULL fit matrix rerun on the TCP
    # plane must be BITWISE-equal to the file plane, and the 2-process
    # kneighbors exchange must pass over sockets
    python -m pytest tests/test_multicontroller.py -q --runslow \
        -k "bitwise_equal_across_planes or (kneighbors_across and tcp)"
fi

# 3b. focused gates for the kNN query-engine contracts (cheap; both files
#     also run inside the full suite above — re-asserted here by name so a
#     selective run or marker drift can never silently drop them):
#     - interpret-mode Pallas kNN kernels, incl. the multi-K-block
#       query-resident grid (revisited output dim must be innermost)
#     - precompile executable cache hit/miss: a repeat same-shape search
#       performs ZERO new compilations (profiling counters)
python -m pytest tests/test_pallas.py -q -k knn
python -m pytest tests/test_precompile.py -q

# 3c. focused gates for the sharded UMAP engine (also inside the full suite;
#     re-asserted by name so marker drift can never silently drop them).
#     Runs on the multi-device CPU mesh — conftest injects the 8-device
#     flag, forced explicitly here so a stripped environment still gets it:
#     - mesh-shape parity: fixed seed => same embedding on a 1-device and
#       an 8-device mesh, and k=15 neighbor preservation within 1% of the
#       single-device reference layout
#     - epoch loop issues ceil(n_epochs / SRML_UMAP_EPOCH_BLOCK) dispatches
#       and repeat same-shape fits perform ZERO new compilations
#     - graph assembly stays on device (single-upload transfer counters)
#     (graftlint re-check rides the step-1 whole-package gate.)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_umap_engine.py -q

# 3d. focused gates for the device-resident forest engine (also inside the
#     full suite; re-asserted by name so marker drift can never silently
#     drop them).  Runs on the 8-device CPU mesh, forced explicitly:
#     - mesh parity: fixed seed => IDENTICAL forest (features, thresholds,
#       leaf values) on a 1-device and an 8-device mesh fit
#     - dispatch counting: ceil(levels / SRML_FOREST_LEVEL_BLOCK) engine
#       dispatches, one early-stop flag sync per block, ONE forest fetch
#       (forest.levels.dispatches / forest.level_syncs / forest.d2h_transfers)
#     - zero-recompile repeat fit + repeat transform (precompile counters)
#     - interpret-mode sharded+psum MXU histogram rule vs the numpy oracle
#     (graftlint re-check rides the step-1 whole-package gate.)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_forest_engine.py -q

# 3e. focused gates for the srml-serve subsystem (also inside the full
#     suite; re-asserted by name so marker drift can never silently drop
#     them).  Runs on the 8-device CPU mesh, forced explicitly:
#     - concurrent single-row clients coalesce into >1-request device
#       batches (occupancy histogram + coalesced_batches counters)
#     - steady state after bucket warmup performs ZERO new executable
#       compilations (precompile compile/fallback counters frozen)
#     - overload rejects fast with ServerOverloaded instead of blocking;
#       queued-request deadlines expire with RequestTimeout
#     - registry serves core.load'ed models with transform-equal outputs
#     plus the save->load->transform persistence matrix the registry
#     builds on, and an open-loop bench_serving smoke over two model types
#     (throughput + p50/p95/p99 columns present, steady-state assertion
#     on).  (graftlint re-check rides the step-1 whole-package gate.)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_serving.py tests/test_persistence_matrix.py -q
SERVE_SMOKE=$(mktemp -d)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.bench_serving --models kmeans,linreg --rates 50,200 \
    --duration 1.5 --fit_rows 1024 --num_cols 8 \
    --report_path "$SERVE_SMOKE/serving.jsonl"
test "$(wc -l < "$SERVE_SMOKE/serving.jsonl")" -eq 4
python - "$SERVE_SMOKE/serving.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1])]
assert {r["model"] for r in recs} == {"kmeans", "linreg"}
for r in recs:
    assert r["steady_compiles"] == 0, r
    assert all(k in r for k in ("throughput_rps", "p50_ms", "p95_ms", "p99_ms")), r
EOF
rm -rf "$SERVE_SMOKE"

# 3f. focused gates for the srml-ann IVF-Flat subsystem (also inside the
#     full suite; re-asserted by name so marker drift can never silently
#     drop them).  Runs on the 8-device CPU mesh, forced explicitly:
#     - recall@10 >= 0.95 vs the exact kneighbors path at the documented
#       nprobe on clustered data (the acceptance gate)
#     - BITWISE 1-device-vs-8-device mesh parity of probed results
#       (lexicographic (d2, pos) selection — extends the UMAP/RF matrix)
#     - repeat same-shape probed search performs ZERO new compilations,
#       and the warm path covers the exact dispatch key
#     - the SRML_UMAP_ANN=ivfflat knob keeps k=15 neighbor preservation
#       within the established 1% of the exact-graph layout
#     plus a bench_approximate_nn smoke asserting recall/qps columns +
#     zero steady-state compiles on tiny clustered data.  (graftlint
#     re-check rides the step-1 whole-package gate.)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_ann_engine.py -q
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_umap_engine.py -q -k ann_graph
ANN_SMOKE=$(mktemp -d)
python -m benchmark.gen_data blobs --num_rows 2000 --num_cols 16 --n_clusters 8 \
    --output_dir "$ANN_SMOKE/blobs" --output_num_files 2
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.benchmark_runner approximate_nearest_neighbors \
    --train_path "$ANN_SMOKE/blobs" --k 10 --nlist 8 --nprobe 4 \
    --report_path "$ANN_SMOKE/ann.jsonl"
python - "$ANN_SMOKE/ann.jsonl" <<'EOF'
import json, sys
rec = json.loads(open(sys.argv[1]).readline())
assert rec["recall_at_k"] >= 0.95, rec
assert rec["qps"] > 0 and "speedup_vs_exact" in rec, rec
assert rec["steady_compiles"] == 0, rec
EOF
rm -rf "$ANN_SMOKE"

# 3g. focused gates for srml-scope observability (also inside the full
#     suite; re-asserted by name so marker drift can never silently drop
#     them), then an end-to-end trace/export smoke: a kmeans fit + a
#     serving session run with SRML_TRACE_DIR set, and the emitted files
#     must parse as valid Chrome trace-event JSON with >0 complete ("X")
#     span events; the fit must surface fit_telemetry() on the model; and
#     export_metrics() must round-trip through json.loads with the stable
#     schema (docs/observability.md).
python -m pytest tests/test_profiling.py -q
TRACE_SMOKE=$(mktemp -d)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    SRML_TRACE_DIR="$TRACE_SMOKE/traces" python - "$TRACE_SMOKE/traces" <<'EOF'
import glob, json, sys
import numpy as np
from spark_rapids_ml_tpu import KMeans, profiling
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.serving import ModelRegistry

X = np.random.default_rng(0).standard_normal((512, 16)).astype(np.float32)
model = KMeans(k=4, maxIter=5, seed=1).fit(DataFrame.from_numpy(X))
telem = model.fit_telemetry()
assert telem is not None and telem.phases["srml.fit"]["count"] == 1, telem
with ModelRegistry(max_batch=32, max_wait_ms=2) as reg:
    reg.register("km", model)
    for i in range(8):
        reg.get("km").predict(X[i])
    snap = reg.telemetry()
    assert snap.counters.get("serving.km.requests", 0) >= 8, snap.counters

traces = glob.glob(sys.argv[1] + "/*.trace.json")
tags = {p.rsplit("/", 1)[-1].split("-")[0] for p in traces}
assert {"fit", "serve"} <= tags, traces
for p in traces:
    doc = json.load(open(p))
    complete = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert complete, f"{p}: no complete span events"
    for e in complete:
        assert set(e) >= {"name", "ts", "dur", "pid", "tid", "args"}, e

exported = profiling.export_metrics()
rt = json.loads(json.dumps(exported))
assert rt == exported and rt["schema"] == "srml-scope/v1"
assert "srml_counter{" in profiling.render_prometheus(exported)
print(f"observability smoke OK: {len(traces)} trace file(s), "
      f"{len(exported['counters'])} counters exported")
EOF
rm -rf "$TRACE_SMOKE"

# 3h. focused gates for the srml-watch health plane (also inside the full
#     suite; re-asserted by name so marker drift can never silently drop
#     them), then a serving health smoke:
#     - induced-hang: a fit task blocking one mocked rank produces a
#       watchdog report naming the stalled rank AND its innermost open span
#     - induced-exception: a failing fit dumps a Perfetto-loadable flight
#       recording with the failing span as the final event
#     - overhead: always-on flight recording stays under 2% of a warm
#       kmeans fit
#     - ModelRegistry.health() reports READY with SLO attainment >= 0 and
#       the health/memory gauge families render through export_metrics()/
#       render_prometheus()
#     (graftlint re-check, incl. R7, rides the step-1 whole-package gate.)
python -m pytest tests/test_watch.py -q
python -m pytest tests/test_watch.py -q -k "induced_hang or induced_exception or overhead"
WATCH_SMOKE=$(mktemp -d)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    SRML_TRACE_DIR="$WATCH_SMOKE/traces" SRML_SERVE_SLO_MS=500 python - <<'EOF'
import numpy as np
from spark_rapids_ml_tpu import KMeans, profiling, watch
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.serving import ModelRegistry

X = np.random.default_rng(0).standard_normal((512, 16)).astype(np.float32)
model = KMeans(k=4, maxIter=5, seed=1).fit(DataFrame.from_numpy(X))
telem = model.fit_telemetry()
assert telem is not None and "mem.host" in telem.memory, telem.memory
with ModelRegistry(max_batch=32, max_wait_ms=2) as reg:
    reg.register("km", model)
    for i in range(16):
        reg.get("km").predict(X[i])
    h = reg.health()
    assert h["state"] == "READY", h
    km = h["models"]["km"]
    assert km["attainment"] >= 0 and 0 <= km["burn"] <= 1, km
    m = profiling.export_metrics()
    g = m["gauges"]
    assert g.get("health.km.state_code") == 1.0, g
    assert any(k.startswith("mem.host.") for k in g), g
    txt = profiling.render_prometheus(m)
    assert "# TYPE srml_health gauge" in txt, txt[-500:]
    assert "# TYPE srml_memory_bytes gauge" in txt
assert watch.ring_stats()["events"] > 0
print("watch smoke OK:", km["state"], f"attainment={km['attainment']}")
EOF
rm -rf "$WATCH_SMOKE"

# 3i. focused gates for the kNN exchange + fused epilogue (also inside the
#     full suite; re-asserted here by name so marker drift can never
#     silently drop them).  Runs on the 8-device CPU mesh, forced
#     explicitly:
#     - BITWISE parity matrix: ring-permute exchange == all-gather
#       exchange == single-device reference on 1/2/8-device meshes
#       (lex (d2, pos) total order + fixed-tile scans)
#     - distributed_kneighbors ring route == allgather route == sklearn,
#       including the collective fallback when a rank's items overflow
#     - repeat same-shape ring search performs ZERO new compilations
#     - fused merge epilogue in interpret mode: nb>1 K-block geometry,
#       the lex tie contract vs the numpy oracle, and the forced
#       self-verify fallback through the fused path
#     plus a bench_nearest_neighbors smoke asserting zero new compiles on
#     repeat search and the bytes-moved fields present.  (graftlint
#     re-check, incl. R8, rides the step-1 whole-package gate.)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_knn_exchange.py -q
python -m pytest tests/test_pallas.py -q -k "fused"
KNN_SMOKE=$(mktemp -d)
python -m benchmark.gen_data blobs --num_rows 2000 --num_cols 16 --n_clusters 8 \
    --output_dir "$KNN_SMOKE/blobs" --output_num_files 2
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.benchmark_runner knn \
    --train_path "$KNN_SMOKE/blobs" --k 10 \
    --report_path "$KNN_SMOKE/knn.jsonl"
python - "$KNN_SMOKE/knn.jsonl" <<'EOF'
import json, sys
rec = json.loads(open(sys.argv[1]).readline())
assert rec["repeat_new_compiles"] == 0, rec
# 8-device mesh: the ring exchange must have moved (and counted) bytes
assert rec["exchange_bytes"] > 0, rec
assert any(s.startswith("knn.ring") for s in rec["exchange_sections"]), rec
EOF
rm -rf "$KNN_SMOKE"

# 3j. srml-shield chaos gates (also inside the full suite; re-asserted by
#     name so marker drift can never silently drop them —
#     docs/robustness.md):
#     - CHAOS MATRIX on 3 real OS processes: a rank killed mid-collective
#       (SRML_FAULTS cp.gather action=die) makes every survivor raise
#       RemoteRankError NAMING the dead rank in < 10 s (vs the 300 s round
#       timeout), with clean teardown and no orphan alive/heartbeat files;
#       the orderly-abort variant carries exception type + failing span
#       through the abort marker
#     - unarmed-path overhead: SRML_FAULTS unset adds no measurable work at
#       injection sites (structural gate, test_watch style)
#     - serving recovery: injected worker death and watchdog-confirmed
#       wedge each return the server to READY via supervised restart, with
#       queued/in-flight requests failed by the typed retryable
#       ServerRecovering (never a hang) and ZERO new compiles across the
#       recovery (buckets re-warm from the retained AOT cache)
#     (graftlint re-check, incl. R9, rides the step-1 whole-package gate.)
# the explicit full-file run IS the by-name gate: nothing in it is
# marker-filtered, so no subset re-run is needed (the chaos matrix is the
# most expensive piece of 3j — run it once)
python -m pytest tests/test_faults.py -q
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_serving.py -q \
    -k "shield or worker_death or wedge_then or drain_during or budget or rolls_up"

# 3k. srml-router gates (also inside the full suite; re-asserted by name
#     so marker drift can never silently drop them — docs/serving.md
#     §srml-router):
#     - replica CHAOS: with 2 replicas under a request stream, killing one
#       (SRML_FAULTS serving.dispatch, tag = replica name) produces ZERO
#       client-visible errors — the routed future re-routes the typed
#       retryable failure to the survivor — and the killed replica
#       re-admits warm (zero new compiles, retained AOT cache)
#     - zero-downtime SWAP: rolling router.swap() under load with zero
#       errors and zero new compiles at cut-over; registry swap()
#       persistence semantics (save -> load -> swap -> serve bit-equal,
#       swap-during-drain, incompatible-signature rejection)
#     - depth-2 continuous batching: the serve.<n>.inflight_depth series
#       reaches 2 (assembly overlapped device execution) and the
#       zero-new-compiles steady gate holds per replica
#     - admission/shedding: batch class sheds first at the configured
#       fill ceilings while interactive traffic is still admitted
#     - the srml_router / srml_health exposition round-trip incl.
#       per-replica restart counts
#     plus a bench_serving router smoke asserting the
#     max-sustained-QPS-at-p99-SLO headline per depth, the PAIRED goodput
#     confirm with depth-2 >= depth-1 at the COMMON SUSTAINED offered
#     load (min of the two search maxima) and equal SLO, and a zero-error
#     swap blip.  The paired rate is min, not max: at the stronger arm's
#     maximum the first thing to fail on a 2-core host is the CLIENT
#     pacing thread (late-arrival bursts into an ~8-request queue), which
#     scores scheduler contention, not the pipeline.  The structural
#     depth-2 > depth-1 admission-capacity dominance is gated
#     deterministically by test_router's goodput test (device leg = GIL-
#     releasing sleep); the smoke gates live-XLA parity at the common
#     load with zero sheds/errors plus the zero-new-compiles steady
#     state.  Trials are best-of-3 and interleaved across the depth arms
#     so one machine-weather phase cannot land entirely on one arm.
#     The depth comparison runs at ONE replica: inflight depth is
#     per-replica pipeline machinery, and 2 replicas x depth-2 is 6
#     serving threads — on a 2-core CI box that oversubscription measures
#     context-switching, not the pipeline.  The multi-replica behaviours
#     (chaos re-route, rolling swap) keep their 2-replica gates.
# the explicit full-file run IS the by-name gate (nothing marker-filtered)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_router.py -q
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_persistence_matrix.py -q -k "swap"
ROUTER_SMOKE=$(mktemp -d)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.bench_serving --models kmeans \
    --headline --headline_trials 3 --duration 1 --slo_ms 500 \
    --replicas 1 \
    --fit_rows 8192 --num_cols 512 --max_batch 4096 --rows_per_request 512 \
    --report_path "$ROUTER_SMOKE/router.jsonl"
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.bench_serving --models kmeans \
    --swap_blip --duration 1 --slo_ms 500 \
    --replicas 2 \
    --fit_rows 8192 --num_cols 512 --max_batch 4096 --rows_per_request 512 \
    --swap_rate 30 --report_path "$ROUTER_SMOKE/router.jsonl"
python - "$ROUTER_SMOKE/router.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1])]
head = {r["inflight_depth"]: r for r in recs
        if r.get("metric") == "max_sustained_qps_at_p99_slo"}
assert set(head) == {1, 2}, sorted(head)
for r in head.values():
    assert r["max_sustained_qps"] > 0, r
# the continuous-batching acceptance bar, measured PAIRED (equal offered
# load, equal SLO, seconds apart): depth-2 delivers >= depth-1
paired = [r for r in recs if r.get("metric") == "paired_goodput_at_slo"]
assert paired, recs
gp = paired[0]["goodput_rps"]
assert gp["2"] >= gp["1"] > 0, paired[0]
swap = [r for r in recs if r.get("metric") == "swap_blip"]
assert swap and swap[0]["errors"] == 0, swap          # zero-downtime
assert swap[0]["replica_swaps"] == 2, swap            # every slot rolled
assert swap[0]["completed"] == swap[0]["requests"], swap
EOF
rm -rf "$ROUTER_SMOKE"

# 3l. srml-sweep batched-tuning gates (also inside the full suite;
#     re-asserted by name so marker drift can never silently drop them —
#     docs/tuning_engine.md).  Runs on the 8-device CPU mesh, forced
#     explicitly:
#     - EXACT batched-vs-sequential equality: avgMetrics/stdMetrics/
#       best_index and sub-model coefficients on 1/2/8-device meshes
#       (linreg bitwise; logreg exact metrics + trajectory-tolerance
#       coefficients), incl. the m=1 grid, the k>rows-per-fold edge, and
#       the cluster-side sequential CV vs the local batched sweep
#     - ONE staged dataset per sweep (ingest.staged transfer counter) and
#       ZERO new compiles on a repeat same-shape sweep with different grid
#       values (the candidate-bucket AOT key: lanes are traced, not baked)
#     - kill switch + fallbacks: SRML_SWEEP_BATCH=0, non-lane-batchable
#       grid params, and sparse CSR input all keep the legacy fold loop
#     plus a bench_tuning smoke at the default CI shape asserting the batched
#     route beats the sequential one in candidates/sec on BOTH solver
#     families and repeats with zero new kernel compilations.
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_tuning.py -q -k "batched_sweep or cv_copy"
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_spark_cv.py -q -k "batched"
TUNE_SMOKE=$(mktemp -d)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.bench_tuning --algos linreg,logreg \
    --rows 20000 --cols 64 --num_folds 3 --grid_size 8 --num_runs 1 \
    --report_path "$TUNE_SMOKE/tuning.jsonl"
python - "$TUNE_SMOKE/tuning.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1])]
assert {r["algo"] for r in recs} == {"linreg", "logreg"}, recs
for r in recs:
    assert r["batched_cps"] > r["sequential_cps"], r   # the perf acceptance bar
    assert r["repeat_new_compiles"] == 0, r            # candidate-bucket AOT key
    assert r["phase_times"].get("tuning.sweep.solve", 0) > 0, r
    # cumulative across the arm's warm-up + timed batched sweeps
    assert r["counters"].get("tuning.candidates", 0) >= r["grid_size"], r
EOF
rm -rf "$TUNE_SMOKE"

# 3m. srml-wire gates (also inside the full suite; re-asserted by name so
#     marker drift can never silently drop them — docs/robustness.md §wire):
#     - control-plane CONFORMANCE: one contract module over the file, TCP,
#       and local planes (rank-indexed gathers, binary round-trip, abort
#       marker shape, typed ControlPlaneTimeout naming round + missing
#       ranks, health surface, close idempotence)
#     - the multi-host-style CHAOS MATRIX on real OS processes over real
#       sockets: SIGKILL'd rank, partitioned rank (injected cp.net sticky
#       drop), and killed coordinator each surface as a TYPED error naming
#       the culprit within 2 heartbeat intervals (wall-clock asserted),
#       with zero orphaned sockets/threads/files; a stale-epoch zombie
#       rejoin is fenced (StaleEpochError), never readmitted
#     plus a bench_control_plane smoke asserting
#     the pushed abort beats one 50 ms file-plane poll interval.
#     (graftlint re-check, incl. R10, rides the step-1 whole-package gate.)
#     (SRML_CI_FULL additionally reruns the full multicontroller fit +
#     kneighbors matrix on SRML_CP=tcp with the bitwise cross-plane gate —
#     see the slow-suite block in step 3.)
python -m pytest tests/test_control_plane_contract.py tests/test_netplane.py -q
WIRE_SMOKE=$(mktemp -d)
python -m benchmark.bench_control_plane --planes file,tcp \
    --gather_rounds 60 --abort_trials 3 \
    --report_path "$WIRE_SMOKE/cp.jsonl"
python - "$WIRE_SMOKE/cp.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1])]
abort = {r["plane"]: r for r in recs if r["metric"] == "cp_abort_propagation"}
gather = {r["plane"]: r for r in recs if r["metric"] == "cp_gather_round"}
assert set(abort) == {"file", "tcp"} and set(gather) == {"file", "tcp"}, recs
# THE srml-wire bar: a coordinator-pushed abort must land inside one
# file-plane poll interval (50 ms) — measured ~1-3 ms on localhost
assert abort["tcp"]["max_ms"] < 50.0, abort["tcp"]
assert abort["tcp"]["survivors"] == 2 * abort["tcp"]["trials"], abort["tcp"]
assert gather["tcp"]["p50_ms"] > 0 and gather["file"]["p50_ms"] > 0
assert abort["tcp"]["wire_counters"].get("cp.net.pushed_aborts", 0) > 0
EOF
rm -rf "$WIRE_SMOKE"

# 3n. srml-pq IVF-PQ gates (also inside the full suite; re-asserted by
#     name so marker drift can never silently drop them —
#     docs/ann_engine.md §IVF-PQ).  Runs on the 8-device CPU mesh, forced
#     explicitly:
#     - the ADC LUT-accumulation kernel EXACT vs the numpy oracle in
#       interpret mode (sequential-j accumulation contract, ragged rows,
#       sub-256 table widths)
#     - BITWISE 1-device-vs-8-device parity of probed AND refined ivfpq
#       results (the flat kernel's lex/merge helpers reused verbatim)
#     - refined recall@10 >= 0.9 at the documented defaults on clustered
#       data, and zero-new-compile repeat/warmed searches
#     plus a paired bench_approximate_nn smoke (flat + pq arms on ONE
#     dataset) asserting refined recall@10 >= 0.9, zero new compiles in
#     the timed repeat window, and the compression headline:
#     pq index_bytes_per_item < 1/8 of the flat arm's.  (graftlint
#     re-check rides the step-1 whole-package gate.)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_pq_engine.py -q
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_pq_engine.py -q \
    -k "lut_kernel or mesh_parity or refined_recall or zero_new_compiles"
PQ_SMOKE=$(mktemp -d)
python -m benchmark.gen_data blobs --num_rows 2000 --num_cols 32 --n_clusters 8 \
    --output_dir "$PQ_SMOKE/blobs" --output_num_files 2
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.benchmark_runner approximate_nearest_neighbors \
    --train_path "$PQ_SMOKE/blobs" --k 10 --nlist 8 --nprobe 4 \
    --report_path "$PQ_SMOKE/ann.jsonl"
# pq operating point for the tiny smoke: every list probed + x8 refine
# (raw ADC recall at 2k rows x 32 dims is ~0.54 — the refine recovery is
# exactly what the gate exercises), n_bits=6 so the fixed codebook bytes
# do not swamp the per-item ratio at this tiny item count
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.benchmark_runner approximate_nearest_neighbors \
    --train_path "$PQ_SMOKE/blobs" --k 10 --nlist 8 --nprobe 8 \
    --algorithm ivfpq --pq_m 8 --pq_bits 6 --refine_ratio 8 \
    --report_path "$PQ_SMOKE/ann.jsonl"
python - "$PQ_SMOKE/ann.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1])]
arms = {r.get("algorithm", "ivfflat"): r for r in recs}
assert set(arms) == {"ivfflat", "ivfpq"}, sorted(arms)
pq, flat = arms["ivfpq"], arms["ivfflat"]
assert pq["recall_at_k"] >= 0.9, pq              # refined recall@10
assert "recall_at_k_raw" in pq and pq["qps"] > 0, pq
assert pq["steady_compiles"] == 0, pq            # repeat_new_compiles == 0
# the compression headline, measured on one dataset: pq < flat / 8
ratio = flat["index_bytes_per_item"] / pq["index_bytes_per_item"]
assert ratio >= 8.0, (flat["index_bytes_per_item"], pq["index_bytes_per_item"])
EOF
rm -rf "$PQ_SMOKE"

# 3o. srml-stream gates (also inside the full suite; re-asserted by name
#     so marker drift can never silently drop them — docs/streaming.md):
#     - streamed==batch EQUALITY: partial_fit over chunks vs batch fit on
#       the union — BITWISE for linreg coefficients and sign-canonicalized
#       PCA components on the exact-arithmetic data family, inertia-/
#       accuracy-gated for the online kmeans/logreg approximations,
#       against 1/2/8-device batch meshes
#     - ZERO-COMPILE steady ingest (same-bucket chunks after the first
#       move aot_hit, never precompile.compile)
#     - live IVF mutation: recall@10 >= 0.95 across an add/delete/repack
#       sequence (incl. through serve.ann and a warm-covered overflow
#       repack with zero steady-state compiles)
#     - train-while-serve: StreamingSession.refresh() through the router
#       under concurrent load — zero client-visible errors, zero new
#       compiles at the same-shape cut-over
#     plus a bench_streaming smoke asserting steady ingest with zero new
#     compiles and a zero-error refresh blip.  (graftlint re-check rides
#     the step-1 whole-package gate.)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_streaming.py -q
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_streaming.py -q \
    -k "bitwise_equals_batch or inertia_quality or metric_quality or steady_ingest_zero or add_delete_repack_recall or overflow_repack or served_ann_absorbs or refresh_under_router_load"
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_persistence_matrix.py -q -k "streamed"
STREAM_SMOKE=$(mktemp -d)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.bench_streaming --algos linreg,kmeans \
    --rows 8000 --cols 32 --chunk_rows 1024 --blip_requests 20 \
    --report_path "$STREAM_SMOKE/stream.jsonl"
python - "$STREAM_SMOKE/stream.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1])]
assert {r["algo"] for r in recs} == {"linreg", "kmeans"}, recs
for r in recs:
    assert r["rows_per_sec"] > 0, r
    assert r["repeat_new_compiles"] == 0, r      # zero-compile steady ingest
    assert r["refresh_errors"] == 0, r           # zero-error refresh blip
    assert r["refreshes"] == 2 and r["p99_before_ms"] > 0, r
    assert r["counters"].get("stream.rows", 0) == r["rows"], r
EOF
rm -rf "$STREAM_SMOKE"

# 3p. graftlint-cc gates: the concurrency pass (R11 lock-order, R12
#     shared-state) and its runtime half (also inside the full suite;
#     re-asserted by name so marker drift can never silently drop them):
#     - fixture suites: a crafted lock-order inversion fires both directly
#       nested and through a one-call interprocedural edge, every
#       blocking-op class under a held lock fires, the condition-wait
#       idiom stays exempt, guarded-vs-unguarded shared-state writes
#       separate (incl. the _locked helper convention), stable finding
#       ids survive line shifts, and --fail-on-new gates fresh findings
#       against a v2 baseline
#     - runtime lockdep: a crafted two-thread inversion raises the typed
#       LockOrderViolation carrying both lock names and both stacks; the
#       disabled path hands back raw threading primitives (zero overhead)
#     then the chaos matrix + serving-recovery gates re-run ONCE with the
#     lockdep sanitizer armed (SRML_SANITIZE=lockdep arms ONLY the
#     lock-order validator — debug_nans/transfer-guard stay off so
#     timings hold).  A violation raises out of the acquiring thread, so
#     a green rerun IS the zero-violations assertion — and the runtime
#     half covers the alias/cross-module edges the static pass documents
#     as invisible (docs/graftlint.md#r11).
python -m pytest tests/test_graftlint_concurrency.py tests/test_lockdep.py -q
SRML_SANITIZE=lockdep python -m pytest tests/test_faults.py tests/test_netplane.py -q
SRML_SANITIZE=lockdep XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_serving.py -q \
    -k "shield or worker_death or wedge_then or drain_during or budget or rolls_up"

# 3q. srml-lanes gates (also inside the full suite; re-asserted by name
#     so marker drift can never silently drop them — docs/serving.md
#     §multiplex):
#     - lane engine: pow2 bucket edges (K=1, non-pow2 K), duplicate-lane
#       padding, and the compile-count gate — growing K across a pow2
#       bucket boundary compiles exactly once, zero within a bucket
#     - multiplex: per-tenant outputs bitwise-equal to dedicated servers
#       for every lane-served model family, paging parity with zero new
#       compiles across page-in/eviction churn, per-tenant counters
#     then the fast multiplex smoke: 8 linreg variants on a 2-LANE HBM
#     budget under a mixed-tenant stream — per-tenant outputs must be
#     BITWISE-equal to 8 dedicated servers (integer-exact data) while
#     every variant pages through the 2 resident lanes, with zero
#     steady-state compiles; plus a bench_multiplex --headline smoke
#     (K=1,8 QPS-at-SLO curve + paging record, backend-tagged).
#     (graftlint re-check rides the step-1 whole-package gate.)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_lanes.py tests/test_multiplex.py -q
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_lanes.py tests/test_multiplex.py -q \
    -k "growing_k or bitwise or paging_parity or interleaved or per_tenant"
python - <<'EOF'
import numpy as np
from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.models.linear_regression import LinearRegressionModel
from spark_rapids_ml_tpu.serving import ModelServer, MultiplexServer

rng = np.random.RandomState(0)
D = 8
models = {
    f"m{i}": LinearRegressionModel(
        coef_=rng.randint(-3, 4, size=D).astype(np.float64),
        intercept_=float(i % 3), n_cols=D, dtype="float32",
    )
    for i in range(8)
}
X = rng.randint(-4, 5, size=(6, D)).astype(np.float32)
expected = {}
for mid, m in models.items():
    with ModelServer(f"ci-ded-{mid}", m) as srv:
        expected[mid] = srv.predict(X)["prediction"]
with MultiplexServer("ci_mux", models, resident_lanes=2,
                     max_batch=64, max_wait_ms=5) as mux:
    assert mux.lanes()["n_lanes"] == 2
    before = profiling.counters("precompile.")
    futs = [(mid, mux.submit(X, model_id=mid))
            for _ in range(3) for mid in models]  # mixed-tenant stream
    for mid, f in futs:
        got = f.result(timeout=60)["prediction"]
        assert np.array_equal(got, expected[mid]), mid  # bitwise per tenant
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.fallback", 0) == 0, delta
    snap = mux.lanes()
    assert snap["page_in"] > 0 and snap["evictions"] > 0, snap
    mux.drain()
    mux.assert_steady_state()   # zero steady-state compiles
print("multiplex smoke: 8 tenants on 2 lanes, bitwise parity, "
      f"{snap['page_in']} page-ins, zero new compiles")
EOF
MUX_SMOKE=$(mktemp -d)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.bench_multiplex --headline --ks 1,8 \
    --duration 0.4 --slo_ms 500 --report_path "$MUX_SMOKE/mux.jsonl"
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.bench_multiplex --paging --registered 16 \
    --resident 2 --rate 100 --duration 1 \
    --report_path "$MUX_SMOKE/mux.jsonl"
python - "$MUX_SMOKE/mux.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1])]
heads = [r for r in recs if r["metric"] == "multiplex_max_sustained_qps_at_p99_slo"]
assert {r["k_variants"] for r in heads} == {1, 8}, heads
for r in heads:
    assert r["max_sustained_qps"] > 0 and r["backend"], r
page = [r for r in recs if r["metric"] == "multiplex_paging"]
assert len(page) == 1, recs
p = page[0]
assert p["errors"] == 0 and p["page_ins"] > 0, p
assert 0.0 <= p["lane_hit_rate"] <= 1.0 and p["page_in_p99_ms"] > 0, p
EOF
rm -rf "$MUX_SMOKE"

# 3r. srml-tier capacity gates (also inside the full suite; re-asserted
#     by name so marker drift can never silently drop them —
#     docs/ann_engine.md §OPQ / §4-bit fast-scan / §Tiered residency):
#     - the 4-bit fast-scan LUT kernel EXACT vs the numpy sequential-ADC
#       oracle in interpret mode, pack/unpack round-trip, typed packer
#       rejections (odd m_sub silently falls back to the unpacked route)
#     - OPQ: refined 4-bit+OPQ recall >= the raw 8-bit arm at half M
#       (equal index bytes), rotation orthonormal, reconstruction error
#       never worse than unrotated; persistence round-trips the rotation
#       bit-identically across meshes
#     - tiered residency BITWISE == all-resident, zero new compiles
#       across a cold->warm probe sweep, ann.tier.* counters move;
#       tombstoned ids never resurface from paged-in cold lists
#     - refine_ratio edge semantics (0 -> typed error, 1 = ADC only) and
#       the hot_fraction param surface (validated at fit)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_pq_engine.py tests/test_persistence_matrix.py -q \
    -k "fastscan or opq or tiered or tombstone or refine_ratio_edge or hot_fraction"
# the tiered pager must stay graftlint-clean (R1: per-group result fetch
# is deferred to ONE batched device_get, never a sync inside the loop)
python -m tools.graftlint \
    spark_rapids_ml_tpu/ann/pq.py spark_rapids_ml_tpu/ann/ivfflat.py \
    spark_rapids_ml_tpu/ann/tier.py spark_rapids_ml_tpu/ann/mutable.py \
    spark_rapids_ml_tpu/ops/pallas_pq.py \
    spark_rapids_ml_tpu/models/approximate_nn.py
# paired bench smoke on ONE dataset: the capacity headline measured at
# like-for-like residency (8-bit vs 4-bit+OPQ, both resident), plus a
# tiered arm exercising the pager end-to-end through the estimator
TIER_SMOKE=$(mktemp -d)
python -m benchmark.gen_data blobs --num_rows 2048 --num_cols 32 --n_clusters 16 \
    --output_dir "$TIER_SMOKE/blobs" --output_num_files 2
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.benchmark_runner approximate_nearest_neighbors \
    --train_path "$TIER_SMOKE/blobs" --k 10 --nlist 16 --nprobe 16 \
    --algorithm ivfpq --pq_m 16 --pq_bits 8 --refine_ratio 8 \
    --report_path "$TIER_SMOKE/ann.jsonl"
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.benchmark_runner approximate_nearest_neighbors \
    --train_path "$TIER_SMOKE/blobs" --k 10 --nlist 16 --nprobe 16 \
    --algorithm ivfpq --pq_m 16 --pq_bits 4 --opq --refine_ratio 8 \
    --report_path "$TIER_SMOKE/ann.jsonl"
# tiered arm at nprobe=4: with hot_fraction 0.5 over 16 lists the pager
# actually pages (8 hot pinned, cold lists LRU-cycle through the pool)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.benchmark_runner approximate_nearest_neighbors \
    --train_path "$TIER_SMOKE/blobs" --k 10 --nlist 16 --nprobe 4 \
    --algorithm ivfpq --pq_m 16 --pq_bits 4 --opq --hot_fraction 0.5 \
    --refine_ratio 8 --report_path "$TIER_SMOKE/ann.jsonl"
python - "$TIER_SMOKE/ann.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1])]
assert len(recs) == 3, len(recs)
b8, b4, tiered = recs
assert b8["pq_bits"] == 8 and b4["pq_bits"] == 4 and b4["pq_opq"], recs
assert tiered["hot_fraction"] == 0.5, tiered
for r in recs:
    assert r["recall_at_k"] >= 0.9, r       # refined recall@10, every arm
    assert r["steady_compiles"] == 0, r     # repeat_new_compiles == 0
# THE capacity headline, at like-for-like (all-resident) residency:
# 4-bit+OPQ HBM bytes/item <= 0.6x the 8-bit arm's (measured ~0.46 at
# this geometry: packed codes halve, codebook tables shrink 16x)
assert b4["hbm_bytes_per_item"] <= 0.6 * b8["hbm_bytes_per_item"], \
    (b4["hbm_bytes_per_item"], b8["hbm_bytes_per_item"])
# the tiered arm really paged: cold lists live in host RAM, the LRU
# counters moved, and the estimator surfaced the residency split
tc = tiered["metrics_export"]["counters"]
assert tc.get("ann.tier.hits", 0) > 0 and tc.get("ann.tier.misses", 0) > 0, tc
assert tc.get("ann.tier.page_bytes", 0) > 0, tc
assert tiered["host_bytes_per_item"] > 0, tiered
EOF
rm -rf "$TIER_SMOKE"

# 3s. srml-topo gates: topology-aware hierarchical collectives (also
#     inside the full suite; re-asserted by name so marker drift can
#     never silently drop them — docs/knn_pipeline.md §topology,
#     docs/observability.md §5):
#     - BITWISE parity: hierarchical device collectives (allgather_rows /
#       gather_stack / psum_merge) == flat on contiguous and interleaved
#       group shapes; the kNN ring+gather kernels == the single-device
#       reference on 1/2/8-device meshes across simulated topologies
#       1x8 / 2x4 / 4x2, with and without the SRML_EXCHANGE_TOPO=flat pin
#     - per-link counter split matches the byte model exactly, and on a
#       simulated 2x4 the hierarchical schedule's DCN bytes <=
#       flat DCN / n_hosts (+10% slack) — the headline collapse
#     - TopologyMap is a compile-cache static (flat / hier / pinned key
#       differently; equal-by-value maps key identically) and the hier
#       route performs ZERO new compilations on repeat search
#     - the host-plane ring adopts the same cycle (CRC-agreed) bitwise
#       vs flat, with ici/dcn attribution only under SRML_TOPO
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_topology.py -q
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_topology.py tests/test_router.py -q \
    -k "test_knn_topology_parity_matrix_bitwise \
        or test_hier_collectives_bitwise_match_flat \
        or test_knn_hier_dcn_bytes_bound_on_2x4 \
        or test_hier_route_zero_new_compiles_on_repeat_search \
        or test_slice_meshes_topology_aware_never_straddles_host_group"
# the exchange plane + its consumers must stay graftlint-clean (R8: only
# exchange.py touches the remote-DMA API; R1/R6 on the new topology path)
python -m tools.graftlint \
    spark_rapids_ml_tpu/parallel/topology.py \
    spark_rapids_ml_tpu/parallel/exchange.py \
    spark_rapids_ml_tpu/parallel/mesh.py \
    spark_rapids_ml_tpu/ops/knn.py
# paired bench smoke on ONE dataset: hierarchical 2x4 vs flat-pinned 2x4;
# the DCN collapse and zero steady-state compiles are captured artifacts
TOPO_SMOKE=$(mktemp -d)
python -m benchmark.gen_data blobs --num_rows 2000 --num_cols 16 --n_clusters 8 \
    --output_dir "$TOPO_SMOKE/blobs" --output_num_files 2
XLA_FLAGS="--xla_force_host_platform_device_count=8" SRML_TOPO=2:4 \
    python -m benchmark.benchmark_runner knn \
    --train_path "$TOPO_SMOKE/blobs" --k 10 \
    --report_path "$TOPO_SMOKE/knn_topo.jsonl"
XLA_FLAGS="--xla_force_host_platform_device_count=8" SRML_TOPO=2:4 \
    SRML_EXCHANGE_TOPO=flat \
    python -m benchmark.benchmark_runner knn \
    --train_path "$TOPO_SMOKE/blobs" --k 10 \
    --report_path "$TOPO_SMOKE/knn_topo.jsonl"
python - "$TOPO_SMOKE/knn_topo.jsonl" <<'EOF'
import json, sys
hier, flat = [json.loads(l) for l in open(sys.argv[1])]
assert hier["topology"] == "2x4/hier", hier["topology"]
assert flat["topology"] == "2x4/flat-pinned", flat["topology"]
for r in (hier, flat):
    assert r["repeat_new_compiles"] == 0, r
    assert r["exchange_route"] != "none", r
hd, fd = hier["exchange_link_bytes"]["dcn"], flat["exchange_link_bytes"]["dcn"]
# flat on a multi-group topology accounts everything as DCN; the
# hierarchical schedule must collapse cross-host traffic by >= n_hosts
assert hier["exchange_link_bytes"]["ici"] > 0, hier
assert fd > 0 and hd <= fd / 2 * 1.10, (hd, fd)
EOF
rm -rf "$TOPO_SMOKE"

# 3t. srml-elastic gates (also inside the full suite; re-asserted by name
#     so marker drift can never silently drop them — docs/serving.md
#     §srml-elastic):
#     - the shared-pool invariant: two models on ONE SlicePool can never
#       be handed overlapping devices; group-major carve under
#       SRML_TOPO=2:4 never straddles a host group; exhaustion is the
#       typed retryable CapacityExhausted (never a silent round-robin),
#       and shared single-device leases exist only under the explicit
#       allow_oversubscribe policy
#     - warm scale-up: deploy-at-max / trim / regrow performs ZERO new
#       compiles (AOT cache keys include slice device ids — the bill is
#       paid once at deploy) with predictions bitwise-identical to a
#       fixed-replica comparator throughout
#     - the preemption storm: replicas killed under a zero restart budget
#       (SRML_FAULTS serving.dispatch kills) are re-sliced + re-warmed
#       through Router.replace_replica with zero client-visible errors
#     then the concurrency-sensitive pair re-run ONCE under the lockdep
#     sanitizer (a violation raises out of the acquiring thread, so a
#     green rerun IS the zero-violations assertion), a focused graftlint
#     pass over the elastic plane + the modules this layer touched, and
#     the bench --autoscale step-load smoke asserting the two required
#     zeros: scale_up_new_compiles and storm_client_errors.
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_autoscale.py -q
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_autoscale.py -q \
    -k "shared_pool_keeps_models_disjoint or never_straddles \
        or scale_up_is_warm or preemption_storm \
        or oversubscription_is_typed"
SRML_SANITIZE=lockdep XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_autoscale.py -q \
    -k "concurrent_allocate_release or preemption_storm"
python -m tools.graftlint \
    spark_rapids_ml_tpu/serving/slicepool.py \
    spark_rapids_ml_tpu/serving/autoscale.py \
    spark_rapids_ml_tpu/serving/router.py \
    spark_rapids_ml_tpu/serving/engine.py \
    spark_rapids_ml_tpu/serving/scheduler.py \
    spark_rapids_ml_tpu/parallel/mesh.py
# rows_per_request is sized to the full batch so one replica saturates
# below the paced client's ceiling on the 2-core image (the burst must
# build REAL queue pressure for the signal-driven scale-up to fire)
ELASTIC_SMOKE=$(mktemp -d)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m benchmark.bench_serving --models kmeans --autoscale \
    --duration 1 --fit_rows 4096 --num_cols 16 \
    --rows_per_request 256 --max_batch 256 \
    --report_path "$ELASTIC_SMOKE/elastic.jsonl"
python - "$ELASTIC_SMOKE/elastic.jsonl" <<'EOF'
import json, sys
rec = json.loads(open(sys.argv[1]).readline())
assert rec["metric"] == "autoscale_step_load", rec
# THE srml-elastic bars: warm scale-up (the deploy-at-max discipline) and
# preemption repair with zero client-visible errors
assert rec["scale_up_new_compiles"] == 0, rec
assert rec["storm_client_errors"] == 0 and rec["errors_total"] == 0, rec
assert rec["storm_restored"] and rec["repairs"] >= 1, rec
assert rec["scale_ups"] >= 1, rec   # the burst really forced a scale event
assert max(p["replicas"] for p in rec["replica_trajectory"]) \
    > rec["min_replicas"], rec
EOF
rm -rf "$ELASTIC_SMOKE"

# 4. benchmark smoke on tiny data (reference ci/test.sh:38-45)
SMOKE_DIR=$(mktemp -d)
trap 'rm -rf "$SMOKE_DIR"' EXIT
python -m benchmark.gen_data blobs --num_rows 1000 --num_cols 8 --n_clusters 4 \
    --output_dir "$SMOKE_DIR/blobs" --output_num_files 2
python -m benchmark.gen_data regression --num_rows 1000 --num_cols 8 \
    --output_dir "$SMOKE_DIR/reg" --output_num_files 2
python -m benchmark.benchmark_runner kmeans --train_path "$SMOKE_DIR/blobs" \
    --k 4 --maxIter 5 --report_path "$SMOKE_DIR/report.jsonl"
python -m benchmark.benchmark_runner linear_regression --train_path "$SMOKE_DIR/reg" \
    --report_path "$SMOKE_DIR/report.jsonl"
test "$(wc -l < "$SMOKE_DIR/report.jsonl")" -eq 2

echo "CI OK"
