#
# MXU forest-histogram path tests (ops/forest_hist.py + ops/forest_mxu.py).
# The pallas kernel runs in interpret mode on the CPU test mesh; on TPU the
# same code compiles to fused one-hot MXU matmuls (validated by bench runs).
#
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.forest import (
    bin_features,
    compute_bin_edges,
    forest_predict_kernel,
    grow_forest,
)
from spark_rapids_ml_tpu.ops.forest_hist import (
    _F_BLOCK,
    _ROW_TILE,
    gather_rows_matmul,
    node_histograms,
    node_histograms_reference,
)
from spark_rapids_ml_tpu.ops.forest_mxu import (
    grow_forest_mxu,
    mxu_depth_supported,
)


def test_gather_rows_matmul_exact():
    rng = np.random.default_rng(0)
    N, D, F = 2 * _ROW_TILE, 23, 7
    bins = rng.integers(0, 128, (D, N)).astype(np.int8)
    feats = rng.choice(D, F, replace=False).astype(np.int32)
    sub = np.asarray(
        gather_rows_matmul(
            jnp.asarray(bins), jnp.asarray(feats), f_pad=_F_BLOCK,
            chunk=_ROW_TILE,
        )
    )
    np.testing.assert_array_equal(sub[:F], bins[feats])
    np.testing.assert_array_equal(sub[F:], 0)


def test_node_histograms_matches_oracle():
    rng = np.random.default_rng(1)
    N = 2 * _ROW_TILE
    T, nodes, S, B = 3, 4, 2, 16
    sub = rng.integers(0, B, (_F_BLOCK, N)).astype(np.int8)
    node_rel = rng.integers(0, nodes + 2, (T, N)).astype(np.int32)
    stats = rng.random((T * S, N)).astype(np.float32)
    H = np.asarray(
        node_histograms(
            jnp.asarray(sub), jnp.asarray(node_rel), jnp.asarray(stats),
            t_pack=T, nodes=nodes, s_dim=S, n_bins=B, interpret=True,
        )
    )
    Href = node_histograms_reference(sub, node_rel, stats, T, nodes, S, B)
    # bf16 operands: ~2^-8 relative on sums of thousands of terms
    np.testing.assert_allclose(H, Href, rtol=2e-2, atol=1e-3)


def test_depth_support():
    # shallow phase: 2^l * S <= 128; deep bucketed phase doubles the depth
    # budget (+1): S=2 -> 13, S=3 -> 11, S=8 (8-class) -> 9
    assert mxu_depth_supported(13, 2)
    assert not mxu_depth_supported(14, 2)
    assert mxu_depth_supported(11, 3)
    assert not mxu_depth_supported(12, 3)
    assert mxu_depth_supported(9, 8)
    assert not mxu_depth_supported(10, 8)


@pytest.mark.parametrize(
    "kind,tiles",
    [
        pytest.param("regression", 2, marks=pytest.mark.slow),
        ("gini", 1),
        # cross-row-tile accumulation is a distinct failure mode: keep an
        # equivalence (not just quality) check spanning two tiles, slow-
        # tagged since the single-tile default already gates the rest
        pytest.param("gini", 2, marks=pytest.mark.slow),
    ],
)
def test_mxu_builder_matches_scatter_builder(kind, tiles):
    """No bootstrap + all features: both builders are deterministic on the
    same binned data and must grow IDENTICAL trees."""
    rng = np.random.default_rng(2)
    N, D, B, T, depth = tiles * _ROW_TILE, 8, 8, 2, 4
    X = rng.standard_normal((N, D)).astype(np.float32)
    y = (X @ rng.standard_normal(D) + 0.2 * rng.standard_normal(N)).astype(
        np.float32
    )
    y_cls = (y > 0).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = np.asarray(bin_features(jnp.asarray(X), jnp.asarray(edges)))
    bins_fm = Xb.T.astype(np.int8)
    w_trees = np.ones((T, N), np.float32)

    if kind == "regression":
        base = np.stack([np.ones(N, np.float32), y])
        stats3 = np.stack([np.ones(N, np.float32), y, y * y])
        st_old = jnp.stack(
            [jnp.ones(N), jnp.asarray(y), jnp.asarray(y) ** 2], axis=1
        )
    else:
        base = np.stack([(y_cls == 0), (y_cls == 1)]).astype(np.float32)
        stats3 = None
        st_old = jnp.asarray(base.T)

    f, t, v, ns, imp = grow_forest_mxu(
        jnp.asarray(bins_fm), jnp.asarray(base), jnp.asarray(w_trees),
        None if stats3 is None else jnp.asarray(stats3),
        edges, max_depth=depth, n_bins=B, kind=kind, max_features=D,
        min_samples_leaf=1.0, min_impurity_decrease=0.0, seed=7,
        interpret=True,
    )
    stats_t = jnp.broadcast_to(st_old[None], (T, N, st_old.shape[1]))
    f2, t2, v2, ns2, imp2 = grow_forest(
        jnp.asarray(Xb), stats_t, edges, max_depth=depth, n_bins=B,
        kind=kind, max_features=D, min_samples_leaf=1.0,
        min_impurity_decrease=0.0, seed=7,
    )
    # bf16 histogram rounding can flip near-tie splits on small samples (and
    # one flipped ancestor rewrites its whole subtree), so demand
    # near-identical structure plus matching predictions rather than exact
    # node-for-node equality — a 4096-row development check matched 100%
    f2_h = np.asarray(f2)
    assert (f == f2_h).mean() > 0.9, (f == f2_h).mean()
    # a flipped near-tie reroutes whole subtrees, so rows near the boundary
    # legitimately get different leaves; model QUALITY must agree
    p1 = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f), jnp.asarray(t), jnp.asarray(v),
            max_depth=depth,
        )
    )
    p2 = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f2), jnp.asarray(t2),
            jnp.asarray(v2), max_depth=depth,
        )
    )
    if kind == "regression":
        e1 = ((p1[:, 0] - y) ** 2).mean() / y.var()
        e2 = ((p2[:, 0] - y) ** 2).mean() / y.var()
    else:
        e1 = (p1.argmax(1) != y_cls).mean()
        e2 = (p2.argmax(1) != y_cls).mean()
    assert abs(e1 - e2) < 0.02, (e1, e2)


@pytest.mark.slow
def test_mxu_builder_feature_subsets_and_bootstrap_quality():
    """With max_features < D and Poisson bootstrap the forests can't be
    compared structurally; check learning quality instead."""
    rng = np.random.default_rng(3)
    N, D, B, T, depth = 2 * _ROW_TILE, 12, 32, 8, 5
    X = rng.standard_normal((N, D)).astype(np.float32)
    y = (2.0 * X[:, 0] - X[:, 3]).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = np.asarray(bin_features(jnp.asarray(X), jnp.asarray(edges)))
    bins_fm = Xb.T.astype(np.int8)
    w_trees = np.random.default_rng(4).poisson(
        1.0, (T, N)
    ).astype(np.float32)
    base = np.stack([np.ones(N, np.float32), y])
    stats3 = np.stack([np.ones(N, np.float32), y, y * y])
    f, t, v, ns, imp = grow_forest_mxu(
        jnp.asarray(bins_fm), jnp.asarray(base), jnp.asarray(w_trees),
        jnp.asarray(stats3), edges, max_depth=depth, n_bins=B,
        kind="regression", max_features=6, min_samples_leaf=1.0,
        min_impurity_decrease=0.0, seed=11, interpret=True,
    )
    pred = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f), jnp.asarray(t), jnp.asarray(v),
            max_depth=depth,
        )
    )[:, 0]
    r2 = 1.0 - ((pred - y) ** 2).mean() / y.var()
    assert r2 > 0.75, r2


@pytest.mark.slow
def test_mxu_deep_phase_smoke_fast():
    """Classification deep-phase gate: 4 classes shrink the slot budget
    (l_s=4), so depth 6 already exercises the bucket sort, the class
    layout and the clamped chunk windows.  Slow-tagged: the REGRESSION
    smoke below stays in default CI (the round-4 advisor's requirement)
    and covers the identical deep machinery; this one rides --runslow
    with the depth-9+ equivalence sweeps."""
    rng = np.random.default_rng(11)
    N, D, B, T, depth, C = _ROW_TILE, 8, 8, 2, 6, 4
    X = rng.standard_normal((N, D)).astype(np.float32)
    logits = X @ rng.standard_normal((D, C))
    y = logits.argmax(1).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = np.asarray(bin_features(jnp.asarray(X), jnp.asarray(edges)))
    bins_fm = Xb.T.astype(np.int8)
    w_trees = np.ones((T, N), np.float32)
    base = np.stack([(y == c) for c in range(C)]).astype(np.float32)

    f, t, v, ns, imp = grow_forest_mxu(
        jnp.asarray(bins_fm), jnp.asarray(base), jnp.asarray(w_trees), None,
        edges, max_depth=depth, n_bins=B, kind="gini", max_features=D,
        min_samples_leaf=1.0, min_impurity_decrease=0.0, seed=3,
        y_vals=jnp.asarray(y), interpret=True,
    )
    stats_t = jnp.broadcast_to(jnp.asarray(base.T)[None], (T, N, C))
    f2, t2, v2, _, _ = grow_forest(
        jnp.asarray(Xb), stats_t, edges, max_depth=depth, n_bins=B,
        kind="gini", max_features=D, min_samples_leaf=1.0,
        min_impurity_decrease=0.0, seed=3,
    )
    f2_h = np.asarray(f2)
    # shallow levels must agree exactly; deep levels tolerate bf16 tie flips
    shallow = slice(0, 2**5 - 1)
    assert (f[:, shallow] == f2_h[:, shallow]).mean() > 0.97
    assert (f == f2_h).mean() > 0.85, (f == f2_h).mean()
    p1 = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f), jnp.asarray(t), jnp.asarray(v),
            max_depth=depth,
        )
    )
    p2 = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f2), jnp.asarray(t2),
            jnp.asarray(v2), max_depth=depth,
        )
    )
    a1 = (p1.argmax(1) == y).mean()
    a2 = (p2.argmax(1) == y).mean()
    assert abs(a1 - a2) < 0.03, (a1, a2)


def test_mxu_deep_phase_smoke_fast_regression():
    """Regression-kind deep-phase gate for default CI (round-3 advice): the
    stats3 plumbing (tot3 rows, base=stat_rows[:2]) through the fused
    shallow/deep steps previously ran only behind --runslow, so a
    regression-kind breakage would merge green.  S=2 stat rows -> l_s=6,
    so depth 7 crosses into the bucketed deep phase."""
    rng = np.random.default_rng(12)
    # B=4 halves the interpreter-mode histogram width — this is the
    # single biggest default-CI cost; the deep machinery it gates is
    # bin-count-invariant
    N, D, B, T, depth = _ROW_TILE, 8, 4, 2, 7
    X = rng.standard_normal((N, D)).astype(np.float32)
    y = (
        X @ rng.standard_normal(D) + 0.1 * rng.standard_normal(N)
    ).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = np.asarray(bin_features(jnp.asarray(X), jnp.asarray(edges)))
    bins_fm = Xb.T.astype(np.int8)
    w_trees = np.ones((T, N), np.float32)
    base = np.stack([np.ones(N, np.float32), y])
    stats3 = np.stack([np.ones(N, np.float32), y, y * y])

    f, t, v, ns, imp = grow_forest_mxu(
        jnp.asarray(bins_fm), jnp.asarray(base), jnp.asarray(w_trees),
        jnp.asarray(stats3), edges, max_depth=depth, n_bins=B,
        kind="regression", max_features=D, min_samples_leaf=1.0,
        min_impurity_decrease=0.0, seed=3, y_vals=jnp.asarray(y),
        interpret=True,
    )
    st_old = jnp.stack(
        [jnp.ones(N), jnp.asarray(y), jnp.asarray(y) ** 2], axis=1
    )
    stats_t = jnp.broadcast_to(st_old[None], (T, N, 3))
    f2, t2, v2, _, _ = grow_forest(
        jnp.asarray(Xb), stats_t, edges, max_depth=depth, n_bins=B,
        kind="regression", max_features=D, min_samples_leaf=1.0,
        min_impurity_decrease=0.0, seed=3,
    )
    f2_h = np.asarray(f2)
    # shallow levels must agree exactly; deep levels tolerate bf16 tie flips
    shallow = slice(0, 2**5 - 1)
    assert (f[:, shallow] == f2_h[:, shallow]).mean() > 0.97
    assert (f == f2_h).mean() > 0.85, (f == f2_h).mean()
    p1 = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f), jnp.asarray(t), jnp.asarray(v),
            max_depth=depth,
        )
    )[:, 0]
    p2 = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f2), jnp.asarray(t2),
            jnp.asarray(v2), max_depth=depth,
        )
    )[:, 0]
    e1 = ((p1 - y) ** 2).mean() / y.var()
    e2 = ((p2 - y) ** 2).mean() / y.var()
    assert abs(e1 - e2) < 0.03, (e1, e2)


@pytest.mark.slow
def test_mxu_deep_phase_matches_scatter_builder():
    """Depth past the slot budget triggers the bucket-sort deep phase;
    tree structure and quality must track the scatter builder."""
    rng = np.random.default_rng(5)
    N, D, B, T, depth = 2 * _ROW_TILE, 10, 16, 2, 9  # l_s=6 -> deep at 7+
    X = rng.standard_normal((N, D)).astype(np.float32)
    y = (
        X @ rng.standard_normal(D) + 0.3 * rng.standard_normal(N) > 0
    ).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = np.asarray(bin_features(jnp.asarray(X), jnp.asarray(edges)))
    bins_fm = Xb.T.astype(np.int8)
    w_trees = np.ones((T, N), np.float32)
    base = np.stack([(y == 0), (y == 1)]).astype(np.float32)

    f, t, v, ns, imp = grow_forest_mxu(
        jnp.asarray(bins_fm), jnp.asarray(base), jnp.asarray(w_trees), None,
        edges, max_depth=depth, n_bins=B, kind="gini", max_features=D,
        min_samples_leaf=1.0, min_impurity_decrease=0.0, seed=7,
        y_vals=jnp.asarray(y), interpret=True,
    )
    st_old = jnp.asarray(base.T)
    stats_t = jnp.broadcast_to(st_old[None], (T, N, 2))
    f2, t2, v2, ns2, imp2 = grow_forest(
        jnp.asarray(Xb), stats_t, edges, max_depth=depth, n_bins=B,
        kind="gini", max_features=D, min_samples_leaf=1.0,
        min_impurity_decrease=0.0, seed=7,
    )
    f2_h = np.asarray(f2)
    # shallow levels must agree exactly; deep levels tolerate bf16 tie flips
    shallow = slice(0, 2**5 - 1)
    assert (f[:, shallow] == f2_h[:, shallow]).mean() > 0.97
    assert (f == f2_h).mean() > 0.85, (f == f2_h).mean()
    p1 = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f), jnp.asarray(t), jnp.asarray(v),
            max_depth=depth,
        )
    )
    p2 = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f2), jnp.asarray(t2),
            jnp.asarray(v2), max_depth=depth,
        )
    )
    a1 = (p1.argmax(1) == y).mean()
    a2 = (p2.argmax(1) == y).mean()
    assert abs(a1 - a2) < 0.02, (a1, a2)


@pytest.mark.slow
def test_mxu_deep_phase_skewed_trees():
    """Heavily skewed label distribution concentrates rows in few deep
    buckets — the size-class layout must stay data-proportional and match
    the scatter builder's quality (the round-1 equal-cap layout bailed out
    on this shape)."""
    rng = np.random.default_rng(5)
    N, D, B, T, depth = 2 * _ROW_TILE, 10, 16, 2, 9
    X = rng.standard_normal((N, D)).astype(np.float32)
    # skew: 95% of rows in one tight blob -> one bucket holds most rows
    blob = rng.random(N) < 0.95
    X[blob] *= 0.05
    y = (
        X @ rng.standard_normal(D) + 0.1 * rng.standard_normal(N) > 0
    ).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = np.asarray(bin_features(jnp.asarray(X), jnp.asarray(edges)))
    bins_fm = Xb.T.astype(np.int8)
    w_trees = np.ones((T, N), np.float32)
    base = np.stack([(y == 0), (y == 1)]).astype(np.float32)

    f, t, v, ns, imp = grow_forest_mxu(
        jnp.asarray(bins_fm), jnp.asarray(base), jnp.asarray(w_trees), None,
        edges, max_depth=depth, n_bins=B, kind="gini", max_features=D,
        min_samples_leaf=1.0, min_impurity_decrease=0.0, seed=7,
        y_vals=jnp.asarray(y), interpret=True,
    )
    p1 = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f), jnp.asarray(t), jnp.asarray(v),
            max_depth=depth,
        )
    )
    acc = (p1.argmax(1) == y).mean()
    # the 0.05-scale blob leaves a thin margin vs the 0.1 label noise, so
    # ~0.88-0.92 train accuracy is what any builder reaches here
    assert acc > 0.85, acc
    assert np.isfinite(np.asarray(imp)).all()


@pytest.mark.slow
def test_mxu_deep_phase_three_classes():
    """s_dim=3: deep slots are 3 per node — non-power-of-two slot packing
    through the size-class deep phase (and the generic stat axis of the
    bucketed node totals)."""
    rng = np.random.default_rng(9)
    N, D, B, T, depth = 2 * _ROW_TILE, 8, 16, 2, 7  # l_s=5 for s_dim=3
    X = rng.standard_normal((N, D)).astype(np.float32)
    logits = X @ rng.standard_normal((D, 3))
    y = logits.argmax(1).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = np.asarray(bin_features(jnp.asarray(X), jnp.asarray(edges)))
    bins_fm = Xb.T.astype(np.int8)
    w_trees = np.ones((T, N), np.float32)
    base = np.stack([(y == c) for c in range(3)]).astype(np.float32)

    f, t, v, ns, imp = grow_forest_mxu(
        jnp.asarray(bins_fm), jnp.asarray(base), jnp.asarray(w_trees), None,
        edges, max_depth=depth, n_bins=B, kind="gini", max_features=D,
        min_samples_leaf=1.0, min_impurity_decrease=0.0, seed=7,
        y_vals=jnp.asarray(y), interpret=True,
    )
    p = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f), jnp.asarray(t), jnp.asarray(v),
            max_depth=depth,
        )
    )
    acc = (p.argmax(1) == y).mean()
    assert acc > 0.85, acc
    assert np.isfinite(np.asarray(imp)).all()


@pytest.mark.slow
def test_mxu_deep_phase_mostly_dead_rows():
    """60% of rows sit in a pure node that leafs at a shallow level, so
    thousands of DEAD rows reach the deep phase — the sorted-layout width
    must account for them (they occupy columns past every bucket), not just
    live + filler rows."""
    rng = np.random.default_rng(13)
    N, D, B, T, depth = 2 * _ROW_TILE, 6, 16, 2, 9
    X = rng.standard_normal((N, D)).astype(np.float32)
    dead = rng.random(N) < 0.6
    X[dead] = 5.0  # one identical (pure) blob far from the rest
    y = np.where(
        dead, 1.0, (X @ rng.standard_normal(D) > 0).astype(np.float64)
    ).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = np.asarray(bin_features(jnp.asarray(X), jnp.asarray(edges)))
    bins_fm = Xb.T.astype(np.int8)
    w_trees = np.ones((T, N), np.float32)
    base = np.stack([(y == 0), (y == 1)]).astype(np.float32)

    f, t, v, ns, imp = grow_forest_mxu(
        jnp.asarray(bins_fm), jnp.asarray(base), jnp.asarray(w_trees), None,
        edges, max_depth=depth, n_bins=B, kind="gini", max_features=D,
        min_samples_leaf=1.0, min_impurity_decrease=0.0, seed=3,
        y_vals=jnp.asarray(y), interpret=True,
    )
    p = np.asarray(
        forest_predict_kernel(
            jnp.asarray(X), jnp.asarray(f), jnp.asarray(t), jnp.asarray(v),
            max_depth=depth,
        )
    )
    # the pure blob must be perfectly classified; the rest reasonably
    assert (p.argmax(1)[dead] == 1.0).all()
    assert (p.argmax(1) == y).mean() > 0.9
