# Hardware kNN audit promoted into the slow suite (ISSUE 10 satellite,
# VERDICT next #8): the float64 ground-truth check that caught the round-5
# excess-precision regression now runs on every TPU hardware CI pass
# (ci/test.sh SRML_CI_FULL) instead of only when someone remembers to run
# benchmark/audit_knn.py by hand.  Capability-probed: on CPU backends the
# audit targets Mosaic/XLA *hardware* lowering differences the virtual mesh
# cannot exhibit, so it skips cleanly.
import os
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _tpu_backend() -> bool:
    import jax

    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend init failure = no TPU
        return False


def test_hardware_knn_audit_against_f64_ground_truth():
    """Both adaptive-kNN verification routes (pool-resident self-verify and
    the SRML_KNN_AUDIT_COUNT bitwise count pair) must agree with float64
    brute force to > 0.999 top-k set agreement on real hardware.  Shape is
    a scaled-down version of the CLI default (the CLI remains the
    full-size manual audit)."""
    if not _tpu_backend():
        pytest.skip(
            "hardware kNN audit requires a TPU backend (Mosaic/XLA "
            "hardware lowering is what it audits); CPU mesh skips cleanly"
        )
    from benchmark.audit_knn import run_audit

    res = run_audit(n_items=50_000, d=512, k=64, qn=2048, sample_stride=256)
    assert res["ok"], (
        "adaptive kNN verification disagrees with f64 ground truth on "
        f"hardware: {res}"
    )
    # the audit count pair is the bitwise route: mismatches mean the two
    # verification strategies disagree with EACH OTHER, which is a bug
    # even when both happen to clear the agreement bar
    assert res["audit_count_mismatches"] == 0, res
