#
# Native host-runtime tests (role of the reference's native-layer tests,
# jvm/src/test PCASuite checking JNI cov/SVD vs Spark): every wrapper is
# checked against its numpy fallback so native and fallback paths cannot
# drift. Skipped (except fallback tests) when the library isn't built; CI
# builds it via `make -C native`.
#

import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_rapids_ml_tpu import native  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    if not native.available():
        # try to build once; skip module if no toolchain
        try:
            subprocess.run(
                ["make", "-C", os.path.join(REPO, "native")],
                check=True,
                capture_output=True,
                timeout=300,
            )
        except Exception:
            pytest.skip("native toolchain unavailable")
        # force re-discovery after the build
        native._lib_tried = False
        native._lib = None
    if not native.available():
        pytest.skip("libsrml_native.so not built")
    yield


def test_version_and_threads():
    assert native.version() == "0.1.0"
    assert native.lib().srml_hardware_threads() >= 1


def test_allocator_reuses_buffers():
    l = native.lib()
    p1 = l.srml_buf_alloc(1 << 20)
    assert p1
    l.srml_buf_free(p1)
    cached = l.srml_buf_cached_bytes()
    assert cached >= (1 << 20)
    p2 = l.srml_buf_alloc(1 << 20)
    assert p2 == p1  # bucket reuse
    l.srml_buf_free(p2)
    l.srml_buf_trim()
    assert l.srml_buf_cached_bytes() == 0


def test_allocator_big_blocks_bypass_pool():
    l = native.lib()
    l.srml_buf_trim()
    big = (64 << 20) + 1  # just over the pooling ceiling
    p = l.srml_buf_alloc(big)
    assert p
    l.srml_buf_free(p)
    # big blocks are returned to the OS, never cached
    assert l.srml_buf_cached_bytes() == 0


@pytest.mark.parametrize(
    "src_dtype,dst_dtype",
    [(np.float32, np.float32), (np.float64, np.float32), (np.float64, np.float64)],
)
def test_concat_matches_numpy(src_dtype, dst_dtype):
    rng = np.random.default_rng(0)
    parts = [
        np.ascontiguousarray(rng.standard_normal((n, 7)).astype(src_dtype))
        for n in (3, 0, 11, 5)
    ]
    got = native.concat_rows(parts, np.dtype(dst_dtype))
    want = np.concatenate(parts).astype(dst_dtype)
    assert got.dtype == dst_dtype and got.flags.c_contiguous
    np.testing.assert_array_equal(got, want)


def test_concat_fallback_mixed_dtypes():
    parts = [np.zeros((2, 3), dtype=np.float32), np.ones((2, 3), dtype=np.float64)]
    got = native.concat_rows(parts, np.dtype(np.float32))
    assert got.shape == (4, 3)


def test_load_csv(tmp_path):
    rng = np.random.default_rng(1)
    want = rng.standard_normal((50, 6)).astype(np.float32)
    path = tmp_path / "data.csv"
    np.savetxt(path, want, delimiter=",", header="a,b,c,d,e,f")
    got = native.load_csv(str(path), 50, 6, skip_rows=1)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_csv_count_rows(tmp_path):
    path = tmp_path / "count.csv"
    path.write_text("h\n1\n2\n3")  # unterminated last line counts
    assert native.csv_count_rows(str(path)) == 4
    got = native.load_csv(str(path), None, 1, skip_rows=1)
    np.testing.assert_allclose(got[:, 0], [1.0, 2.0, 3.0])


def test_load_csv_rejects_short_rows(tmp_path):
    path = tmp_path / "bad.csv"
    path.write_text("1.0,2.0,3.0\n4.0,5.0\n6.0,7.0,8.0\n")
    with pytest.raises(RuntimeError):
        native.load_csv(str(path), 3, 3)


@pytest.mark.slow
def test_out_of_core_knn_matches_in_core():
    from spark_rapids_ml_tpu.ops.knn import knn_search, knn_search_out_of_core
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    rng = np.random.default_rng(7)
    items = rng.standard_normal((600, 8)).astype(np.float32)
    ids = np.arange(600, dtype=np.int64) * 10  # non-trivial user ids
    queries = rng.standard_normal((37, 8)).astype(np.float32)
    mesh = get_mesh()
    d_full, i_full = knn_search(items, ids, queries, 5, mesh)
    d_ooc, i_ooc = knn_search_out_of_core(items, ids, queries, 5, mesh, item_block=256)
    np.testing.assert_allclose(d_ooc, d_full, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(i_ooc, i_full)
    # item_block < k: blocks return fewer than k candidates each, but the
    # merged result must still produce all k true neighbors
    d_tiny, i_tiny = knn_search_out_of_core(items, ids, queries, 16, mesh, item_block=8)
    d_want, i_want = knn_search(items, ids, queries, 16, mesh)
    assert d_tiny.shape == (37, 16)
    np.testing.assert_allclose(d_tiny, d_want, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(i_tiny, i_want)


def test_covariance_matches_numpy():
    rng = np.random.default_rng(2)
    X = rng.standard_normal((500, 12)) * rng.uniform(0.5, 3.0, 12) + 5.0
    cov, mean = native.covariance(X)
    np.testing.assert_allclose(mean, X.mean(axis=0), rtol=1e-12)
    np.testing.assert_allclose(cov, np.cov(X, rowvar=False), rtol=1e-10)


def test_eigh_jacobi_matches_numpy():
    rng = np.random.default_rng(3)
    M = rng.standard_normal((16, 16))
    A = M @ M.T
    evals, comps = native.eigh_descending(A)
    w_np = np.sort(np.linalg.eigvalsh(A))[::-1]
    np.testing.assert_allclose(evals, w_np, rtol=1e-8)
    # eigen-equation holds and signs are deterministic
    for i in range(16):
        np.testing.assert_allclose(A @ comps[i], evals[i] * comps[i], atol=1e-7)
        assert comps[i, np.argmax(np.abs(comps[i]))] > 0
    # orthonormal
    np.testing.assert_allclose(comps @ comps.T, np.eye(16), atol=1e-9)


def test_topk_select_matches_numpy():
    rng = np.random.default_rng(4)
    tile = rng.standard_normal((40, 100)).astype(np.float32)
    d, i = native.topk_select(tile, 5, id_base=1000)
    want = np.sort(tile, axis=1)[:, :5]
    np.testing.assert_allclose(d, want, rtol=1e-6)
    np.testing.assert_array_equal(np.take_along_axis(tile, i - 1000, axis=1), d)
    assert (np.diff(d, axis=1) >= 0).all()


def test_topk_merge():
    rng = np.random.default_rng(5)
    a = np.sort(rng.standard_normal((30, 8)).astype(np.float32), axis=1)
    b = np.sort(rng.standard_normal((30, 8)).astype(np.float32), axis=1)
    ia = np.arange(8)[None, :].repeat(30, 0).astype(np.int64)
    ib = ia + 100
    d, i = native.topk_merge(a, ia, b, ib)
    want = np.sort(np.concatenate([a, b], axis=1), axis=1)[:, :8]
    np.testing.assert_allclose(d, want, rtol=1e-6)
    assert ((i < 8) | (i >= 100)).all()


def test_wide_pca_host_eigh_route_matches_device_route():
    """PCA beyond HOST_EIGH_MIN_D columns routes eigh through the host native
    runtime; both routes must agree."""
    import pandas as pd

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.dataframe import DataFrame
    from spark_rapids_ml_tpu.ops import linalg

    assert linalg.HOST_EIGH_MIN_D <= 150
    rng = np.random.default_rng(8)
    X = (rng.standard_normal((400, 150)) @ rng.standard_normal((150, 150))).astype(
        np.float32
    )
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=4)
    model = PCA(k=5).setInputCol("features").fit(df)  # host-eigh route (d>=128)
    from sklearn.decomposition import PCA as SkPCA

    sk = SkPCA(n_components=5).fit(X.astype(np.float64))
    np.testing.assert_allclose(
        model.explained_variance_ratio_, sk.explained_variance_ratio_, rtol=1e-2
    )
    for i in range(5):
        dot = abs(np.dot(model.components_[i], sk.components_[i]))
        assert dot > 0.99


def test_pca_via_native_matches_sklearn():
    """End-to-end: native cov + eigh reproduces sklearn PCA components (the
    reference's JNI PCA fit path, RapidsRowMatrix.scala:59-89)."""
    from sklearn.decomposition import PCA as SkPCA

    rng = np.random.default_rng(6)
    X = rng.standard_normal((300, 10)) @ rng.standard_normal((10, 10))
    cov, mean = native.covariance(X)
    evals, comps = native.eigh_descending(cov)
    sk = SkPCA(n_components=3).fit(X)
    for i in range(3):
        np.testing.assert_allclose(evals[i], sk.explained_variance_[i], rtol=1e-8)
        dot = abs(np.dot(comps[i], sk.components_[i]))
        np.testing.assert_allclose(dot, 1.0, atol=1e-8)
