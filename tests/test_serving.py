# srml-serve gates (docs/serving.md): dynamic micro-batching, bucket-warmed
# executables (steady state = zero new compiles), admission control with
# fast ServerOverloaded rejection, per-request deadlines, clean drain, the
# registry's load path over core persistence, and serving-vs-transform
# output equivalence for every served model class.
#
# Counter-based assertions follow the PR2-4 idiom: profiling counters and
# duration percentiles, never wall-clock thresholds.
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.serving import (
    ModelRegistry,
    ModelServer,
    RequestTimeout,
    ServerOverloaded,
    ServingEntry,
    bucket_rows,
    serve_buckets,
)

SERVED_ARMS = ["kmeans", "pca", "linreg", "logreg", "rf_clf", "rf_reg"]


# -- a controllable fake model for policy tests ------------------------------


class _EchoModel:
    """Servable stub: echoes row sums; optional per-dispatch delay lets the
    policy tests hold the worker busy to build a backlog deterministically."""

    def __init__(self, n_cols=4, delay_s=0.0):
        self.n_cols = n_cols
        self.delay_s = delay_s
        self.calls = []

    def _serving_entry(self, mesh=None):
        def call(batch):
            if self.delay_s:
                time.sleep(self.delay_s)
            self.calls.append(batch.shape[0])
            return {"echo": batch.sum(axis=1)}

        return ServingEntry(
            name="serve.echo",
            n_cols=self.n_cols,
            dtype=np.dtype(np.float32),
            out_cols=["echo"],
            call=call,
            warm=lambda buckets: [],
        )


# -- bucket rules -------------------------------------------------------------


def test_bucket_rules():
    assert bucket_rows(1, 256) == 16  # SRML_SERVE_MIN_BUCKET default
    assert bucket_rows(17, 256) == 32
    assert bucket_rows(256, 256) == 256
    assert bucket_rows(300, 256) == 256  # clamped to the max-batch bucket
    assert serve_buckets(256) == [16, 32, 64, 128, 256]
    assert serve_buckets(100) == [16, 32, 64, 128]
    assert serve_buckets(8) == [16]


def test_submit_validation():
    srv = ModelServer("echo_val", _EchoModel(), max_batch=8, max_wait_ms=1)
    try:
        with pytest.raises(ValueError, match="features must be"):
            srv.submit(np.zeros((2, 3), np.float32))  # wrong width
        with pytest.raises(ValueError, match="empty request"):
            srv.submit(np.zeros((0, 4), np.float32))
        with pytest.raises(ValueError, match="exceeds max_batch"):
            srv.submit(np.zeros((9, 4), np.float32))
    finally:
        srv.shutdown()


# -- batching policy ----------------------------------------------------------


def test_single_row_requests_coalesce_into_one_device_batch():
    model = _EchoModel(delay_s=0.05)
    srv = ModelServer("echo_coal", model, max_batch=64, max_wait_ms=20)
    try:
        before = profiling.counters("serving.echo_coal.")
        # first request occupies the worker (delay_s); the rest pile up in
        # the queue and MUST flush as one multi-request batch
        futs = [
            srv.submit(np.full(4, i, np.float32)) for i in range(8)
        ]
        results = [f.result(timeout=30) for f in futs]
        delta = profiling.counter_deltas(before, "serving.echo_coal.")
        assert delta["serving.echo_coal.requests"] == 8
        assert delta["serving.echo_coal.batches"] < 8  # coalescing happened
        assert delta.get("serving.echo_coal.coalesced_batches", 0) >= 1
        # batch occupancy > 1 observed by the engine's own histogram
        occ = profiling.percentiles("serve.echo_coal.occupancy")
        assert occ["max"] > 1
        # scatter is per request, in order, with the right values
        for i, r in enumerate(results):
            assert r["echo"].shape == (1,)
            assert r["echo"][0] == pytest.approx(4.0 * i)
    finally:
        srv.shutdown()


def test_deadline_flush_of_partial_batch():
    srv = ModelServer("echo_partial", _EchoModel(), max_batch=64, max_wait_ms=5)
    try:
        before = profiling.counters("serving.echo_partial.")
        out = srv.predict(np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)
        delta = profiling.counter_deltas(before, "serving.echo_partial.")
        # one lone request under max_batch flushed at the deadline
        assert delta.get("serving.echo_partial.flush_deadline", 0) >= 1
    finally:
        srv.shutdown()


def test_full_batch_flushes_without_waiting():
    srv = ModelServer(
        "echo_full", _EchoModel(delay_s=0.05), max_batch=4, max_wait_ms=10_000
    )
    try:
        futs = [srv.submit(np.ones((2, 4), np.float32)) for _ in range(4)]
        for f in futs:
            f.result(timeout=30)  # would hang for 10 s if deadline-bound
        delta = profiling.counters("serving.echo_full.")
        assert delta.get("serving.echo_full.flush_full", 0) >= 1
    finally:
        srv.shutdown()


def test_padding_to_pow2_bucket():
    model = _EchoModel()
    srv = ModelServer("echo_pad", model, max_batch=64, max_wait_ms=1)
    try:
        srv.predict(np.ones((3, 4), np.float32))
    finally:
        srv.shutdown()
    # warmup dispatches every bucket (16, 32, 64); traffic adds one 16-pad
    assert model.calls[:3] == [16, 32, 64]
    assert model.calls[-1] == 16  # 3 rows padded to the min bucket


# -- admission control / deadlines -------------------------------------------


def test_overload_rejects_fast_instead_of_blocking():
    model = _EchoModel(delay_s=0.2)
    srv = ModelServer(
        "echo_over", model, max_batch=4, max_wait_ms=1, queue_depth=8
    )
    try:
        before = profiling.counters("serving.echo_over.")
        futs = []
        rejected = 0
        # worker is busy 200 ms per dispatch; queue bound is 8 rows — the
        # burst MUST hit ServerOverloaded, and the submit path must return
        # immediately either way (no blocking admission)
        t0 = time.perf_counter()
        for _ in range(64):
            try:
                futs.append(srv.submit(np.ones(4, np.float32)))
            except ServerOverloaded:
                rejected += 1
        submit_wall = time.perf_counter() - t0
        assert rejected > 0
        assert submit_wall < 1.0  # 64 admissions/rejections, zero dispatch waits
        delta = profiling.counter_deltas(before, "serving.echo_over.")
        assert delta["serving.echo_over.rejected"] == rejected
        for f in futs:
            f.result(timeout=30)  # admitted requests still complete
    finally:
        srv.shutdown()


def test_request_deadline_expires_in_queue():
    model = _EchoModel(delay_s=0.25)
    srv = ModelServer("echo_to", model, max_batch=2, max_wait_ms=1)
    try:
        first = srv.submit(np.ones((2, 4), np.float32))  # occupies the worker
        doomed = srv.submit(np.ones(4, np.float32), timeout_ms=50.0)
        survivor = srv.submit(np.ones(4, np.float32))  # no deadline
        assert first.result(timeout=30)
        with pytest.raises(RequestTimeout):
            doomed.result(timeout=30)
        assert survivor.result(timeout=30)["echo"][0] == pytest.approx(4.0)
        assert profiling.counter("serving.echo_to.timeouts") >= 1
    finally:
        srv.shutdown()


def test_drain_and_shutdown_are_clean():
    srv = ModelServer("echo_drain", _EchoModel(delay_s=0.05), max_batch=4, max_wait_ms=50)
    futs = [srv.submit(np.ones(4, np.float32)) for _ in range(6)]
    srv.drain()  # flushes the partial batch immediately (quiescence)
    for f in futs:
        assert f.done()
    with pytest.raises(RuntimeError, match="shut down"):
        srv.submit(np.ones(4, np.float32))
    srv.shutdown()
    assert not srv._worker.is_alive()


def test_dispatch_error_fails_the_batch_not_the_server():
    class _Flaky(_EchoModel):
        def _serving_entry(self, mesh=None):
            entry = super()._serving_entry(mesh)
            calls = {"n": 0}
            inner = entry.call

            def call(batch):
                calls["n"] += 1
                if calls["n"] == 4:  # first post-warmup dispatch fails
                    raise RuntimeError("boom")
                return inner(batch)

            entry.call = call
            return entry

    srv = ModelServer("echo_flaky", _Flaky(), max_batch=64, max_wait_ms=1)
    try:
        with pytest.raises(RuntimeError, match="boom"):
            srv.predict(np.ones(4, np.float32))
        assert profiling.counter("serving.echo_flaky.errors") == 1
        # the worker survives and serves the next request
        out = srv.predict(np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)
    finally:
        srv.shutdown()


# -- srml-shield: self-healing serving (docs/robustness.md) -------------------


def _wait_state(srv, want, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if srv.state() == want:
            return True
        time.sleep(0.02)
    return srv.state() == want


def test_injected_worker_death_fails_requests_retryable_and_recovers(
    armed_faults,
):
    """Worker death mid-stream (SRML_FAULTS kill at serving.dispatch): every
    affected request resolves with the typed RETRYABLE ServerRecovering —
    never a hang — and the supervisor restarts the worker back to READY."""
    from spark_rapids_ml_tpu.serving import READY, ServerRecovering

    armed_faults("serving.dispatch:tag=shield_die:call=1:action=kill")
    srv = ModelServer(
        "shield_die", _EchoModel(), max_batch=4, max_wait_ms=5
    )
    try:
        futs = [srv.submit(np.ones(4, np.float32)) for _ in range(3)]
        for f in futs:
            with pytest.raises(ServerRecovering) as exc_info:
                f.result(timeout=30)  # resolves with the typed error, fast
            assert exc_info.value.retryable is True
        assert _wait_state(srv, READY), srv.state()
        assert profiling.counter("serving.shield_die.worker_deaths") == 1
        assert profiling.counter("serving.shield_die.restarts") == 1
        # the recovery window is a recorded duration series
        rec = profiling.percentiles("serve.shield_die.recovery")
        assert rec and rec["count"] >= 1
        # post-recovery the same request succeeds (the retryable contract)
        out = srv.predict(np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)
        assert srv.health()["restarts"] == 1
    finally:
        srv.shutdown(drain=False)


def test_worker_death_recovery_adds_zero_new_compiles(model_zoo, armed_faults):
    """The acceptance gate: recovery re-warms buckets from the RETAINED AOT
    executable cache, so a real model's death->restart->serve cycle
    performs ZERO new executable compilations and steady state stays
    clean."""
    from spark_rapids_ml_tpu.serving import READY, ServerRecovering

    model, X = model_zoo("kmeans")
    srv = ModelServer("shield_km", model, max_batch=32, max_wait_ms=2)
    try:
        srv.predict(X[:3])  # healthy traffic first
        # arming RESETS arrival counters (reload), so the next dispatch of
        # this server is call #1 of the new plan
        armed_faults("serving.dispatch:tag=shield_km:call=1:action=kill")
        before = profiling.counters("precompile.")
        with pytest.raises(ServerRecovering):
            srv.predict(X[:3])  # this dispatch dies; future gets typed error
        assert _wait_state(srv, READY), srv.state()
        out = srv.predict(X[:3])  # post-recovery traffic
        assert out["prediction"].shape == (3,)
        delta = profiling.counter_deltas(before, "precompile.")
        assert delta.get("precompile.compile", 0) == 0, delta
        assert delta.get("precompile.fallback", 0) == 0, delta
        srv.drain()
        srv.assert_steady_state()
        assert profiling.counter("serving.shield_km.steady_compiles") == 0
    finally:
        srv.shutdown(drain=False)


def test_wedge_then_recover_via_acting_watchdog(armed_faults, monkeypatch):
    """The PR 8 watchdog wired to ACT: a dispatch wedged past
    SRML_WATCH_STALL_S flips UNHEALTHY, and the supervisor SUPERSEDES the
    stuck worker (its in-flight request gets the typed retryable error)
    and restarts back to READY — the wedged thread's eventual return is a
    harmless no-op exit."""
    from spark_rapids_ml_tpu.serving import READY, ServerRecovering

    monkeypatch.setenv("SRML_WATCH_STALL_S", "0.3")
    armed_faults("serving.dispatch:tag=shield_wedge:call=1:delay=2.5")
    srv = ModelServer(
        "shield_wedge", _EchoModel(), max_batch=4, max_wait_ms=2
    )
    try:
        fut = srv.submit(np.ones(4, np.float32))  # worker wedges 2.5 s
        # wedge detection is lazy: polling state() is what notices, and
        # the restart counter is the proof the watchdog ACTED
        deadline = time.monotonic() + 15.0
        while (
            profiling.counter("serving.shield_wedge.restarts") < 1
            and time.monotonic() < deadline
        ):
            srv.state()
            time.sleep(0.05)
        assert profiling.counter("serving.shield_wedge.restarts") == 1
        assert _wait_state(srv, READY, timeout_s=15.0), srv.state()
        with pytest.raises(ServerRecovering):
            fut.result(timeout=30)
        assert profiling.counter("serving.shield_wedge.unhealthy") >= 1
        assert profiling.counter("serving.shield_wedge.restarts") == 1
        out = srv.predict(np.ones((2, 4), np.float32))
        assert out["echo"].shape == (2,)
        # give the superseded worker time to wake and exit cleanly; the
        # server must still be READY afterwards (no state clobber)
        time.sleep(3.0)
        assert srv.state() == READY
    finally:
        monkeypatch.setenv("SRML_WATCH_STALL_S", "0")
        srv.shutdown(drain=False)


def test_drain_during_recovery_resolves(armed_faults):
    """Queued requests shed by a recovery resolve immediately, so a drain
    racing the restart returns instead of timing out (quiescence counts
    EVERY admitted request, shed or served)."""
    from spark_rapids_ml_tpu.serving import ServerRecovering

    armed_faults("serving.dispatch:tag=shield_drain:call=1:action=kill")
    srv = ModelServer(
        "shield_drain", _EchoModel(delay_s=0.02), max_batch=2, max_wait_ms=1
    )
    try:
        futs = [srv.submit(np.ones(4, np.float32)) for _ in range(4)]
        srv.drain(timeout_s=20.0)  # must NOT raise TimeoutError
        for f in futs:
            assert f.done()
            with pytest.raises((ServerRecovering, RuntimeError)):
                f.result(timeout=0)
    finally:
        srv.shutdown(drain=False)


def test_restart_budget_exhaustion_goes_unhealthy(armed_faults, monkeypatch):
    """Bounded restarts: a server that dies on EVERY dispatch burns its
    budget and lands UNHEALTHY for good — submits then shed with
    ServerUnhealthy (fail over), never an infinite restart storm."""
    from spark_rapids_ml_tpu.serving import (
        UNHEALTHY,
        ServerRecovering,
        ServerUnhealthy,
    )

    monkeypatch.setenv("SRML_SERVE_MAX_RESTARTS", "1")
    armed_faults("serving.dispatch:tag=shield_budget:action=kill")
    srv = ModelServer(
        "shield_budget", _EchoModel(), max_batch=4, max_wait_ms=2
    )
    try:
        from spark_rapids_ml_tpu.serving import READY

        with pytest.raises(ServerRecovering):
            srv.predict(np.ones(4, np.float32))  # death #1: restart
        assert _wait_state(srv, READY), srv.state()
        with pytest.raises(ServerRecovering):
            srv.predict(np.ones(4, np.float32))  # death #2: budget spent
        assert _wait_state(srv, UNHEALTHY), srv.state()
        with pytest.raises((ServerUnhealthy, ServerRecovering)):
            srv.submit(np.ones(4, np.float32))
        assert profiling.counter("serving.shield_budget.restarts") == 1
        assert profiling.counter("serving.shield_budget.worker_deaths") == 2
    finally:
        srv.shutdown(drain=False)


def test_registry_rolls_up_recovering_severity_and_restarts(model_zoo):
    """RECOVERING outranks DRAINING in the registry's worst-state rollup,
    and registry.health() carries the plane-wide restart total."""
    from spark_rapids_ml_tpu.serving import (
        DRAINING,
        ModelRegistry,
        RECOVERING,
        SEVERITY,
        UNHEALTHY,
    )

    assert SEVERITY.index(RECOVERING) > SEVERITY.index(DRAINING)
    assert SEVERITY.index(UNHEALTHY) > SEVERITY.index(RECOVERING)
    model, X = model_zoo("kmeans")
    with ModelRegistry(max_batch=16, max_wait_ms=1) as reg:
        reg.register("shield_roll", model)
        h = reg.health()
        assert h["state"] == "READY"
        assert h["restarts"] == 0
        assert h["models"]["shield_roll"]["restarts"] == 0


# -- real models: equivalence + zero-new-compiles steady state ----------------


def _direct_transform(model, X):
    from spark_rapids_ml_tpu.dataframe import DataFrame

    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=1)
    if model.hasParam("featuresCol"):
        model.setFeaturesCol("features")
    out = model.transform(df)
    return {
        c: np.asarray(list(out.partitions[0][c]))
        for c in out.columns
        if c != "features"
    }


@pytest.mark.parametrize("arm", SERVED_ARMS)
def test_served_outputs_match_batch_transform(arm, model_zoo):
    model, X = model_zoo(arm)
    expect = _direct_transform(model, X[:10])
    with ModelServer(f"eq_{arm}", model, max_batch=32, max_wait_ms=2) as srv:
        got = srv.predict(X[:10])
        assert sorted(got) == sorted(expect)
        for col in expect:
            np.testing.assert_allclose(
                np.asarray(got[col], np.float64),
                np.asarray(expect[col], np.float64),
                rtol=1e-5,
                atol=1e-5,
                err_msg=f"{arm}: column {col!r} diverged from transform()",
            )
        srv.drain()
        srv.assert_steady_state()


def test_served_ann_matches_probed_search(model_zoo):
    """Served ANN == batch probed search (the srml-ann serving gate): the
    online entry answers from the same staged index + cached executables
    the batch kneighbors path dispatches, so ids are exactly equal."""
    model, X = model_zoo("ann")
    _, _, knn_df = model.kneighbors(
        __import__("spark_rapids_ml_tpu.dataframe", fromlist=["DataFrame"])
        .DataFrame.from_numpy(X[:8], num_partitions=1)
    )
    expect_ids = np.asarray(list(knn_df.partitions[0]["indices"]))
    expect_d = np.asarray(list(knn_df.partitions[0]["distances"]))
    with ModelServer("eq_ann", model, max_batch=32, max_wait_ms=2) as srv:
        got = srv.predict(X[:8])
        assert np.array_equal(got["indices"], expect_ids)
        np.testing.assert_allclose(got["distances"], expect_d, rtol=1e-5, atol=1e-5)
        srv.drain()
        srv.assert_steady_state()


def test_served_ivfpq_matches_probed_search(model_zoo):
    """Served IVF-PQ == batch probed+refined search (the srml-pq serving
    gate): the online entry answers from the same staged code index,
    cached probe executables, and host refine the batch kneighbors path
    uses — ids exactly equal, steady state zero new compiles."""
    model, X = model_zoo("ivfpq")
    _, _, knn_df = model.kneighbors(
        __import__("spark_rapids_ml_tpu.dataframe", fromlist=["DataFrame"])
        .DataFrame.from_numpy(X[:8], num_partitions=1)
    )
    expect_ids = np.asarray(list(knn_df.partitions[0]["indices"]))
    expect_d = np.asarray(list(knn_df.partitions[0]["distances"]))
    with ModelServer("eq_ivfpq", model, max_batch=32, max_wait_ms=2) as srv:
        assert srv._entry.info["algorithm"] == "ivfpq"
        got = srv.predict(X[:8])
        assert np.array_equal(got["indices"], expect_ids)
        np.testing.assert_allclose(got["distances"], expect_d, rtol=1e-5, atol=1e-5)
        srv.drain()
        srv.assert_steady_state()


def test_served_knn_matches_kneighbors(model_zoo):
    model, X = model_zoo("knn")
    _, _, knn_df = model.kneighbors(
        __import__("spark_rapids_ml_tpu.dataframe", fromlist=["DataFrame"])
        .DataFrame.from_numpy(X[:8], num_partitions=1)
    )
    expect_ids = np.asarray(list(knn_df.partitions[0]["indices"]))
    expect_d = np.asarray(list(knn_df.partitions[0]["distances"]))
    with ModelServer("eq_knn", model, max_batch=32, max_wait_ms=2) as srv:
        got = srv.predict(X[:8])
        assert np.array_equal(got["indices"], expect_ids)
        np.testing.assert_allclose(got["distances"], expect_d, rtol=1e-5, atol=1e-5)
        srv.drain()
        srv.assert_steady_state()


def test_steady_state_zero_new_compiles(model_zoo):
    """The acceptance gate: after warmup, a mixed stream of single-row and
    small-batch requests across every bucket performs ZERO new executable
    compilations (precompile compile/fallback counters frozen)."""
    model, X = model_zoo("kmeans")
    srv = ModelServer("steady_km", model, max_batch=64, max_wait_ms=2)
    try:
        before = profiling.counters("precompile.")
        rng = np.random.default_rng(3)
        for size in (1, 1, 3, 17, 33, 64, 5, 1, 64):
            srv.predict(
                rng.standard_normal((size, X.shape[1])).astype(np.float32)
            )
        delta = profiling.counter_deltas(before, "precompile.")
        assert delta.get("precompile.compile", 0) == 0, delta
        assert delta.get("precompile.fallback", 0) == 0, delta
        srv.drain()
        srv.assert_steady_state()
        assert profiling.counter("serving.steady_km.steady_compiles") == 0
    finally:
        srv.shutdown()


def test_latency_percentiles_surface(model_zoo):
    model, X = model_zoo("linreg")
    with ModelServer("slo_lin", model, max_batch=32, max_wait_ms=2) as srv:
        for i in range(12):
            srv.predict(X[i])
        stats = srv.stats()
    lat = stats["latency"]
    assert lat["count"] >= 12
    assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    assert stats["counters"]["serving.slo_lin.requests"] >= 12
    assert stats["buckets"] == serve_buckets(32)


def test_failed_server_init_releases_trace_scope(model_zoo, monkeypatch, tmp_path):
    """A ModelServer whose warmup fails must close its lifetime trace
    session on the way out — a leaked collection scope would silently
    starve every later fit/search trace in the process."""
    from spark_rapids_ml_tpu import profiling
    import spark_rapids_ml_tpu.serving.engine as engine_mod

    model, X = model_zoo("kmeans")
    monkeypatch.setenv(profiling.TRACE_ENV, str(tmp_path))
    depth0 = profiling._collect_depth

    class Boom(RuntimeError):
        pass

    def bad_warm(self):
        raise Boom("warm failed")

    monkeypatch.setattr(engine_mod.ModelServer, "_warm_buckets", bad_warm)
    with pytest.raises(Boom):
        ModelServer("leaky", model, max_batch=16, max_wait_ms=2)
    assert profiling._collect_depth == depth0


def test_registry_telemetry_snapshot_and_delta(model_zoo):
    """registry.telemetry() is a mergeable TelemetrySnapshot of the serving
    plane; telemetry(since=prev) reports only what moved in the window —
    the scrape/ship surface that works on live Spark executors (snapshots
    merge driver-side like fit telemetry)."""
    model, X = model_zoo("kmeans")
    with ModelRegistry(max_batch=32, max_wait_ms=2) as reg:
        reg.register("telem_km", model)
        reg.get("telem_km").predict(X[:2])
        snap0 = reg.telemetry()
        assert snap0.counters.get("serving.telem_km.requests", 0) >= 1
        assert any(
            k.startswith("serve.telem_km.") for k in snap0.durations
        ), snap0.durations
        for i in range(5):
            reg.get("telem_km").predict(X[i : i + 1])
        delta = reg.telemetry(since=snap0)
        assert delta.counters.get("serving.telem_km.requests") == 5
        lat = delta.durations.get("serve.telem_km.latency")
        assert lat is not None and lat["count"] == 5
        # snapshots from different "processes" merge associatively
        merged = snap0.merge(delta)
        assert merged.counters["serving.telem_km.requests"] == (
            snap0.counters["serving.telem_km.requests"] + 5
        )


# -- registry -----------------------------------------------------------------


def test_registry_register_get_unregister(model_zoo):
    model, X = model_zoo("kmeans")
    with ModelRegistry(max_batch=32, max_wait_ms=2) as reg:
        srv = reg.register("km", model)
        assert "km" in reg and reg.get("km") is srv
        with pytest.raises(ValueError, match="already registered"):
            reg.register("km", model)
        out = reg.get("km").predict(X[:3])
        assert out["prediction"].shape == (3,)
        assert reg.names() == ["km"]
        assert "km" in reg.stats()
        reg.unregister("km")
        with pytest.raises(KeyError):
            reg.get("km")


def test_registry_loads_saved_models_and_serves(model_zoo, tmp_path):
    """The registry's load path: core.load resolves the class from
    metadata, the server warms at load, outputs match the in-memory
    model's transform (the persistence-matrix fixture doing double duty)."""
    with ModelRegistry(max_batch=32, max_wait_ms=2) as reg:
        for arm in ("kmeans", "rf_clf"):
            model, X = model_zoo(arm)
            path = str(tmp_path / arm)
            model.save(path)
            srv = reg.load(arm, path)
            got = srv.predict(X[:6])
            expect = _direct_transform(model, X[:6])
            for col in expect:
                np.testing.assert_allclose(
                    np.asarray(got[col], np.float64),
                    np.asarray(expect[col], np.float64),
                    rtol=1e-5,
                    atol=1e-5,
                )
            srv.drain()
            srv.assert_steady_state()


def test_registry_rejects_estimators(tmp_path):
    from spark_rapids_ml_tpu import KMeans

    est = KMeans(k=2)
    path = str(tmp_path / "est")
    est.save(path)
    with ModelRegistry() as reg:
        with pytest.raises(TypeError, match="not a fitted model"):
            reg.load("est", path)


def test_unservable_model_gives_actionable_error(model_zoo):
    model, _X = model_zoo("umap")  # no serving entry (transform-only)
    with pytest.raises(NotImplementedError, match="no serving entry"):
        ModelServer("umap", model)
