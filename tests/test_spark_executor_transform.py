#
# model.transform(pyspark_df) and _transformEvaluate must run partition-wise
# ON THE EXECUTORS via mapInPandas — never toPandas()/collect the dataset to
# the driver (VERDICT round 2, item 2; reference core.py:1277-1361 runs a
# pandas_udf per executor, umap.py:1147-1224 is distributed inference by
# design).  pyspark is not installable on this image, so the surfaces
# executor_transform touches (schema.fields/dataType.simpleString,
# mapInPandas, collect) are mocked faithfully; spark_to_facade is patched to
# raise, PROVING the driver-collect path is never entered.
#
import types

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import (
    KMeans,
    LinearRegression,
    LogisticRegression,
    PCA,
    RandomForestClassifier,
    UMAP,
)
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.evaluation import (
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)


class _FakeField:
    def __init__(self, name: str, ddl: str):
        self.name = name
        self.dataType = types.SimpleNamespace(simpleString=lambda: ddl)


class _FakeTransformSparkDataFrame:
    """Just enough of pyspark.sql.DataFrame for executor_transform: schema
    introspection + mapInPandas + collect.  Deliberately NO toPandas — any
    driver-collect of the dataset fails loudly."""

    def __init__(self, partitions, fields):
        self._partitions = partitions
        self._fields = fields

    @property
    def schema(self):
        return types.SimpleNamespace(fields=list(self._fields))

    @property
    def columns(self):
        return [f.name for f in self._fields]

    def mapInPandas(self, udf, schema=None):
        out_parts, out_fields = [], None
        for part in self._partitions:
            chunks = list(udf(iter([part])))
            if chunks:
                pdf = pd.concat(chunks, ignore_index=True)
                out_parts.append(pdf)
                if out_fields is None:
                    out_fields = [_FakeField(c, "?") for c in pdf.columns]
        return _FakeTransformSparkDataFrame(out_parts, out_fields or [])

    def collect(self):
        rows = []
        for part in self._partitions:
            rows.extend(part.to_dict("records"))
        return rows

    # test-only materializer (NOT part of the mocked pyspark surface)
    def _materialize(self) -> pd.DataFrame:
        return pd.concat(self._partitions, ignore_index=True)


_FakeTransformSparkDataFrame.__module__ = "pyspark.sql.dataframe"


@pytest.fixture(autouse=True)
def _no_driver_collect(monkeypatch):
    """Prove the executor path: any spark_to_facade call (the driver
    collect) fails the test outright."""
    from spark_rapids_ml_tpu.spark import adapter

    def _boom(sdf):
        raise AssertionError("transform collected the dataset to the driver")

    monkeypatch.setattr(adapter, "spark_to_facade", _boom)
    monkeypatch.delenv("SRML_SPARK_COLLECT", raising=False)


def _data(n=400, d=6, seed=2):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal(d).astype(np.float32)
    y = (X @ w + 0.05 * rng.standard_normal(n)).astype(np.float32)
    y_cls = (X @ w > 0).astype(np.float32)
    return X, y, y_cls


def _fake_sdf(X, y=None, n_parts=3, with_extra=True):
    fields = [_FakeField("features", "array<float>")]
    if with_extra:
        fields.append(_FakeField("rowid", "bigint"))
    if y is not None:
        fields.append(_FakeField("label", "double"))
    parts = []
    for ix in np.array_split(np.arange(len(X)), n_parts):
        pdf = pd.DataFrame({"features": list(X[ix])})
        if with_extra:
            pdf["rowid"] = ix
        if y is not None:
            pdf["label"] = y[ix]
        parts.append(pdf.reset_index(drop=True))
    return _FakeTransformSparkDataFrame(parts, fields)


def test_logreg_transform_runs_on_executors():
    X, _, y_cls = _data()
    model = LogisticRegression(maxIter=40, regParam=0.01).fit(
        DataFrame.from_numpy(X, y_cls)
    )
    out = model.transform(_fake_sdf(X))
    assert isinstance(out, _FakeTransformSparkDataFrame)  # still a "pyspark" df
    got = out._materialize()
    # original columns preserved, outputs appended
    assert list(got["rowid"]) == list(range(len(X)))
    local = model.transform(DataFrame.from_numpy(X)).toPandas()
    np.testing.assert_array_equal(
        got["prediction"].to_numpy(np.float64),
        local["prediction"].to_numpy(np.float64),
    )
    np.testing.assert_allclose(
        np.stack(got["probability"].to_numpy()),
        np.stack(local["probability"].to_numpy()),
        rtol=1e-6,
    )


def test_kmeans_transform_int_schema():
    X, _, _ = _data()
    model = KMeans(k=3, maxIter=10, seed=1).fit(DataFrame.from_numpy(X))
    from spark_rapids_ml_tpu.spark.adapter import transform_output_ddl

    sdf = _fake_sdf(X)
    ddl = transform_output_ddl(model, sdf)
    assert "`prediction` int" in ddl and "`features` array<float>" in ddl
    got = model.transform(sdf)._materialize()
    assert got["prediction"].dtype == np.int32
    local = model.transform(DataFrame.from_numpy(X)).toPandas()["prediction"]
    np.testing.assert_array_equal(got["prediction"].to_numpy(np.int64), local.to_numpy(np.int64))


def test_pca_umap_embedding_transforms():
    X, _, _ = _data(n=256)
    pca = PCA(k=2).fit(DataFrame.from_numpy(X))
    got = pca.transform(_fake_sdf(X))._materialize()
    local = pca.transform(DataFrame.from_numpy(X)).toPandas()
    np.testing.assert_allclose(
        np.stack(got["pca_features"].to_numpy()),
        np.stack(local["pca_features"].to_numpy()),
        rtol=1e-5, atol=1e-5,
    )
    um = UMAP(n_neighbors=5, n_epochs=30, random_state=4).fit(
        DataFrame.from_numpy(X)
    )
    got = um.transform(_fake_sdf(X))._materialize()
    emb = np.stack(got[um.getOrDefault("outputCol")].to_numpy())
    assert emb.shape == (len(X), 2) and np.isfinite(emb).all()


def test_rf_transform_runs_on_executors():
    X, _, y_cls = _data()
    model = RandomForestClassifier(
        numTrees=6, maxDepth=4, maxBins=16, seed=5
    ).fit(DataFrame.from_numpy(X, y_cls))
    got = model.transform(_fake_sdf(X))._materialize()
    local = model.transform(DataFrame.from_numpy(X)).toPandas()
    np.testing.assert_array_equal(
        got["prediction"].to_numpy(np.float64),
        local["prediction"].to_numpy(np.float64),
    )


def test_empty_partition_keeps_schema():
    X, _, _ = _data(n=60)
    model = KMeans(k=2, maxIter=5, seed=1).fit(DataFrame.from_numpy(X))
    sdf = _fake_sdf(X, n_parts=2)
    sdf._partitions.insert(1, sdf._partitions[0].iloc[:0].copy())
    got = model.transform(sdf)._materialize()
    assert len(got) == len(X) and "prediction" in got.columns


def test_logreg_transform_evaluate_executor_side():
    X, _, y_cls = _data()
    model = LogisticRegression(maxIter=40, regParam=0.01).fit(
        DataFrame.from_numpy(X, y_cls)
    )
    sdf = _fake_sdf(X, y=y_cls)
    for metric in ("accuracy", "logLoss", "f1"):
        ev = MulticlassClassificationEvaluator(metricName=metric)
        got = model._transformEvaluate(sdf, ev)
        want = model._transformEvaluate(DataFrame.from_numpy(X, y_cls), ev)
        np.testing.assert_allclose(got, want, rtol=1e-9)


def test_linreg_transform_evaluate_executor_side():
    X, y, _ = _data()
    est = LinearRegression(maxIter=30)
    pm = [{est.getParam("regParam"): 0.0}, {est.getParam("regParam"): 0.3}]
    models = est.fit(DataFrame.from_numpy(X, y), pm)
    combined = type(models[0])._combine(models)
    sdf = _fake_sdf(X, y=y)
    for metric in ("rmse", "r2", "mae"):
        ev = RegressionEvaluator(metricName=metric)
        got = combined._transformEvaluate(sdf, ev)
        want = combined._transformEvaluate(DataFrame.from_numpy(X, y), ev)
        assert len(got) == 2
        np.testing.assert_allclose(got, want, rtol=1e-9)


def test_collect_override_routes_to_driver_local(monkeypatch):
    """SRML_SPARK_COLLECT=1 flips back to the driver-collect path (which the
    patched spark_to_facade turns into a loud failure — proving the switch
    selects the path)."""
    monkeypatch.setenv("SRML_SPARK_COLLECT", "1")
    X, _, _ = _data(n=60)
    model = KMeans(k=2, maxIter=5, seed=1).fit(DataFrame.from_numpy(X))
    with pytest.raises(Exception):
        model.transform(_fake_sdf(X))
