# The topology-aware exchange plane (parallel/topology.py + the
# hierarchical DeviceSection schedules in parallel/exchange.py + the kNN
# adoption): TopologyMap derivation / SRML_TOPO override semantics, the
# single-n-cycle ring property, BITWISE parity of the hierarchical
# collectives vs the flat schedule (and of the kNN exchange kernels across
# simulated topologies 1x8 / 2x4 / 4x2 on 1/2/8-device meshes), the
# ici/dcn counter split with the O(n_hosts) DCN headline bound, the
# cache-key staticness of the map, and the host-plane distributed ring
# cycle.  Runs on the virtual 8-device CPU mesh (conftest).
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.compat import shard_map
from spark_rapids_ml_tpu.parallel import topology
from spark_rapids_ml_tpu.parallel.exchange import (
    device_collective,
    link_totals,
)
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS


def _mesh(n_dev: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:n_dev]), (DATA_AXIS,))


# -- TopologyMap derivation ---------------------------------------------------


def test_topology_map_default_is_flat(monkeypatch):
    monkeypatch.delenv(topology.TOPO_ENV, raising=False)
    monkeypatch.delenv(topology.EXCHANGE_TOPO_ENV, raising=False)
    topo = topology.topology_map(mesh=_mesh(8))
    assert topo.n_groups == 1 and topo.n_devices == 8
    assert topo.schedule == "flat" and not topo.is_hierarchical
    assert topo.describe() == "1x8/flat"
    assert topology.topology_map(n_devices=4).describe() == "1x4/flat"


def test_topology_map_env_override_and_pin(monkeypatch):
    monkeypatch.setenv(topology.TOPO_ENV, "2:4")
    monkeypatch.delenv(topology.EXCHANGE_TOPO_ENV, raising=False)
    topo = topology.topology_map(mesh=_mesh(8))
    assert topo.source == "env"
    assert topo.groups == ((0, 1, 2, 3), (4, 5, 6, 7))
    assert topo.gateways == (0, 4)
    assert topo.group_of == (0, 0, 0, 0, 1, 1, 1, 1)
    assert topo.is_hierarchical and topo.describe() == "2x4/hier"
    # the pin keeps the derived groups (honest link attribution) but
    # forces the flat schedule — the parity comparator's escape hatch
    monkeypatch.setenv(topology.EXCHANGE_TOPO_ENV, "flat")
    pinned = topology.topology_map(mesh=_mesh(8))
    assert pinned.groups == topo.groups
    assert pinned.schedule == "flat" and pinned.describe() == "2x4/flat-pinned"


def test_topology_map_malformed_override_raises(monkeypatch):
    for bad in ("2x4", "2:", ":4", "2:4:1", "0:4", "2:-1", "a:b"):
        monkeypatch.setenv(topology.TOPO_ENV, bad)
        with pytest.raises(ValueError):
            topology.topology_map(n_devices=8)


def test_topology_map_uneven_groups_degenerate_to_flat_schedule(monkeypatch):
    # 8 devices at 3 per host -> groups of 3/3/2: the hierarchical
    # schedules refuse unequal groups (group_size == 0) and run flat
    monkeypatch.setenv(topology.TOPO_ENV, "3:3")
    topo = topology.topology_map(mesh=_mesh(8))
    assert topo.n_groups == 3 and topo.group_size == 0
    assert topo.schedule == "flat"


def test_topology_map_groups_by_device_id_not_position(monkeypatch):
    # a SHUFFLED device list must still group by physical id — that is
    # what makes the simulated topology genuinely non-contiguous in
    # logical axis positions
    monkeypatch.setenv(topology.TOPO_ENV, "2:4")
    devs = list(jax.devices())
    shuf = [devs[j] for j in (3, 7, 0, 5, 2, 6, 1, 4)]
    topo = topology.topology_map(devices=shuf)
    # positions of ids 0..3 in shuf: 2, 6, 4, 0 -> group ordered ascending
    assert topo.groups == ((0, 2, 4, 6), (1, 3, 5, 7))


def test_ring_cycle_is_single_n_cycle_with_g_gateway_edges():
    for groups in (
        ((0, 1, 2, 3), (4, 5, 6, 7)),
        ((0, 2, 4, 6), (1, 3, 5, 7)),   # interleaved
        ((0, 1), (2, 3), (4, 5), (6, 7)),
        ((0, 1, 2, 3, 4, 5, 6, 7),),
    ):
        topo = topology.TopologyMap(groups=groups, source="env")
        cycle = topology.ring_cycle(topo)
        n = topo.n_devices
        nxt = dict(cycle)
        assert sorted(nxt) == list(range(n))
        assert sorted(nxt.values()) == list(range(n))
        # single n-cycle: following nxt from 0 visits all n exactly once
        seen, at = [], 0
        for _ in range(n):
            seen.append(at)
            at = nxt[at]
        assert at == 0 and sorted(seen) == list(range(n))
        # exactly one cross-group edge per adjacent group pair
        gof = topo.group_of
        cross = sum(1 for s, d in cycle if gof[s] != gof[d])
        assert cross == (topo.n_groups if topo.n_groups > 1 else 0)


def test_group_major_devices_and_slice_meshes_never_straddle(monkeypatch):
    monkeypatch.setenv(topology.TOPO_ENV, "2:4")
    devs = list(jax.devices())
    shuf = [devs[j] for j in (3, 7, 0, 5, 2, 6, 1, 4)]
    ordered = topology.group_major_devices(shuf)
    assert [d.id for d in ordered] == [3, 0, 2, 1, 7, 5, 6, 4]


# -- hierarchical collective parity (shard_map level) -------------------------


def _apply_collective(mesh, topo, op, x):
    def body(xs):
        sec = device_collective(f"topo_test.{op}", topo)
        if op == "allgather_rows":
            return sec.allgather_rows(xs, DATA_AXIS)
        if op == "gather_stack":
            return sec.gather_stack(xs, DATA_AXIS)
        if op == "psum_merge":
            return sec.psum_merge(xs, DATA_AXIS)
        if op == "psum":
            return sec.psum(xs, DATA_AXIS)
        raise AssertionError(op)

    f = shard_map(
        body, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(),
        check_vma=False,
    )
    return np.asarray(jax.jit(f)(x))


@pytest.mark.parametrize(
    "groups",
    [
        ((0, 1, 2, 3), (4, 5, 6, 7)),      # 2x4 contiguous
        ((0, 1), (2, 3), (4, 5), (6, 7)),  # 4x2 contiguous
        ((0, 2, 4, 6), (1, 3, 5, 7)),      # 2x4 interleaved
    ],
)
def test_hier_collectives_bitwise_match_flat(groups):
    """allgather_rows / gather_stack / psum_merge: the hierarchical
    schedule keeps the one-value-plus-zeros summand structure of the flat
    zeros-slab psum, so results are BITWISE identical.  psum carries
    integer-valued floats here (exact addition), pinning the re-associated
    schedule too."""
    mesh = _mesh(8)
    hier = topology.TopologyMap(groups=groups, source="env")
    assert hier.is_hierarchical
    flat = topology.flat_topology(8)
    rng = np.random.default_rng(11)
    x = rng.standard_normal((64, 5)).astype(np.float32)
    xi = rng.integers(-100, 100, size=(64, 5)).astype(np.float32)
    for op, data in (
        ("allgather_rows", x),
        ("gather_stack", x),
        ("psum_merge", x),
        ("psum", xi),
    ):
        a = _apply_collective(mesh, hier, op, data)
        b = _apply_collective(mesh, flat, op, data)
        np.testing.assert_array_equal(a, b, err_msg=f"{op} {groups}")


def test_hier_ring_shift_full_pass_is_identity():
    """n_dev applications of the hierarchical cycle return every block
    home (single n-cycle => permutation^n = identity), and on CONTIGUOUS
    groups the cycle degenerates to the flat +1 rotation, so even a single
    hop is bitwise-equal to flat."""
    mesh = _mesh(8)
    hier = topology.TopologyMap(
        groups=((0, 1, 2, 3), (4, 5, 6, 7)), source="env"
    )
    flat = topology.flat_topology(8)
    rng = np.random.default_rng(12)
    x = rng.standard_normal((64, 3)).astype(np.float32)

    def full_pass(topo, hops):
        def body(xs):
            sec = device_collective("topo_test.ring", topo)
            for _ in range(hops):
                xs = sec.ring_shift(xs)
            return xs

        f = shard_map(
            body, mesh=mesh, in_specs=(P(DATA_AXIS),),
            out_specs=P(DATA_AXIS), check_vma=False,
        )
        return np.asarray(jax.jit(f)(x))

    np.testing.assert_array_equal(full_pass(hier, 8), x)
    np.testing.assert_array_equal(full_pass(hier, 1), full_pass(flat, 1))


def test_hier_counter_split_matches_byte_model():
    """The ici/dcn split counters follow the documented trace-time model,
    and the headline bound holds: hierarchical DCN bytes <= flat cross-
    host bytes / n_hosts (the flat schedule on a multi-group topology is
    all-DCN — it pins nothing to a link)."""
    mesh = _mesh(8)
    hier = topology.TopologyMap(
        groups=((0, 1, 2, 3), (4, 5, 6, 7)), source="env"
    )
    pinned = topology.TopologyMap(
        groups=hier.groups, source="env", pinned=True
    )
    x = np.ones((64, 4), np.float32)
    B = (64 // 8) * 4 * 4  # per-shard payload bytes
    for name in ("hsplit", "fsplit"):
        profiling.reset_counters(f"exchange.topo_test.{name}")
    profiling.reset_counters("exchange.topo_test.")
    _apply_collective(mesh, hier, "gather_stack", x)
    ctr = profiling.counters("exchange.topo_test.")
    G, g, n = 2, 4, 8
    assert ctr["exchange.topo_test.gather_stack.ici_bytes"] == (
        n * (g - 1) * B + G * (g - 1) * (n - g) * B
    )
    hier_dcn = ctr["exchange.topo_test.gather_stack.dcn_bytes"]
    assert hier_dcn == G * (G - 1) * g * B
    profiling.reset_counters("exchange.topo_test.")
    _apply_collective(mesh, pinned, "gather_stack", x)
    ctr = profiling.counters("exchange.topo_test.")
    flat_dcn = ctr["exchange.topo_test.gather_stack.dcn_bytes"]
    assert flat_dcn == n * (n - 1) * B
    assert "exchange.topo_test.gather_stack.ici_bytes" not in ctr
    # the acceptance headline, at the collective level
    assert hier_dcn <= flat_dcn / G * 1.1
    profiling.reset_counters("exchange.topo_test.")


# -- the kNN exchange kernels across simulated topologies ---------------------


def _knn_case(n_dev, route, topo_env, pin, monkeypatch, k=9):
    from spark_rapids_ml_tpu.ops.knn import (
        _exchange_geometry,
        _exchange_topology,
        knn_block_kernel_exchange,
        prepare_items,
    )

    monkeypatch.delenv(topology.TOPO_ENV, raising=False)
    monkeypatch.delenv(topology.EXCHANGE_TOPO_ENV, raising=False)
    if topo_env:
        monkeypatch.setenv(topology.TOPO_ENV, topo_env)
    if pin:
        monkeypatch.setenv(topology.EXCHANGE_TOPO_ENV, "flat")
    rng = np.random.default_rng(2)
    items = rng.standard_normal((1024, 16)).astype(np.float32)
    ids = np.arange(1024, dtype=np.int64)
    queries = rng.standard_normal((128, 16)).astype(np.float32)
    mesh = _mesh(n_dev)
    prepared = prepare_items(items, ids, mesh, shuffle=False)
    chunk, qt = _exchange_geometry(
        prepared.items.shape[0] // n_dev, 128, n_dev, route
    )
    topo = _exchange_topology(mesh)
    d, p = knn_block_kernel_exchange(
        prepared.items, prepared.norm, prepared.pos, prepared.valid,
        jnp.asarray(queries), mesh, k, route, chunk, qt, topo,
    )
    return np.asarray(d), np.asarray(p), topo


def test_knn_topology_parity_matrix_bitwise(monkeypatch):
    """The acceptance gate: hierarchical == flat-pinned == 1-device
    reference, BITWISE, for the ring and gather exchange kernels on
    1/2/8-device meshes under simulated topologies 1x8 / 2x4 / 4x2."""
    for route in ("ring", "gather"):
        ref_d, ref_p, _ = _knn_case(1, route, None, False, monkeypatch)
        for n_dev in (1, 2, 8):
            for topo_env in (None, "1:8", "2:4", "4:2"):
                for pin in (False, True):
                    d, p, topo = _knn_case(
                        n_dev, route, topo_env, pin, monkeypatch
                    )
                    tag = f"{route}/{n_dev}dev/{topo_env}/pin={pin}"
                    np.testing.assert_array_equal(d, ref_d, err_msg=tag)
                    np.testing.assert_array_equal(p, ref_p, err_msg=tag)


def test_knn_hier_dcn_bytes_bound_on_2x4(monkeypatch):
    """`exchange.knn.*.dcn_bytes` under the hierarchical route <= the
    flat route's cross-host bytes / n_hosts (+10% slack) on the 2x4 CI
    topology — the measurable O(n_dev) -> O(n_hosts) collapse."""
    def dcn(route, pin):
        profiling.reset_counters("exchange.knn.")
        # k=11 keeps these statics distinct from every other test's, so
        # the jit traces fresh here (sections count at TRACE time — a jit
        # cache hit records nothing)
        _knn_case(8, route, "2:4", pin, monkeypatch, k=11)
        ctr = profiling.counters("exchange.knn.")
        return sum(v for k, v in ctr.items() if k.endswith(".dcn_bytes"))

    for route in ("ring", "gather"):
        hier, flat = dcn(route, False), dcn(route, True)
        assert flat > 0
        assert hier <= flat / 2 * 1.1, (route, hier, flat)
    profiling.reset_counters("exchange.knn.")


def test_topology_is_a_cache_key_static(monkeypatch):
    """A topology change re-keys the AOT executable cache — same shapes,
    same route, different TopologyMap must NEVER reuse the same compiled
    schedule.  Equal maps (by value) key identically."""
    from spark_rapids_ml_tpu.ops.precompile import kernel_cache_key

    mesh = _mesh(8)
    args = (jax.ShapeDtypeStruct((128, 16), np.float32),)
    base = dict(k=9, route="ring", chunk=128, qt=16)
    k_flat = kernel_cache_key(
        "knn_ring", args, mesh,
        dict(base, topo=topology.flat_topology(8)),
    )
    hier = topology.TopologyMap(
        groups=((0, 1, 2, 3), (4, 5, 6, 7)), source="env"
    )
    k_hier = kernel_cache_key("knn_ring", args, mesh, dict(base, topo=hier))
    k_hier2 = kernel_cache_key(
        "knn_ring", args, mesh,
        dict(base, topo=topology.TopologyMap(
            groups=((0, 1, 2, 3), (4, 5, 6, 7)), source="env"
        )),
    )
    k_pin = kernel_cache_key(
        "knn_ring", args, mesh,
        dict(base, topo=topology.TopologyMap(
            groups=hier.groups, source="env", pinned=True
        )),
    )
    assert k_flat != k_hier != k_pin
    assert k_hier == k_hier2


def test_hier_route_zero_new_compiles_on_repeat_search(monkeypatch):
    """Repeat same-shape search under SRML_TOPO=2:4: the second search
    rides the AOT cache with ZERO new compilations — the steady-state
    contract holds on the hierarchical schedule too."""
    from spark_rapids_ml_tpu.ops.knn import (
        knn_search_prepared, prepare_items,
    )

    monkeypatch.setenv(topology.TOPO_ENV, "2:4")
    monkeypatch.setenv("SRML_KNN_EXCHANGE", "ring")
    rng = np.random.default_rng(4)
    items = rng.standard_normal((2048, 24)).astype(np.float32)
    queries = rng.standard_normal((256, 24)).astype(np.float32)
    mesh = _mesh(8)
    prepared = prepare_items(
        items, np.arange(2048, dtype=np.int64), mesh, shuffle=False
    )
    d1, i1 = knn_search_prepared(prepared, queries, 9, mesh)
    c0 = profiling.counters("precompile")
    d2, i2 = knn_search_prepared(prepared, queries, 9, mesh)
    c1 = profiling.counters("precompile")
    assert c1.get("precompile.compile", 0) == c0.get("precompile.compile", 0)
    assert c1.get("precompile.aot_hit", 0) > c0.get("precompile.aot_hit", 0)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(i1, i2)


# -- host-plane distributed ring cycle ----------------------------------------


def test_distributed_ring_topology_cycle_bitwise_vs_flat(monkeypatch):
    """distributed_kneighbors under SRML_TOPO=2:2 (4 thread-ranks, 2
    simulated hosts): identical bits to the flat run, and the host-ring
    hops are attributed to exchange.ring.ici_bytes / .dcn_bytes."""
    from test_knn_exchange import _distributed_case

    profiling.reset_counters("exchange.ring")
    res_flat, q_split, sk_d, sk_ids = _distributed_case("ring", monkeypatch)
    flat_ctr = profiling.counters("exchange.ring")
    assert "exchange.ring.ici_bytes" not in flat_ctr  # no grouping, no split
    monkeypatch.setenv(topology.TOPO_ENV, "2:2")
    profiling.reset_counters("exchange.ring")
    res_topo, _, _, _ = _distributed_case("ring", monkeypatch)
    topo_ctr = profiling.counters("exchange.ring")
    for rank in range(4):
        ((df, i_f),) = res_flat[rank]
        ((dt, i_t),) = res_topo[rank]
        np.testing.assert_array_equal(dt, df)
        np.testing.assert_array_equal(i_t, i_f)
        rows = q_split[rank]
        np.testing.assert_allclose(dt, sk_d[rows], rtol=1e-4, atol=1e-4)
    # 2:2 on 4 ranks: ranks 0/2 drive intra-host edges, 1/3 the gateways
    assert topo_ctr.get("exchange.ring.ici_bytes", 0) > 0
    assert topo_ctr.get("exchange.ring.dcn_bytes", 0) > 0
    profiling.reset_counters("exchange.ring")


# -- telemetry rollup ---------------------------------------------------------


def test_link_totals_and_prometheus_family(monkeypatch):
    """The per-link rollup reaches export_metrics()['gauges'] and renders
    as the srml_exchange_bytes{link=ici|dcn} Prometheus family."""
    mesh = _mesh(8)
    hier = topology.TopologyMap(
        groups=((0, 1, 2, 3), (4, 5, 6, 7)), source="env"
    )
    before = link_totals()
    _apply_collective(mesh, hier, "gather_stack", np.ones((64, 4), np.float32))
    after = link_totals()
    assert after["ici"] > before["ici"] and after["dcn"] > before["dcn"]
    gauges = profiling.export_metrics()["gauges"]
    assert gauges["exchange.link.ici_bytes"] == float(after["ici"])
    assert gauges["exchange.link.dcn_bytes"] == float(after["dcn"])
    prom = profiling.render_prometheus()
    assert "# TYPE srml_exchange_bytes gauge" in prom
    assert f'srml_exchange_bytes{{link="ici"}} {float(after["ici"])}' in prom
    assert f'srml_exchange_bytes{{link="dcn"}} {float(after["dcn"])}' in prom
    profiling.reset_counters("exchange.topo_test.")
