#
# Multi-controller execution tests: the done-criterion for the distributed
# product path (VERDICT round 1, item 1).  Two real OS processes — stand-ins
# for Spark barrier tasks — each with 4 virtual CPU devices, bootstrap
# jax.distributed through TpuContext over a FileControlPlane, build ONE
# global 8-device mesh, and fit KMeans / PCA / LinearRegression through the
# exact same jitted solvers as single-controller mode.  The resulting models
# must match a single-process 8-device fit of the same data numerically.
#
# The reference's equivalent surface is the barrier fit UDF + NCCL bootstrap
# (core.py:488-640, cuml_context.py:75-147), which it can only test on a live
# Spark cluster; the process-level harness here needs no Spark.
#

import json
import os
import subprocess
import sys

import numpy as np
import pytest

# the 2-process jax.distributed fits cost minutes of setup; full coverage
# stays behind --runslow (default CI budget: VERDICT r2 weak-item 7)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from spark_rapids_ml_tpu import (  # noqa: E402
    KMeans,
    LinearRegression,
    LogisticRegression,
    PCA,
    RandomForestClassifier,
    RandomForestRegressor,
)
from spark_rapids_ml_tpu.dataframe import DataFrame  # noqa: E402

NRANKS = 2
DEVS_PER_RANK = 4
N, D = 4096, 12  # divisible by 8 so single- and multi-controller layouts match


def _make_data():
    rng = np.random.default_rng(7)
    X = rng.standard_normal((N, D)).astype(np.float32)
    # decaying per-feature scales: a well-separated spectrum keeps the PCA
    # eigenvectors well-conditioned, so cross-process reduction-order noise
    # (gloo vs in-process collectives) cannot swing them
    X *= (1.25 ** -np.arange(D, dtype=np.float32))[None, :]
    X[: N // 2] += 3.0  # two lumps so KMeans has structure
    true_w = rng.standard_normal(D).astype(np.float32)
    y = (X @ true_w + 0.1 * rng.standard_normal(N)).astype(np.float32)
    # classification labels over the same features: binary by the margin
    # sign, 3-class by margin terciles (deliberately NOT contiguous from 0
    # to exercise class discovery, reference classification.py:936-1001)
    margin = X @ true_w
    y_bin = (margin > 0).astype(np.float32)
    y_multi = (
        np.digitize(margin, np.quantile(margin, [1 / 3, 2 / 3])) * 2.0 + 1.0
    ).astype(np.float32)
    return X, y, y_bin, y_multi


def _estimators():
    return {
        "kmeans": KMeans(k=4, maxIter=15, seed=11),
        "pca": PCA(k=3),
        "linreg": LinearRegression(),
        "ridge": LinearRegression(regParam=0.05),
        # round-3 additions: the two families whose fits previously gated
        # multi-process training (VERDICT round 2, item 1).  Both logreg
        # arms are L2-regularized: y_bin is perfectly separable, so the
        # unregularized optimum is at infinity and the coefficient norm
        # would depend on the stopping point, not the data
        "logreg_bin": LogisticRegression(
            maxIter=60, regParam=0.01, labelCol="y_bin"
        ),
        "logreg_multi": LogisticRegression(
            maxIter=60, regParam=0.01, labelCol="y_multi"
        ),
        "rf_clf": RandomForestClassifier(
            numTrees=8, maxDepth=4, maxBins=16, seed=3, labelCol="y_multi"
        ),
        "rf_reg": RandomForestRegressor(
            numTrees=8, maxDepth=4, maxBins=16, seed=3
        ),
    }


def _worker_env(devs_per_rank: int = DEVS_PER_RANK, plane: str = "file"):
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devs_per_rank}"
    env["PYTHONPATH"] = REPO
    # which control plane the workers rendezvous over (srml-wire: the SAME
    # matrix must pass on the TCP plane with bitwise-equal results)
    env["SRML_CP"] = plane
    return env


# one fit-matrix run per control plane, cached so the per-plane fixture
# params and the cross-plane bitwise gate share the two expensive runs
_MATRIX_CACHE: dict = {}
_BASELINE_CACHE: dict = {}


def _matrix_payload(tmp_path_factory, plane: str):
    if plane in _MATRIX_CACHE:
        return _MATRIX_CACHE[plane]
    root = str(tmp_path_factory.mktemp(f"mcjob_{plane}"))
    X, y, y_bin, y_multi = _make_data()
    halves = np.array_split(np.arange(N), NRANKS)
    for r, idx in enumerate(halves):
        np.savez(
            os.path.join(root, f"shard_{r}.npz"),
            X=X[idx], y=y[idx], y_bin=y_bin[idx], y_multi=y_multi[idx],
        )

    ests = _estimators()
    with open(os.path.join(root, "estimators.json"), "w") as f:
        json.dump(list(ests.keys()), f)
    for name, est in ests.items():
        est.save(os.path.join(root, f"est_{name}"))

    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "mc_worker.py"),
             str(r), str(NRANKS), root],
            env=_worker_env(plane=plane),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(NRANKS)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"[{plane}] rank {r} failed:\n{out}"

    with open(os.path.join(root, "attrs.json")) as f:
        payload = json.load(f)
    _MATRIX_CACHE[plane] = payload
    return payload


def _baselines():
    """Single-controller baseline on the identical global dataset (the main
    pytest process runs an 8-device CPU mesh via conftest); cached across
    the plane params."""
    if _BASELINE_CACHE:
        return _BASELINE_CACHE["models"]
    import pandas as pd

    X, y, y_bin, y_multi = _make_data()
    pdf = pd.DataFrame(
        {"features": list(X), "label": y, "y_bin": y_bin, "y_multi": y_multi}
    )
    df = DataFrame.from_pandas(pdf, num_partitions=NRANKS)
    _BASELINE_CACHE["models"] = {
        name: est.fit(df) for name, est in _estimators().items()
    }
    return _BASELINE_CACHE["models"]


@pytest.fixture(scope="module", params=["file", "tcp"])
def multicontroller_attrs(request, tmp_path_factory):
    """The 2-process fit matrix attrs + single-controller baselines — run
    once per CONTROL PLANE (file, then srml-wire tcp), so every numeric
    gate below holds verbatim over the socket plane."""
    return _matrix_payload(tmp_path_factory, request.param), _baselines()


def test_fit_matrix_bitwise_equal_across_planes(tmp_path_factory):
    """srml-wire acceptance: the full fit matrix on SRML_CP=tcp produces
    BITWISE-equal model attributes vs the file plane — the plane carries
    rendezvous metadata only, it must never touch the math."""
    pf = _matrix_payload(tmp_path_factory, "file")
    pt = _matrix_payload(tmp_path_factory, "tcp")
    assert set(pf["results"]) == set(pt["results"])
    for name in sorted(pf["results"]):
        a, b = _decoded(pf, name), _decoded(pt, name)
        assert set(a) == set(b), (name, set(a) ^ set(b))
        for key in sorted(a):
            va, vb = np.asarray(a[key]), np.asarray(b[key])
            assert va.shape == vb.shape and va.dtype == vb.dtype, (name, key)
            np.testing.assert_array_equal(
                va, vb,
                err_msg=f"{name}.{key} drifted between file and tcp planes",
            )


def test_global_mesh_spans_both_processes(multicontroller_attrs):
    payload, _ = multicontroller_attrs
    meta = payload["meta"]
    assert meta["device_count"] == NRANKS * DEVS_PER_RANK
    assert meta["local_device_count"] == DEVS_PER_RANK


def _decoded(payload, name):
    from spark_rapids_ml_tpu.core import TELEMETRY_ATTR
    from spark_rapids_ml_tpu.parallel.runner import decode_attrs

    results = payload["results"][name]
    assert len(results) == 1
    attrs = decode_attrs(results[0])
    # the merged telemetry snapshot rides the attribute wire; production
    # (core._fit_internal) pops it before model construction — tests that
    # feed attrs straight to _create_model must do the same
    attrs.pop(TELEMETRY_ATTR, None)
    return attrs


def test_kmeans_matches_single_controller(multicontroller_attrs):
    payload, baselines = multicontroller_attrs
    attrs = _decoded(payload, "kmeans")
    np.testing.assert_allclose(
        attrs["cluster_centers_"],
        np.asarray(baselines["kmeans"].cluster_centers_),
        rtol=1e-5, atol=1e-5,
    )


def test_pca_matches_single_controller(multicontroller_attrs):
    payload, baselines = multicontroller_attrs
    attrs = _decoded(payload, "pca")
    b = baselines["pca"]
    np.testing.assert_allclose(attrs["mean_"], np.asarray(b.mean_), atol=1e-5)
    # components tolerate reduction-order noise between the gloo
    # (cross-process) and in-process collective implementations
    np.testing.assert_allclose(
        attrs["components_"], np.asarray(b.components_), atol=1e-4
    )
    np.testing.assert_allclose(
        attrs["explained_variance_"],
        np.asarray(b.explained_variance_),
        rtol=1e-4,
    )


@pytest.mark.parametrize("name", ["linreg", "ridge"])
def test_linear_regression_matches_single_controller(multicontroller_attrs, name):
    payload, baselines = multicontroller_attrs
    attrs = _decoded(payload, name)
    b = baselines[name]
    # f32 normal equations amplify cross-process reduction-order noise by
    # the (mild) condition number; observed deltas are ~4e-5 relative
    np.testing.assert_allclose(
        attrs["coef_"], np.asarray(b.coef_), rtol=2e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        attrs["intercept_"], np.asarray(b.intercept_), rtol=2e-4, atol=1e-4
    )


@pytest.mark.parametrize("name", ["logreg_bin", "logreg_multi"])
def test_logistic_regression_matches_single_controller(
    multicontroller_attrs, name
):
    """LogReg across 2 OS processes (round-3 capability: VERDICT item 1).
    Class discovery runs per-rank + control-plane union; the L-BFGS loop
    accumulates cross-process reduction-order noise over its iterations,
    hence looser tolerances than the closed-form solvers."""
    payload, baselines = multicontroller_attrs
    attrs = _decoded(payload, name)
    b = baselines[name]
    np.testing.assert_array_equal(attrs["classes_"], np.asarray(b.classes_))
    # tolerances widened for the REAL cross-process path: gloo collectives
    # (compat.ensure_cpu_collectives) order reductions differently than the
    # in-process collectives these were first tuned on, and L-BFGS
    # compounds the noise over its iterations (observed max |Δcoef| ~0.012
    # on O(1) coefficients)
    np.testing.assert_allclose(
        attrs["coef_"], np.asarray(b.coef_), rtol=2e-2, atol=2e-2
    )
    np.testing.assert_allclose(
        attrs["intercept_"], np.asarray(b.intercept_), rtol=2e-2, atol=2e-2
    )


def test_rf_classifier_matches_single_controller(multicontroller_attrs):
    """RandomForestClassifier across 2 OS processes: identical bin edges by
    construction (per-shard strided sample + rank-ordered gather); split
    decisions may flip only on float-tie reduction noise, so agreement is
    asserted at the prediction level."""
    payload, baselines = multicontroller_attrs
    est = RandomForestClassifier(
        numTrees=8, maxDepth=4, maxBins=16, seed=3, labelCol="y_multi"
    )
    model = est._create_model(_decoded(payload, "rf_clf"))
    est._copyValues(model)
    b = baselines["rf_clf"]
    np.testing.assert_array_equal(model.classes_, b.classes_)
    X, _, _, y_multi = _make_data()
    df = DataFrame.from_numpy(X)
    p_mc = model.transform(df).toPandas()["prediction"].to_numpy(np.float64)
    p_sc = b.transform(df).toPandas()["prediction"].to_numpy(np.float64)
    assert (p_mc == p_sc).mean() >= 0.98
    assert (p_mc == y_multi).mean() >= 0.70  # and the model is actually good


def test_rf_regressor_matches_single_controller(multicontroller_attrs):
    payload, baselines = multicontroller_attrs
    est = RandomForestRegressor(numTrees=8, maxDepth=4, maxBins=16, seed=3)
    model = est._create_model(_decoded(payload, "rf_reg"))
    est._copyValues(model)
    b = baselines["rf_reg"]
    X, y, _, _ = _make_data()
    df = DataFrame.from_numpy(X)
    p_mc = model.transform(df).toPandas()["prediction"].to_numpy(np.float64)
    p_sc = b.transform(df).toPandas()["prediction"].to_numpy(np.float64)
    resid = p_mc - p_sc
    assert float(np.sqrt((resid**2).mean())) < 0.05 * float(p_sc.std())


def test_model_rebuilt_from_barrier_attrs_transforms(multicontroller_attrs):
    """Driver-side model construction from the gathered attrs (what
    barrier_fit_estimator hands to _create_model) predicts sensibly."""
    payload, baselines = multicontroller_attrs
    attrs = _decoded(payload, "linreg")
    est = LinearRegression()
    model = est._create_model(attrs)
    est._copyValues(model)
    X, y, _, _ = _make_data()
    preds = model.transform(DataFrame.from_numpy(X)).toPandas()["prediction"]
    resid = np.asarray(preds, dtype=np.float64) - y
    assert float(np.sqrt((resid**2).mean())) < 0.2


def test_empty_rank_joins_fit(tmp_path):
    """Fewer rows than ranks on one side: rank 1 holds ZERO rows but must
    still join every gather (bailing out would hang the barrier) and the fit
    must match a single-controller fit of the same rows."""
    root = str(tmp_path)
    rng = np.random.default_rng(5)
    X = rng.standard_normal((96, 5)).astype(np.float32)
    y = (X @ np.ones(5, np.float32)).astype(np.float32)
    np.savez(os.path.join(root, "shard_0.npz"), X=X, y=y)
    np.savez(
        os.path.join(root, "shard_1.npz"),
        X=np.zeros((0, 5), np.float32),
        y=np.zeros(0, np.float32),
    )
    LinearRegression().save(os.path.join(root, "est_lr"))
    with open(os.path.join(root, "estimators.json"), "w") as f:
        json.dump(["lr"], f)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "mc_worker.py"),
             str(r), str(NRANKS), root],
            env=_worker_env(),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for r in range(NRANKS)
    ]
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=600)
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
    from spark_rapids_ml_tpu.parallel.runner import decode_attrs

    with open(os.path.join(root, "attrs.json")) as f:
        attrs = decode_attrs(json.load(f)["results"]["lr"][0])
    b = LinearRegression().fit(DataFrame.from_numpy(X, y))
    np.testing.assert_allclose(
        attrs["coef_"], np.asarray(b.coef_), rtol=2e-4, atol=1e-4
    )


@pytest.mark.parametrize("nranks", [3, 4])
def test_fit_parity_at_three_plus_ranks(tmp_path, nranks):
    """3- and 4-process fit parity (ISSUE 10 satellite, VERDICT weak #6):
    rank-indexing bugs in the gather/exchange framing are invisible at
    nranks=2, where "my rank" and "the other rank" are the only cases.
    Deliberately UNEVEN partitions with the LAST rank empty, so padded
    shares, rank_rows derivations, and the empty-rank join all run at odd
    rank counts.  2 virtual devices per rank keeps the matrix affordable."""
    root = str(tmp_path)
    rng = np.random.default_rng(29)
    n, d = 768, 6
    X = rng.standard_normal((n, d)).astype(np.float32)
    X[: n // 3] += 2.5  # structure for kmeans
    y = (X @ np.arange(1.0, d + 1.0, dtype=np.float32)
         + 0.05 * rng.standard_normal(n).astype(np.float32))
    # uneven splits, last rank EMPTY: 3 ranks -> [499, 269, 0],
    # 4 ranks -> [384, 307, 77, 0]
    bounds = sorted(set([0, int(0.65 * n), n] if nranks == 3
                        else [0, int(0.5 * n), int(0.9 * n), n]))
    shards = [np.arange(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)]
    shards.append(np.arange(0))  # the empty rank
    assert len(shards) == nranks
    for r, idx in enumerate(shards):
        np.savez(os.path.join(root, f"shard_{r}.npz"), X=X[idx], y=y[idx])
    ests = {
        "kmeans": KMeans(k=3, maxIter=12, seed=5),
        "linreg": LinearRegression(),
    }
    with open(os.path.join(root, "estimators.json"), "w") as f:
        json.dump(list(ests.keys()), f)
    for name, est in ests.items():
        est.save(os.path.join(root, f"est_{name}"))
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "mc_worker.py"),
             str(r), str(nranks), root],
            env=_worker_env(devs_per_rank=2),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for r in range(nranks)
    ]
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=600)
        assert p.returncode == 0, f"rank {r}/{nranks} failed:\n{out}"

    with open(os.path.join(root, "attrs.json")) as f:
        payload = json.load(f)
    assert payload["meta"]["device_count"] == nranks * 2

    import pandas as pd

    pdf = pd.DataFrame({"features": list(X), "label": y})
    df = DataFrame.from_pandas(pdf, num_partitions=nranks)
    for name, est in {
        "kmeans": KMeans(k=3, maxIter=12, seed=5),
        "linreg": LinearRegression(),
    }.items():
        b = est.fit(df)
        attrs = _decoded(payload, name)
        if name == "kmeans":
            # center-exact parity needs IDENTICAL padded layouts (the
            # 2-rank gate engineers N divisible by 8, equal halves); with
            # uneven partitions + an empty rank the k-means|| Gumbel pool
            # draws over a different padded length, so the gate here is
            # CLUSTERING QUALITY: the multi-controller fit must converge
            # to an optimum as good as the single-controller one
            sc = float(np.asarray(b.inertia_))
            mc = float(np.asarray(attrs["inertia_"]))
            assert mc <= sc * 1.05, (
                f"nranks={nranks}: multi-controller kmeans inertia {mc:.1f} "
                f"is worse than single-controller {sc:.1f} by > 5%"
            )
            assert attrs["cluster_centers_"].shape == (3, d)
        else:
            np.testing.assert_allclose(
                attrs["coef_"], np.asarray(b.coef_), rtol=2e-4, atol=2e-4,
                err_msg=f"linreg coef diverged at nranks={nranks}",
            )
            np.testing.assert_allclose(
                attrs["intercept_"], np.asarray(b.intercept_),
                rtol=2e-4, atol=2e-4,
            )


@pytest.mark.parametrize("nranks", [3, 4])
def test_kneighbors_multirank_uneven_and_empty_rank(tmp_path, nranks):
    """distributed_kneighbors at 3 and 4 ranks with UNEVEN query/item
    partitions and the last rank holding ZERO rows of both — the exchange
    framing (ring rotation arithmetic, alltoall slicing) must stay exact
    when "previous rank" wraps through an empty one."""
    from spark_rapids_ml_tpu.ops.knn import knn_search
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    root = str(tmp_path)
    rng = np.random.default_rng(31 + nranks)
    n_items, n_query, d, k = 520, 72, 9, 7
    items = rng.standard_normal((n_items, d)).astype(np.float32)
    queries = rng.standard_normal((n_query, d)).astype(np.float32)
    item_ids = rng.permutation(n_items).astype(np.int64) * 3
    # uneven, last rank empty on BOTH sides
    q_bounds = np.linspace(0, n_query, nranks, dtype=int)
    i_bounds = (np.linspace(0, np.sqrt(n_items), nranks) ** 2).astype(int)
    i_bounds[-1] = n_items
    query_rows = [
        np.arange(q_bounds[r], q_bounds[r + 1]) if r < nranks - 1 else
        np.arange(0)
        for r in range(nranks)
    ]
    query_rows[nranks - 2] = np.arange(q_bounds[nranks - 2], n_query)
    item_rows = [
        np.arange(i_bounds[r], i_bounds[r + 1]) if r < nranks - 1 else
        np.arange(0)
        for r in range(nranks)
    ]
    item_rows[nranks - 2] = np.arange(i_bounds[nranks - 2], n_items)
    assert sum(len(q) for q in query_rows) == n_query
    assert sum(len(i) for i in item_rows) == n_items
    assert len(query_rows[-1]) == 0 and len(item_rows[-1]) == 0
    for r in range(nranks):
        np.savez(
            os.path.join(root, f"knn_shard_{r}.npz"),
            item_X=items[item_rows[r]], item_id=item_ids[item_rows[r]],
            q_X=queries[query_rows[r]],
            q_id=query_rows[r].astype(np.int64),
        )
    with open(os.path.join(root, "knn_job.json"), "w") as f:
        json.dump({"k": k}, f)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "knn_mc_worker.py"),
             str(r), str(nranks), root],
            env=_worker_env(devs_per_rank=2),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for r in range(nranks)
    ]
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=600)
        assert p.returncode == 0, f"rank {r}/{nranks} failed:\n{out}"

    d_mc = np.zeros((n_query, k), np.float32)
    i_mc = np.zeros((n_query, k), np.int64)
    for r in range(nranks):
        if len(query_rows[r]) == 0:
            continue
        got = np.load(os.path.join(root, f"knn_out_{r}.npz"))
        d_mc[query_rows[r]] = got["d"]
        i_mc[query_rows[r]] = got["i"]
    d_sc, i_sc = knn_search(items, item_ids, queries, k, get_mesh(None))
    np.testing.assert_allclose(d_mc, d_sc, rtol=1e-5, atol=1e-6)
    assert (i_mc == i_sc).mean() > 0.99  # ids may swap only on exact ties


@pytest.mark.parametrize("plane", ["file", "tcp"])
def test_kneighbors_across_processes_matches_single_controller(tmp_path, plane):
    """distributed_kneighbors over 2 OS processes (VERDICT round 3, item 1):
    item rows stay in their owning process, query blocks + candidate lists
    ride the control plane — the FileControlPlane AND the srml-wire TCP
    plane (the kneighbors protocol is pure control-plane traffic, so the
    plane swap exercises every binary-gather path) — and the merged result
    must equal a single-process knn_search over the concatenated item
    set."""
    from spark_rapids_ml_tpu.ops.knn import knn_search
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    root = str(tmp_path)
    rng = np.random.default_rng(13)
    n_items, n_query, d, k = 700, 96, 10, 9
    items = rng.standard_normal((n_items, d)).astype(np.float32)
    queries = rng.standard_normal((n_query, d)).astype(np.float32)
    item_ids = rng.permutation(n_items).astype(np.int64) * 5  # non-trivial ids
    query_rows = np.array_split(np.arange(n_query), NRANKS)
    item_rows = np.array_split(np.arange(n_items), NRANKS)
    for r in range(NRANKS):
        np.savez(
            os.path.join(root, f"knn_shard_{r}.npz"),
            item_X=items[item_rows[r]], item_id=item_ids[item_rows[r]],
            q_X=queries[query_rows[r]],
            q_id=query_rows[r].astype(np.int64),
        )
    with open(os.path.join(root, "knn_job.json"), "w") as f:
        json.dump({"k": k}, f)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "knn_mc_worker.py"),
             str(r), str(NRANKS), root],
            env=_worker_env(plane=plane),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for r in range(NRANKS)
    ]
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=600)
        assert p.returncode == 0, f"[{plane}] rank {r} failed:\n{out}"

    d_mc = np.zeros((n_query, k), np.float32)
    i_mc = np.zeros((n_query, k), np.int64)
    for r in range(NRANKS):
        got = np.load(os.path.join(root, f"knn_out_{r}.npz"))
        d_mc[query_rows[r]] = got["d"]
        i_mc[query_rows[r]] = got["i"]
    d_sc, i_sc = knn_search(items, item_ids, queries, k, get_mesh(None))
    np.testing.assert_allclose(d_mc, d_sc, rtol=1e-5, atol=1e-6)
    assert (i_mc == i_sc).mean() > 0.99  # ids may swap only on exact ties


def _knn_4proc_run(root, env_extra, n_items=520, n_query=64, d=9, k=7):
    """4-process distributed_kneighbors over even partitions; returns the
    merged (d, i) plus the inputs so callers can gate vs sklearn."""
    nranks = 4
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(41)
    items = rng.standard_normal((n_items, d)).astype(np.float32)
    queries = rng.standard_normal((n_query, d)).astype(np.float32)
    item_ids = rng.permutation(n_items).astype(np.int64) * 3
    query_rows = np.array_split(np.arange(n_query), nranks)
    item_rows = np.array_split(np.arange(n_items), nranks)
    for r in range(nranks):
        np.savez(
            os.path.join(root, f"knn_shard_{r}.npz"),
            item_X=items[item_rows[r]], item_id=item_ids[item_rows[r]],
            q_X=queries[query_rows[r]],
            q_id=query_rows[r].astype(np.int64),
        )
    with open(os.path.join(root, "knn_job.json"), "w") as f:
        json.dump({"k": k}, f)
    env = _worker_env(devs_per_rank=2)
    env.update(env_extra)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "knn_mc_worker.py"),
             str(r), str(nranks), root],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(nranks)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<TIMEOUT: killed by driver>"
        outs.append(out)
    d_mc = np.zeros((n_query, k), np.float32)
    i_mc = np.zeros((n_query, k), np.int64)
    done = all(p.returncode == 0 for p in procs)
    if done:
        for r in range(nranks):
            got = np.load(os.path.join(root, f"knn_out_{r}.npz"))
            d_mc[query_rows[r]] = got["d"]
            i_mc[query_rows[r]] = got["i"]
    return procs, outs, d_mc, i_mc, items, item_ids, queries


def test_kneighbors_topology_ring_bitwise_vs_flat_and_sklearn(tmp_path):
    """srml-topo acceptance (satellite): the 4-process ring under
    SRML_TOPO=2:2 (two simulated hosts of two ranks) returns BITWISE the
    same results as the topology-oblivious flat run — the cycle checksum
    agreed in the metadata round only reorders hops, and the traveling
    lex merges are visit-order independent — and both match sklearn."""
    from sklearn.neighbors import NearestNeighbors as SkNN

    base = {"SRML_KNN_EXCHANGE": "ring"}
    pf, of, d_flat, i_flat, items, ids, queries = _knn_4proc_run(
        str(tmp_path / "flat"), base
    )
    for r, (p, out) in enumerate(zip(pf, of)):
        assert p.returncode == 0, f"[flat] rank {r} failed:\n{out}"
    pt, ot, d_topo, i_topo, _, _, _ = _knn_4proc_run(
        str(tmp_path / "topo"), dict(base, SRML_TOPO="2:2")
    )
    for r, (p, out) in enumerate(zip(pt, ot)):
        assert p.returncode == 0, f"[2:2] rank {r} failed:\n{out}"
    np.testing.assert_array_equal(d_topo, d_flat)
    np.testing.assert_array_equal(i_topo, i_flat)
    sk_d, sk_i = SkNN(n_neighbors=7, algorithm="brute").fit(
        items
    ).kneighbors(queries)
    np.testing.assert_allclose(d_topo, sk_d, rtol=1e-4, atol=1e-4)
    assert (i_topo == ids[sk_i]).mean() > 0.99


def test_chaos_gateway_rank_death_hierarchical_ring(tmp_path):
    """Chaos arm of the hierarchical route: under SRML_TOPO=2:2, rank 2 —
    the GATEWAY of the second simulated host — dies mid-ring (knn.ring_hop
    fault site, die at its 2nd hop).  Every survivor must surface a typed
    RemoteRankError naming rank 2 within the dead-peer bound, never hang
    to the driver timeout."""
    import time as _time

    from spark_rapids_ml_tpu.parallel.faults import DIE_EXIT_CODE

    root = str(tmp_path)
    t0 = _time.monotonic()
    procs, outs, *_ = _knn_4proc_run(
        root,
        {
            "SRML_KNN_EXCHANGE": "ring",
            "SRML_TOPO": "2:2",
            "SRML_FAULTS": "knn.ring_hop:rank=2:call=2:action=die",
        },
    )
    wall = _time.monotonic() - t0
    assert procs[2].returncode == DIE_EXIT_CODE, outs[2]
    for r in (0, 1, 3):
        assert procs[r].returncode not in (0, None), (r, outs[r])
        assert "<TIMEOUT" not in outs[r], f"rank {r} hung:\n{outs[r]}"
        assert "RemoteRankError" in outs[r] and "rank 2" in outs[r], outs[r]
    assert wall < 120.0, f"cohort wind-down took {wall:.0f}s"


@pytest.mark.parametrize("plane", ["file", "tcp"])
def test_killed_rank_mid_fit_surfaces_typed_and_bounded(tmp_path, plane):
    """Chaos over a REAL jax.distributed session (the gap the srml-wire
    verify drive exposed): rank 1 dies mid-fit (action=die at its 2nd
    gather — after the jax.distributed bootstrap, before the solve).  The
    survivor must (a) raise RemoteRankError NAMING rank 1, and (b) have
    its whole teardown complete in bounded wall time — the stock jax
    coordination heartbeats (10 s x 10) let the survivor dangle ~100 s in
    the collective shutdown barrier and then LOG(FATAL) the process,
    eating the typed error.  Fixed by the abort-path shutdown skip
    (TpuContext.__exit__) + tightened heartbeats
    (compat.distributed_initialize)."""
    import time

    root = str(tmp_path)
    rng = np.random.default_rng(3)
    X = rng.standard_normal((128, 4)).astype(np.float32)
    y = (X @ np.ones(4, np.float32)).astype(np.float32)
    for r, idx in enumerate(np.array_split(np.arange(128), NRANKS)):
        np.savez(os.path.join(root, f"shard_{r}.npz"), X=X[idx], y=y[idx])
    LinearRegression().save(os.path.join(root, "est_lr"))
    with open(os.path.join(root, "estimators.json"), "w") as f:
        json.dump(["lr"], f)
    env = _worker_env(devs_per_rank=2, plane=plane)
    env["SRML_FAULTS"] = "cp.gather:rank=1:call=2:action=die"
    t0 = time.monotonic()
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "mc_worker.py"),
             str(r), str(NRANKS), root],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(NRANKS)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=90)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<TIMEOUT: killed by driver>"
        outs.append(out)
    wall = time.monotonic() - t0
    from spark_rapids_ml_tpu.parallel.faults import DIE_EXIT_CODE

    assert procs[1].returncode == DIE_EXIT_CODE, outs[1]
    assert procs[0].returncode not in (0, None), outs[0]
    assert "RemoteRankError" in outs[0] and "rank 1" in outs[0], outs[0]
    assert "<TIMEOUT" not in outs[0], "survivor teardown dangled"
    assert wall < 60.0, (
        f"[{plane}] cohort took {wall:.0f}s to wind down — the jax-layer "
        "teardown tail is unbounded again"
    )


def test_allgather_bytes_chunks_over_frame_limit(tmp_path):
    """exchange.allgather_bytes must reassemble payloads wider than the
    per-message chunk, with ragged per-rank sizes (rank 1 sends a short
    message), over the FileControlPlane's native-bytes path."""
    import threading

    from spark_rapids_ml_tpu.parallel.exchange import allgather_bytes
    from spark_rapids_ml_tpu.parallel.runner import FileControlPlane

    payloads = {0: b"a" * 2500, 1: b"b" * 3, 2: b"c" * 7001}
    results = {}

    def run(rank):
        cp = FileControlPlane(str(tmp_path / "cp"), rank, 3, timeout=30)
        results[rank] = allgather_bytes(cp, payloads[rank], chunk=1000)

    threads = [threading.Thread(target=run, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for rank in range(3):
        assert results[rank] == [payloads[0], payloads[1], payloads[2]]


def test_partition_descriptor_gather_over_file_control_plane(tmp_path):
    """PartitionDescriptor.gather exchanges per-rank sizes like the
    reference's allGather (utils.py:178-196) — driven here with threads over
    the same FileControlPlane the workers use."""
    import threading

    from spark_rapids_ml_tpu.parallel.partition import PartitionDescriptor
    from spark_rapids_ml_tpu.parallel.runner import FileControlPlane

    results = {}

    def run(rank, rows, n_cols):
        cp = FileControlPlane(str(tmp_path / "cp"), rank, 3, timeout=30)
        results[rank] = PartitionDescriptor.gather(rows, n_cols, rank, 3, cp)

    threads = [
        threading.Thread(target=run, args=(0, [5, 2], 4)),
        threading.Thread(target=run, args=(1, [7], 4)),
        threading.Thread(target=run, args=(2, [], 0)),  # empty rank
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for rank, pdesc in results.items():
        assert pdesc.m == 14 and pdesc.n == 4 and pdesc.rank == rank
        assert pdesc.parts_rank_size == [(0, 5), (0, 2), (1, 7)]
        assert pdesc.rank_rows(0) == 7 and pdesc.rank_rows(1) == 7
        assert pdesc.rank_rows(2) == 0
