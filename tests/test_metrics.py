#
# Metrics tests (reference python/tests/test_metrics.py): MulticlassMetrics
# and RegressionMetrics checked against sklearn ground truth, plus the
# mergeability property the distributed evaluate path depends on — metrics
# from per-partition partials must equal metrics from the whole array.
#

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_rapids_ml_tpu.evaluation import (  # noqa: E402
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.metrics import (  # noqa: E402
    MulticlassMetrics,
    RegressionMetrics,
    log_loss,
)


@pytest.fixture
def cls_data():
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, size=500).astype(np.float64)
    preds = labels.copy()
    flip = rng.random(500) < 0.3  # 30% errors
    preds[flip] = rng.integers(0, 4, size=int(flip.sum())).astype(np.float64)
    probs = rng.dirichlet(np.ones(4), size=500)
    # make probs consistent-ish with preds
    probs[np.arange(500), preds.astype(int)] += 1.0
    probs /= probs.sum(axis=1, keepdims=True)
    return labels, preds, probs


@pytest.fixture
def reg_data():
    rng = np.random.default_rng(1)
    labels = rng.standard_normal(400) * 3.0 + 1.0
    preds = labels + rng.standard_normal(400) * 0.7
    return labels, preds


class TestMulticlassMetrics:
    def test_against_sklearn(self, cls_data):
        from sklearn.metrics import (
            accuracy_score,
            f1_score,
            precision_score,
            recall_score,
        )

        labels, preds, probs = cls_data
        m = MulticlassMetrics.from_arrays(labels, preds, probs=probs, eps=1e-15)
        assert m.accuracy() == pytest.approx(accuracy_score(labels, preds))
        assert m.weighted_fmeasure() == pytest.approx(
            f1_score(labels, preds, average="weighted")
        )
        assert m.weighted_precision() == pytest.approx(
            precision_score(labels, preds, average="weighted")
        )
        assert m.weighted_recall() == pytest.approx(
            recall_score(labels, preds, average="weighted")
        )
        assert m.hamming_loss() == pytest.approx(1.0 - accuracy_score(labels, preds))

    def test_log_loss_against_sklearn(self, cls_data):
        from sklearn.metrics import log_loss as sk_log_loss

        labels, _, probs = cls_data
        ours = log_loss(labels, probs, eps=1e-15)
        want = sk_log_loss(labels, probs, labels=[0.0, 1.0, 2.0, 3.0]) * len(labels)
        assert ours == pytest.approx(want, rel=1e-9)

    def test_merge_equals_whole(self, cls_data):
        labels, preds, probs = cls_data
        whole = MulticlassMetrics.from_arrays(labels, preds, probs=probs, eps=1e-15)
        partials = [
            MulticlassMetrics.from_arrays(
                labels[i::3], preds[i::3], probs=probs[i::3], eps=1e-15
            )
            for i in range(3)
        ]
        merged = partials[0].merge(partials[1]).merge(partials[2])
        assert merged.accuracy() == pytest.approx(whole.accuracy())
        assert merged.weighted_fmeasure() == pytest.approx(whole.weighted_fmeasure())
        assert merged.log_loss_metric() == pytest.approx(whole.log_loss_metric())

    def test_by_label_metrics(self, cls_data):
        from sklearn.metrics import precision_score, recall_score

        labels, preds, _ = cls_data
        m = MulticlassMetrics.from_arrays(labels, preds)
        assert m._precision(2.0) == pytest.approx(
            precision_score(labels, preds, labels=[2.0], average="macro")
        )
        assert m._recall(1.0) == pytest.approx(
            recall_score(labels, preds, labels=[1.0], average="macro")
        )

    def test_evaluator_routing(self, cls_data):
        labels, preds, probs = cls_data
        m = MulticlassMetrics.from_arrays(labels, preds, probs=probs, eps=1e-15)
        for name, want in [
            ("accuracy", m.accuracy()),
            ("f1", m.weighted_fmeasure()),
            ("weightedPrecision", m.weighted_precision()),
            ("weightedRecall", m.weighted_recall()),
            ("logLoss", m.log_loss_metric()),
            ("hammingLoss", m.hamming_loss()),
        ]:
            ev = MulticlassClassificationEvaluator(metricName=name)
            assert m.evaluate(ev) == pytest.approx(want)
        larger = MulticlassClassificationEvaluator(metricName="accuracy")
        assert larger.isLargerBetter()
        smaller = MulticlassClassificationEvaluator(metricName="logLoss")
        assert not smaller.isLargerBetter()


class TestRegressionMetrics:
    def test_against_sklearn(self, reg_data):
        from sklearn.metrics import (
            mean_absolute_error,
            mean_squared_error,
            r2_score,
        )

        labels, preds = reg_data
        m = RegressionMetrics.from_arrays(labels, preds)
        assert m.mean_squared_error == pytest.approx(mean_squared_error(labels, preds))
        assert m.root_mean_squared_error == pytest.approx(
            np.sqrt(mean_squared_error(labels, preds))
        )
        assert m.mean_absolute_error == pytest.approx(
            mean_absolute_error(labels, preds)
        )
        assert m.r2(through_origin=False) == pytest.approx(r2_score(labels, preds))

    def test_merge_equals_whole(self, reg_data):
        labels, preds = reg_data
        whole = RegressionMetrics.from_arrays(labels, preds)
        parts = [
            RegressionMetrics.from_arrays(labels[i::4], preds[i::4]) for i in range(4)
        ]
        merged = parts[0]
        for p in parts[1:]:
            merged = merged.merge(p)
        assert merged.mean_squared_error == pytest.approx(whole.mean_squared_error)
        assert merged.r2(False) == pytest.approx(whole.r2(False))
        assert merged.mean_absolute_error == pytest.approx(whole.mean_absolute_error)

    def test_evaluator_routing(self, reg_data):
        labels, preds = reg_data
        m = RegressionMetrics.from_arrays(labels, preds)
        for name, want in [
            ("rmse", m.root_mean_squared_error),
            ("mse", m.mean_squared_error),
            ("mae", m.mean_absolute_error),
            ("r2", m.r2(False)),
        ]:
            ev = RegressionEvaluator(metricName=name)
            assert m.evaluate(ev) == pytest.approx(want)
        assert not RegressionEvaluator(metricName="rmse").isLargerBetter()
        assert RegressionEvaluator(metricName="r2").isLargerBetter()

    def test_explained_variance(self, reg_data):
        labels, preds = reg_data
        m = RegressionMetrics.from_arrays(labels, preds)
        # Spark's explainedVariance = SSreg / n (not sklearn's
        # explained_variance_score); check against the direct formula
        want = np.sum((preds - labels.mean()) ** 2) / len(labels)
        assert m.explained_variance == pytest.approx(want, rel=1e-6)


class TestBinaryClassificationMetrics:
    """The round-5 VERDICT gap fix: AUC from mergeable per-partition
    threshold partials (metrics/binary.py) must equal the driver-local
    computation (sklearn on the whole array) metric-for-metric — ties,
    weights, merge order, and the JSON wire format included."""

    @pytest.fixture
    def bin_data(self):
        rng = np.random.default_rng(7)
        n = 3000
        labels = rng.integers(0, 2, size=n).astype(np.float64)
        # rounded scores: plenty of exact ties across partitions
        raw = np.round(rng.normal(size=n) + 1.2 * labels, 2)
        weights = rng.uniform(0.25, 4.0, size=n)
        return labels, raw, weights

    def test_partials_match_sklearn(self, bin_data):
        from sklearn.metrics import average_precision_score, roc_auc_score

        from spark_rapids_ml_tpu.metrics import BinaryClassificationMetrics

        labels, raw, weights = bin_data
        m = None
        for idx in np.array_split(np.arange(len(labels)), 9):
            p = BinaryClassificationMetrics.from_arrays(
                labels[idx], raw[idx], weights[idx]
            )
            m = p if m is None else m.merge(p)
        assert m.area_under_roc() == pytest.approx(
            roc_auc_score(labels, raw, sample_weight=weights), abs=1e-12
        )
        assert m.area_under_pr() == pytest.approx(
            average_precision_score(labels, raw, sample_weight=weights),
            abs=1e-12,
        )

    def test_json_wire_round_trip(self, bin_data):
        import json

        from spark_rapids_ml_tpu.metrics import BinaryClassificationMetrics

        labels, raw, weights = bin_data
        rows = []
        for idx in np.array_split(np.arange(len(labels)), 5):
            p = BinaryClassificationMetrics.from_arrays(
                labels[idx], raw[idx], weights[idx]
            )
            rows.append(json.loads(json.dumps(p.to_row(0))))
        merged = BinaryClassificationMetrics._from_rows(1, rows)[0]
        whole = BinaryClassificationMetrics.from_arrays(labels, raw, weights)
        assert merged.area_under_roc() == pytest.approx(
            whole.area_under_roc(), abs=1e-12
        )
        assert merged.area_under_pr() == pytest.approx(
            whole.area_under_pr(), abs=1e-12
        )

    def test_bin_cap_compresses_and_stays_close(self, bin_data):
        from sklearn.metrics import roc_auc_score

        from spark_rapids_ml_tpu.metrics import BinaryClassificationMetrics

        labels, _raw, _w = bin_data
        rng = np.random.default_rng(1)
        raw = rng.normal(size=len(labels)) + labels  # high-cardinality
        capped = BinaryClassificationMetrics.from_arrays(
            labels, raw, max_bins=256
        )
        assert capped.scores.size <= 256
        exact = roc_auc_score(labels, raw)
        # numBins-style downsampling: close, not exact (documented)
        assert capped.area_under_roc() == pytest.approx(exact, abs=0.01)

    def test_one_class_raises(self):
        from spark_rapids_ml_tpu.metrics import BinaryClassificationMetrics

        m = BinaryClassificationMetrics.from_arrays(
            np.ones(10), np.arange(10.0)
        )
        with pytest.raises(ValueError, match="one class"):
            m.area_under_roc()

    def test_evaluator_partition_merge_equals_driver_local(self, bin_data):
        """The evaluator gate: multi-partition facade evaluate (the same
        partial merge the executor route ships as JSON) == the driver-local
        whole-frame computation, for both metrics, with and without
        weightCol."""
        import pandas as pd
        from sklearn.metrics import average_precision_score, roc_auc_score

        from spark_rapids_ml_tpu.dataframe import DataFrame
        from spark_rapids_ml_tpu.evaluation import BinaryClassificationEvaluator

        labels, raw, weights = bin_data
        # rawPrediction as the usual [neg, pos] score arrays
        pdf = pd.DataFrame(
            {
                "label": labels,
                "rawPrediction": list(np.stack([-raw, raw], axis=1)),
                "w": weights,
            }
        )
        df = DataFrame.from_pandas(pdf, 6)
        for name, want in (
            ("areaUnderROC", roc_auc_score(labels, raw)),
            ("areaUnderPR", average_precision_score(labels, raw)),
        ):
            ev = BinaryClassificationEvaluator(metricName=name)
            assert ev.evaluate(df) == pytest.approx(want, abs=1e-12)
        ev_w = BinaryClassificationEvaluator()
        ev_w.set(ev_w.getParam("weightCol"), "w")
        assert ev_w.evaluate(df) == pytest.approx(
            roc_auc_score(labels, raw, sample_weight=weights), abs=1e-12
        )
        assert BinaryClassificationEvaluator().isLargerBetter()
