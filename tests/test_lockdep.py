# Runtime lockdep (sanitize.lockdep_lock): a crafted two-thread inversion
# must raise a typed LockOrderViolation carrying BOTH stacks, a clean
# serving smoke must record an acyclic order graph, and the disabled path
# must hand back the raw threading primitive with nothing registered (the
# zero-overhead span pattern).  The static R11 pass proves the graph it
# can SEE is acyclic; these tests prove the runtime half catches what
# actually executes.
import threading

import numpy as np
import pytest

from spark_rapids_ml_tpu import sanitize


@pytest.fixture
def lockdep(monkeypatch):
    """Arm lockdep (the 'lockdep' token: no debug_nans side effects) with
    a clean process-wide graph, and clean up after."""
    monkeypatch.setenv("SRML_SANITIZE", "lockdep")
    sanitize.lockdep_reset()
    yield
    sanitize.lockdep_reset()


# -- enablement ---------------------------------------------------------------

def test_lockdep_enabled_parsing(monkeypatch):
    for val, want in [
        ("0", False),
        ("1", True),
        ("lockdep", True),
        ("lockdep,other", True),
        ("other", False),
    ]:
        monkeypatch.setenv("SRML_SANITIZE", val)
        assert sanitize.lockdep_enabled() is want, val
    # the 'lockdep' token must NOT switch on the transfer-guard/NaN half
    monkeypatch.setenv("SRML_SANITIZE", "lockdep")
    assert not sanitize.enabled()


def test_disabled_path_allocates_nothing(monkeypatch):
    monkeypatch.setenv("SRML_SANITIZE", "0")
    sanitize.lockdep_reset()
    lock = sanitize.lockdep_lock("t.raw")
    assert isinstance(lock, type(threading.Lock()))
    rlock = sanitize.lockdep_lock("t.raw_r", factory=threading.RLock)
    assert isinstance(rlock, type(threading.RLock()))
    assert sanitize.lockdep_stats() == {
        "locks": 0, "edges": 0, "violations": 0,
    }
    assert sanitize.lockdep_graph() == {}


# -- the inversion ------------------------------------------------------------

def test_two_thread_inversion_raises_typed(lockdep):
    a = sanitize.lockdep_lock("t.A")
    b = sanitize.lockdep_lock("t.B")
    with a:
        with b:
            pass

    caught = []

    def reversed_order():
        try:
            with b:
                with a:
                    pass
        except sanitize.LockOrderViolation as e:
            caught.append(e)

    t = threading.Thread(target=reversed_order, name="lockdep-rev")
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    assert len(caught) == 1
    e = caught[0]
    assert e.held == "t.B" and e.acquiring == "t.A"
    # both lock names and BOTH stacks in the rendered message
    msg = str(e)
    assert "t.A" in msg and "t.B" in msg
    assert "this acquisition" in msg
    assert "first reverse-order acquisition" in msg
    assert "reversed_order" in e.current_stack
    assert "test_two_thread_inversion_raises_typed" in e.prior_stack
    assert e.prior_thread == "MainThread"
    assert sanitize.lockdep_stats()["violations"] == 1
    # the violating acquisition released its inner lock on the way out:
    # the forward order must still be freely usable
    with a:
        with b:
            pass


def test_violation_through_intermediate_lock(lockdep):
    a = sanitize.lockdep_lock("t.A")
    b = sanitize.lockdep_lock("t.B")
    c = sanitize.lockdep_lock("t.C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(sanitize.LockOrderViolation) as ei:
        with c:
            with a:
                pass
    assert ei.value.held == "t.C" and ei.value.acquiring == "t.A"


def test_clean_nesting_records_acyclic_graph(lockdep):
    a = sanitize.lockdep_lock("t.A")
    b = sanitize.lockdep_lock("t.B")
    c = sanitize.lockdep_lock("t.C")
    for _ in range(3):  # steady-state reacquisition adds no new edges
        with a:
            with b:
                with c:
                    pass
    stats = sanitize.lockdep_stats()
    assert stats["violations"] == 0
    assert stats["edges"] == 3  # A->B, A->C, B->C
    graph = sanitize.lockdep_graph()
    assert graph["t.A"] == ["t.B", "t.C"]
    assert graph["t.B"] == ["t.C"]


def test_same_name_nesting_is_reentrant_not_an_edge(lockdep):
    # class-level naming: two instances' locks share one node, and RLock
    # recursion on one instance is counted, never edged
    r = sanitize.lockdep_lock("t.R", factory=threading.RLock)
    with r:
        with r:
            pass
    assert sanitize.lockdep_graph() == {}
    assert sanitize.lockdep_stats()["violations"] == 0


def test_condition_over_proxy(lockdep):
    lock = sanitize.lockdep_lock("t.cond")
    cond = threading.Condition(lock)
    hits = []
    parked = threading.Event()

    def waiter():
        with cond:
            parked.set()  # set UNDER the lock: wait() releases it next
            if cond.wait(timeout=10):
                hits.append(1)

    t = threading.Thread(target=waiter, name="lockdep-waiter")
    t.start()
    parked.wait(timeout=10)
    # acquiring the lock here proves the waiter released it inside wait()
    with cond:
        cond.notify()
    t.join(timeout=10)
    assert not t.is_alive()
    assert hits == [1]
    assert sanitize.lockdep_stats()["violations"] == 0


def test_nonblocking_acquire_failure_records_nothing(lockdep):
    a = sanitize.lockdep_lock("t.A")
    b = sanitize.lockdep_lock("t.B")
    grabbed = threading.Event()
    release = threading.Event()

    def holder():
        with b:
            grabbed.set()
            release.wait(timeout=10)

    t = threading.Thread(target=holder, name="lockdep-holder")
    t.start()
    grabbed.wait(timeout=10)
    with a:
        assert b.acquire(blocking=False) is False
    release.set()
    t.join(timeout=10)
    # the failed acquire must not have recorded an A->B edge
    assert "t.A" not in sanitize.lockdep_graph()


# -- serving smoke ------------------------------------------------------------

def test_batcher_smoke_under_lockdep(lockdep):
    from spark_rapids_ml_tpu.serving.batcher import MicroBatcher, resolve_future

    batcher = MicroBatcher(
        n_cols=4, dtype=np.float32, counter_ns="serving.lockdep_smoke",
        max_batch=8, max_wait_ms=1.0, queue_depth=64,
    )
    # armed construction: the queue/done locks are lockdep proxies
    assert type(batcher._lock).__name__ == "_DepLock"

    futs = [batcher.submit(np.ones(4, dtype=np.float32)) for _ in range(6)]
    served = 0
    while served < 6:
        got = batcher.take()
        assert got is not None
        reqs, _reason = got
        for req in reqs:
            resolve_future(req.future, {"ok": np.zeros(1)})
            served += 1
    assert batcher.wait_quiescent(timeout_s=10)
    batcher.stop()

    stats = sanitize.lockdep_stats()
    assert stats["violations"] == 0
    # the recorded order graph is a DAG: no name reaches itself
    graph = sanitize.lockdep_graph()

    def reaches(src, dst, seen=None):
        seen = seen or set()
        for nxt in graph.get(src, []):
            if nxt == dst:
                return True
            if nxt not in seen:
                seen.add(nxt)
                if reaches(nxt, dst, seen):
                    return True
        return False

    for name in graph:
        assert not reaches(name, name), graph


def test_gauges_registered_when_armed(lockdep):
    from spark_rapids_ml_tpu import profiling

    sanitize.lockdep_lock("t.gauge")
    gauges = profiling.collect_gauges()
    assert gauges.get("lockdep.locks", 0.0) >= 1.0
    assert "lockdep.violations" in gauges
