# srml-wire gates (docs/robustness.md §wire plane), in ISSUE order:
#   - frame codec: length-prefixed binary frames fail LOUDLY on corruption
#     (magic, bounds, meta JSON) — never decode garbage
#   - pushed aborts: a blocked gather wakes in ~one RTT (≪ the file
#     plane's 50 ms poll floor), naming origin rank / etype / span
#   - leases: a member that falls silent (SIGKILL, wedge, partition) is
#     declared dead within the lease and every survivor's gather raises
#     RemoteRankError naming it
#   - session-epoch fencing: a zombie from a previous incarnation (stale
#     epoch, or any rejoin of a dead rank) is rejected with the typed
#     StaleEpochError — never silently readmitted
#   - coordinator loss: a dead/partitioned coordinator surfaces as the
#     typed CoordinatorLost in bounded time, never a hang or bare OSError
#   - coordinator-allocated jax.distributed ports: never handed out twice
#   - wire fault sites (cp.net.send/recv): drop, partition, corrupt
#   - teardown: no orphaned sockets, threads, or files after close()
#   - THE CHAOS MATRIX on real OS processes over real sockets:
#     SIGKILL'd rank, partitioned rank, killed coordinator — each surfaces
#     as a typed error naming the culprit within 2 heartbeat intervals
#     (wall-clock asserted)
import contextlib
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.parallel import faults
from spark_rapids_ml_tpu.parallel.context import (
    ControlPlaneTimeout,
    RemoteRankError,
)
from spark_rapids_ml_tpu.parallel.netplane import (
    CoordinatorLost,
    CoordinatorServer,
    ProtocolError,
    StaleEpochError,
    TcpControlPlane,
    _pack_frame,
    _reparse_frame,
    bootstrap_tcp_plane,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the chaos heartbeat cadence: 2 s heartbeats => 3 s lease => the asserted
# detection bound is 2 heartbeat intervals = 4 s (lease + scan poll = 3.75)
_HB_S = 2.0
_DETECT_BOUND_S = 2 * _HB_S


def _netcp_threads():
    return [
        t.name for t in threading.enumerate()
        if t.name.startswith("srml-netcp")
    ]


@pytest.fixture
def coordinator():
    """A running coordinator + client factory; teardown asserts the
    no-orphan-threads contract for everything the test built."""
    made = []

    def build(nranks, lease_s=1.0, timeout=10.0):
        srv = CoordinatorServer(
            nranks, host="127.0.0.1", advertise_host="127.0.0.1",
            lease_s=lease_s,
        )
        addr = srv.start()
        made.append(srv)

        def client(rank, **kw):
            kw.setdefault("timeout", timeout)
            cp = TcpControlPlane(addr, rank, nranks, **kw)
            made.append(cp)
            return cp

        return srv, addr, client

    yield build
    for m in reversed(made):
        with contextlib.suppress(Exception):
            (m.close if isinstance(m, TcpControlPlane) else m.stop)()
    deadline = time.monotonic() + 10.0
    while _netcp_threads() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert _netcp_threads() == [], "orphaned netplane threads after teardown"


# -- frame codec --------------------------------------------------------------


def test_frame_codec_round_trip_and_loud_corruption():
    frame = _pack_frame(b"G", {"round": 3, "rank": 1}, b"\x00\xffpayload")
    ftype, meta, blob = _reparse_frame(frame)
    assert (ftype, meta["round"], blob) == (b"G", 3, b"\x00\xffpayload")
    # flipped magic: the fail-loud contract for wire corruption
    bad = bytearray(frame)
    bad[0] ^= 0xFF
    with pytest.raises(ProtocolError, match="magic"):
        _reparse_frame(bytes(bad))
    # implausible length field
    bad2 = bytearray(frame)
    bad2[7] = 0xFF  # meta-length high byte
    with pytest.raises(ProtocolError):
        _reparse_frame(bytes(bad2))
    # garbled meta JSON
    bad3 = bytearray(frame)
    bad3[len(bad3) - len(b"\x00\xffpayload") - 2] ^= 0xFF
    with pytest.raises(ProtocolError):
        _reparse_frame(bytes(bad3))


# -- pushed aborts ------------------------------------------------------------


def test_pushed_abort_beats_the_poll_floor(coordinator):
    """The wire plane's reason to exist: an abort marker reaches a blocked
    gather as a coordinator PUSH — survivors raise RemoteRankError naming
    rank/etype/span in well under the file plane's 50 ms poll interval."""
    _srv, _addr, client = coordinator(3)
    planes = {r: client(r) for r in range(3)}
    errs = {}
    t_abort = [0.0]

    def waiter(rank):
        try:
            planes[rank].allGather("never-completes")
        except RemoteRankError as exc:
            errs[rank] = (exc, time.monotonic() - t_abort[0])

    threads = [
        threading.Thread(target=waiter, args=(r,), name=f"wire-r{r}")
        for r in (0, 2)
    ]
    for t in threads:
        t.start()
    time.sleep(0.2)  # both are blocked in the gather wait
    t_abort[0] = time.monotonic()
    planes[1].abort(json.dumps({
        "rank": 1, "etype": "ValueError",
        "message": "induced failure", "span": "exchange.ring",
    }))
    for t in threads:
        t.join(timeout=10.0)
    assert set(errs) == {0, 2}, "survivors never raised"
    for rank, (exc, dt) in errs.items():
        assert exc.rank == 1 and exc.etype == "ValueError"
        assert exc.span == "exchange.ring"
        assert dt < 0.05, (
            f"rank {rank} took {dt * 1e3:.1f} ms — a push must beat the "
            "file plane's 50 ms poll floor"
        )
    assert planes[0].check_abort()["rank"] == 1  # non-blocking surface too


# -- leases + fencing ---------------------------------------------------------


def test_lease_expiry_names_the_silent_rank(coordinator):
    _srv, _addr, client = coordinator(2, lease_s=0.5)
    cp0, cp1 = client(0), client(1)
    # silence rank 1 without closing its socket: the wedge/partition shape
    # (a SIGKILL would close the socket and be detected even faster)
    cp1._stop.set()
    got = {}
    t0 = time.monotonic()

    def waiter():
        try:
            cp0.allGather("x")
        except RemoteRankError as exc:
            got["e"] = (exc, time.monotonic() - t0)

    w = threading.Thread(target=waiter, name="wire-lease-waiter")
    w.start()
    w.join(timeout=10.0)
    exc, dt = got["e"]
    assert exc.rank == 1 and "lease expired" in str(exc)
    assert "SRML_CP_LEASE_S" in str(exc)  # the error names its knob
    assert dt < 2 * 0.5 + 0.5, f"detection took {dt:.2f}s"


def test_stale_epoch_rejoin_is_fenced(coordinator):
    """THE fencing acceptance gate: after a rank is declared dead, neither
    its old incarnation (stale epoch) nor a fresh rejoin is readmitted —
    both get the typed StaleEpochError, because its peers have already
    been told it is gone."""
    srv, addr, client = coordinator(2, lease_s=0.4)
    cp0, cp1 = client(0), client(1)
    zombie_epoch = cp1.epoch
    cp1._stop.set()  # fall silent; the lease declares rank 1 dead
    with pytest.raises(RemoteRankError, match="rank 1"):
        cp0.allGather("x")
    before = profiling.counter("cp.net.fenced_rejoins")
    with pytest.raises(StaleEpochError, match="fenced"):
        TcpControlPlane(addr, 1, 2, timeout=5, resume_epoch=zombie_epoch)
    with pytest.raises(StaleEpochError, match="fenced"):
        TcpControlPlane(addr, 1, 2, timeout=5)  # fresh rejoin: also fenced
    assert profiling.counter("cp.net.fenced_rejoins") - before == 2


def test_duplicate_live_rank_join_is_fenced(coordinator):
    _srv, addr, client = coordinator(2)
    client(0)
    client(1)
    with pytest.raises(StaleEpochError, match="duplicate"):
        TcpControlPlane(addr, 1, 2, timeout=5)


# -- coordinator loss ---------------------------------------------------------


def test_coordinator_death_is_typed_and_bounded(coordinator):
    srv, _addr, client = coordinator(2, lease_s=0.5)
    cp0 = client(0)
    got = {}
    t0 = time.monotonic()

    def waiter():
        try:
            cp0.allGather("x")
        except CoordinatorLost as exc:
            got["e"] = (exc, time.monotonic() - t0)

    w = threading.Thread(target=waiter, name="wire-lost-waiter")
    w.start()
    time.sleep(0.2)
    srv.stop(grace_s=0.0)  # hard stop mid-gather: the killed coordinator
    w.join(timeout=10.0)
    exc, dt = got["e"]
    assert "coordinator" in str(exc) and dt < 2.0


# -- port reservation ---------------------------------------------------------


def test_allocated_ports_are_never_reissued(coordinator):
    _srv, _addr, client = coordinator(1)
    cp = client(0)
    ports = [cp.allocate_port() for _ in range(16)]
    assert len(set(ports)) == 16, "coordinator reissued a reserved port"
    assert all(1024 <= p <= 65535 for p in ports)


def test_tpu_context_uses_coordinator_allocated_port(monkeypatch):
    """TpuContext rank 0 must route its jax.distributed port pick through
    the plane's allocate_port when the surface exists (the rebind-race
    fix): the advertised coordinator address must carry the port the
    ledger reserved, not an unreserved _free_port pick."""
    import jax

    class _PortPlane:
        def __init__(self):
            self.handed = []

        def allGather(self, message):
            return [message]

        def barrier(self):
            return None

        def allocate_port(self):
            self.handed.append(45713)
            return 45713

    captured = {}
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: captured.update(kw),
    )
    # no real distributed client behind the stub: arming gloo here would
    # break every later backend init in this process
    from spark_rapids_ml_tpu import compat

    monkeypatch.setattr(compat, "ensure_cpu_collectives", lambda: False)
    from spark_rapids_ml_tpu.parallel.context import TpuContext

    cp = _PortPlane()
    ctx = TpuContext(rank=0, nranks=2, control_plane=cp)
    ctx.__enter__()
    try:
        assert cp.handed == [45713]
        assert captured["coordinator_address"].endswith(":45713")
    finally:
        ctx._initialized_distributed = False  # initialize was a stub
        ctx.__exit__(None, None, None)


# -- wire fault sites ---------------------------------------------------------


def test_wire_drop_and_partition_grammar(armed_faults):
    armed_faults("cp.net.send:rank=0:call=1:action=drop")
    assert faults.site("cp.net.send", rank=0, payload=b"f") is faults.DROPPED
    assert faults.site("cp.net.send", rank=0, payload=b"g") == b"g"
    # partition is sticky and bidirectional across the cp.net.* family
    armed_faults("cp.net.send:rank=1:action=partition")
    assert faults.site("cp.net.send", rank=1, payload=b"a") is faults.DROPPED
    assert faults.site("cp.net.recv", rank=1, payload=b"b") is faults.DROPPED
    assert faults.site("cp.net.send", rank=0, payload=b"c") == b"c"
    assert faults.plan().partitioned() == {1}
    # drop/partition outside the wire family is a strict-parse error
    with pytest.raises(ValueError, match="wire sites"):
        faults.parse_plan("cp.gather:action=drop")
    with pytest.raises(ValueError, match="wire sites"):
        faults.parse_plan("cp.barrier:action=partition")


def test_partitioned_rank_is_named_by_survivor(coordinator, armed_faults):
    """An injected partition (sticky drop of every cp.net.* frame for rank
    1) must surface exactly like a real one: the survivor's gather raises
    RemoteRankError naming rank 1 via lease expiry, and the partitioned
    rank itself loses the coordinator (typed, bounded)."""
    armed_faults("cp.net.send:rank=1:action=partition")
    _srv, _addr, client = coordinator(2, lease_s=0.5)
    cp0, cp1 = client(0), client(1)
    out = {}

    def r0():
        try:
            for i in range(50):
                cp0.allGather(f"r0-{i}")
        except RemoteRankError as exc:
            out[0] = exc

    def r1():
        try:
            for i in range(50):
                cp1.allGather(f"r1-{i}")
        except (CoordinatorLost, RemoteRankError) as exc:
            out[1] = exc

    threads = [
        threading.Thread(target=r0, name="wire-part-r0"),
        threading.Thread(target=r1, name="wire-part-r1"),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15.0)
    assert isinstance(out.get(0), RemoteRankError) and out[0].rank == 1
    assert "lease expired" in str(out[0])
    assert isinstance(out.get(1), CoordinatorLost)


def test_corrupt_frame_kills_the_sender_loudly(coordinator, armed_faults):
    """corrupt on cp.net.send garbles rank 1's wire frames: the
    coordinator's codec must refuse the frame (protocol violation), declare
    rank 1 dead, and the survivor must learn WHO — never decode garbage
    into a gather round."""
    armed_faults("cp.net.send:rank=1:action=corrupt")
    _srv, _addr, client = coordinator(2, lease_s=5.0)
    cp0, cp1 = client(0), client(1)
    out = {}

    def r0():
        try:
            cp0.allGather("r0")
        except RemoteRankError as exc:
            out[0] = exc

    t = threading.Thread(target=r0, name="wire-corrupt-r0")
    t.start()
    time.sleep(0.1)
    with pytest.raises((CoordinatorLost, RemoteRankError, StaleEpochError)):
        cp1.allGather("r1")  # its own corrupt frame severs the connection
        cp1.allGather("r1-again")  # at worst the next round surfaces it
    t.join(timeout=10.0)
    assert isinstance(out.get(0), RemoteRankError) and out[0].rank == 1
    assert "protocol violation" in str(out[0])


# -- timeout typing -----------------------------------------------------------


def test_gather_timeout_is_typed_and_names_missing_ranks(coordinator):
    _srv, _addr, client = coordinator(3)
    cp0, cp2 = client(0, timeout=0.5), client(2, timeout=0.5)
    errs = {}

    def run(rank, cp):
        try:
            cp.allGather("present")
        except ControlPlaneTimeout as exc:
            errs[rank] = exc

    threads = [
        threading.Thread(target=run, args=(r, cp), name=f"wire-to-r{r}")
        for r, cp in ((0, cp0), (2, cp2))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
    for rank in (0, 2):
        exc = errs[rank]
        assert isinstance(exc, TimeoutError)  # compatibility contract
        assert exc.round_no == 0 and exc.missing_ranks == [1]
        assert exc.knob == "SRML_CP_ROUND_TIMEOUT_S"
        assert "ranks [1]" in str(exc)


# -- bootstrap + teardown -----------------------------------------------------


def test_bootstrap_via_shared_directory(tmp_path):
    planes = {}
    results = {}

    def run(rank):
        cp = bootstrap_tcp_plane(str(tmp_path), rank, 3, timeout=20)
        planes[rank] = cp
        results[rank] = cp.allGather(f"boot-{rank}")

    threads = [
        threading.Thread(target=run, args=(r,), name=f"wire-boot-r{r}")
        for r in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20.0)
    assert set(planes) == {0, 1, 2}
    for r in range(3):
        assert results[r] == ["boot-0", "boot-1", "boot-2"]
    assert os.path.exists(tmp_path / "coordinator.addr")
    for r in (1, 2, 0):  # rank 0 (the server owner) closes LAST
        planes[r].close()
        planes[r].close()  # close is idempotent
    # no orphan files (the addr file is reaped), threads, or sockets
    assert not os.path.exists(tmp_path / "coordinator.addr")
    deadline = time.monotonic() + 10.0
    while _netcp_threads() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert _netcp_threads() == []


# -- the chaos matrix: real OS processes over real sockets --------------------


def _spawn_netchaos(root, nranks, env_extra, rounds=4):
    env = dict(os.environ)
    env.pop("SRML_FAULTS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["SRML_CP"] = "tcp"
    env["SRML_WATCH_HEARTBEAT_S"] = str(_HB_S)  # lease = 1.5 hb = 3 s
    env.update(env_extra)
    return [
        subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "tests", "netchaos_worker.py"),
             str(r), str(nranks), str(root), str(rounds)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(nranks)
    ]


def _communicate_all(procs, timeout=120):
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<TIMEOUT: killed by driver>"
        outs.append(out)
    return outs


def _shield_line(out):
    for line in out.splitlines():
        if line.startswith("SHIELD ") and "culprit=" in line:
            return dict(
                kv.split("=", 1) for kv in line.split()[1:] if "=" in kv
            )
    return None


def test_netchaos_clean_run_no_orphans(tmp_path):
    """3 real OS processes over real sockets, no faults: every rank
    completes every round; teardown leaves no coordinator.addr, no
    presence files, nothing."""
    procs = _spawn_netchaos(tmp_path, nranks=3, env_extra={})
    outs = _communicate_all(procs)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
    assert os.listdir(tmp_path / "cp") == []


def test_netchaos_sigkilled_rank_named_within_two_heartbeats(tmp_path):
    """Acceptance gate 1: rank 1 of 3 dies mid-collective (os._exit — the
    SIGKILL shape: no marker, no teardown, kernel FIN only).  Both
    survivors must raise RemoteRankError NAMING rank 1 within 2 heartbeat
    intervals, wall-clock asserted."""
    procs = _spawn_netchaos(
        tmp_path, nranks=3,
        env_extra={"SRML_FAULTS": "cp.gather:rank=1:call=3:action=die"},
    )
    outs = _communicate_all(procs)
    from spark_rapids_ml_tpu.parallel.faults import DIE_EXIT_CODE

    assert procs[1].returncode == DIE_EXIT_CODE, outs[1]
    for r in (0, 2):
        assert procs[r].returncode == 7, f"rank {r}:\n{outs[r]}"
        info = _shield_line(outs[r])
        assert info is not None, outs[r]
        assert info["kind"] == "remote" and info["culprit"] == "1"
        assert float(info["dt"]) < _DETECT_BOUND_S, (
            f"rank {r} took {info['dt']}s (> 2 heartbeat intervals = "
            f"{_DETECT_BOUND_S}s) to notice the killed rank"
        )
    # the surviving coordinator owner (rank 0) reaps the session's files
    assert os.listdir(tmp_path / "cp") == []


def test_netchaos_partitioned_rank_named_within_two_heartbeats(tmp_path):
    """Acceptance gate 2: rank 2 of 3 is PARTITIONED (injected sticky
    cp.net drop, both directions — the process is alive but unreachable).
    Survivors name rank 2 via lease expiry within 2 heartbeat intervals;
    the partitioned rank itself exits with the typed plane-lost error."""
    procs = _spawn_netchaos(
        tmp_path, nranks=3,
        env_extra={
            "SRML_FAULTS": "cp.net.send:rank=2:call=6:action=partition",
            # lease pinned BELOW the 1.5x-heartbeat default so worst-case
            # expiry + scan poll (2.5 + 0.625 s) clears the 2-heartbeat
            # bound with CI-scheduler headroom
            "SRML_CP_LEASE_S": "2.5",
        },
        rounds=40,
    )
    outs = _communicate_all(procs)
    for r in (0, 1):
        assert procs[r].returncode == 7, f"rank {r}:\n{outs[r]}"
        info = _shield_line(outs[r])
        assert info["kind"] == "remote" and info["culprit"] == "2"
        assert float(info["dt"]) < _DETECT_BOUND_S, (
            f"rank {r} took {info['dt']}s (> {_DETECT_BOUND_S}s) to notice "
            "the partitioned rank"
        )
    assert procs[2].returncode == 8, f"rank 2:\n{outs[2]}"
    assert _shield_line(outs[2])["etype"] == "CoordinatorLost"


def test_netchaos_killed_coordinator_surfaces_typed_and_bounded(tmp_path):
    """Acceptance gate 3: the COORDINATOR (hosted in rank 0's process) is
    SIGKILLed mid-matrix.  Ranks 1 and 2 must fail with the typed
    CoordinatorLost within 2 heartbeat intervals — never a hang, never a
    bare socket error."""
    procs = _spawn_netchaos(tmp_path, nranks=3, env_extra={}, rounds=0)
    # wait until the cohort is demonstrably gathering (every worker prints
    # its join line after bootstrap), then kill the coordinator host
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if os.path.exists(tmp_path / "cp" / "coordinator.addr"):
            break
        time.sleep(0.05)
    time.sleep(1.0)  # let a few rounds complete
    os.kill(procs[0].pid, signal.SIGKILL)
    outs = _communicate_all(procs)
    assert procs[0].returncode == -signal.SIGKILL
    for r in (1, 2):
        assert procs[r].returncode == 8, f"rank {r}:\n{outs[r]}"
        info = _shield_line(outs[r])
        assert info["kind"] == "plane"
        assert info["etype"] == "CoordinatorLost"
        assert float(info["dt"]) < _DETECT_BOUND_S, (
            f"rank {r} took {info['dt']}s (> {_DETECT_BOUND_S}s) to notice "
            "the dead coordinator"
        )
