# RandomForest classifier/regressor quality vs sklearn + persistence +
# evaluate (strategy modeled on the reference's test_random_forest.py).
import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    RandomForestClassificationModel,
    RandomForestClassifier,
    RandomForestRegressionModel,
    RandomForestRegressor,
)
from spark_rapids_ml_tpu.core import load
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.evaluation import (
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)


def _cls_data(n=500, d=8, k=3, seed=0):
    from sklearn.datasets import make_classification

    X, y = make_classification(
        n_samples=n, n_features=d, n_informative=5, n_classes=k, random_state=seed
    )
    return X.astype(np.float64), y.astype(np.float64)


def _reg_data(n=500, d=8, seed=0):
    from sklearn.datasets import make_regression

    X, y = make_regression(n_samples=n, n_features=d, n_informative=5, noise=5.0, random_state=seed)
    return X.astype(np.float64), y.astype(np.float64)


def test_default_params():
    rf = RandomForestClassifier()
    assert rf.tpu_params["n_estimators"] == 20   # spark numTrees default
    assert rf.tpu_params["n_bins"] == 32         # spark maxBins default
    assert rf.tpu_params["max_depth"] == 5       # spark maxDepth default
    assert rf.tpu_params["split_criterion"] == "gini"
    rf = RandomForestRegressor(numTrees=7, maxBins=16, maxDepth=4)
    assert rf.tpu_params["n_estimators"] == 7
    assert rf.tpu_params["split_criterion"] == "variance"


def test_param_mapping_and_unsupported():
    rf = RandomForestClassifier(featureSubsetStrategy="onethird")
    assert rf.tpu_params["max_features"] == pytest.approx(1 / 3)
    rf = RandomForestClassifier(featureSubsetStrategy="0.5")
    assert rf.tpu_params["max_features"] == 0.5
    with pytest.raises(ValueError):
        RandomForestClassifier(weightCol="w")
    with pytest.raises(ValueError):
        RandomForestClassifier(impurity="nope")
    # silently-ignored params accepted
    rf = RandomForestClassifier(minInfoGain=0.1, subsamplingRate=0.5)
    assert "minInfoGain" not in rf.tpu_params


@pytest.mark.slow
def test_classifier_accuracy():
    X, y = _cls_data()
    df = DataFrame.from_numpy(X, y=y, num_partitions=4)
    model = RandomForestClassifier(numTrees=30, maxDepth=8, seed=7).fit(df)
    out = model.transform(df).toPandas()
    acc = (out["prediction"].to_numpy() == y).mean()
    assert acc > 0.9, acc
    probs = np.stack(out["probability"].to_numpy())
    assert probs.shape == (len(y), 3)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    raw = np.stack(out["rawPrediction"].to_numpy())
    assert raw.shape == (len(y), 3)
    assert model.numClasses == 3
    assert model.getNumTrees == 30


@pytest.mark.slow
def test_classifier_vs_sklearn_holdout():
    from sklearn.ensemble import RandomForestClassifier as SkRF
    from sklearn.model_selection import train_test_split

    X, y = _cls_data(n=800)
    Xtr, Xte, ytr, yte = train_test_split(X, y, random_state=0)
    df = DataFrame.from_numpy(Xtr, y=ytr, num_partitions=4)
    model = RandomForestClassifier(numTrees=40, maxDepth=8, seed=3).fit(df)
    ours = (
        model.transform(DataFrame.from_numpy(Xte)).toPandas()["prediction"].to_numpy()
    )
    sk = SkRF(n_estimators=40, max_depth=8, random_state=3).fit(Xtr, ytr)
    acc_ours = (ours == yte).mean()
    acc_sk = (sk.predict(Xte) == yte).mean()
    assert acc_ours >= acc_sk - 0.05, (acc_ours, acc_sk)


def test_regressor_quality():
    from sklearn.ensemble import RandomForestRegressor as SkRF
    from sklearn.metrics import r2_score

    X, y = _reg_data()
    df = DataFrame.from_numpy(X, y=y, num_partitions=4)
    # 10 trees depth 6 keep the quality claim while shrinking the default
    # CI cost of this test (was 30 x depth-8, ~23 s)
    model = RandomForestRegressor(numTrees=10, maxDepth=6, seed=5).fit(df)
    preds = model.transform(df).toPandas()["prediction"].to_numpy()
    r2 = r2_score(y, preds)
    sk = SkRF(n_estimators=10, max_depth=6, random_state=5).fit(X, y)
    r2_sk = r2_score(y, sk.predict(X))
    assert r2 > 0.8, r2
    assert r2 >= r2_sk - 0.15, (r2, r2_sk)


@pytest.mark.slow
def test_binary_classification():
    X, y = _cls_data(k=2)
    df = DataFrame.from_numpy(X, y=y, num_partitions=3)
    model = RandomForestClassifier(numTrees=20, maxDepth=6, seed=1).fit(df)
    out = model.transform(df).toPandas()
    assert (out["prediction"].to_numpy() == y).mean() > 0.9
    assert model.predict(X[0]) in (0.0, 1.0)
    assert model.predictProbability(X[0]).shape == (2,)


def test_no_bootstrap_deterministic_with_all_features():
    X, y = _reg_data(n=200)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    kw = dict(numTrees=3, maxDepth=5, bootstrap=False, featureSubsetStrategy="all", seed=1)
    m1 = RandomForestRegressor(**kw).fit(df)
    # without bootstrap and with all features every tree is identical
    assert np.array_equal(m1.features_[0], m1.features_[1])
    np.testing.assert_allclose(m1.leaf_values_[0], m1.leaf_values_[2])


def test_min_instances_per_node():
    X, y = _cls_data(n=300, k=2)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    model = RandomForestClassifier(numTrees=5, maxDepth=8, minInstancesPerNode=50, seed=2).fit(df)
    # every split node must have had >= 2*min instances to split at all
    split_counts = model.node_counts_[model.features_ >= 0]
    assert split_counts.min() >= 2 * 50
    # children of any split satisfy the constraint: check leaves reached by data
    leaf_counts = model.node_counts_[(model.features_ < 0) & (model.node_counts_ > 0)]
    assert leaf_counts.min() >= 50


@pytest.mark.slow
def test_transform_evaluate():
    X, y = _cls_data(n=300)
    df = DataFrame.from_numpy(X, y=y, num_partitions=3)
    model = RandomForestClassifier(numTrees=10, maxDepth=6, seed=4).fit(df)
    ev = MulticlassClassificationEvaluator(metricName="accuracy")
    scores = model._transformEvaluate(df, ev)
    direct = ev.evaluate(model.transform(df))
    assert abs(scores[0] - direct) < 1e-9

    Xr, yr = _reg_data(n=300)
    dfr = DataFrame.from_numpy(Xr, y=yr, num_partitions=3)
    rmodel = RandomForestRegressor(numTrees=10, maxDepth=6, seed=4).fit(dfr)
    evr = RegressionEvaluator(metricName="rmse")
    scores = rmodel._transformEvaluate(dfr, evr)
    direct = evr.evaluate(rmodel.transform(dfr))
    assert abs(scores[0] - direct) < 1e-9


@pytest.mark.slow
def test_persistence(tmp_path):
    X, y = _cls_data(n=200)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    model = RandomForestClassifier(numTrees=8, maxDepth=5, seed=9).fit(df)
    model.save(str(tmp_path / "rf"))
    loaded = load(str(tmp_path / "rf"))
    assert isinstance(loaded, RandomForestClassificationModel)
    p1 = model.transform(df).toPandas()["prediction"]
    p2 = loaded.transform(df).toPandas()["prediction"]
    assert (p1 == p2).all()

    Xr, yr = _reg_data(n=150)
    dfr = DataFrame.from_numpy(Xr, y=yr, num_partitions=2)
    rmodel = RandomForestRegressor(numTrees=5, maxDepth=4, seed=9).fit(dfr)
    rmodel.save(str(tmp_path / "rfr"))
    rloaded = load(str(tmp_path / "rfr"))
    assert isinstance(rloaded, RandomForestRegressionModel)
    np.testing.assert_allclose(
        rloaded.transform(dfr).toPandas()["prediction"],
        rmodel.transform(dfr).toPandas()["prediction"],
    )


def test_trees_to_dicts():
    X, y = _reg_data(n=150)
    model = RandomForestRegressor(numTrees=2, maxDepth=3, seed=0).fit(
        DataFrame.from_numpy(X, y=y)
    )
    dicts = model.trees_to_dicts()
    assert len(dicts) == 2
    root = dicts[0]
    assert "split_feature" in root and "yes" in root and "no" in root


def test_max_depth_limit():
    X, y = _reg_data(n=100)
    with pytest.raises(ValueError, match="maxDepth"):
        RandomForestRegressor(maxDepth=20).fit(DataFrame.from_numpy(X, y=y))


@pytest.mark.slow
def test_fit_multiple():
    X, y = _cls_data(n=250)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    est = RandomForestClassifier(maxDepth=5, seed=11)
    pmaps = [
        {RandomForestClassifier.numTrees: 5},
        {RandomForestClassifier.numTrees: 10},
    ]
    models = [m for _, m in est.fitMultiple(df, pmaps)]
    assert models[0].getNumTrees == 5
    assert models[1].getNumTrees == 10


@pytest.mark.slow
def test_wide_level_kernel_matches_node_chunked():
    # the deep-level one-pass kernel (level_split_kernel_wide) must grow the
    # same tree as the node-chunked kernel; force it by shrinking node_batch
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.forest import (
        bin_features,
        compute_bin_edges,
        grow_tree,
    )

    rng = np.random.default_rng(5)
    N, D, B = 2000, 12, 32
    X = rng.normal(size=(N, D)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 3] > 0).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = bin_features(jnp.asarray(X), jnp.asarray(edges))
    stats = jnp.asarray(
        np.stack([1.0 - y, y], axis=1).astype(np.float32)
    )
    kw = dict(
        max_depth=6, n_bins=B, kind="gini", max_features=D,
        min_samples_leaf=1.0, min_impurity_decrease=0.0, seed=3,
    )
    t_chunked = grow_tree(Xb, stats, edges, node_batch=256, **kw)
    t_wide = grow_tree(Xb, stats, edges, node_batch=1, **kw)  # all levels >1 wide
    np.testing.assert_array_equal(
        np.asarray(t_chunked.feature), np.asarray(t_wide.feature)
    )
    np.testing.assert_allclose(
        np.asarray(t_chunked.threshold), np.asarray(t_wide.threshold)
    )
    np.testing.assert_allclose(
        np.asarray(t_chunked.leaf_value), np.asarray(t_wide.leaf_value), atol=1e-6
    )


def test_wide_level_kernel_feature_subset_and_chunking():
    # wide path with max_features < D and feat_batch smaller than D (uneven)
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.forest import (
        bin_features,
        compute_bin_edges,
        level_split_kernel_wide,
    )

    rng = np.random.default_rng(7)
    N, D, B, n_nodes = 500, 10, 16, 4
    X = rng.normal(size=(N, D)).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = bin_features(jnp.asarray(X), jnp.asarray(edges))
    yb = rng.integers(0, 2, N)
    stats = jnp.asarray(np.stack([1.0 - yb, yb], axis=1).astype(np.float32))
    rel = jnp.asarray(rng.integers(0, n_nodes, N).astype(np.int32))
    out = level_split_kernel_wide(
        Xb, stats, rel, jax.random.PRNGKey(0),
        n_nodes=n_nodes, n_bins=B, feat_batch=3, kind="gini",
        max_features=4, min_samples_leaf=1.0, min_impurity_decrease=0.0,
    )
    bf, bb, ok, cnt, imp, val = [np.asarray(o) for o in out]
    assert bf.shape == (n_nodes,) and np.all((bf >= 0) & (bf < D))
    assert np.all((bb >= 0) & (bb < B))
    np.testing.assert_allclose(cnt.sum(), N)


def test_mxu_route_wiring_feature_major(monkeypatch):
    """The MXU route is TPU-gated, so a broken symbol/shape in its wiring
    would merge green on the CPU suite (round-4 regression: the lazily
    bound feature-major binner raised NameError only on hardware).  Force
    the route and verify _maybe_grow_mxu receives the (D, n_pad) int8
    feature-major bins and its result flows into the model."""
    import numpy as np

    import spark_rapids_ml_tpu.models.random_forest as rfm
    from spark_rapids_ml_tpu import RandomForestRegressor
    from spark_rapids_ml_tpu.dataframe import DataFrame
    from spark_rapids_ml_tpu.ops.forest_hist import _ROW_TILE

    rng = np.random.default_rng(4)
    X = rng.standard_normal((300, 7)).astype(np.float32)
    y = (X @ np.ones(7, np.float32)).astype(np.float32)
    seen = {}

    monkeypatch.setattr(
        rfm, "_mxu_eligible", lambda *a, **kw: True
    )

    def _fake_mxu(inputs, bins_fm, edges, stats, n_trees, *a, **kw):
        seen["shape"] = tuple(bins_fm.shape)
        seen["dtype"] = str(bins_fm.dtype)
        depth = kw["max_depth"]
        m = 2 ** (depth + 1) - 1
        return (
            np.full((n_trees, m), -1, np.int32),
            np.zeros((n_trees, m), np.float32),
            np.zeros((n_trees, m, 1), np.float32),
            np.zeros((n_trees, m), np.float32),
            np.zeros((n_trees, m), np.float32),
        )

    monkeypatch.setattr(rfm, "_maybe_grow_mxu", _fake_mxu)
    model = RandomForestRegressor(numTrees=3, maxDepth=3, maxBins=8).fit(
        DataFrame.from_numpy(X, y)
    )
    n_pad = -(-X.shape[0] // _ROW_TILE) * _ROW_TILE
    assert seen["shape"] == (7, n_pad) and seen["dtype"] == "int8"
    assert model.getNumTrees == 3


def test_device_bin_edges_match_host():
    """compute_bin_edges_device (chunked device sort + f32 interpolation)
    must reproduce the host float64 quantile edges up to f32 interpolation
    error — including a ragged column count that exercises the 256-column
    chunk padding."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.forest import (
        compute_bin_edges,
        compute_bin_edges_device,
    )

    rng = np.random.default_rng(17)
    for S, D, B in [(2778, 300, 128), (513, 700, 32), (100, 5, 16)]:
        # offset-heavy features stress the f32 interpolation the most
        X = (rng.normal(size=(S, D)) * rng.gamma(1.0, 5.0, size=D)[None]
             + rng.normal(size=D)[None] * 100).astype(np.float32)
        host = compute_bin_edges(X, B)
        dev = compute_bin_edges_device(jnp.asarray(X), B)
        assert dev.shape == host.shape == (D, B - 1)
        np.testing.assert_allclose(dev, host, rtol=2e-5, atol=2e-4)
