# KMeans correctness vs sklearn + param/persistence tests (strategy modeled
# on the reference's test_kmeans.py).
import numpy as np
import pytest

from spark_rapids_ml_tpu import KMeans, KMeansModel
from spark_rapids_ml_tpu.core import load
from spark_rapids_ml_tpu.dataframe import DataFrame


def _blobs(n=600, d=6, k=4, seed=0, spread=0.05):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-5, 5, size=(k, d))
    labels = rng.integers(0, k, size=n)
    X = centers[labels] + spread * rng.normal(size=(n, d))
    return X.astype(np.float64), centers, labels


def _inertia(X, centers):
    d2 = ((X[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
    return d2.min(axis=1).sum()


def test_default_params():
    km = KMeans()
    assert km.tpu_params["n_clusters"] == 2  # k default 2 pushed into solver
    assert km.tpu_params["max_iter"] == 20
    assert km.tpu_params["init"] == "scalable-k-means++"
    km = KMeans(k=10, maxIter=50, tol=1e-6)
    assert km.tpu_params["n_clusters"] == 10
    assert km.tpu_params["max_iter"] == 50
    km = KMeans(initMode="random")
    assert km.tpu_params["init"] == "random"


def test_unsupported_params():
    with pytest.raises(ValueError):
        KMeans(distanceMeasure="cosine")
    with pytest.raises(ValueError):
        KMeans().setWeightCol("w")
    # silently-ignored param accepted
    km = KMeans(initSteps=5)
    assert "initSteps" not in km.tpu_params


def test_kmeans_recovers_blobs():
    X, true_centers, _ = _blobs()
    df = DataFrame.from_numpy(X, num_partitions=4)
    model = KMeans(k=4, initMode="k-means||", maxIter=100, seed=42).fit(df)
    centers = model.cluster_centers_
    assert centers.shape == (4, 6)
    # every true center matched by some learned center
    for tc in true_centers:
        dist = np.min(np.linalg.norm(centers - tc, axis=1))
        assert dist < 0.5, f"center {tc} unmatched (nearest {dist})"
    # inertia close to optimal
    assert model.inertia_ <= 1.5 * _inertia(X, true_centers)


def test_kmeans_random_init_converges():
    # random init can land in a genuine local minimum on tight blobs (the
    # reason k-means|| exists), so assert convergence/sanity, not recovery
    X, true_centers, _ = _blobs()
    df = DataFrame.from_numpy(X, num_partitions=4)
    model = KMeans(k=4, initMode="random", maxIter=100, seed=42).fit(df)
    assert model.cluster_centers_.shape == (4, 6)
    assert np.all(np.isfinite(model.cluster_centers_))
    assert model.n_iter_ >= 1
    assert np.isfinite(model.inertia_)


def test_kmeans_transform_assignments():
    X, true_centers, labels = _blobs(n=300)
    df = DataFrame.from_numpy(X, num_partitions=3)
    model = KMeans(k=4, maxIter=50, seed=1).fit(df)
    out = model.transform(df).toPandas()
    pred = out["prediction"].to_numpy()
    assert pred.dtype.kind in "iu"
    # same-blob rows map to the same cluster id (allow relabeling)
    for b in range(4):
        ids = pred[labels == b]
        assert len(np.unique(ids)) == 1


def test_kmeans_vs_sklearn_quality():
    from sklearn.cluster import KMeans as SkKMeans

    X, _, _ = _blobs(n=500, d=8, k=5, spread=0.5, seed=3)
    df = DataFrame.from_numpy(X, num_partitions=4)
    model = KMeans(k=5, maxIter=300, seed=7).fit(df)
    sk = SkKMeans(n_clusters=5, n_init=1, random_state=7).fit(X)
    assert model.inertia_ <= 1.1 * sk.inertia_


@pytest.mark.slow
def test_kmeans_mesh_invariance():
    X, _, _ = _blobs(n=256, d=5)
    df = DataFrame.from_numpy(X, num_partitions=4)
    m1 = KMeans(k=4, seed=5, maxIter=100, num_workers=1).fit(df)
    m8 = KMeans(k=4, seed=5, maxIter=100, num_workers=8).fit(df)
    # same seed, same data -> same converged centers up to ordering
    c1 = m1.cluster_centers_[np.lexsort(m1.cluster_centers_.T)]
    c8 = m8.cluster_centers_[np.lexsort(m8.cluster_centers_.T)]
    np.testing.assert_allclose(c1, c8, atol=1e-2)


def test_kmeans_persistence(tmp_path):
    X, _, _ = _blobs(n=200)
    df = DataFrame.from_numpy(X, num_partitions=2)
    est = KMeans(k=4, maxIter=30, seed=11)
    est.save(str(tmp_path / "est"))
    est2 = load(str(tmp_path / "est"))
    assert isinstance(est2, KMeans)
    assert est2.getK() == 4

    model = est.fit(df)
    model.save(str(tmp_path / "model"))
    loaded = load(str(tmp_path / "model"))
    assert isinstance(loaded, KMeansModel)
    np.testing.assert_allclose(loaded.cluster_centers_, model.cluster_centers_)
    p1 = model.transform(df).toPandas()["prediction"].to_numpy()
    p2 = loaded.transform(df).toPandas()["prediction"].to_numpy()
    np.testing.assert_array_equal(p1, p2)


def test_kmeans_single_predict():
    X, _, _ = _blobs(n=200)
    model = KMeans(k=4, seed=2).fit(DataFrame.from_numpy(X))
    cid = model.predict(X[0])
    assert 0 <= cid < 4
    assert len(model.clusterCenters()) == 4
