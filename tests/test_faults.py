# srml-shield gates (docs/robustness.md), in ISSUE order:
#   - FaultPlan grammar: strict parsing, rank/call/tag selection, actions
#   - unarmed path: SRML_FAULTS unset => site() is ONE module-global None
#     check — no env read, no plan lookup, no measurable per-call cost
#     (structural, same style as test_watch's overhead gate)
#   - abort-marker protocol: a rank publishing abort-r<k> makes every
#     peer's in-flight gather raise RemoteRankError naming the origin
#     rank, exception type, and failing span within ~one poll interval
#   - dead-peer detection: the CHAOS MATRIX — real OS processes, one
#     killed mid-collective by the fault plan, survivors raise
#     RemoteRankError naming the dead rank in < 10 s (vs the 300 s round
#     timeout), teardown clean, no orphan alive/heartbeat files
#   - TpuContext abort-vs-clean __exit__ (the NCCL abort/destroy contract)
#   - control-plane I/O retries with exponential backoff + jitter
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.parallel import faults
from spark_rapids_ml_tpu.parallel.context import RemoteRankError, TpuContext
from spark_rapids_ml_tpu.parallel.runner import FileControlPlane

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- grammar ------------------------------------------------------------------


def test_plan_grammar_single_spec():
    plan = faults.parse_plan("cp.gather:rank=1:call=2:action=die")
    assert plan is not None and len(plan.specs) == 1
    s = plan.specs[0]
    assert (s.site, s.rank, s.call, s.action) == ("cp.gather", 1, 2, "die")


def test_plan_grammar_multi_spec_and_defaults():
    plan = faults.parse_plan(
        "cp.barrier:rank=0:delay=2.5;"
        "serving.dispatch:tag=km:action=kill;"
        "exchange.ring_pass:action=corrupt"
    )
    assert [s.site for s in plan.specs] == [
        "cp.barrier", "serving.dispatch", "exchange.ring_pass",
    ]
    barrier, dispatch, ring = plan.specs
    assert barrier.action == "delay" and barrier.delay_s == 2.5  # shorthand
    assert dispatch.tag == "km" and dispatch.rank is None
    assert ring.call is None  # every arrival


def test_plan_grammar_is_strict():
    # a typo'd plan must fail LOUDLY: a chaos gate that silently disarms
    # passes vacuously forever
    with pytest.raises(ValueError, match="unknown action"):
        faults.parse_plan("cp.gather:action=explode")
    with pytest.raises(ValueError, match="no action"):
        faults.parse_plan("cp.gather:rank=1")
    with pytest.raises(ValueError, match="unknown field"):
        faults.parse_plan("cp.gather:frequency=2:action=die")
    with pytest.raises(ValueError, match="delay="):
        faults.parse_plan("cp.gather:action=delay")
    assert faults.parse_plan(None) is None
    assert faults.parse_plan("  ") is None


# -- unarmed zero-overhead path -----------------------------------------------


def test_unarmed_site_is_a_single_none_check(monkeypatch):
    """Tier-1 runs with SRML_FAULTS unset: plan() must be None, site() must
    never re-read the env or touch a plan, and the per-call cost over an
    empty function must be negligible (structural bound, test_watch
    style — the unarmed path is the one EVERY production collective round
    pays)."""
    assert faults.plan() is None  # the suite-wide invariant
    loads = []
    monkeypatch.setattr(faults, "_load", lambda: loads.append(1))
    for _ in range(64):
        assert faults.site("cp.gather", rank=0, payload=b"x") == b"x"
    assert not loads  # no env re-read, no plan construction

    N = 20000

    def bench(fn):
        t0 = profiling.now()
        for _ in range(N):
            fn("cp.gather")
        return (profiling.now() - t0) / N

    def empty(_name):
        return None

    site_cost = min(bench(faults.site) for _ in range(3))
    base = min(bench(empty) for _ in range(3))
    added = max(site_cost - base, 0.0)
    # 10k site arrivals (far more than any fit performs) must add < 5 ms
    assert added * 10_000 < 0.005, (
        f"unarmed faults.site adds {added * 1e9:.0f} ns/call — the "
        "disabled path must be a bare None check"
    )


def test_armed_plan_selects_by_rank(armed_faults):
    armed_faults("cp.gather:rank=1:action=raise")
    assert faults.site("cp.gather", rank=0, payload=b"a") == b"a"
    assert faults.site("cp.gather", rank=2, payload=b"a") == b"a"
    with pytest.raises(faults.FaultInjected, match="cp.gather"):
        faults.site("cp.gather", rank=1)


def test_armed_plan_counts_arrivals_per_site_and_tag(armed_faults):
    """call=N fires on the Nth arrival of that (site, tag) counter — in
    the real topology each rank is its own process, so the counter IS the
    per-rank arrival count; tags give in-process callers (serving: one per
    server name) independent counters."""
    armed_faults("serving.dispatch:tag=srv_a:call=2:action=raise")
    faults.site("serving.dispatch", tag="srv_b")  # other tag: own counter
    faults.site("serving.dispatch", tag="srv_a")  # call 1: no fire
    with pytest.raises(faults.FaultInjected, match="serving.dispatch"):
        faults.site("serving.dispatch", tag="srv_a")  # call 2: fires
    faults.site("serving.dispatch", tag="srv_a")  # call 3: done firing
    assert faults.plan().counts()[("serving.dispatch", "srv_a")] == 3


def test_action_delay_and_corrupt(armed_faults):
    armed_faults("cp.barrier:delay=0.05;exchange.ring_pass:action=corrupt")
    t0 = time.monotonic()
    faults.site("cp.barrier", rank=0)
    assert time.monotonic() - t0 >= 0.045
    payload = b"SRX1" + b"\x00" * 32
    corrupted = faults.site("exchange.ring_pass", rank=0, payload=payload)
    assert corrupted != payload and len(corrupted) == len(payload)
    assert corrupted[:4] != b"SRX1"  # the magic is dead: decoders fail loudly
    # corrupt with nothing to corrupt degrades to the orderly failure
    with pytest.raises(faults.FaultInjected):
        faults.site("exchange.ring_pass", rank=0)


def test_action_kill_is_a_base_exception(armed_faults):
    armed_faults("serving.dispatch:action=kill")
    with pytest.raises(faults.InjectedWorkerDeath):
        faults.site("serving.dispatch", tag="x")
    assert not issubclass(faults.InjectedWorkerDeath, Exception)  # escapes
    #   per-batch `except Exception` relays by design


# -- abort-marker protocol (threads over one FileControlPlane root) -----------


def _plane(root, rank, nranks, timeout=30.0, poll=0.02):
    return FileControlPlane(str(root), rank, nranks, timeout=timeout, poll=poll)


def test_abort_marker_interrupts_gather_within_poll_interval(tmp_path):
    """Rank 1 publishes an abort marker while ranks 0/2 wait in a gather:
    both must raise RemoteRankError naming rank 1, its exception type, and
    its failing span — in ~one poll interval, nowhere near the round
    timeout."""
    results = {}

    def survivor(rank):
        cp = _plane(tmp_path, rank, 3, timeout=30.0)
        t0 = time.monotonic()
        try:
            cp.allGather(f"hello-{rank}")
        except RemoteRankError as exc:
            results[rank] = (exc, time.monotonic() - t0)

    threads = [
        threading.Thread(target=survivor, args=(r,), name=f"shield-r{r}")
        for r in (0, 2)
    ]
    for t in threads:
        t.start()
    time.sleep(0.15)  # both are now waiting on rank 1's round file
    aborter = _plane(tmp_path, 1, 3)
    aborter.abort(json.dumps({
        "rank": 1,
        "etype": "ValueError",
        "message": "induced failure",
        "span": "exchange.ring",
    }))
    for t in threads:
        t.join(timeout=10.0)
    assert set(results) == {0, 2}, "survivors never raised"
    for rank, (exc, dt) in results.items():
        assert exc.rank == 1 and exc.etype == "ValueError"
        assert exc.span == "exchange.ring"
        assert "rank 1" in str(exc) and "exchange.ring" in str(exc)
        assert dt < 5.0, f"rank {rank} took {dt:.1f}s — not a fast abort"


def test_corrupted_ring_frame_fails_loudly_at_the_receiver(
    tmp_path, armed_faults
):
    """exchange.ring_pass corruption: the receiver's SRX1 codec must raise
    on the flipped magic, never decode garbage into candidate lists."""
    from spark_rapids_ml_tpu.parallel.exchange import (
        pack_arrays, ring_pass_bytes, unpack_arrays,
    )

    armed_faults("exchange.ring_pass:rank=0:action=corrupt")
    payloads = {
        r: pack_arrays([np.full((4,), r, np.float32)]) for r in range(2)
    }
    results, errors = {}, {}

    def hop(rank):
        cp = _plane(tmp_path, rank, 2, timeout=30.0)
        try:
            got = ring_pass_bytes(cp, rank, 2, payloads[rank])
            results[rank] = unpack_arrays(got)
        except ValueError as exc:
            errors[rank] = exc

    threads = [
        threading.Thread(target=hop, args=(r,), name=f"ring-r{r}")
        for r in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    # rank 1 receives rank 0's corrupted frame -> loud SRX1 failure;
    # rank 0 receives rank 1's intact frame
    assert 1 in errors and "SRX1" in str(errors[1])
    np.testing.assert_array_equal(
        results[0][0], np.full((4,), 1, np.float32)
    )


# -- TpuContext abort-vs-clean ------------------------------------------------


class _RecordingPlane:
    """Gather-capable fake with the abort surface, for __exit__ testing
    without a jax.distributed bootstrap."""

    def __init__(self):
        self.aborts = []

    def allGather(self, message):
        return [message]

    def barrier(self):
        return None

    def abort(self, payload):
        self.aborts.append(json.loads(payload))


def test_context_exit_broadcasts_abort_on_exception_only():
    cp = _RecordingPlane()
    ctx = TpuContext(rank=1, nranks=2, control_plane=cp)
    # clean path: destroy-like, NO abort marker
    ctx.__exit__(None, None, None)
    assert cp.aborts == []
    # exception path: abort-like — the marker carries the encoded exception
    err = ValueError("solver diverged")
    ctx.__exit__(ValueError, err, None)
    assert len(cp.aborts) == 1
    marker = cp.aborts[0]
    assert marker["rank"] == 1 and marker["etype"] == "ValueError"
    assert "solver diverged" in marker["message"]


def test_context_exit_never_rebroadcasts_a_relayed_abort():
    """A RemoteRankError unwinding through __exit__ is a RELAYED abort:
    re-publishing it would cascade markers around the ring and misname the
    culprit on every survivor."""
    cp = _RecordingPlane()
    ctx = TpuContext(rank=0, nranks=2, control_plane=cp)
    err = RemoteRankError(rank=1, message="died", span="runner.fit")
    ctx.__exit__(RemoteRankError, err, None)
    assert cp.aborts == []


def test_context_exit_single_controller_is_noop():
    cp = _RecordingPlane()
    ctx = TpuContext(rank=0, nranks=1, control_plane=cp)
    ctx.__exit__(RuntimeError, RuntimeError("x"), None)
    assert cp.aborts == []  # no peers to warn


# -- retry-with-backoff -------------------------------------------------------


def test_cp_io_retries_with_backoff(tmp_path, monkeypatch):
    monkeypatch.setenv("SRML_CP_RETRIES", "3")
    monkeypatch.setenv("SRML_CP_BACKOFF_S", "0.01")
    cp = _plane(tmp_path, 0, 1)
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise OSError("transient NFS burp")
        return "ok"

    before = profiling.counter("cp.io_retries")
    assert cp._retry_io(flaky, "flaky") == "ok"
    assert len(attempts) == 3
    assert profiling.counter("cp.io_retries") - before == 2

    def always():
        raise OSError("permanent")

    with pytest.raises(OSError, match="permanent"):
        cp._retry_io(always, "always")


def test_round_timeout_is_bounded_and_names_the_knob(tmp_path):
    cp = _plane(tmp_path, 0, 2, timeout=0.3)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="SRML_CP_ROUND_TIMEOUT_S"):
        cp.allGather("alone")
    assert time.monotonic() - t0 < 5.0  # per-ROUND budget, not session-wide


def test_close_removes_presence_files(tmp_path):
    cp = _plane(tmp_path, 0, 2)
    cp.publish_health('{"rank": 0}')
    assert os.path.exists(cp._alive_path(0))
    cp.close()
    leftovers = [
        f for f in os.listdir(tmp_path)
        if f.startswith(("alive_", "health_"))
    ]
    assert leftovers == []


# -- the chaos matrix: real OS processes --------------------------------------


def _spawn_chaos(root, nranks, env_extra, rounds=4):
    env = dict(os.environ)
    env.pop("SRML_FAULTS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env.update(env_extra)
    return [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "chaos_worker.py"),
             str(r), str(nranks), str(root), str(rounds)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(nranks)
    ]


def _communicate_all(procs, timeout=240):
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<TIMEOUT: killed by driver>"
        outs.append(out)
    return outs


def _shield_line(out):
    for line in out.splitlines():
        if line.startswith("SHIELD "):
            return dict(
                kv.split("=", 1) for kv in line.split()[1:] if "=" in kv
            )
    return None


def test_chaos_clean_run_leaves_no_control_plane_orphans(tmp_path):
    """3 real OS processes, no faults: every rank completes every round and
    teardown leaves no alive/heartbeat file behind."""
    procs = _spawn_chaos(tmp_path, nranks=3, env_extra={})
    outs = _communicate_all(procs)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
    leftovers = [
        f for f in os.listdir(tmp_path / "cp")
        if f.startswith(("alive_", "health_", "abort-"))
    ]
    assert leftovers == []


def test_chaos_killed_rank_names_culprit_in_seconds(tmp_path):
    """THE acceptance gate: rank 1 of 3 dies (os._exit — the SIGKILL shape:
    no marker, no teardown) on its 3rd gather.  Both survivors must raise
    RemoteRankError NAMING rank 1 in < 10 s (the unshielded behavior was a
    300 s TimeoutError naming nobody), and their teardown must reap every
    alive/heartbeat file including the dead rank's."""
    procs = _spawn_chaos(
        tmp_path, nranks=3,
        env_extra={"SRML_FAULTS": "cp.gather:rank=1:call=3:action=die"},
    )
    outs = _communicate_all(procs)
    from spark_rapids_ml_tpu.parallel.faults import DIE_EXIT_CODE

    assert procs[1].returncode == DIE_EXIT_CODE, outs[1]
    for r in (0, 2):
        assert procs[r].returncode == 7, f"rank {r}:\n{outs[r]}"
        info = _shield_line(outs[r])
        assert info is not None, outs[r]
        assert info["culprit"] == "1"
        assert float(info["dt"]) < 10.0, (
            f"rank {r} took {info['dt']}s to notice the dead rank"
        )
    leftovers = [
        f for f in os.listdir(tmp_path / "cp")
        if f.startswith(("alive_", "health_"))
    ]
    assert leftovers == [], "survivor teardown left orphan presence files"


def test_chaos_orderly_abort_carries_span_and_etype(tmp_path):
    """action=raise on rank 2 of 3: the victim publishes its abort marker
    (the TpuContext exception-path contract) and the survivors'
    RemoteRankError names the origin rank, its exception type, AND the
    failing span from the marker."""
    procs = _spawn_chaos(
        tmp_path, nranks=3,
        env_extra={"SRML_FAULTS": "cp.gather:rank=2:call=2:action=raise"},
    )
    outs = _communicate_all(procs)
    assert procs[2].returncode == 9, outs[2]  # orderly victim
    for r in (0, 1):
        assert procs[r].returncode == 7, f"rank {r}:\n{outs[r]}"
        info = _shield_line(outs[r])
        assert info["culprit"] == "2"
        assert info["etype"] == "FaultInjected"
        assert info["span"] == "chaos.gather"
        assert float(info["dt"]) < 10.0
