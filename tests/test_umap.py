# UMAP embedding quality (cluster preservation / trustworthiness) +
# transform + persistence (strategy modeled on the reference's test_umap.py,
# which scores trustworthiness vs cuml).
import numpy as np
import pytest

from spark_rapids_ml_tpu import UMAP, UMAPModel
from spark_rapids_ml_tpu.core import load
from spark_rapids_ml_tpu.dataframe import DataFrame


def _blob_data(n=300, d=10, k=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = 10.0 * rng.normal(size=(k, d))
    labels = rng.integers(0, k, size=n)
    X = centers[labels] + rng.normal(size=(n, d))
    return X.astype(np.float64), labels


def test_default_params():
    um = UMAP()
    assert um.tpu_params["n_neighbors"] == 15
    assert um.tpu_params["n_components"] == 2
    assert um.tpu_params["init"] == "spectral"
    um = UMAP(n_neighbors=10, n_components=3, random_state=1)
    assert um.tpu_params["n_neighbors"] == 10
    assert um.getOrDefault("n_components") == 3


def test_umap_preserves_clusters():
    X, labels = _blob_data()
    df = DataFrame.from_numpy(X, num_partitions=3)
    model = UMAP(n_neighbors=10, random_state=0, n_epochs=150).fit(df)
    emb = model.embedding
    assert emb.shape == (300, 2)
    assert np.all(np.isfinite(emb))
    # same-cluster centroid distances << cross-cluster distances
    cents = np.stack([emb[labels == c].mean(axis=0) for c in range(3)])
    intra = np.mean(
        [np.linalg.norm(emb[labels == c] - cents[c], axis=1).mean() for c in range(3)]
    )
    inter = np.mean(
        [
            np.linalg.norm(cents[i] - cents[j])
            for i in range(3)
            for j in range(i + 1, 3)
        ]
    )
    assert inter > 2.0 * intra, (intra, inter)


def test_umap_trustworthiness():
    from sklearn.manifold import trustworthiness

    X, _ = _blob_data(n=250, d=8)
    df = DataFrame.from_numpy(X, num_partitions=2)
    model = UMAP(n_neighbors=12, random_state=3, n_epochs=150).fit(df)
    t = trustworthiness(X, model.embedding, n_neighbors=10)
    assert t > 0.85, t


def test_umap_transform():
    X, labels = _blob_data(n=200)
    df = DataFrame.from_numpy(X, num_partitions=2)
    model = UMAP(n_neighbors=10, random_state=1, n_epochs=100).fit(df)
    out = model.transform(df).toPandas()
    emb = np.stack(out["embedding"].to_numpy())
    assert emb.shape == (200, 2)
    # transformed training points land near their fit embedding's cluster
    fit_emb = model.embedding
    cents_fit = np.stack([fit_emb[labels == c].mean(axis=0) for c in range(3)])
    assign = np.argmin(
        np.linalg.norm(emb[:, None, :] - cents_fit[None], axis=2), axis=1
    )
    agree = (assign == np.argmin(
        np.linalg.norm(fit_emb[:, None, :] - cents_fit[None], axis=2), axis=1
    )).mean()
    assert agree > 0.9, agree


def test_umap_sample_fraction_and_random_init():
    X, _ = _blob_data(n=200)
    df = DataFrame.from_numpy(X, num_partitions=2)
    model = UMAP(
        n_neighbors=8, init="random", random_state=2, n_epochs=80,
        sample_fraction=0.5,
    ).fit(df)
    assert model.raw_data_.shape[0] < 200
    assert model.embedding.shape[0] == model.raw_data_.shape[0]


def test_umap_persistence(tmp_path):
    X, _ = _blob_data(n=150)
    df = DataFrame.from_numpy(X, num_partitions=2)
    model = UMAP(n_neighbors=8, random_state=4, n_epochs=60).fit(df)
    model.save(str(tmp_path / "umap"))
    loaded = load(str(tmp_path / "umap"))
    assert isinstance(loaded, UMAPModel)
    np.testing.assert_allclose(loaded.embedding_, model.embedding_)
    e1 = np.stack(model.transform(df).toPandas()["embedding"].to_numpy())
    e2 = np.stack(loaded.transform(df).toPandas()["embedding"].to_numpy())
    np.testing.assert_allclose(e1, e2, atol=1e-5)


def test_umap_params_reach_solver_via_spark_api():
    # copy(extra) / set() must reach the solver dict (identity _param_mapping)
    um = UMAP()
    um2 = um.copy({um.getParam("n_neighbors"): 30})
    assert um2.tpu_params["n_neighbors"] == 30
    um._set_params(min_dist=0.4)
    assert um._tpu_params["min_dist"] == 0.4
    assert um.getOrDefault("min_dist") == 0.4


def test_umap_precomputed_knn():
    X, _ = _blob_data(n=60)
    df = DataFrame.from_numpy(X, num_partitions=2)
    from sklearn.neighbors import NearestNeighbors as SkNN

    k = 10
    dists, ids = SkNN(n_neighbors=k).fit(X.astype(np.float32)).kneighbors(
        X.astype(np.float32)
    )
    m = UMAP(
        n_neighbors=k, precomputed_knn=(ids, dists), random_state=5, n_epochs=60
    ).fit(df)
    assert m.embedding_.shape == (60, 2)
    # a wrong-sized graph must be rejected loudly
    with pytest.raises((ValueError, RuntimeError)):
        UMAP(n_neighbors=k, precomputed_knn=(ids[:10], dists[:10])).fit(df)


def test_umap_supervised():
    # labelCol set -> supervised fit (reference umap.py:722-724, 939-947):
    # the label intersection must tighten class clusters vs unsupervised
    X, labels = _blob_data(n=240, d=8, k=3, seed=7)
    # drown the blob geometry so labels carry information the features
    # barely do: at scale 4 the unsupervised embedding already separates the
    # classes near-perfectly and the comparison is a coin flip; at scale 8
    # unsupervised clearly fails (sep ~3.6) while the supervised
    # intersection recovers the classes (sep ~9.3)
    X += np.random.default_rng(1).normal(scale=8.0, size=X.shape)
    df = DataFrame.from_numpy(X, y=labels.astype(np.float64), num_partitions=2)

    def sep_score(emb):
        cents = np.stack([emb[labels == c].mean(axis=0) for c in range(3)])
        intra = np.mean(
            [np.linalg.norm(emb[labels == c] - cents[c], axis=1).mean() for c in range(3)]
        )
        inter = np.mean(
            [np.linalg.norm(cents[i] - cents[j]) for i in range(3) for j in range(i + 1, 3)]
        )
        return inter / max(intra, 1e-9)

    sup = UMAP(n_neighbors=10, random_state=0, n_epochs=150).setLabelCol("label").fit(df)
    unsup = UMAP(n_neighbors=10, random_state=0, n_epochs=150).fit(df)
    assert sup.embedding.shape == (240, 2)
    assert sep_score(sup.embedding) > 1.5 * sep_score(unsup.embedding), (
        sep_score(sup.embedding),
        sep_score(unsup.embedding),
    )


def test_umap_supervised_nan_and_unknown_labels():
    # NaN labels are "unknown" (reference umap.py:939-947 passes them to
    # cuML as unlabeled): edges touching them get the exp(-unknown_dist)
    # downweight, not the exp(-far_dist) cross-class one, and the fit must
    # stay finite end to end
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.umap import (
        categorical_simplicial_set_intersection,
    )

    W = jnp.asarray(np.full((4, 2), 0.8, np.float32))
    ids = jnp.asarray(np.array([[1, 2], [0, 3], [3, 0], [2, 1]], np.int32))
    codes = jnp.asarray(np.array([0, 0, 1, -1], np.int32))  # -1 = unknown
    out = np.asarray(categorical_simplicial_set_intersection(W, ids, codes))
    raw = 0.8 * np.array(
        [
            [1.0, np.exp(-5.0)],          # 0-1 same, 0-2 differ
            [1.0, np.exp(-1.0)],          # 1-0 same, 1-3 unknown
            [np.exp(-1.0), np.exp(-5.0)], # 2-3 unknown, 2-0 differ
            [np.exp(-1.0), np.exp(-1.0)], # 3-* unknown
        ]
    )
    expect = raw / np.maximum(raw.max(axis=1, keepdims=True), 1e-12)
    np.testing.assert_allclose(out, expect, rtol=1e-5)

    # model-level: a label column carrying NaNs must fit finite
    X, labels = _blob_data(n=120, d=6)
    y = labels.astype(np.float64)
    y[::5] = np.nan
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    m = UMAP(n_neighbors=8, random_state=1, n_epochs=60).setLabelCol("label").fit(df)
    assert m.embedding.shape == (120, 2)
    assert np.all(np.isfinite(m.embedding))


def test_umap_precomputed_knn_row_mismatch_message():
    # the models/umap.py guard must name both row counts, and must fire
    # BEFORE any layout work (a wrong-sized graph is a user error, not a
    # shape crash deep in the engine)
    X, _ = _blob_data(n=60)
    df = DataFrame.from_numpy(X, num_partitions=2)
    k = 5
    ids = np.tile(np.arange(k), (40, 1))
    dists = np.abs(np.random.default_rng(0).random((40, k))).cumsum(axis=1)
    with pytest.raises(ValueError, match=r"precomputed_knn has 40 rows.*60"):
        UMAP(n_neighbors=k, precomputed_knn=(ids, dists), random_state=0).fit(df)


def test_umap_supervised_ignored_when_label_unset():
    # a label column present in the df but labelCol unset -> unsupervised
    X, labels = _blob_data(n=80, d=6)
    df = DataFrame.from_numpy(X, y=labels.astype(np.float64), num_partitions=2)
    m1 = UMAP(n_neighbors=8, random_state=3, n_epochs=60).fit(df)
    df2 = DataFrame.from_numpy(X, num_partitions=2)
    m2 = UMAP(n_neighbors=8, random_state=3, n_epochs=60).fit(df2)
    np.testing.assert_allclose(m1.embedding_, m2.embedding_, atol=1e-5)


def test_umap_empty_sample_raises():
    X, _ = _blob_data(n=20)
    df = DataFrame.from_numpy(X, num_partitions=1)
    with pytest.raises(RuntimeError, match="0 rows"):
        UMAP(n_neighbors=3, sample_fraction=1e-9, random_state=0).fit(df)


def test_spectral_init_is_graph_smooth():
    # the spectral init must be a low-frequency embedding of the fuzzy graph
    # (kNN-graph eigengaps are too small for a fixed-iteration method to pin
    # exact eigenvectors, so graph-smoothness + cluster separation are the
    # meaningful checks)
    from spark_rapids_ml_tpu.ops.umap import spectral_init

    rng = np.random.default_rng(0)
    n, k = 120, 8
    X = np.concatenate(
        [rng.normal(size=(60, 4)), rng.normal(size=(60, 4)) + 6.0]
    )
    from sklearn.neighbors import NearestNeighbors as SkNN

    d, ids = SkNN(n_neighbors=k).fit(X).kneighbors(X)
    W = np.exp(-(d**2))
    emb = spectral_init(ids, W, 2, seed=1)
    assert emb.shape == (n, 2) and np.all(np.isfinite(emb))

    # dense ground truth
    A = np.zeros((n, n))
    for i in range(n):
        for j_, w in zip(ids[i], W[i]):
            if i != j_:
                A[i, j_] = max(A[i, j_], w)
                A[j_, i] = max(A[j_, i], w)
    deg = A.sum(1)
    dinv = 1.0 / np.sqrt(np.maximum(deg, 1e-12))
    Ah = dinv[:, None] * A * dinv[None, :]
    # kNN-graph spectral gaps are tiny, so a fixed-iteration subspace method
    # cannot pin the exact top eigenvectors; the property the init needs is
    # graph-SMOOTHNESS: its normalized-Laplacian Rayleigh quotient must be
    # far below a random vector's (~1.0)
    L = np.eye(n) - Ah

    def rayleigh(v):
        v = v - v.mean()
        return float(v @ L @ v) / max(float(v @ v), 1e-12)

    r_emb = np.mean([rayleigh(emb[:, c]) for c in range(2)])
    rng2 = np.random.default_rng(3)
    r_rand = np.mean([rayleigh(rng2.normal(size=n)) for _ in range(5)])
    assert r_emb < 0.3 * r_rand, (r_emb, r_rand)
    # and the two-block structure must separate along the embedding
    labels = np.array([0] * 60 + [1] * 60)
    c0, c1 = emb[labels == 0].mean(0), emb[labels == 1].mean(0)
    intra = np.mean([np.linalg.norm(emb[labels == c] - m, axis=1).mean() for c, m in ((0, c0), (1, c1))])
    assert np.linalg.norm(c0 - c1) > 1.5 * intra


def test_hub_heavy_graph_layout_quality():
    """Hub-heavy data (power-law radial density: a dense core whose points
    become kNN hubs for the sparse shell) must keep trustworthiness — the
    padded head layout truncates hub edges beyond the P98-degree pad width
    (advisor round-4: validate beyond i.i.d. blobs).  Also checks the
    SRML_UMAP_DEGREE_CAP tunable widens the layout."""
    import os

    from sklearn.manifold import trustworthiness

    from spark_rapids_ml_tpu.ops.umap import padded_head_layout

    rng = np.random.default_rng(6)
    n, d = 300, 6
    dirs = rng.standard_normal((n, d))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    r = rng.lognormal(mean=0.0, sigma=1.6, size=n)  # heavy-tailed radii
    X = (dirs * r[:, None]).astype(np.float32)
    df = DataFrame.from_numpy(X, num_partitions=2)

    def _fit_trust(cap, quantile):
        os.environ["SRML_UMAP_DEGREE_CAP"] = str(cap)
        os.environ["SRML_UMAP_DEGREE_QUANTILE"] = str(quantile)
        try:
            m = UMAP(n_neighbors=12, random_state=3, n_epochs=150).fit(df)
        finally:
            del os.environ["SRML_UMAP_DEGREE_CAP"]
            del os.environ["SRML_UMAP_DEGREE_QUANTILE"]
        return trustworthiness(X, m.embedding, n_neighbors=10)

    t_default = _fit_trust(36, 0.98)
    t_full = _fit_trust(200, 1.0)  # no hub truncation at all
    # the claim under test: the P98/cap truncation does not degrade
    # hub-heavy embeddings vs keeping every hub edge (measured here:
    # 0.758 truncated vs 0.750 untruncated — heavy-tailed radial data is
    # intrinsically hard to embed, the truncation is not the limiter)
    assert t_default >= t_full - 0.03, (t_default, t_full)
    assert t_default > 0.7, t_default

    # the cap tunable must actually widen the padded layout
    heads = np.repeat(np.arange(50), 40).astype(np.int64)
    tails = rng.integers(0, 50, size=heads.size).astype(np.int64)
    w = rng.random(heads.size).astype(np.float32) + 0.1
    tp_default, _ = padded_head_layout(heads, tails, w, 50)
    os.environ["SRML_UMAP_DEGREE_CAP"] = "80"
    try:
        tp_wide, _ = padded_head_layout(heads, tails, w, 50)
    finally:
        del os.environ["SRML_UMAP_DEGREE_CAP"]
    assert tp_wide.shape[1] > tp_default.shape[1]
