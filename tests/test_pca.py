# PCA correctness vs sklearn + param/persistence parity (modeled on the
# reference's test_pca.py strategy: default-param parity, small
# hand-checkable correctness, layouts, persistence).
import numpy as np
import pytest

from spark_rapids_ml_tpu import PCA, PCAModel
from spark_rapids_ml_tpu.core import load
from spark_rapids_ml_tpu.dataframe import DataFrame


def _data(n=500, d=8, seed=0):
    rng = np.random.default_rng(seed)
    # low-rank + noise so components are well separated
    basis = rng.normal(size=(3, d))
    X = rng.normal(size=(n, 3)) @ basis + 0.01 * rng.normal(size=(n, d))
    return X.astype(np.float64)


def test_default_params():
    pca = PCA()
    assert pca.tpu_params["n_components"] is None
    assert pca.tpu_params["whiten"] is False
    pca = PCA(k=3)
    assert pca.getK() == 3
    assert pca.tpu_params["n_components"] == 3
    pca = PCA(n_components=4)
    assert pca.getOrDefault("k") == 4


def test_pca_basic_vs_sklearn():
    from sklearn.decomposition import PCA as SkPCA

    X = _data()
    df = DataFrame.from_numpy(X, num_partitions=4)
    model = PCA(k=3).fit(df)
    sk = SkPCA(n_components=3, svd_solver="full").fit(X)

    # compare up to sign via abs (sign handled separately below)
    np.testing.assert_allclose(model.mean_, X.mean(axis=0), atol=1e-4)
    np.testing.assert_allclose(
        np.abs(model.components_), np.abs(sk.components_), atol=1e-3
    )
    np.testing.assert_allclose(
        model.explained_variance_ratio_, sk.explained_variance_ratio_, atol=1e-4
    )
    np.testing.assert_allclose(
        model.singular_values_, sk.singular_values_, rtol=1e-3
    )
    # deterministic sign: largest-|.| element of each component positive
    for row in model.components_:
        assert row[np.argmax(np.abs(row))] > 0


def test_pca_transform_spark_semantics():
    X = _data(n=100, d=6)
    df = DataFrame.from_numpy(X, num_partitions=3)
    model = PCA(k=2).fit(df)
    out = model.transform(df).toPandas()
    got = np.stack(out["pca_features"].to_numpy())
    # Spark semantics: projection WITHOUT mean centering
    expect = X @ model.components_.T
    np.testing.assert_allclose(got, expect, atol=1e-3)


@pytest.mark.parametrize("layout", ["array", "multi_cols"])
def test_pca_layouts(layout):
    X = _data(n=200, d=5)
    df = DataFrame.from_numpy(X, feature_layout=layout, num_partitions=2)
    pca = PCA(k=2)
    if layout == "multi_cols":
        pca.setInputCols(df.columns)
    model = pca.fit(df)
    assert model.components_.shape == (2, 5)


def test_pca_float64():
    X = _data(n=200, d=5)
    df = DataFrame.from_numpy(X, num_partitions=2)
    m32 = PCA(k=2).fit(df)
    m64 = PCA(k=2, float32_inputs=False).fit(df)
    np.testing.assert_allclose(m32.components_, m64.components_, atol=1e-2)


def test_pca_persistence(tmp_path):
    X = _data(n=100, d=5)
    df = DataFrame.from_numpy(X, num_partitions=2)
    est = PCA(k=2)
    est.save(str(tmp_path / "est"))
    est2 = load(str(tmp_path / "est"))
    assert isinstance(est2, PCA)
    assert est2.getK() == 2

    model = est.fit(df)
    model.save(str(tmp_path / "model"))
    loaded = load(str(tmp_path / "model"))
    assert isinstance(loaded, PCAModel)
    np.testing.assert_allclose(loaded.components_, model.components_)
    np.testing.assert_allclose(loaded.mean_, model.mean_)
    assert loaded.n_cols == 5
    out1 = model.transform(df).toPandas()["pca_features"]
    out2 = loaded.transform(df).toPandas()["pca_features"]
    np.testing.assert_allclose(np.stack(out1), np.stack(out2), atol=1e-6)


def test_pca_model_accessors():
    X = _data(n=100, d=5)
    model = PCA(k=2).fit(DataFrame.from_numpy(X))
    assert model.pc.shape == (5, 2)
    assert len(model.mean) == 5
    assert model.explainedVariance.shape == (2,)
    assert model.getK() == 2


def test_pca_mesh_invariance():
    """Multi-device result == single-device result (distribution is exact for
    covariance accumulation)."""
    X = _data(n=256, d=6)
    df = DataFrame.from_numpy(X, num_partitions=4)
    m1 = PCA(k=3, num_workers=1).fit(df)
    m8 = PCA(k=3, num_workers=8).fit(df)
    np.testing.assert_allclose(m1.components_, m8.components_, atol=1e-3)
    np.testing.assert_allclose(m1.singular_values_, m8.singular_values_, rtol=1e-3)


def test_pca_subspace_kernel_matches_eigh():
    # the TPU small-k fast path (subspace iteration) must agree with the
    # dense eigh kernel; exercised explicitly here since CPU runs route to
    # the host eigh by default
    import jax
    import numpy as np

    from spark_rapids_ml_tpu.ops.linalg import (
        SUBSPACE_RESIDUAL_TOL,
        pca_fit_kernel,
        pca_fit_subspace_kernel,
    )
    from spark_rapids_ml_tpu.parallel.mesh import data_sharding, get_mesh, shard_rows

    rng = np.random.default_rng(0)
    # low-rank + noise, like the reference PCA benchmark workload
    X = (
        rng.standard_normal((512, 16)).astype(np.float32)
        @ rng.standard_normal((16, 96)).astype(np.float32)
        + 0.05 * rng.standard_normal((512, 96)).astype(np.float32)
    )
    mesh = get_mesh(8)
    Xs, _ = shard_rows(X, mesh)
    w = jax.device_put(np.ones(Xs.shape[0], np.float32), data_sharding(mesh))
    k = 3
    m1, c1, v1, r1, s1 = [np.asarray(o) for o in pca_fit_kernel(Xs, w, k)]
    m2, c2, v2, r2, s2, resid = [
        np.asarray(o) for o in pca_fit_subspace_kernel(Xs, w, k)
    ]
    assert float(resid) < SUBSPACE_RESIDUAL_TOL  # converged on this spectrum
    np.testing.assert_allclose(m1, m2, atol=1e-4)
    np.testing.assert_allclose(v1, v2, rtol=1e-3)
    np.testing.assert_allclose(r1, r2, rtol=1e-3)
    np.testing.assert_allclose(s1, s2, rtol=1e-3)
    # components up to sign already fixed by sign_flip -> direct compare
    np.testing.assert_allclose(c1, c2, atol=5e-3)


def test_pca_subspace_residual_flags_nonconvergence():
    # near-isotropic spectrum + crippled iteration count: the kernel must
    # REPORT non-convergence via its residual output (pca_fit falls back to
    # the exact eigh path on accelerators when it does)
    import jax
    import numpy as np

    from spark_rapids_ml_tpu.ops.linalg import (
        SUBSPACE_RESIDUAL_TOL,
        pca_fit_subspace_kernel,
    )

    rng = np.random.default_rng(1)
    X = rng.standard_normal((2048, 96)).astype(np.float32)  # iid: flat spectrum
    w = jax.device_put(np.ones(2048, np.float32))
    out = pca_fit_subspace_kernel(jax.device_put(X), w, 3, n_iter=1)
    resid = float(np.asarray(out[-1]))
    assert resid > SUBSPACE_RESIDUAL_TOL
