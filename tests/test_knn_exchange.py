# The kNN candidate-exchange routes (ops/knn.knn_block_kernel_exchange +
# the distributed_kneighbors ring protocol): the bitwise 1/2/8-device
# parity matrix — ring-permute exchange == all-gather exchange ==
# single-device reference — plus routing, zero-recompile, and byte-counter
# gates.  Runs on the virtual 8-device CPU mesh (conftest), where
# DeviceSection.ring_shift takes the lax.ppermute fallback with semantics
# identical to the TPU remote-DMA kernel.
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.ops.knn import (
    _exchange_geometry,
    knn_block_kernel_exchange,
    knn_search_prepared,
    lex_topk,
    prepare_items,
)
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS


def _mesh(n_dev: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:n_dev]), (DATA_AXIS,))


def _make_data(n=4096, d=48, q=512, seed=0):
    rng = np.random.default_rng(seed)
    items = rng.standard_normal((n, d)).astype(np.float32)
    ids = np.arange(n, dtype=np.int64)
    queries = rng.standard_normal((q, d)).astype(np.float32)
    return items, ids, queries


# -- lex_topk oracle ----------------------------------------------------------


def test_lex_topk_matches_numpy_lexsort():
    rng = np.random.default_rng(5)
    Qn, C, k = 32, 3000, 17
    d2 = rng.integers(0, 50, size=(Qn, C)).astype(np.float32)  # many ties
    pos = rng.permutation(C).astype(np.int32)[None].repeat(Qn, 0)
    sd, sp = lex_topk(jnp.asarray(d2), jnp.asarray(pos), k)
    order = np.lexsort((pos, d2), axis=1)[:, :k]
    np.testing.assert_array_equal(
        np.asarray(sd), np.take_along_axis(d2, order, axis=1)
    )
    np.testing.assert_array_equal(
        np.asarray(sp), np.take_along_axis(pos, order, axis=1)
    )


# -- the bitwise parity matrix ------------------------------------------------


def test_exchange_parity_matrix_bitwise():
    """ring == gather == 1-device reference, BITWISE, on 1/2/8-device
    meshes: the lex (d2, pos) key is a total order and the fixed-tile
    scans keep every distance tile identically shaped, so any route and
    any mesh must land on the same bits (the acceptance gate)."""
    from sklearn.neighbors import NearestNeighbors as SkNN

    items, ids, queries = _make_data()
    k = 17
    qd = jnp.asarray(queries)
    handles = {}
    for n_dev in (1, 2, 8):
        mesh = _mesh(n_dev)
        prepared = prepare_items(items, ids, mesh, shuffle=False)
        n_loc = prepared.items.shape[0] // n_dev
        for route in ("ring", "gather"):
            chunk, qt = _exchange_geometry(n_loc, len(queries), n_dev, route)
            handles[(n_dev, route)] = knn_block_kernel_exchange(
                prepared.items, prepared.norm, prepared.pos, prepared.valid,
                qd, mesh, k, route, chunk, qt,
            )
    results = {key: jax.device_get(v) for key, v in handles.items()}
    ref_d, ref_p = results[(1, "ring")]
    for key, (dist, pos) in results.items():
        np.testing.assert_array_equal(dist, ref_d, err_msg=str(key))
        np.testing.assert_array_equal(pos, ref_p, err_msg=str(key))
    # and the reference is exact vs sklearn
    sd, si = SkNN(n_neighbors=k, algorithm="brute").fit(items).kneighbors(
        queries
    )
    np.testing.assert_allclose(ref_d, sd, rtol=1e-4, atol=1e-4)
    assert (ref_p == si).mean() > 0.999


def test_exchange_parity_with_invalid_rows_and_k_over_items():
    """Padding rows (valid=False) and k > n_items: every route must mask
    identically and mark unfillable slots with inf distance."""
    rng = np.random.default_rng(9)
    n, d, q = 512, 32, 128
    items = rng.standard_normal((n, d)).astype(np.float32)
    ids = np.arange(n, dtype=np.int64)
    queries = rng.standard_normal((q, d)).astype(np.float32)
    k = n + 13  # more neighbors than items
    qd = jnp.asarray(queries)
    handles = {}
    for n_dev in (1, 8):
        mesh = _mesh(n_dev)
        prepared = prepare_items(items, ids, mesh, shuffle=False)
        n_loc = prepared.items.shape[0] // n_dev
        for route in ("ring", "gather"):
            chunk, qt = _exchange_geometry(n_loc, q, n_dev, route)
            handles[(n_dev, route)] = knn_block_kernel_exchange(
                prepared.items, prepared.norm, prepared.pos, prepared.valid,
                qd, mesh, k, route, chunk, qt,
            )
    outs = {key: jax.device_get(v) for key, v in handles.items()}
    ref = outs[(1, "ring")]
    for key, (dist, pos) in outs.items():
        np.testing.assert_array_equal(dist, ref[0], err_msg=str(key))
        np.testing.assert_array_equal(pos, ref[1], err_msg=str(key))
    assert np.isinf(ref[0][:, n:]).all(), "unfillable slots must be inf"
    assert np.isfinite(ref[0][:, :n]).all()


# -- route plumbing through knn_search_prepared -------------------------------


def test_search_prepared_ring_equals_gather_and_legacy(monkeypatch):
    """The full pipelined search must give identical distances (and ids,
    data has no ties) on every exchange route of the same mesh."""
    items, ids, queries = _make_data(n=2048, d=24, q=300, seed=3)
    k = 9
    mesh = _mesh(8)
    out = {}
    for route in ("ring", "gather", "legacy"):
        monkeypatch.setenv("SRML_KNN_EXCHANGE", route)
        prepared = prepare_items(items, ids, mesh, shuffle=False)
        d, i = knn_search_prepared(prepared, queries, k, mesh)
        out[route] = (d, i)
    for route in ("gather", "legacy"):
        np.testing.assert_allclose(
            out["ring"][0], out[route][0], rtol=1e-5, atol=1e-6
        )
        np.testing.assert_array_equal(out["ring"][1], out[route][1])


def test_ring_route_zero_new_compiles_on_repeat_search():
    """Repeat same-shape search over the ring route: every kernel rides
    the AOT executable cache, so the second search performs ZERO new
    compilations (the steady-state contract the bench smoke asserts)."""
    items, ids, queries = _make_data(n=2048, d=24, q=256, seed=4)
    mesh = _mesh(8)
    prepared = prepare_items(items, ids, mesh, shuffle=False)
    d1, i1 = knn_search_prepared(prepared, queries, 9, mesh)
    c0 = profiling.counters("precompile")
    d2_, i2 = knn_search_prepared(prepared, queries, 9, mesh)
    c1 = profiling.counters("precompile")
    assert c1.get("precompile.compile", 0) == c0.get("precompile.compile", 0)
    assert c1.get("precompile.fallback", 0) == c0.get(
        "precompile.fallback", 0
    )
    assert c1.get("precompile.aot_hit", 0) > c0.get("precompile.aot_hit", 0)
    np.testing.assert_array_equal(d1, d2_)
    np.testing.assert_array_equal(i1, i2)


def test_warm_covers_ring_dispatch_key():
    """warm_search_kernels must submit the EXACT executable the routed
    ring dispatch later looks up — sharded query aval included — so a
    warmed search is compile-free from its very first block (no
    input-incompat fallback, straight aot_hit)."""
    from spark_rapids_ml_tpu.ops.knn import warm_search_kernels
    from spark_rapids_ml_tpu.ops.precompile import global_precompiler

    items, ids, queries = _make_data(n=2048, d=24, q=256, seed=7)
    mesh = _mesh(8)
    prepared = prepare_items(items, ids, mesh, shuffle=False)
    keys = warm_search_kernels(prepared, 7, mesh, n_queries=256, d_query=24)
    assert keys, "exact ring route submitted no warm keys"
    global_precompiler().wait(keys)
    c0 = profiling.counters("precompile")
    knn_search_prepared(prepared, queries, 7, mesh)
    c1 = profiling.counters("precompile")
    assert c1.get("precompile.compile", 0) == c0.get("precompile.compile", 0)
    assert c1.get("precompile.fallback", 0) == c0.get(
        "precompile.fallback", 0
    )
    assert c1.get("precompile.aot_hit", 0) > c0.get("precompile.aot_hit", 0)


def test_ring_sections_report_bytes():
    """The ring exchange reports per-hop payload bytes through the typed
    exchange sections (exchange.knn.ring_q / exchange.knn.ring_cand) — the
    counters the bench `bytes moved` column totals."""
    profiling.reset_counters("exchange.knn.ring")
    items, ids, queries = _make_data(n=1024, d=16, q=128, seed=6)
    mesh = _mesh(8)
    prepared = prepare_items(items, ids, mesh, shuffle=False)
    n_loc = prepared.items.shape[0] // 8
    chunk, qt = _exchange_geometry(n_loc, len(queries), 8, "ring")
    knn_block_kernel_exchange(
        prepared.items, prepared.norm, prepared.pos, prepared.valid,
        jnp.asarray(queries), mesh, 5, "ring", chunk, qt,
    )
    ctr = profiling.counters("exchange.knn.ring")
    # 8 hops x per-shard (16, 16) f32 query block
    assert ctr["exchange.knn.ring_q.bytes"] == 8 * (128 // 8) * 16 * 4
    # 8 hops x per-shard (16, 5) f32 + (16, 5) i32 running candidates
    assert ctr["exchange.knn.ring_cand.bytes"] == 8 * 2 * (128 // 8) * 5 * 4
    profiling.reset_counters("exchange.knn.ring")


# -- distributed_kneighbors: host-plane ring route ----------------------------


class _StringBarrier:
    """String-only allGather mock with true barrier semantics (the same
    shape as Spark's BarrierTaskContext; see tests/test_exchange.py)."""

    def __init__(self, nranks):
        self.nranks = nranks
        self._barrier = threading.Barrier(nranks)
        self._slots = [None] * nranks
        self._lock = threading.Lock()

    def plane(self, rank):
        outer = self

        class _P:
            def allGather(self, message):
                assert isinstance(message, str)
                with outer._lock:
                    outer._slots[rank] = message
                outer._barrier.wait()
                out = list(outer._slots)
                outer._barrier.wait()
                return out

        return _P()


def _run_ranks(nranks, fn):
    results, errors = {}, {}

    def run(r):
        try:
            results[r] = fn(r)
        except Exception as e:  # surfaced below
            errors[r] = e

    ts = [
        threading.Thread(target=run, args=(r,), name=f"knnx-rank{r}")
        for r in range(nranks)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    return results


def _distributed_case(route_env, monkeypatch, budget=None):
    from sklearn.neighbors import NearestNeighbors as SkNN

    from spark_rapids_ml_tpu.ops.knn import distributed_kneighbors

    monkeypatch.setenv("SRML_KNN_EXCHANGE", route_env)
    if budget is not None:
        monkeypatch.setenv("SRML_KNN_HBM_BUDGET", str(budget))
    nranks = 4
    rng = np.random.default_rng(3)
    n, d, k = 700, 9, 11
    items = rng.normal(size=(n, d)).astype(np.float32)
    ids = np.arange(n, dtype=np.int64) * 7 + 3
    queries = rng.normal(size=(37, d)).astype(np.float32)
    item_split = np.array_split(np.arange(n), nranks)
    # rank 2 owns NO queries; rank 1 owns none of the items
    q_split = [
        np.arange(0, 20), np.arange(20, 30), np.arange(0, 0),
        np.arange(30, 37),
    ]
    item_split[1] = np.arange(0)
    bar = _StringBarrier(nranks)

    def fn(rank):
        ip = [(items[item_split[rank]], ids[item_split[rank]])]
        qp = [(queries[q_split[rank]], q_split[rank].astype(np.int64))]
        return distributed_kneighbors(
            ip, qp, k, rank, nranks, bar.plane(rank)
        )

    results = _run_ranks(nranks, fn)
    sk_d, sk_i = SkNN(n_neighbors=k).fit(
        items[np.concatenate([item_split[r] for r in range(nranks)])]
    ).kneighbors(queries)
    return results, q_split, sk_d, ids[
        np.concatenate([item_split[r] for r in range(nranks)])
    ][sk_i]


def test_distributed_ring_route_matches_reference(monkeypatch):
    """4 thread-ranks over the string plane, ring route: every rank's
    query partitions must come back exactly as a single-process search
    would give them — including the empty-query and empty-item ranks."""
    profiling.reset_counters("exchange.")
    results, q_split, sk_d, sk_ids = _distributed_case("ring", monkeypatch)
    for rank in range(4):
        ((d_out, i_out),) = results[rank]
        rows = q_split[rank]
        assert d_out.shape == (len(rows), 11)
        np.testing.assert_allclose(d_out, sk_d[rows], rtol=1e-4, atol=1e-4)
        if len(rows):
            assert (i_out == sk_ids[rows]).mean() > 0.99
    ctr = profiling.counters("exchange.")
    # the ring route never broadcast queries: exactly nranks ring passes
    # per rank, and round-2 alltoall never ran
    assert ctr.get("exchange.ring.calls", 0) == 4 * 4
    assert ctr.get("exchange.alltoall.calls", 0) == 0
    profiling.reset_counters("exchange.")


def test_distributed_ring_and_allgather_routes_agree(monkeypatch):
    res_ring, q_split, sk_d, _ = _distributed_case("ring", monkeypatch)
    res_ag, _, _, _ = _distributed_case("gather", monkeypatch)
    for rank in range(4):
        ((dr, ir),) = res_ring[rank]
        ((da, ia),) = res_ag[rank]
        np.testing.assert_allclose(dr, da, rtol=1e-5, atol=1e-6)
        # data has no distance ties -> ids must agree exactly
        np.testing.assert_array_equal(ir, ia)


def test_distributed_ring_falls_back_when_any_rank_overflows(monkeypatch):
    """A rank whose items exceed its device budget publishes ring_ok=0 in
    the metadata round, so EVERY rank takes the allgather route — the
    route decision is collective, never split-brain."""
    profiling.reset_counters("exchange.")
    # 175 items x 9 cols x 4B = 6300 B/rank > 4096-byte budget -> no ring
    results, q_split, sk_d, sk_ids = _distributed_case(
        "ring", monkeypatch, budget=2048
    )
    for rank in range(4):
        ((d_out, i_out),) = results[rank]
        rows = q_split[rank]
        np.testing.assert_allclose(d_out, sk_d[rows], rtol=1e-4, atol=1e-4)
    ctr = profiling.counters("exchange.")
    assert ctr.get("exchange.ring.calls", 0) == 0
    assert ctr.get("exchange.alltoall.calls", 0) == 4
    profiling.reset_counters("exchange.")
