# Framework proof with a fake algorithm — the analog of the reference's
# test_common_estimator.py (CumlDummy/SparkRapidsMLDummy,
# /root/reference/python/tests/test_common_estimator.py:46-310): exercises the
# param translation layer, fit/transform dispatch, PartitionDescriptor
# visibility inside the fit function, persistence, and num_workers handling —
# with no real algorithm.
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.core import (
    FitInputs,
    _TpuEstimator,
    _TpuModel,
    load,
)
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.params import Param, Params, TypeConverters, _dummy, HasFeaturesCol, HasFeaturesCols


class _DummyParams(HasFeaturesCol, HasFeaturesCols):
    alpha = Param(_dummy(), "alpha", "alpha param", TypeConverters.toFloat)
    beta = Param(_dummy(), "beta", "ignored param", TypeConverters.toInt)
    gamma = Param(_dummy(), "gamma", "unsupported param", TypeConverters.toString)

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._setDefault(alpha=1.0, beta=2, gamma="three")


class TpuDummy(_DummyParams, _TpuEstimator):
    """Fake estimator: solver params are {alpha_: float, k: int}; spark param
    `beta` is silently ignored, `gamma` is unsupported (raises on set)."""

    @classmethod
    def _param_mapping(cls):
        return {"alpha": "alpha_", "beta": "", "gamma": None}

    @classmethod
    def _get_tpu_params_default(cls):
        return {"alpha_": 1.0, "k": 4}

    def __init__(self, **kwargs):
        super().__init__()
        self._initialize_tpu_params()
        self._set_params(**kwargs)
        self.fit_calls = []

    def _get_tpu_fit_func(self, dataset, extra_params=None):
        n_expected = dataset.count()
        pdesc_rows = [len(p) for p in dataset.partitions]

        def _fit(inputs: FitInputs, params):
            # PartitionDescriptor carries original partition layout
            assert inputs.pdesc.m == n_expected
            assert [s for _, s in inputs.pdesc.parts_rank_size] == pdesc_rows
            assert inputs.X.shape[0] >= inputs.n_rows
            assert inputs.X.shape[1] == inputs.n_cols
            # weighted row count equals true row count (padding masked)
            assert float(np.sum(np.asarray(inputs.weight))) == pytest.approx(inputs.n_rows)
            mean = np.asarray(
                (inputs.X * inputs.weight[:, None]).sum(axis=0)
            ) / inputs.n_rows
            return {
                "mean": np.asarray(mean, dtype=np.float64),
                "n_cols": inputs.n_cols,
                "alpha_used": params["alpha_"],
            }

        return _fit

    def _create_model(self, result):
        return TpuDummyModel(**result)


class TpuDummyModel(_DummyParams, _TpuModel):
    @classmethod
    def _param_mapping(cls):
        return {"alpha": "alpha_", "beta": "", "gamma": None}

    @classmethod
    def _get_tpu_params_default(cls):
        return {"alpha_": 1.0, "k": 4}

    def __init__(self, mean, n_cols, alpha_used):
        super().__init__(mean=np.asarray(mean), n_cols=int(n_cols), alpha_used=float(alpha_used))
        self.mean = np.asarray(mean)
        self.n_cols = int(n_cols)
        self.alpha_used = float(alpha_used)

    def _out_columns(self):
        return ["centered_norm"]

    def _get_tpu_transform_func(self, dataset):
        mean = self.mean

        def _transform(features: np.ndarray):
            return {"centered_norm": np.linalg.norm(features - mean, axis=1)}

        return _transform


def _make_df(layout, n_parts=3):
    X = np.arange(24, dtype=np.float64).reshape(8, 3)
    return X, DataFrame.from_numpy(X, feature_layout=layout, num_partitions=n_parts)


def test_param_mapping_and_defaults():
    est = TpuDummy()
    assert est.tpu_params == {"alpha_": 1.0, "k": 4}
    est = TpuDummy(alpha=2.5)
    assert est.getOrDefault("alpha") == 2.5
    assert est.tpu_params["alpha_"] == 2.5
    # solver-name route reflects back into the Spark param
    est = TpuDummy(alpha_=3.5)
    assert est.getOrDefault("alpha") == 3.5
    # solver-only param
    est = TpuDummy(k=9)
    assert est.tpu_params["k"] == 9
    # ignored param: settable, not propagated
    est = TpuDummy(beta=7)
    assert est.getOrDefault("beta") == 7
    assert "beta" not in est.tpu_params and "" not in est.tpu_params


def test_unsupported_param_raises():
    with pytest.raises(ValueError, match="not supported"):
        TpuDummy(gamma="x")
    with pytest.raises(ValueError, match="Unsupported param"):
        TpuDummy(nonexistent=1)


@pytest.mark.parametrize("layout", ["array", "vector", "multi_cols"])
def test_fit_transform_layouts(layout):
    X, df = _make_df(layout)
    est = TpuDummy()
    if layout == "multi_cols":
        est.setFeaturesCol([c for c in df.columns])
    model = est.fit(df)
    np.testing.assert_allclose(model.mean, X.mean(axis=0), rtol=1e-6)
    out = model.transform(df)
    assert "centered_norm" in out.columns
    got = np.asarray(out.toPandas()["centered_norm"].to_numpy(), dtype=np.float64)
    np.testing.assert_allclose(
        got, np.linalg.norm(X - X.mean(axis=0), axis=1), rtol=1e-5
    )


def test_float32_inputs_flag():
    X, df = _make_df("array")
    est = TpuDummy(float32_inputs=False)
    assert est._float32_inputs is False
    model = est.fit(df)
    np.testing.assert_allclose(model.mean, X.mean(axis=0), rtol=1e-12)


def test_num_workers(n_devices):
    est = TpuDummy()
    assert est.num_workers == n_devices
    est = TpuDummy(num_workers=2)
    assert est.num_workers == 2
    _, df = _make_df("array")
    model = est.fit(df)
    assert model is not None


def test_empty_dataset_raises():
    df = DataFrame.from_pandas(pd.DataFrame({"features": []}))
    with pytest.raises(RuntimeError, match="empty"):
        TpuDummy().fit(df)


def test_estimator_persistence(tmp_path):
    est = TpuDummy(alpha=4.0, k=11, num_workers=3, float32_inputs=False)
    path = str(tmp_path / "dummy_est")
    est.save(path)
    loaded = load(path)
    assert isinstance(loaded, TpuDummy)
    assert loaded.getOrDefault("alpha") == 4.0
    assert loaded.tpu_params["alpha_"] == 4.0
    assert loaded.tpu_params["k"] == 11
    assert loaded.num_workers == 3
    assert loaded._float32_inputs is False


def test_model_persistence(tmp_path):
    X, df = _make_df("array")
    model = TpuDummy(alpha=2.0).fit(df)
    path = str(tmp_path / "dummy_model")
    model.save(path)
    loaded = load(path)
    assert isinstance(loaded, TpuDummyModel)
    np.testing.assert_allclose(loaded.mean, model.mean)
    assert loaded.n_cols == 3
    assert loaded.alpha_used == 2.0
    out = loaded.transform(df)
    assert "centered_norm" in out.columns


def test_copy_semantics():
    est = TpuDummy(alpha=2.0)
    est2 = est.copy({TpuDummy.alpha: 5.0})
    assert est.getOrDefault("alpha") == 2.0
    assert est2.getOrDefault("alpha") == 5.0


def test_fit_with_params_list():
    _, df = _make_df("array")
    est = TpuDummy()
    models = est.fit(df, [{TpuDummy.alpha: 1.5}, {TpuDummy.alpha: 2.5}])
    assert len(models) == 2
    assert models[0].getOrDefault("alpha") == 1.5
    assert models[1].getOrDefault("alpha") == 2.5


def test_sparse_feature_cells():
    # pyspark SparseVector/DenseVector cells and scipy CSR rows densify at
    # ingest (the reference accepts Vectors.sparse inputs,
    # classification.py:418,435)
    import scipy.sparse as sp

    from spark_rapids_ml_tpu.utils import stack_feature_cells

    # duck-typed stand-ins for pyspark.ml.linalg vectors (pyspark itself is
    # not installed in the test image; ingest keys on toArray/indices/values)
    class FakeSparseVector:
        def __init__(self, size, indices, values):
            self.size, self.indices = size, np.asarray(indices)
            self.values = np.asarray(values, dtype=np.float64)

        def __len__(self):
            return self.size

        def toArray(self):
            out = np.zeros(self.size)
            out[self.indices] = self.values
            return out

    class FakeDenseVector:
        def __init__(self, values):
            self.values = np.asarray(values, dtype=np.float64)

        def __len__(self):
            return len(self.values)

        def toArray(self):
            return self.values

    dense = np.array([[1.0, 0.0, 2.0], [0.0, 3.0, 0.0]])
    cells_ps = [FakeSparseVector(3, [0, 2], [1.0, 2.0]), FakeDenseVector([0.0, 3.0, 0.0])]
    np.testing.assert_allclose(stack_feature_cells(cells_ps, np.float32), dense)
    csr = sp.csr_matrix(dense)
    cells_sp = [csr[i] for i in range(2)]
    np.testing.assert_allclose(stack_feature_cells(cells_sp, np.float32), dense)

    # end-to-end: fit from a DataFrame whose feature cells are SparseVectors
    rng = np.random.default_rng(0)
    Xd = rng.normal(size=(40, 5))
    Xd[rng.random(Xd.shape) < 0.6] = 0.0
    cells = [
        FakeSparseVector(5, np.nonzero(r)[0], r[np.nonzero(r)[0]]) for r in Xd
    ]
    pdf = pd.DataFrame({"features": cells})
    df = DataFrame([pdf])
    model = TpuDummy().fit(df)
    np.testing.assert_allclose(model.mean, Xd.mean(axis=0), atol=1e-5)


def test_from_numpy_scipy_sparse():
    import scipy.sparse as sp

    rng = np.random.default_rng(1)
    Xd = rng.normal(size=(30, 4))
    Xd[rng.random(Xd.shape) < 0.7] = 0.0
    df = DataFrame.from_numpy(sp.csr_matrix(Xd), num_partitions=2)
    model = TpuDummy().fit(df)
    np.testing.assert_allclose(model.mean, Xd.mean(axis=0), atol=1e-5)


def test_low_precision_features_keep_float32_labels():
    """A half/bfloat16 FEATURE dtype must never round labels: integer
    values above the half-precision mantissa (e.g. 2049 in f16, 257 in
    bf16) have to survive ingest exactly on all three paths — host
    partitions, from_device frames, and the multicontroller global build
    (parallel/runner.DistributedFitSession).  weightCol is unsupported by
    every estimator (reference parity), so only the default ones-mask
    weight dtype is assertable."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import LinearRegression
    from spark_rapids_ml_tpu.parallel.context import LocalControlPlane
    from spark_rapids_ml_tpu.parallel.runner import DistributedFitSession

    n, d = 32, 4
    rng = np.random.default_rng(0)
    X16 = rng.standard_normal((n, d)).astype(np.float16)
    labels = (2048 + np.arange(n)).astype(np.float64)  # 2049 rounds in f16

    est = LinearRegression(float32_inputs=False)
    pdf = pd.DataFrame({"features": list(X16), "label": labels})
    df = DataFrame.from_pandas(pdf, 2)
    feats, labs, _ws, dtype = est._pre_process_data(df)
    assert np.dtype(dtype) == np.float16  # features keep their precision
    y = np.concatenate(labs)
    assert y.dtype == np.float32
    np.testing.assert_array_equal(y, labels)  # no rounding

    inputs = est._build_fit_inputs(df)
    np.testing.assert_array_equal(
        np.asarray(inputs.y)[: inputs.n_rows], labels
    )
    assert np.asarray(inputs.weight).dtype == np.float32

    # the multicontroller global build (rank 0 of 1 over the local mesh)
    sess = DistributedFitSession(0, 1, LocalControlPlane())
    inputs_mc = sess.build_fit_inputs(est, df)
    np.testing.assert_array_equal(
        np.asarray(inputs_mc.y)[: inputs_mc.n_rows], labels
    )
    assert np.asarray(inputs_mc.weight).dtype == np.float32

    # from_device with a bf16 feature array
    Xd = jax.device_put(rng.standard_normal((n, d)).astype(np.float32)).astype(
        jnp.bfloat16
    )
    labels_b = (256 + np.arange(n)).astype(np.float64)  # 257 rounds in bf16
    dfd = DataFrame.from_device(Xd, y=labels_b)
    inputs2 = est._build_fit_inputs(dfd)
    np.testing.assert_array_equal(
        np.asarray(inputs2.y)[: inputs2.n_rows], labels_b
    )
