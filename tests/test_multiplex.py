# srml-lanes multiplexed serving gates (docs/serving.md): K same-shape model
# variants behind ONE lane-batched kernel per micro-batch, bitwise-equal per
# tenant to dedicated per-model serving (the integer-exact-data discipline of
# the sweep parity gates), HBM lane paging with zero-recompile page-in, LRU
# eviction bounded by in-flight pins, per-tenant counters, and the registry/
# router deployment surfaces.
import numpy as np
import pytest

from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.models.kmeans import KMeansModel
from spark_rapids_ml_tpu.models.linear_regression import LinearRegressionModel
from spark_rapids_ml_tpu.models.logistic_regression import LogisticRegressionModel
from spark_rapids_ml_tpu.models.pca import PCAModel
from spark_rapids_ml_tpu.serving import (
    ModelRegistry,
    ModelServer,
    MultiplexServer,
    Router,
    ServerOverloaded,
    lane_entry_for,
    lane_signature,
)

D = 5  # feature width shared by the variant zoo


def _linreg(rng, i):
    return LinearRegressionModel(
        coef_=rng.randint(-3, 4, size=D).astype(np.float64),
        intercept_=float(i % 3),
        n_cols=D,
        dtype="float32",
    )


def _logreg(rng, i):
    return LogisticRegressionModel(
        coef_=rng.randint(-2, 3, size=(3, D)).astype(np.float64),
        intercept_=rng.randint(-2, 3, size=3).astype(np.float64),
        classes_=np.array([0.0, 1.0, 2.0]),
        n_cols=D,
        dtype="float32",
    )


def _kmeans(rng, i):
    return KMeansModel(
        cluster_centers_=rng.randint(-5, 6, size=(4, D)).astype(np.float64),
        n_cols=D,
        dtype="float32",
    )


def _pca(rng, i):
    return PCAModel(
        mean_=np.zeros(D),
        components_=rng.randint(-2, 3, size=(2, D)).astype(np.float64),
        explained_variance_=np.array([4.0, 1.0]),
        explained_variance_ratio_=np.array([0.8, 0.2]),
        singular_values_=np.array([2.0, 1.0]),
        n_cols=D,
        dtype="float32",
    )


FAMILIES = {"linreg": _linreg, "logreg": _logreg, "kmeans": _kmeans, "pca": _pca}


def _variants(family, k, seed=0):
    rng = np.random.RandomState(seed)
    return {f"m{i}": FAMILIES[family](rng, i) for i in range(k)}


def _int_X(n, seed=1):
    # integer-valued f32: exactly representable, every reduction order
    # exact — the bitwise-parity basis the sweep gates established
    return np.random.RandomState(seed).randint(-4, 5, size=(n, D)).astype(np.float32)


def _dedicated_outputs(models, X):
    out = {}
    for mid, m in models.items():
        with ModelServer(f"ded-{mid}-{id(m):x}", m) as srv:
            out[mid] = {c: np.asarray(v) for c, v in srv.predict(X).items()}
    return out


# -- per-tenant bitwise parity ------------------------------------------------


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_multiplex_matches_dedicated_bitwise(family):
    models = _variants(family, 4)
    X = _int_X(7)
    expected = _dedicated_outputs(models, X)
    with MultiplexServer(f"mux_{family}", models) as mux:
        for mid in models:
            got = mux.predict(X, model_id=mid)
            assert sorted(got) == sorted(expected[mid])
            for c in got:
                np.testing.assert_array_equal(
                    np.asarray(got[c]), expected[mid][c], err_msg=f"{mid}/{c}"
                )
        mux.drain()
        mux.assert_steady_state()


def test_interleaved_tenants_share_one_dispatch_plane():
    """A mixed-tenant stream: per-row lane routing through the shared
    micro-batcher keeps every tenant's outputs bitwise-equal to its
    dedicated server, and steady state stays zero new compiles."""
    models = _variants("linreg", 4)
    X = _int_X(3)
    expected = _dedicated_outputs(models, X)
    with MultiplexServer("mux_mixed", models, max_batch=64, max_wait_ms=5) as mux:
        before = profiling.counters("precompile.")
        futs = [
            (mid, mux.submit(X, model_id=mid))
            for _ in range(6)
            for mid in models
        ]
        for mid, f in futs:
            got = f.result(timeout=60)
            np.testing.assert_array_equal(
                np.asarray(got["prediction"]), expected[mid]["prediction"]
            )
        delta = profiling.counter_deltas(before, "precompile.")
        assert delta.get("precompile.compile", 0) == 0, delta
        assert delta.get("precompile.fallback", 0) == 0, delta
        mux.drain()
        mux.assert_steady_state()


def test_single_variant_defaults_model_id():
    models = _variants("linreg", 1)
    X = _int_X(4)
    expected = _dedicated_outputs(models, X)
    with MultiplexServer("mux_one", models) as mux:
        got = mux.predict(X)  # no model_id: the single variant is implied
        np.testing.assert_array_equal(
            np.asarray(got["prediction"]), expected["m0"]["prediction"]
        )


# -- HBM lane paging ----------------------------------------------------------


def test_paging_parity_and_zero_new_compiles():
    """8 registered variants on a 2-lane HBM budget: every request pages
    its variant in on demand (LRU eviction of idle lanes), outputs stay
    bitwise-equal to dedicated servers ACROSS page-in/eviction churn, and
    the whole paged stream adds zero new compiles — the traced-lane-index
    write kernel is the PR 12 insight made load-bearing."""
    models = _variants("linreg", 8, seed=3)
    X = _int_X(5, seed=4)
    expected = _dedicated_outputs(models, X)
    with MultiplexServer("mux_paged", models, resident_lanes=2) as mux:
        assert mux.lanes()["n_lanes"] == 2
        before = profiling.counters("precompile.")
        for _ in range(2):  # two full walks: forces eviction + re-page-in
            for mid in models:
                got = mux.predict(X, model_id=mid)
                np.testing.assert_array_equal(
                    np.asarray(got["prediction"]),
                    expected[mid]["prediction"],
                    err_msg=mid,
                )
        delta = profiling.counter_deltas(before, "precompile.")
        assert delta.get("precompile.compile", 0) == 0, delta
        assert delta.get("precompile.fallback", 0) == 0, delta
        snap = mux.lanes()
        assert snap["registered"] == 8 and snap["resident"] == 2
        # 16 requests on 2 lanes: at most 2 hits (the initial residents),
        # every other access is a page-in over an eviction
        assert snap["page_in"] >= 14, snap
        assert snap["evictions"] >= 12, snap
        assert snap["page_in_latency"]["count"] == snap["page_in"]
        mux.drain()
        mux.assert_steady_state()


def test_page_wait_timeout_is_typed_overload(monkeypatch):
    """Every lane pinned by in-flight traffic + a page-in request for a
    spilled variant = the bounded wait converts to the typed retryable
    ServerOverloaded instead of parking forever (graftlint R9)."""
    monkeypatch.setenv("SRML_SERVE_PAGE_WAIT_S", "0.2")
    models = _variants("linreg", 3)
    X = _int_X(2)
    with MultiplexServer("mux_pin", models, resident_lanes=1,
                         max_batch=16, max_wait_ms=2000) as mux:
        # hold m0's lane pinned: the request sits in the coalesce window
        # (max_wait_ms) with pending > 0 on the only lane
        fut = mux.submit(X, model_id="m0")
        with pytest.raises(ServerOverloaded, match="resident lanes"):
            mux.submit(X, model_id="m1")
        fut.result(timeout=60)
        mux.drain()


# -- contract errors ----------------------------------------------------------


def test_unknown_model_id_is_keyerror():
    with MultiplexServer("mux_err", _variants("linreg", 2)) as mux:
        with pytest.raises(KeyError, match="no registered variant"):
            mux.submit(_int_X(1), model_id="nope")


def test_missing_model_id_with_many_variants_is_valueerror():
    with MultiplexServer("mux_noid", _variants("linreg", 2)) as mux:
        with pytest.raises(ValueError, match="requires model_id"):
            mux.submit(_int_X(1))


def test_signature_mismatch_rejected():
    rng = np.random.RandomState(0)
    a = _linreg(rng, 0)
    wide = LinearRegressionModel(
        coef_=np.arange(D + 1, dtype=np.float64),
        intercept_=0.0,
        n_cols=D + 1,
        dtype="float32",
    )
    with pytest.raises(ValueError, match="lane_signature"):
        MultiplexServer("mux_sig", {"a": a, "b": wide})
    # class mismatch is also a signature mismatch (different kernel ns)
    with pytest.raises(ValueError, match="lane_signature"):
        MultiplexServer("mux_cls", {"a": a, "b": _kmeans(rng, 0)})


def test_unmultiplexable_model_gives_actionable_error():
    class _NoLanes:
        pass

    with pytest.raises(TypeError, match="not multiplexable"):
        lane_entry_for(_NoLanes())


def test_lane_signature_distinguishes_logistic_classes():
    rng = np.random.RandomState(0)
    a = _logreg(rng, 0)
    b = _logreg(rng, 1)
    assert lane_signature(lane_entry_for(a)) == lane_signature(lane_entry_for(b))
    c = LogisticRegressionModel(
        coef_=np.asarray(a.coef_),
        intercept_=np.asarray(a.intercept_),
        classes_=np.array([10.0, 20.0, 30.0]),  # different label vocabulary
        n_cols=D,
        dtype="float32",
    )
    assert lane_signature(lane_entry_for(a)) != lane_signature(lane_entry_for(c))


# -- observability ------------------------------------------------------------


def test_per_tenant_counters_and_stats():
    models = _variants("linreg", 2)
    X = _int_X(3)
    with MultiplexServer("mux_obs", models) as mux:
        for _ in range(3):
            mux.predict(X, model_id="m0")
        mux.predict(X, model_id="m1")
        stats = mux.stats()
        assert stats["lanes"]["registered"] == 2
        assert stats["lanes"]["resident"] == 2
        assert mux.model_ids() == ["m0", "m1"]
        ns = "serving.mux_obs"
        assert profiling.counter(f"{ns}.tenant.m0.requests") == 3
        assert profiling.counter(f"{ns}.tenant.m0.rows") == 9
        assert profiling.counter(f"{ns}.tenant.m1.requests") == 1
        lat = profiling.percentiles("serve.mux_obs.tenant.m0.latency")
        assert lat["count"] == 3 and lat["p50"] > 0
        mux.drain()


# -- registry / router deployment ---------------------------------------------


def test_registry_multiplex_lifecycle():
    models = _variants("linreg", 3)
    X = _int_X(4)
    expected = _dedicated_outputs(models, X)
    with ModelRegistry() as reg:
        srv = reg.multiplex("fleet", models, resident_lanes=2)
        assert isinstance(srv, MultiplexServer)
        assert "fleet" in reg and reg.get("fleet") is srv
        with pytest.raises(ValueError, match="already registered"):
            reg.multiplex("fleet", models)
        with pytest.raises(ValueError, match="already registered"):
            reg.register("fleet", models["m0"])
        got = reg.get("fleet").predict(X, model_id="m2")
        np.testing.assert_array_equal(
            np.asarray(got["prediction"]), expected["m2"]["prediction"]
        )
        health = reg.health()
        from spark_rapids_ml_tpu.serving import READY

        assert health["models"]["fleet"]["state"] == READY
        reg.unregister("fleet")
        assert "fleet" not in reg


def test_registry_multiplex_failed_init_releases_name():
    rng = np.random.RandomState(0)
    bad = {"a": _linreg(rng, 0), "b": _kmeans(rng, 0)}
    with ModelRegistry() as reg:
        with pytest.raises(ValueError, match="lane_signature"):
            reg.multiplex("doomed", bad)
        assert "doomed" not in reg
        reg.multiplex("doomed", _variants("linreg", 2))  # name is free again


def test_router_serves_multiplexed_set():
    models = _variants("linreg", 3)
    X = _int_X(4)
    expected = _dedicated_outputs(models, X)
    router = Router(replicas=1)
    try:
        router.serve_multiplex("tenants", models)
        for mid in models:
            got = router.predict("tenants", X, model_id=mid)
            np.testing.assert_array_equal(
                np.asarray(got["prediction"]), expected[mid]["prediction"]
            )
        # client errors resolve the routed future (typed, no failover loop)
        fut = router.submit("tenants", X, model_id="nope")
        with pytest.raises(KeyError, match="no registered variant"):
            fut.result(timeout=60)
        fut = router.submit("tenants", X)  # 3 variants, no model_id
        with pytest.raises(ValueError, match="requires model_id"):
            fut.result(timeout=60)
    finally:
        router.shutdown()
