#
# fit(pyspark_df) must train through the Spark barrier path — NOT collect to
# the driver (VERDICT round 1, item 1).  pyspark is not installable on this
# image (no network; see NOTES.md), so the pyspark surfaces run_barrier_fit
# actually touches (repartition/mapInPandas/rdd.barrier/collect,
# BarrierTaskContext) are mocked faithfully in-process with ONE barrier task;
# the real multi-process jax.distributed execution underneath is covered by
# test_multicontroller.py with OS-process workers.
#
import sys
import types

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import KMeans, LinearRegression
from spark_rapids_ml_tpu.dataframe import DataFrame


class _FakeBarrierTaskContext:
    _current = None

    def __init__(self, rank: int):
        self._rank = rank

    @classmethod
    def get(cls):
        return cls._current

    def partitionId(self):
        return self._rank

    def allGather(self, message):
        return [message]

    def barrier(self):
        return None


class _FakeRdd:
    def __init__(self, partitions, udf=None):
        self._partitions = partitions
        self._udf = udf

    def getNumPartitions(self):
        return len(self._partitions)

    def barrier(self):
        return self

    def mapPartitions(self, f):
        return self

    def withResources(self, profile):
        return self

    def collect(self):
        rows = []
        for rank, part in enumerate(self._partitions):
            _FakeBarrierTaskContext._current = _FakeBarrierTaskContext(rank)
            try:
                for out in self._udf(iter([part])):
                    for _, r in out.iterrows():
                        rows.append({"model_attributes": r["model_attributes"]})
            finally:
                _FakeBarrierTaskContext._current = None
        return rows


class _FakeConf:
    def __init__(self, conf=None):
        self._conf = {"spark.master": "local[1]", **(conf or {})}

    def get(self, key, default=None):
        return self._conf.get(key, default)


class _FakeSparkSession:
    version = "3.5.0"

    def __init__(self, conf=None):
        self.sparkContext = types.SimpleNamespace(
            getConf=lambda: _FakeConf(conf)
        )


class _FakeSparkDataFrame:
    """Just enough of pyspark.sql.DataFrame for run_barrier_fit; the class
    advertises the pyspark module path so core._is_pyspark_dataframe routes
    it to the barrier dispatcher."""

    def __init__(self, partitions, udf=None, conf=None):
        self._partitions = partitions
        self._udf = udf
        self._conf = conf
        self.sparkSession = _FakeSparkSession(conf)

    def repartition(self, n):
        if n == len(self._partitions):
            return self
        whole = pd.concat(self._partitions, ignore_index=True)
        idx = np.array_split(np.arange(len(whole)), n)
        return _FakeSparkDataFrame(
            [whole.iloc[ix].reset_index(drop=True) for ix in idx],
            conf=self._conf,
        )

    def sample(self, fraction=None, seed=None, withReplacement=None):
        rng = np.random.default_rng(seed)
        return _FakeSparkDataFrame(
            [
                p[rng.random(len(p)) < fraction].reset_index(drop=True)
                for p in self._partitions
            ],
            conf=self._conf,
        )

    def mapInPandas(self, udf, schema=None):
        return _FakeSparkDataFrame(self._partitions, udf=udf, conf=self._conf)

    @property
    def rdd(self):
        return _FakeRdd(self._partitions, self._udf)

    @property
    def columns(self):
        return list(self._partitions[0].columns)


_FakeSparkDataFrame.__module__ = "pyspark.sql.dataframe"


@pytest.fixture()
def fake_pyspark(monkeypatch):
    mod = types.ModuleType("pyspark")
    mod.BarrierTaskContext = _FakeBarrierTaskContext
    monkeypatch.setitem(sys.modules, "pyspark", mod)
    monkeypatch.delenv("SRML_SPARK_COLLECT", raising=False)


def _data():
    rng = np.random.default_rng(3)
    X = rng.standard_normal((600, 6)).astype(np.float32)
    X[:300] += 4.0
    y = (X @ rng.standard_normal(6).astype(np.float32)).astype(np.float32)
    return X, y


def _fake_sdf(X, y=None):
    pdf = pd.DataFrame({"features": list(X)})
    if y is not None:
        pdf["label"] = y
    return _FakeSparkDataFrame([pdf])


def test_kmeans_fit_routes_through_barrier(fake_pyspark):
    X, _ = _data()
    model = KMeans(k=2, maxIter=15, seed=5).fit(_fake_sdf(X))
    baseline = KMeans(k=2, maxIter=15, seed=5).fit(DataFrame.from_numpy(X))
    np.testing.assert_allclose(
        np.asarray(model.cluster_centers_),
        np.asarray(baseline.cluster_centers_),
        rtol=1e-5, atol=1e-5,
    )
    # and the returned model is a full working model
    preds = model.transform(DataFrame.from_numpy(X)).toPandas()["prediction"]
    assert set(np.unique(preds)) == {0, 1}


def test_linreg_fit_multiple_single_pass_over_barrier(fake_pyspark):
    X, y = _data()
    est = LinearRegression(maxIter=50)
    pm = [
        {est.getParam("regParam"): 0.0},
        {est.getParam("regParam"): 0.5},
    ]
    models = est.fit(_fake_sdf(X, y), pm)
    assert len(models) == 2
    facade = DataFrame.from_numpy(X, y)
    for m, p in zip(models, pm):
        b = LinearRegression(maxIter=50, regParam=list(p.values())[0]).fit(facade)
        np.testing.assert_allclose(
            np.asarray(m.coef_), np.asarray(b.coef_), rtol=1e-4, atol=1e-5
        )
    # the two regularizations genuinely differ
    assert not np.allclose(
        np.asarray(models[0].coef_), np.asarray(models[1].coef_), rtol=1e-3
    )


def test_barrier_fit_surfaces_merged_telemetry(fake_pyspark):
    """The executor-side fit's TelemetrySnapshot must ride the model-
    attribute wire and surface on the DRIVER-side model — the live-Spark
    half of the srml-scope acceptance gate (the local half lives in
    test_profiling.test_local_fit_attaches_telemetry)."""
    from spark_rapids_ml_tpu.core import TELEMETRY_ATTR

    X, _ = _data()
    model = KMeans(k=2, maxIter=5, seed=5).fit(_fake_sdf(X))
    t = model.fit_telemetry()
    assert t is not None, "barrier fit lost its telemetry snapshot"
    # the executor phases (runner.*) are what must cross the wire — the
    # driver thread never ran the fit
    assert t.phases["runner.fit"]["count"] == 1
    assert t.phases["runner.fit"]["total_s"] > 0.0
    assert "runner.build_inputs" in t.phases
    assert t.meta["ranks"] == [0]
    # driver-side phase view is rebuilt from the snapshot
    est = KMeans(k=2, maxIter=5, seed=5)
    est.fit(_fake_sdf(X))
    assert est._last_fit_phase_times.get("runner.fit", 0.0) > 0.0
    # and the wire key never leaks into model attributes
    assert TELEMETRY_ATTR not in model._get_model_attributes()


def test_missing_input_column_fails_on_driver(fake_pyspark):
    """A wrong featuresCol must raise BEFORE any barrier stage launches —
    not as an executor traceback."""
    X, _ = _data()
    est = KMeans(k=2, maxIter=5).setFeaturesCol("nope")
    with pytest.raises(ValueError, match="nope"):
        est.fit(_fake_sdf(X))


def test_num_workers_inference_order(fake_pyspark):
    from spark_rapids_ml_tpu.spark.adapter import (
        NUM_WORKERS_CONF,
        infer_spark_num_workers,
    )

    class _Spark:
        def __init__(self, conf):
            self.sparkContext = types.SimpleNamespace(
                getConf=lambda: types.SimpleNamespace(get=conf.get)
            )

    est = KMeans(k=2)
    # estimator num_workers means mesh DEVICE count everywhere else, so the
    # barrier task count deliberately ignores it — even when set
    est._num_workers = 3
    assert infer_spark_num_workers(est, _Spark({NUM_WORKERS_CONF: "5"})) == 5
    est._num_workers = None
    # our own conf beats executor instances
    assert infer_spark_num_workers(
        est, _Spark({NUM_WORKERS_CONF: "5", "spark.executor.instances": "7"})
    ) == 5
    # then executor instances
    assert infer_spark_num_workers(
        est, _Spark({"spark.executor.instances": "7"})
    ) == 7
    # fallback: single worker (NOT the partition or device count)
    assert infer_spark_num_workers(est, _Spark({})) == 1


def test_umap_cluster_fit_degrades_to_single_task(fake_pyspark):
    """UMAP on a >1-worker cluster must NOT raise: the adapter runs a 1-task
    barrier stage (the reference samples + coalesces to one worker,
    umap.py:831-850) and inference stays distributed."""
    from spark_rapids_ml_tpu import UMAP
    from spark_rapids_ml_tpu.spark.adapter import NUM_WORKERS_CONF

    rng = np.random.default_rng(6)
    X = rng.standard_normal((256, 6)).astype(np.float32)
    parts = [
        pd.DataFrame({"features": list(X[ix])}).reset_index(drop=True)
        for ix in np.array_split(np.arange(len(X)), 4)
    ]
    sdf = _FakeSparkDataFrame(parts, conf={NUM_WORKERS_CONF: "4"})
    model = UMAP(n_neighbors=5, n_epochs=30, random_state=4).fit(sdf)
    emb = np.asarray(model.embedding_)
    assert emb.shape == (256, 2) and np.isfinite(emb).all()


def test_umap_cluster_fit_samples_with_spark(fake_pyspark):
    """sample_fraction < 1 on the cluster path samples the DISTRIBUTED frame
    before the 1-task stage — only the sampled rows reach the fit."""
    from spark_rapids_ml_tpu import UMAP
    from spark_rapids_ml_tpu.spark.adapter import NUM_WORKERS_CONF

    rng = np.random.default_rng(8)
    X = rng.standard_normal((400, 5)).astype(np.float32)
    parts = [
        pd.DataFrame({"features": list(X[ix])}).reset_index(drop=True)
        for ix in np.array_split(np.arange(len(X)), 4)
    ]
    sdf = _FakeSparkDataFrame(parts, conf={NUM_WORKERS_CONF: "4"})
    est = UMAP(n_neighbors=5, n_epochs=30, random_state=7, sample_fraction=0.5)
    model = est.fit(sdf)
    n_fit = model.raw_data_.shape[0]
    assert 120 <= n_fit <= 280  # ~half the rows, sampled Spark-side
    # the estimator the user holds is untouched by the internal copy
    assert est.getSampleFraction() == 0.5


def test_collect_override_falls_back_to_driver_local(fake_pyspark, monkeypatch):
    """SRML_SPARK_COLLECT=1 keeps the old driver-collect path for single
    TPU-VM notebooks; the mock lacks toPandas so routing there must fail
    loudly (proving the switch flips the path, not just the default)."""
    monkeypatch.setenv("SRML_SPARK_COLLECT", "1")
    X, _ = _data()
    with pytest.raises((AttributeError, TypeError)):
        KMeans(k=2, maxIter=5).fit(_fake_sdf(X))
