#
# CrossValidator on a live pyspark DataFrame must fold with Spark
# (randomSplit + union on the distributed frame), fit each fold through the
# barrier stage, and score through executor-side transform-evaluate — the
# dataset is NEVER collected to the driver (VERDICT round 3, item 4;
# reference tuning.py:91-148 rides fitMultiple/_transformEvaluate on the
# cluster).  pyspark is absent on this image, so the touched surfaces
# (randomSplit/union/repartition/mapInPandas/rdd.barrier/collect/schema +
# BarrierTaskContext) are mocked faithfully; spark_to_facade is patched to
# raise, PROVING no driver collect happens anywhere in CrossValidator.fit.
#
# The mock's randomSplit implements the same seeded-permutation assignment
# as the local facade's DataFrame.randomSplit, so the executor-side CV can
# be compared metric-for-metric against the driver-local CV on identical
# folds.
#
import sys
import types

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import LinearRegression, LogisticRegression
from spark_rapids_ml_tpu.dataframe import DataFrame, _split_pandas
from spark_rapids_ml_tpu.evaluation import (
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder


class _FakeBarrierTaskContext:
    _current = None

    def __init__(self, rank):
        self._rank = rank

    @classmethod
    def get(cls):
        return cls._current

    def partitionId(self):
        return self._rank

    def allGather(self, message=""):
        return [message]

    def barrier(self):
        return None


class _FakeRdd:
    def __init__(self, df):
        self._df = df

    def barrier(self):
        return self

    def mapPartitions(self, f):
        return self

    def withResources(self, profile):
        return self

    def collect(self):
        rows = []
        for rank, part in enumerate(self._df._partitions):
            _FakeBarrierTaskContext._current = _FakeBarrierTaskContext(rank)
            try:
                for out in self._df._udf(iter([part])):
                    rows.extend(out.to_dict("records"))
            finally:
                _FakeBarrierTaskContext._current = None
        return rows


class _FakeField:
    def __init__(self, name, ddl):
        self.name = name
        self.dataType = types.SimpleNamespace(simpleString=lambda d=ddl: d)


class _FakeConf:
    def get(self, key, default=None):
        return {"spark.master": "local[1]"}.get(key, default)


class _FakeSparkSession:
    version = "3.5.0"

    def __init__(self):
        self.sparkContext = types.SimpleNamespace(getConf=lambda: _FakeConf())


class _FakeSparkDataFrame:
    """pyspark surface for cluster CV: fold ops (randomSplit/union) + the
    barrier fit ops + the executor transform-evaluate ops.  NO toPandas."""

    def __init__(self, partitions, udf=None):
        self._partitions = partitions
        self._udf = udf
        self.sparkSession = _FakeSparkSession()

    def _whole(self):
        return pd.concat(self._partitions, ignore_index=True)

    @property
    def columns(self):
        return list(self._partitions[0].columns)

    @property
    def schema(self):
        ddl = {"features": "array<float>", "label": "double"}
        return types.SimpleNamespace(
            fields=[_FakeField(c, ddl.get(c, "double")) for c in self.columns]
        )

    @property
    def rdd(self):
        return _FakeRdd(self)

    def randomSplit(self, weights, seed=0):
        # same seeded-permutation split as the facade DataFrame.randomSplit
        whole = self._whole()
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(whole))
        total = float(sum(weights))
        bounds = np.cumsum([w / total for w in weights])[:-1]
        cut = (bounds * len(whole)).astype(int)
        nparts = max(1, len(self._partitions))
        return [
            _FakeSparkDataFrame(
                _split_pandas(
                    whole.iloc[np.sort(g)].reset_index(drop=True), nparts
                )
            )
            for g in np.split(perm, cut)
        ]

    def union(self, other):
        assert self.columns == other.columns
        return _FakeSparkDataFrame(self._partitions + other._partitions)

    def cache(self):
        return self

    def unpersist(self):
        return self

    def repartition(self, n):
        if n == len(self._partitions):
            return self
        return _FakeSparkDataFrame(_split_pandas(self._whole(), n))

    def mapInPandas(self, udf, schema=None):
        if self._udf is None:
            return _FakeSparkDataFrame(self._partitions, udf=udf)
        # compose stages like lazy pyspark: a mapInPandas over an already
        # udf-bearing frame (e.g. evaluate over a transform) applies to the
        # PREVIOUS stage's output, per partition
        prev = self._udf

        def chained(part_iter):
            def gen():
                for part in part_iter:
                    yield from prev(iter([part]))

            return udf(gen())

        return _FakeSparkDataFrame(self._partitions, udf=chained)

    def collect(self):
        # executor_transform_evaluate collects METRIC rows (never data rows)
        rows = []
        for part in self._partitions:
            for out in self._udf(iter([part])):
                rows.extend(out.to_dict("records"))
        return rows


_FakeSparkDataFrame.__module__ = "pyspark.sql.dataframe"


@pytest.fixture(autouse=True)
def fake_pyspark(monkeypatch):
    mod = types.ModuleType("pyspark")
    mod.BarrierTaskContext = _FakeBarrierTaskContext
    monkeypatch.setitem(sys.modules, "pyspark", mod)
    monkeypatch.delenv("SRML_SPARK_COLLECT", raising=False)
    # the cluster-parity tests compare the executor-side CV against the
    # driver-local SEQUENTIAL CV (identical folds, identical solver path);
    # the batched sweep route has its own equality gates in test_tuning.py
    # and a dedicated cluster-vs-batched test below
    monkeypatch.setenv("SRML_SWEEP_BATCH", "0")

    from spark_rapids_ml_tpu.spark import adapter

    def _boom(sdf):
        raise AssertionError("CrossValidator collected the dataset to the driver")

    monkeypatch.setattr(adapter, "spark_to_facade", _boom)


def _data(n=600, d=6, seed=21):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal(d).astype(np.float32)
    y = (X @ w + 0.1 * rng.standard_normal(n)).astype(np.float32)
    y_cls = (X @ w > 0).astype(np.float32)
    return X, y, y_cls


def _frames(X, y, n_parts=3):
    pdf = pd.DataFrame({"features": list(X), "label": y.astype(np.float64)})
    return (
        _FakeSparkDataFrame(_split_pandas(pdf, n_parts)),
        DataFrame.from_pandas(pdf, n_parts),
    )


def test_cv_linreg_runs_cluster_side_single_pass():
    X, y, _ = _data()
    sdf, facade = _frames(X, y)

    def _cv():
        est = LinearRegression(maxIter=30)
        grid = (
            ParamGridBuilder()
            .addGrid(est.getParam("regParam"), [0.0, 0.1, 1.0])
            .build()
        )
        return CrossValidator(
            estimator=est,
            estimatorParamMaps=grid,
            evaluator=RegressionEvaluator(),
            numFolds=3,
            seed=17,
        )

    got = _cv().fit(sdf)
    want = _cv().fit(facade)
    # identical folds (same seeded split), identical solvers underneath —
    # the cluster path must reproduce the driver-local CV
    np.testing.assert_allclose(got.avgMetrics, want.avgMetrics, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(got.bestModel.coef_),
        np.asarray(want.bestModel.coef_),
        rtol=1e-5, atol=1e-6,
    )
    assert got.bestModel.getOrDefault("regParam") == want.bestModel.getOrDefault(
        "regParam"
    )


def test_cv_logreg_cluster_side():
    X, _, y_cls = _data(n=400)
    sdf, facade = _frames(X, y_cls)

    def _cv():
        est = LogisticRegression(maxIter=40)
        grid = (
            ParamGridBuilder()
            .addGrid(est.getParam("regParam"), [0.01, 0.5])
            .build()
        )
        return CrossValidator(
            estimator=est,
            estimatorParamMaps=grid,
            evaluator=MulticlassClassificationEvaluator(metricName="logLoss"),
            numFolds=2,
            seed=5,
        )

    got = _cv().fit(sdf)
    want = _cv().fit(facade)
    np.testing.assert_allclose(got.avgMetrics, want.avgMetrics, rtol=1e-5)
    assert got.bestModel.getOrDefault("regParam") == want.bestModel.getOrDefault(
        "regParam"
    )


def test_cv_random_forest_single_pass_cluster_side():
    """RF rides the SINGLE-PASS CV route on the cluster (fitMultiple ->
    _combine -> executor transform-evaluate).  Regression guard: the
    combined multi-model's sub-model split (_tree_counts) must survive
    serialization to the executors — without it the combined forest
    scored as ONE model and indexed out of bounds.  Metric-for-metric
    parity with the driver-local CV on identical folds; spark_to_facade
    is patched to raise, so any driver collect fails loudly."""
    from spark_rapids_ml_tpu import RandomForestClassifier

    X, _, y_cls = _data(n=200, d=4, seed=9)
    sdf, facade = _frames(X, y_cls)

    def _cv():
        est = RandomForestClassifier(numTrees=3, maxDepth=3, seed=7)
        grid = (
            ParamGridBuilder()
            .addGrid(est.getParam("numTrees"), [2, 3])
            .build()
        )
        return CrossValidator(
            estimator=est,
            estimatorParamMaps=grid,
            evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
            numFolds=2,
            seed=11,
        )

    got = _cv().fit(sdf)
    want = _cv().fit(facade)
    np.testing.assert_allclose(got.avgMetrics, want.avgMetrics, rtol=1e-6)
    assert got.bestModel.getNumTrees == want.bestModel.getNumTrees


def test_cv_linreg_cluster_equals_local_batched_sweep(monkeypatch):
    """The cluster-side sequential CV and the driver-local BATCHED sweep
    (srml-sweep) must agree EXACTLY on integer-valued data: folds come from
    the one shared seeded-split definition, the masked-fold statistics sum
    the same exact integers the restaged folds do, and the lane solves are
    bit-identical to the sequential solves (docs/tuning_engine.md)."""
    monkeypatch.setenv("SRML_SWEEP_BATCH", "1")
    rng = np.random.default_rng(3)
    X = rng.integers(-3, 4, size=(360, 5)).astype(np.float32)
    c = rng.integers(-2, 3, size=5).astype(np.float32)
    y = (X @ c + rng.integers(-2, 3, size=360)).astype(np.float32)
    pdf = pd.DataFrame({"features": list(X), "label": y.astype(np.float64)})
    sdf = _FakeSparkDataFrame(_split_pandas(pdf, 3))
    facade = DataFrame.from_pandas(pdf, 3)

    def _cv():
        est = LinearRegression(standardization=False)
        grid = (
            ParamGridBuilder()
            .addGrid(est.getParam("regParam"), [0.0, 0.1, 1.0])
            .build()
        )
        return CrossValidator(
            estimator=est,
            estimatorParamMaps=grid,
            evaluator=RegressionEvaluator(),
            numFolds=3,
            seed=17,
        )

    got = _cv().fit(sdf)      # executor path: sequential per-fold loop
    want = _cv().fit(facade)  # local path: batched sweep engine
    assert got.avgMetrics == want.avgMetrics
    assert got.stdMetrics == want.stdMetrics
    np.testing.assert_array_equal(
        np.asarray(got.bestModel.coef_), np.asarray(want.bestModel.coef_)
    )


def test_cv_kmeans_cluster_side_with_clustering_evaluator():
    """KMeans CV on the cluster: folds with Spark, fits through the
    barrier, silhouette scored via the two-pass executor-side partials
    (ClusteringEvaluator).  Must reproduce the driver-local CV and never
    collect the dataset."""
    from spark_rapids_ml_tpu import KMeans
    from spark_rapids_ml_tpu.evaluation import ClusteringEvaluator

    rng = np.random.default_rng(2)
    centers = rng.normal(size=(3, 6)) * 6
    X = np.concatenate(
        [rng.normal(size=(120, 6)) + c for c in centers]
    ).astype(np.float32)
    rng.shuffle(X)
    pdf = pd.DataFrame({"features": list(X)})
    sdf = _FakeSparkDataFrame(_split_pandas(pdf, 3))
    facade = DataFrame.from_pandas(pdf, 3)

    def _cv():
        est = KMeans(seed=4, maxIter=20)
        grid = ParamGridBuilder().addGrid(est.getParam("k"), [2, 3]).build()
        return CrossValidator(
            estimator=est,
            estimatorParamMaps=grid,
            evaluator=ClusteringEvaluator(),
            numFolds=2,
            seed=13,
        )

    got = _cv().fit(sdf)
    want = _cv().fit(facade)
    np.testing.assert_allclose(got.avgMetrics, want.avgMetrics, rtol=1e-6)
    assert got.bestModel.getK() == want.bestModel.getK() == 3


def test_clustering_evaluator_matches_sklearn_silhouette():
    from sklearn.metrics import silhouette_score as sk_sil

    from spark_rapids_ml_tpu.evaluation import ClusteringEvaluator

    rng = np.random.default_rng(0)
    X = np.concatenate(
        [rng.normal(size=(80, 5)) + c for c in (0, 4, 9)]
    ).astype(np.float32)
    preds = np.repeat([0.0, 1.0, 2.0], 80)
    pdf = pd.DataFrame({"features": list(X), "prediction": preds})
    got = ClusteringEvaluator().evaluate(DataFrame.from_pandas(pdf, 3))
    want = sk_sil(X, preds.astype(int), metric="sqeuclidean")
    np.testing.assert_allclose(got, want, rtol=1e-9)
    # single-cluster predictions must raise like pyspark
    one = pd.DataFrame({"features": list(X), "prediction": np.zeros(len(X))})
    with pytest.raises(AssertionError):
        ClusteringEvaluator().evaluate(DataFrame.from_pandas(one, 2))
