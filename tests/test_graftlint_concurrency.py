# graftlint concurrency pass (R11/R12): the lock-order graph must catch a
# crafted inversion both directly nested and through a same-module call,
# every blocking-op class must fire under a held lock, the sanctioned
# condition-wait idiom must stay exempt, and the shared-state rule must
# separate guarded from unguarded writes — including the `_locked` helper
# convention.  Stable finding ids must survive line shifts (the property
# the v2 baseline depends on).
import json
import os
import textwrap

import pytest

from tools.graftlint import assign_ids, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# fixture path inside the thread-spawning scope: both R11 and R12 apply
SERVE = "spark_rapids_ml_tpu/serving/fixture.py"


def _lint(src: str, path: str = SERVE, rules=None):
    return lint_source(textwrap.dedent(src), path=path, rules=rules)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# -- R11(a): lock-order inversions --------------------------------------------

R11_DIRECT_INVERSION = """
    import threading

    class S:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def fwd(self):
            with self._a:
                with self._b:
                    pass

        def rev(self):
            with self._b:
                with self._a:
                    pass
"""


def test_r11_direct_nesting_inversion():
    findings = _lint(R11_DIRECT_INVERSION, rules=["R11"])
    assert len(findings) == 2  # each order is a witness on the cycle
    for f in findings:
        assert f.rule == "R11"
        assert "lock-order inversion" in f.message
    # each message names the counter-witness site of the OTHER order
    assert {f.func for f in findings} == {"S.fwd", "S.rev"}


R11_INTERPROCEDURAL = """
    import threading

    class S:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def fwd(self):
            with self._a:
                self._grab_b()

        def _grab_b(self):
            with self._b:
                pass

        def rev(self):
            with self._b:
                with self._a:
                    pass
"""


def test_r11_one_call_interprocedural_inversion():
    findings = _lint(R11_INTERPROCEDURAL, rules=["R11"])
    assert findings and all(f.rule == "R11" for f in findings)
    via = [f for f in findings if f.func == "S.fwd"]
    assert via and "via call to S._grab_b()" in via[0].message


R11_CLEAN_DAG = """
    import threading

    class S:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._a:
                self._grab_b()

        def _grab_b(self):
            with self._b:
                pass
"""


def test_r11_consistent_order_is_silent():
    assert _lint(R11_CLEAN_DAG, rules=["R11"]) == []


def test_r11_scoped_to_package_paths():
    assert _lint(R11_DIRECT_INVERSION, path="tests/x.py", rules=["R11"]) == []


# -- R11(b): blocking ops under a held lock -----------------------------------

def _blocking_fixture(call_line: str, prelude: str = "") -> str:
    return f"""
        import threading
        {prelude}

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def work(self, x):
                with self._lock:
                    {call_line}
    """


@pytest.mark.parametrize(
    "prelude,call,kind",
    [
        ("import time", "time.sleep(0.1)", "time.sleep()"),
        ("import jax", "y = jax.device_get(x)", "device->host sync"),
        ("import subprocess", "subprocess.run([x])", "subprocess"),
        ("", "y = cached_call(x)", "AOT compile wait"),
        ("", "y = x.block_until_ready()", "device sync"),
        ("", "y = x.result()", "Future wait"),
        ("", "y = x.recv(4)", "socket wait"),
        ("", "y = x.accept()", "socket wait"),
    ],
)
def test_r11_blocking_classes_under_lock(prelude, call, kind):
    findings = _lint(_blocking_fixture(call, prelude), rules=["R11"])
    assert len(findings) == 1
    assert findings[0].rule == "R11"
    assert "blocking" in findings[0].message
    assert kind in findings[0].message


def test_r11_blocking_without_lock_is_silent():
    src = """
        import time

        class S:
            def work(self):
                time.sleep(0.1)
    """
    assert _lint(src, rules=["R11"]) == []


def test_r11_blocking_reached_through_call():
    src = """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def work(self):
                with self._lock:
                    self._settle()

            def _settle(self):
                time.sleep(0.1)
    """
    findings = _lint(src, rules=["R11"])
    assert len(findings) == 1
    assert "reaches a blocking time.sleep()" in findings[0].message
    assert findings[0].func == "S.work"


def test_r11_condition_wait_on_own_lock_is_exempt():
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._ready = threading.Condition(self._lock)

            def take(self):
                with self._lock:
                    while not self._have():
                        self._ready.wait(timeout=1.0)

            def _have(self):
                return True
    """
    assert _lint(src, rules=["R11"]) == []


def test_r11_foreign_condition_wait_fires():
    # waiting on a condition bound to lock B while ALSO holding lock A
    # does NOT release A — the exemption must not cover it
    src = """
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._ready = threading.Condition(self._b)

            def take(self):
                with self._a:
                    with self._b:
                        self._ready.wait(timeout=1.0)
    """
    findings = _lint(src, rules=["R11"])
    assert any("blocking .wait()" in f.message for f in findings)


def test_r11_pragma_suppresses_with_reason():
    src = R11_DIRECT_INVERSION.replace(
        "with self._b:\n                with self._a:",
        "with self._b:\n                # graftlint: disable=R11 (crafted)\n"
        "                with self._a:",
    )
    findings = _lint(src, rules=["R11"])
    # the suppressed witness is gone; the forward witness remains
    assert all(f.func != "S.rev" for f in findings)


# -- R12: shared-state write discipline ---------------------------------------

R12_MIXED = """
    import threading

    class S:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def hit(self):
            with self._lock:
                self._n += 1

        def reset(self):
            self._n = 0
"""


def test_r12_mixed_guarded_unguarded_write_fires():
    findings = _lint(R12_MIXED, rules=["R12"])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "R12" and f.func == "S.reset"
    assert "written under a lock" in f.message
    assert "no lock held" in f.message


def test_r12_ctor_only_writes_are_silent():
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._items = []

            def read(self):
                return self._n
    """
    assert _lint(src, rules=["R12"]) == []


def test_r12_container_mutation_on_lock_free_attr():
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add(self, x):
                self._items.append(x)
    """
    findings = _lint(src, rules=["R12"])
    assert len(findings) == 1
    assert "non-atomic .append() mutation" in findings[0].message


def test_r12_locked_helper_convention_is_silent():
    # a helper whose EVERY same-module call site holds the lock is
    # analyzed as running under it — no unguarded-write finding
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def hit(self):
                with self._lock:
                    self._bump()

            def also_hit(self):
                with self._lock:
                    self._bump()

            def _bump(self):
                self._n += 1
    """
    assert _lint(src, rules=["R12"]) == []


def test_r12_reference_swap_stays_legal():
    # the lock-free discipline: plain rebinds with no guarded sibling
    # site are NOT flagged (atomic reference swap is the sanctioned
    # pattern — only container mutation needs a guard)
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._index = None

            def swap(self, new):
                self._index = new
    """
    assert _lint(src, rules=["R12"]) == []


def test_r12_scoped_to_thread_spawning_modules():
    assert _lint(
        R12_MIXED, path="spark_rapids_ml_tpu/ops/x.py", rules=["R12"]
    ) == []


# -- stable ids + baseline ----------------------------------------------------

def test_finding_ids_survive_line_shifts():
    before = _lint(R11_DIRECT_INVERSION, rules=["R11"])
    shifted = _lint(
        "\n\n# moved\n\n" + textwrap.dedent(R11_DIRECT_INVERSION),
        rules=["R11"],
    )
    ids_before = [fid for fid, _ in assign_ids(before)]
    ids_after = [fid for fid, _ in assign_ids(shifted)]
    assert ids_before == ids_after
    assert [f.line for f in before] != [f.line for f in shifted]


def test_finding_ids_disambiguate_duplicates():
    findings = _lint(R12_MIXED, rules=["R12"])
    ids = [fid for fid, _ in assign_ids(findings + findings)]
    assert len(ids) == len(set(ids))
    assert any(fid.endswith("~2") for fid in ids)


def test_cli_fail_on_new_gates_fresh_findings(tmp_path, capsys):
    from tools.graftlint.__main__ import main

    bad = tmp_path / "spark_rapids_ml_tpu" / "serving"
    bad.mkdir(parents=True)
    (bad / "mod.py").write_text(textwrap.dedent(R11_DIRECT_INVERSION))
    baseline = tmp_path / "baseline.json"

    # write the baseline: current findings become audited debt
    rc = main([str(bad), "--write-baseline", str(baseline)])
    assert rc == 0
    data = json.loads(baseline.read_text())
    assert data["version"] == 2 and len(data["ids"]) == 2
    capsys.readouterr()

    # same tree vs the baseline: warnings only, exit 0
    rc = main([str(bad), "--baseline", str(baseline), "--fail-on-new"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "baselined warning" in out

    # a NEW finding (blocking sleep under lock) fails the build
    (bad / "mod2.py").write_text(
        textwrap.dedent(_blocking_fixture("time.sleep(1)", "import time"))
    )
    rc = main([str(bad), "--baseline", str(baseline), "--fail-on-new"])
    capsys.readouterr()
    assert rc == 1


def test_cli_fail_on_new_rejects_v1_baseline(tmp_path, capsys):
    from tools.graftlint.__main__ import main

    bad = tmp_path / "spark_rapids_ml_tpu" / "serving"
    bad.mkdir(parents=True)
    (bad / "mod.py").write_text(textwrap.dedent(R11_DIRECT_INVERSION))
    baseline = tmp_path / "v1.json"
    baseline.write_text(json.dumps({"whatever::R11": 2}))
    rc = main([str(bad), "--baseline", str(baseline), "--fail-on-new"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "v2" in err


def test_cli_json_format(tmp_path, capsys):
    from tools.graftlint.__main__ import main

    bad = tmp_path / "spark_rapids_ml_tpu" / "serving"
    bad.mkdir(parents=True)
    (bad / "mod.py").write_text(textwrap.dedent(R12_MIXED))
    rc = main([str(bad), "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["summary"]["per_rule"]["R12"] == 1
    (finding,) = payload["findings"]
    assert finding["rule"] == "R12"
    assert finding["name"] == "shared-state"
    assert finding["baselined"] is False
    assert finding["id"].startswith("R12:")
    assert "~" not in finding["id"]
