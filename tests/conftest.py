# Tests run on a virtual 8-device CPU mesh so multi-chip sharding is exercised
# without TPU hardware (the role Spark local[N] + N GPUs plays in the
# reference, /root/reference/python/tests/conftest.py:44-70).
#
# Some TPU PJRT plugin environments (e.g. axon) import jax and register their
# backend from sitecustomize before any user code runs, so env vars alone are
# too late: we must flip the already-imported jax config to cpu and inject the
# host-device-count flag before the first backend initialization.  Set
# SRML_TPU_TESTS=1 to run the suite on real TPU devices instead.
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("SRML_TPU_TESTS") != "1":
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    # Persistent compile cache for the suite: the default run is
    # COMPILE-bound on this 1-core image (profiled: 42 of 45 s of the
    # deep-forest smoke is XLA compilation of shape-keyed kernels that
    # never change between runs) — the cache is the standard CI answer,
    # same role as a restored build cache.  First run on a cold cache
    # pays full compiles; ci/test.sh prints the wall-clock either way.
    # SRML_TEST_NO_CACHE=1 forces cold-compile timings.
    #
    # KNOWN LIMIT of this jax/XLA build (not of the framework): ONE
    # pytest process running the ENTIRE suite with --runslow (default +
    # slow, ~310 tests, ~600 resident executables) segfaults inside XLA
    # CPU compilation near the end (reproduced 3x at the same tests,
    # with AND without this cache, 128 GB RAM free, map count far under
    # the limit).  Run full coverage the way ci/test.sh does — the
    # default suite and the slow remainder (--runslow -m slow) as two
    # processes — which passes reliably.
    if os.environ.get("SRML_TEST_NO_CACHE") != "1":
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get(
                "SRML_TEST_JAX_CACHE", "/tmp/srml_test_jax_cache"
            ),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import pytest  # noqa: E402

# SRML_SANITIZE=1 runs the whole suite under the runtime sanitizer: per-fit
# transfer-guard scopes activate inside core/runner dispatch, and NaN
# checking goes suite-wide here (sanitize.py documents the split).
from spark_rapids_ml_tpu import sanitize as _sanitize  # noqa: E402

_sanitize.enable_global_debug_nans()


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False, help="run slow tests"
    )


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: mark test as slow to run")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="need --runslow option to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)


@pytest.fixture
def n_devices():
    import jax

    return jax.device_count()


@pytest.fixture
def armed_faults(monkeypatch):
    """Arm an SRML_FAULTS plan for ONE test: `armed_faults(spec)` sets the
    env var and reloads the faults module's plan (arrival counters reset
    with it); teardown disarms and reloads so the suite's unarmed-path
    invariant (faults.plan() is None) holds for every other test."""
    from spark_rapids_ml_tpu.parallel import faults

    def arm(spec: str):
        monkeypatch.setenv(faults.FAULTS_ENV, spec)
        return faults.reload()

    yield arm
    monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
    faults.reload()


@pytest.fixture(scope="session")
def model_zoo():
    """Lazily-fitted tiny models over one shared dataset, keyed by arm name
    ("kmeans", "pca", "linreg", "logreg", "rf_clf", "rf_reg", "umap",
    "knn", "ann", "ivfpq", "ivfpq_opq").  Returns a factory: model_zoo(name) -> (model, X) with X the
    float32 feature matrix the model was fit on.  Session-scoped and cached
    so the persistence matrix and the serving tests share ONE fit per
    class instead of re-fitting per test."""
    import numpy as np

    rng = np.random.default_rng(7)
    X = rng.standard_normal((96, 5)).astype(np.float32)
    y_reg = (X @ np.arange(1.0, 6.0) + 0.1 * rng.standard_normal(96)).astype(
        np.float64
    )
    y_clf = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    cache = {}

    def _build(name):
        from spark_rapids_ml_tpu import (
            ApproximateNearestNeighbors,
            KMeans,
            LinearRegression,
            LogisticRegression,
            NearestNeighbors,
            PCA,
            RandomForestClassifier,
            RandomForestRegressor,
            UMAP,
        )
        from spark_rapids_ml_tpu.dataframe import DataFrame

        df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=2)
        df_reg = DataFrame.from_numpy(X, y=y_reg, num_partitions=2)
        df_clf = DataFrame.from_numpy(X, y=y_clf, num_partitions=2)
        if name == "kmeans":
            return KMeans(k=3, maxIter=4, seed=1).setFeaturesCol("features").fit(df)
        if name == "pca":
            return PCA(k=3).setInputCol("features").fit(df)
        if name == "linreg":
            return LinearRegression(maxIter=20).fit(df_reg)
        if name == "logreg":
            return LogisticRegression(maxIter=10).fit(df_clf)
        if name == "rf_clf":
            return RandomForestClassifier(
                numTrees=3, maxDepth=3, maxBins=8, seed=1
            ).fit(df_clf)
        if name == "rf_reg":
            return RandomForestRegressor(
                numTrees=3, maxDepth=3, maxBins=8, seed=1
            ).fit(df_reg)
        if name == "umap":
            return UMAP(
                n_neighbors=8, n_epochs=30, init="random", random_state=2
            ).setFeaturesCol("features").fit(df)
        if name == "knn":
            return NearestNeighbors(k=4).setFeaturesCol("features").fit(df)
        if name == "ann":
            # nprobe == nlist: every list probed, so serving/persistence
            # equivalence gates are deterministic AND recall-1.0 vs exact
            return ApproximateNearestNeighbors(
                k=4, algoParams={"nlist": 4, "nprobe": 4}
            ).setFeaturesCol("features").fit(df)
        if name == "ivfpq":
            # the PQ tier at tiny geometry (2 subspaces x 16 codewords,
            # every list probed + refine): deterministic end to end, so the
            # serving/persistence gates hold bit-exactly like the flat arm
            return ApproximateNearestNeighbors(
                k=4,
                algorithm="ivfpq",
                algoParams={"nlist": 4, "nprobe": 4, "M": 2, "n_bits": 4},
            ).setFeaturesCol("features").fit(df)
        if name == "ivfpq_opq":
            # the OPQ x fast-scan composition: a learned rotation rides the
            # wire with the payload, codes stay 4-bit packed — persistence
            # must restage BOTH bit-identically on any mesh
            return ApproximateNearestNeighbors(
                k=4,
                algorithm="ivfpq",
                algoParams={
                    "nlist": 4, "nprobe": 4, "M": 2, "n_bits": 4,
                    "opq": True,
                },
            ).setFeaturesCol("features").fit(df)
        raise KeyError(name)

    def get(name):
        if name not in cache:
            cache[name] = (_build(name), X)
        return cache[name]

    return get
