# srml-router gates (docs/serving.md §router): sliced-mesh replica sets,
# priority-class admission / load shedding, least-outstanding health-aware
# dispatch with failover, depth-2 continuous batching, zero-downtime rolling
# swap, and the router-plane health/Prometheus surface.
#
# The scheduler policy tests are pure-function unit tests (no replicas);
# the router gates use the _EchoModel stub for policy behaviour and the
# model_zoo fixture for the real-compile gates (chaos re-admit warm, swap
# at zero new compiles) — same idiom split as test_serving.py.
import threading
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu import profiling, watch
from spark_rapids_ml_tpu.serving import (
    DEGRADED,
    READY,
    UNHEALTHY,
    ModelServer,
    NoReplicaAvailable,
    RequestShed,
    ServerOverloaded,
    Router,
    ServingEntry,
)
from spark_rapids_ml_tpu.serving import scheduler


class _EchoModel:
    """Servable stub (test_serving.py idiom): echoes row sums; optional
    delay holds a replica's worker busy to build backlog deterministically."""

    def __init__(self, n_cols=4, delay_s=0.0, out_col="echo"):
        self.n_cols = n_cols
        self.delay_s = delay_s
        self.out_col = out_col
        self.calls = []

    def _serving_entry(self, mesh=None):
        def call(batch):
            if self.delay_s:
                time.sleep(self.delay_s)
            self.calls.append(batch.shape[0])
            return {self.out_col: batch.sum(axis=1)}

        return ServingEntry(
            name="serve.echo",
            n_cols=self.n_cols,
            dtype=np.dtype(np.float32),
            out_cols=[self.out_col],
            call=call,
            warm=lambda buckets: [],
        )


def _wait(pred, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


# -- mesh slice carving -------------------------------------------------------


def test_slice_meshes_disjoint_and_oversubscribed():
    import jax

    from spark_rapids_ml_tpu.parallel.mesh import slice_meshes

    n = jax.device_count()
    slices = slice_meshes(2)
    assert len(slices) == 2
    d0 = {d.id for d in slices[0].devices.flat}
    d1 = {d.id for d in slices[1].devices.flat}
    assert d0.isdisjoint(d1)  # the load-bearing property
    assert len(d0) == len(d1) == n // 2
    # more slices than devices: one device each, round-robin
    over = slice_meshes(n + 3)
    assert all(m.devices.size == 1 for m in over)
    with pytest.raises(ValueError, match="n_slices"):
        slice_meshes(0)


def test_slice_meshes_topology_aware_never_straddles_host_group(monkeypatch):
    """Simulated 2x4 topology (SRML_TOPO groups by device ID), shuffled
    device list: the group-major carve (parallel/topology.py) must land
    every replica slice entirely inside ONE host group — a replica
    spanning DCN would pay the slow link on every dispatch."""
    import jax

    from spark_rapids_ml_tpu.parallel.mesh import slice_meshes

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    monkeypatch.setenv("SRML_TOPO", "2:4")
    devs = list(jax.devices())
    shuf = [devs[j] for j in (3, 7, 0, 5, 2, 6, 1, 4)]
    slices = slice_meshes(2, devices=shuf)
    groups = [{d.id // 4 for d in m.devices.flat} for m in slices]
    assert all(len(g) == 1 for g in groups), groups  # no straddling
    assert groups[0] != groups[1]  # and still disjoint across hosts
    # four slices of two: still one host group each
    for m in slice_meshes(4, devices=shuf):
        assert len({d.id // 4 for d in m.devices.flat}) == 1


# -- scheduler policy units (pure functions, no replicas) --------------------


def test_shed_fractions_env_parsing(monkeypatch):
    monkeypatch.delenv(scheduler.SHED_FRACTIONS_ENV, raising=False)
    assert scheduler.shed_fractions() == (1.0, 0.75, 0.5)
    monkeypatch.setenv(scheduler.SHED_FRACTIONS_ENV, "0.9,0.6,0.3")
    assert scheduler.shed_fractions() == (0.9, 0.6, 0.3)
    # short lists repeat the last value; values clamp into [0, 1]
    monkeypatch.setenv(scheduler.SHED_FRACTIONS_ENV, "0.8")
    assert scheduler.shed_fractions() == (0.8, 0.8, 0.8)
    monkeypatch.setenv(scheduler.SHED_FRACTIONS_ENV, "2.0,-1.0")
    assert scheduler.shed_fractions() == (1.0, 0.0, 0.0)
    # junk never raises — admission policy must not take a server down
    monkeypatch.setenv(scheduler.SHED_FRACTIONS_ENV, "lots,of,junk")
    assert scheduler.shed_fractions() == (1.0, 0.75, 0.5)


def test_admission_sheds_in_priority_order(monkeypatch):
    monkeypatch.delenv(scheduler.SHED_FRACTIONS_ENV, raising=False)
    # below every ceiling: everyone admitted
    assert all(scheduler.admit(c, 0.2) for c in scheduler.PRIORITY_CLASSES)
    # half-full: batch sheds first, the rest ride
    assert scheduler.admit("interactive", 0.6)
    assert scheduler.admit("standard", 0.6)
    assert not scheduler.admit("batch", 0.6)
    # three-quarters: standard sheds too
    assert scheduler.admit("interactive", 0.8)
    assert not scheduler.admit("standard", 0.8)
    # hard-full: even interactive sheds (fill < 1.0 fails)
    assert not scheduler.admit("interactive", 1.0)
    with pytest.raises(ValueError, match="unknown priority class"):
        scheduler.admit("junk", 0.0)


class _FakeReplica:
    def __init__(self, name, state, outstanding, queued=0, depth=64):
        self.name = name
        self._state = state
        self._outstanding = outstanding
        self._queued = queued
        self._depth = depth

    def effective_state(self):
        return self._state

    def state(self):
        return self._state

    def outstanding(self):
        return self._outstanding

    def queued_rows(self):
        return self._queued

    def queue_depth(self):
        return self._depth


def test_pick_least_outstanding_then_degraded_then_typed_error():
    r0 = _FakeReplica("m-r0", READY, 5)
    r1 = _FakeReplica("m-r1", READY, 2)
    r2 = _FakeReplica("m-r2", DEGRADED, 0)
    rep, mode = scheduler.pick([r0, r1, r2])
    assert rep is r1 and mode == "ready"  # least outstanding among READY
    # nothing READY: degraded mode beats hard failure
    rep, mode = scheduler.pick([_FakeReplica("m-r0", UNHEALTHY, 0), r2])
    assert rep is r2 and mode == "degraded"
    # nothing dispatchable: the typed retryable error names every state
    with pytest.raises(NoReplicaAvailable, match="m-r0=UNHEALTHY") as ei:
        scheduler.pick([_FakeReplica("m-r0", UNHEALTHY, 0)])
    assert ei.value.retryable is True


def test_aggregate_fill_counts_dark_capacity():
    live = _FakeReplica("m-r0", READY, 0, queued=32, depth=64)
    dark = _FakeReplica("m-r1", UNHEALTHY, 0, queued=0, depth=64)
    # the dark replica's provisioned depth stays in the denominator …
    assert scheduler.aggregate_fill([live, dark]) == pytest.approx(0.25)
    # … so the same backlog on a half-dead set reads as fuller
    assert scheduler.aggregate_fill([live]) == pytest.approx(0.5)
    # no capacity at all reads as hard-full, not a ZeroDivisionError
    assert scheduler.aggregate_fill([]) == 1.0


# -- router: deployment + request path ---------------------------------------


def test_router_serves_replicas_and_routes_requests():
    with Router(replicas=2, max_batch=8, max_wait_ms=1) as router:
        reps = router.serve("echo", _EchoModel())
        assert [r.name for r in reps] == ["echo-r0", "echo-r1"]
        assert "echo" in router and router.names() == ["echo"]
        # replicas sit on DISJOINT mesh slices
        slices = router._sets["echo"].slices
        d0 = {d.id for d in slices[0].devices.flat}
        d1 = {d.id for d in slices[1].devices.flat}
        assert d0.isdisjoint(d1)
        out = router.predict("echo", np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)
        assert profiling.counter("router.echo.admitted") >= 1
        assert profiling.counter("router.echo.dispatched") >= 1
        with pytest.raises(ValueError, match="already routed"):
            router.serve("echo", _EchoModel())
        with pytest.raises(KeyError, match="no routed model"):
            router.submit("nope", np.ones(4, np.float32))
        with pytest.raises(ValueError, match="unknown priority class"):
            router.serve("echo2", _EchoModel(), priority="junk")
        assert "echo2" not in router  # failed deploy leaves no reservation
        with pytest.raises(ValueError, match="unknown priority class"):
            router.submit("echo", np.ones(4, np.float32), priority="junk")


def test_router_least_outstanding_spreads_load_across_replicas():
    model = _EchoModel(delay_s=0.05)
    with Router(
        replicas=2, inflight_depth=1, max_batch=4, max_wait_ms=1
    ) as router:
        reps = router.serve("spread", model)
        futs = [
            router.submit("spread", np.ones(4, np.float32)) for _ in range(8)
        ]
        for f in futs:
            assert f.result(timeout=30)["echo"][0] == pytest.approx(4.0)
        # with r0's worker busy (50 ms per dispatch) the balancer must have
        # dispatched to BOTH replicas — least-outstanding, not sticky
        dispatched = {
            r.name: profiling.percentiles(f"serve.{r.name}.dispatch").get(
                "count", 0
            )
            for r in reps
        }
        assert all(v > 0 for v in dispatched.values()), dispatched


def test_router_sheds_batch_class_first_under_queue_pressure():
    model = _EchoModel(delay_s=0.05)
    with Router(
        replicas=2, inflight_depth=1, max_batch=4, max_wait_ms=200,
        queue_depth=8,
    ) as router:
        router.serve("shedme", model)
        # build a real backlog: 8 queued rows over 16 aggregate depth = 0.5
        # (the 200 ms coalescing window keeps the rows QUEUED while the
        # admission probes below run)
        futs = []
        try:
            for _ in range(10):
                futs.append(
                    router.submit("shedme", np.ones(4, np.float32))
                )
                if scheduler.aggregate_fill(router.replicas("shedme")) >= 0.5:
                    break
            assert scheduler.aggregate_fill(router.replicas("shedme")) >= 0.5
            # batch traffic sheds at the half-full ceiling …
            with pytest.raises(RequestShed) as ei:
                router.submit(
                    "shedme", np.ones(4, np.float32), priority="batch"
                )
            assert ei.value.retryable is True
            assert profiling.counter("router.shedme.shed_batch") >= 1
            # … while interactive traffic is still admitted
            futs.append(
                router.submit(
                    "shedme", np.ones(4, np.float32), priority="interactive"
                )
            )
        finally:
            for f in futs:
                try:
                    f.result(timeout=30)
                except Exception:  # noqa: BLE001 - only quiescence matters here
                    pass


def test_router_degraded_mode_and_no_replica_typed_error(monkeypatch):
    with Router(replicas=2, max_batch=8, max_wait_ms=1) as router:
        router.serve("deg", _EchoModel())
        # force the SLO-burn verdict: both replicas report DEGRADED — the
        # router serves anyway (single-replica degraded mode, counted)
        monkeypatch.setattr(
            ModelServer, "effective_state", lambda self: DEGRADED
        )
        out = router.predict("deg", np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)
        assert profiling.counter("router.deg.degraded_mode") >= 1
        assert router.health()["models"]["deg"]["in_rotation"] == 0
        # nothing dispatchable at all: the typed retryable error, resolved
        # through the future (submit itself only sheds/raises KeyError)
        monkeypatch.setattr(
            ModelServer, "effective_state", lambda self: UNHEALTHY
        )
        fut = router.submit("deg", np.ones(4, np.float32))
        with pytest.raises(NoReplicaAvailable) as ei:
            fut.result(timeout=30)
        assert ei.value.retryable is True
        assert profiling.counter("router.deg.no_replica") >= 1


# -- chaos: replica death under load -----------------------------------------


def test_replica_death_is_rerouted_never_client_visible(armed_faults):
    """The router chaos gate (policy half, echo model): kill replica r0's
    worker mid-batch under a stream of requests — every client future
    still resolves with a RESULT (the router absorbs the typed retryable
    failure and re-routes to the survivor), and the killed replica is
    re-admitted after its supervised restart."""
    armed_faults("serving.dispatch:tag=chaos-r0:call=1:action=kill")
    with Router(replicas=2, max_batch=4, max_wait_ms=2) as router:
        reps = router.serve("chaos", _EchoModel())
        futs = [
            router.submit("chaos", np.ones(4, np.float32)) for _ in range(12)
        ]
        for f in futs:  # ZERO client-visible errors — the acceptance bar
            assert f.result(timeout=30)["echo"][0] == pytest.approx(4.0)
        assert profiling.counter("router.chaos.rerouted") >= 1
        assert profiling.counter("serving.chaos-r0.worker_deaths") == 1
        # the dead replica re-admits: supervised restart back to READY,
        # and the router dispatches to it again
        assert _wait(lambda: reps[0].state() == READY), reps[0].state()
        n0 = profiling.percentiles("serve.chaos-r0.dispatch").get("count", 0)
        for _ in range(8):
            router.predict("chaos", np.ones(4, np.float32))
        assert (
            profiling.percentiles("serve.chaos-r0.dispatch").get("count", 0)
            > n0
        )


def test_chaos_readmit_is_warm_zero_new_compiles(model_zoo, armed_faults):
    """The full chaos acceptance gate on a REAL model: with 2 replicas
    under load, killing one produces no client-visible errors, the
    survivor absorbs traffic, and the killed replica re-admits warm —
    zero new executable compilations across death, restart, re-warm, and
    resumed traffic (the retained AOT cache covers its slice's buckets)."""
    model, X = model_zoo("kmeans")
    with Router(replicas=2, max_batch=16, max_wait_ms=2) as router:
        reps = router.serve("ckm", model)
        router.predict("ckm", X[:3])  # healthy traffic, warm verified
        armed_faults("serving.dispatch:tag=ckm-r0:call=1:action=kill")
        before = profiling.counters("precompile.")
        futs = [router.submit("ckm", X[i : i + 2]) for i in range(10)]
        for f in futs:
            assert f.result(timeout=60)["prediction"].shape == (2,)
        assert profiling.counter("router.ckm.rerouted") >= 1
        assert _wait(lambda: reps[0].state() == READY), reps[0].state()
        out = router.predict("ckm", X[:3])  # post-recovery traffic
        assert out["prediction"].shape == (3,)
        delta = profiling.counter_deltas(before, "precompile.")
        assert delta.get("precompile.compile", 0) == 0, delta
        assert delta.get("precompile.fallback", 0) == 0, delta
        for r in router.replicas("ckm"):
            r.drain()
            r.assert_steady_state()


# -- depth-2 continuous batching ---------------------------------------------


def test_depth2_pipeline_overlaps_assembly_with_dispatch():
    """inflight_depth=2 splits assembly from dispatch: under a burst the
    assembler stages the NEXT batch while the worker has one on device,
    so the serve.<n>.inflight_depth series must reach 2 — and outputs
    stay identical to the depth-1 path."""
    model = _EchoModel(delay_s=0.05)
    srv = ModelServer(
        "d2", model, max_batch=4, max_wait_ms=1, inflight_depth=2
    )
    try:
        assert srv.inflight_depth == 2
        assert srv.stats()["inflight_depth"] == 2
        futs = [srv.submit(np.ones(4, np.float32)) for _ in range(10)]
        outs = [f.result(timeout=30)["echo"][0] for f in futs]
        assert outs == pytest.approx([4.0] * 10)
        depths = profiling.durations("serve.d2.inflight_depth").get(
            "serve.d2.inflight_depth", []
        )
        assert depths and max(depths) >= 2.0, depths
    finally:
        srv.shutdown()


def test_depth2_drain_and_shutdown_resolve_everything():
    model = _EchoModel(delay_s=0.02)
    srv = ModelServer(
        "d2drain", model, max_batch=4, max_wait_ms=1, inflight_depth=2
    )
    futs = [srv.submit(np.ones(4, np.float32)) for _ in range(9)]
    srv.drain()
    srv.shutdown()
    # a drained depth-2 server resolved EVERY admitted request (none
    # stranded in the assembly pipe)
    assert all(f.done() for f in futs)
    assert [f.result(timeout=0)["echo"][0] for f in futs] == (
        pytest.approx([4.0] * 9)
    )


def test_depth2_worker_death_flushes_pipe_and_recovers(armed_faults):
    """Depth-2 recovery: a worker death fails the on-device batch AND any
    assembled-but-undispatched batches with the typed retryable error
    (never a hang), the superseded assembler exits without consuming the
    new generation's work, and the restarted pipeline serves again."""
    from spark_rapids_ml_tpu.serving import ServerRecovering

    armed_faults("serving.dispatch:tag=d2die:call=2:action=kill")
    model = _EchoModel(delay_s=0.05)
    srv = ModelServer(
        "d2die", model, max_batch=4, max_wait_ms=1, inflight_depth=2
    )
    try:
        srv.predict(np.ones(4, np.float32))  # call 1 survives
        futs = [srv.submit(np.ones(4, np.float32)) for _ in range(8)]
        resolved = 0
        for f in futs:
            try:
                f.result(timeout=30)
                resolved += 1
            except ServerRecovering:
                resolved += 1
        assert resolved == len(futs)  # typed error or result — no hangs
        assert _wait(lambda: srv.state() == READY), srv.state()
        out = srv.predict(np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)
        assert profiling.counter("serving.d2die.restarts") == 1
    finally:
        srv.shutdown(drain=False)


def test_batcher_cancelled_sentinel_leaves_queue_intact():
    from spark_rapids_ml_tpu.serving.batcher import CANCELLED, MicroBatcher

    b = MicroBatcher(
        n_cols=4,
        dtype=np.dtype(np.float32),
        counter_ns="serving.cansent",
        max_batch=8,
        max_wait_ms=1,
        queue_depth=64,
    )
    fut = b.submit(np.ones((1, 4), np.float32))
    # a superseded consumer leaves WITHOUT consuming …
    assert b.take(cancelled=lambda: True) is CANCELLED
    # … so the successor generation still gets the queued request
    batch, _reason = b.take()
    assert len(batch) == 1
    from spark_rapids_ml_tpu.serving.batcher import resolve_future

    resolve_future(batch[0].future, {"ok": np.ones(1)})
    assert fut.result(timeout=5)
    b.stop()


def test_batcher_hold_keeps_deadline_expired_batch_open():
    """take(hold=...) — iteration-level continuous batching: while the
    depth>1 staging slot is occupied a deadline-expired partial batch
    stays open to late arrivals (full/drain still flush immediately), and
    kick() releases a held take the moment the slot frees."""
    import threading

    from spark_rapids_ml_tpu.serving.batcher import MicroBatcher

    b = MicroBatcher(
        n_cols=4,
        dtype=np.dtype(np.float32),
        counter_ns="serving.holdopen",
        max_batch=4,
        max_wait_ms=1,
        queue_depth=64,
    )
    held = threading.Event()
    held.set()
    out = {}

    def consume():
        out["batch"], out["reason"] = b.take(hold=held.is_set)

    b.submit(np.ones((1, 4), np.float32))
    t = threading.Thread(target=consume, name="test-hold-consumer")
    t.start()
    time.sleep(0.1)  # deadline (1 ms) long expired — held open, not flushed
    assert t.is_alive(), out
    # late arrivals still join the held batch; reaching max_batch flushes
    # regardless of hold
    for _ in range(3):
        b.submit(np.ones((1, 4), np.float32))
    t.join(timeout=5)
    assert not t.is_alive()
    assert len(out["batch"]) == 4 and out["reason"] == "full", out
    assert profiling.counter("serving.holdopen.held_open") > 0

    # releasing the hold + kick() flushes an expired partial immediately
    b.submit(np.ones((1, 4), np.float32))
    t = threading.Thread(target=consume, name="test-hold-consumer2")
    t.start()
    time.sleep(0.1)
    assert t.is_alive(), out
    held.clear()
    b.kick()
    t.join(timeout=5)
    assert not t.is_alive()
    assert len(out["batch"]) == 1 and out["reason"] == "deadline", out

    # drain overrides hold: an expired held batch flushes at begin_drain()
    held.set()
    b.submit(np.ones((1, 4), np.float32))
    t = threading.Thread(target=consume, name="test-hold-consumer3")
    t.start()
    time.sleep(0.1)
    assert t.is_alive(), out
    b.begin_drain()
    t.join(timeout=5)
    assert not t.is_alive()
    assert len(out["batch"]) == 1 and out["reason"] == "drain", out
    b.stop()


def test_depth2_goodput_dominates_depth1_at_equal_offered_load():
    """THE deterministic continuous-batching gate (ci step 3k): at equal
    offered load against the same device-leg duration, depth-2 delivers
    at least one full batch MORE goodput than depth-1 before shedding.

    The device leg is a GIL-releasing wall-clock sleep — what a real
    accelerator looks like from the host — so the margin is structural
    (the staged pipe batch plus the held-open assembling batch admit work
    a depth-1 server must shed while its worker is on device) and immune
    to the CPU weather that makes live throughput races on a 2-core box
    unscoreable (see bench_serving's paired confirm)."""
    results = {}
    for depth in (1, 2):
        model = _EchoModel(delay_s=0.25)
        srv = ModelServer(
            f"gd{depth}", model, max_batch=4, max_wait_ms=1,
            queue_depth=8, inflight_depth=depth,
        )
        try:
            first = srv.submit(np.ones(4, np.float32))
            # pre-block: the worker must be ON DEVICE with the probe before
            # the burst, so both depths see an identical starting state
            assert _wait(
                lambda: srv._batcher.queued_requests() == 0
                and not first.done()
            )
            admitted, shed = [first], 0
            for _ in range(24):  # equal offered load, far above capacity
                try:
                    admitted.append(srv.submit(np.ones(4, np.float32)))
                except ServerOverloaded:
                    shed += 1
                # open-loop pacing: a GIL-releasing inter-arrival gap lets
                # the assembly thread actually run between arrivals (a
                # 0-gap burst never yields the GIL, so BOTH depths degrade
                # to the queue bound).  24 * 5 ms = 120 ms, well inside the
                # 250 ms device leg — depth-1 still cannot take() mid-burst
                time.sleep(0.005)
            outs = [f.result(timeout=30)["echo"][0] for f in admitted]
            assert outs == pytest.approx([4.0] * len(admitted))
            results[depth] = len(admitted)
            assert shed == 25 - len(admitted)
        finally:
            srv.shutdown()
    # depth-1 admits the device batch + the queue; depth-2 additionally
    # holds a staged batch (and an assembling one) — >= one max_batch of
    # extra goodput at the same offered load, deterministically
    assert results[1] >= 9, results
    assert results[2] >= results[1] + 4, results


# -- zero-downtime rolling swap ----------------------------------------------


def test_router_swap_under_load_zero_errors(model_zoo):
    """The swap() acceptance gate: rolling hot-swap across the replica set
    under continuous load — zero dropped/errored requests, zero new
    compiles at cut-over (same-shape successor re-warms from the retained
    AOT cache), and traffic lands on the new generation afterwards."""
    model, X = model_zoo("kmeans")
    with Router(replicas=2, max_batch=16, max_wait_ms=2) as router:
        router.serve("swkm", model)
        router.predict("swkm", X[:3])
        stop = threading.Event()
        failures: list = []
        n_ok = [0]

        def pump():
            while not stop.is_set():
                try:
                    out = router.predict("swkm", X[:2], timeout_ms=10_000)
                    assert out["prediction"].shape == (2,)
                    n_ok[0] += 1
                except Exception as exc:  # noqa: BLE001 - the gate counts these
                    failures.append(exc)

        pumper = threading.Thread(
            target=pump, name="test-swap-pump", daemon=True
        )
        pumper.start()
        try:
            time.sleep(0.2)  # load flowing before the swap begins
            before = profiling.counters("precompile.")
            swapped = router.swap("swkm", model)  # same-shape successor
            delta = profiling.counter_deltas(before, "precompile.")
            time.sleep(0.2)  # load flowing after
        finally:
            stop.set()
            pumper.join(timeout=30)
        assert not failures, failures[:3]  # ZERO client-visible errors
        assert n_ok[0] > 0
        # zero new compiles at cut-over: the incoming generation warmed
        # entirely from the retained AOT cache
        assert delta.get("precompile.compile", 0) == 0, delta
        assert delta.get("precompile.fallback", 0) == 0, delta
        assert profiling.counter("router.swkm.replica_swaps") == 2
        assert profiling.counter("router.swkm.swaps") == 1
        # the set now IS the new generation, still healthy and steady
        assert router.replicas("swkm") == swapped
        assert router.health()["models"]["swkm"]["state"] == READY
        for r in swapped:
            r.drain()
            r.assert_steady_state()


def test_submit_racing_a_draining_replica_fails_over():
    """The cut-over race: a submit that lands on a replica AFTER its drain
    began gets the typed ServerDraining — and the router fails over to a
    live replica instead of surfacing it (zero-downtime depends on it)."""
    from spark_rapids_ml_tpu.serving import ServerDraining

    with Router(replicas=2, max_batch=8, max_wait_ms=1) as router:
        reps = router.serve("drace", _EchoModel())
        # the worst-case interleaving, made deterministic: r0's batcher has
        # begun draining but its lifecycle state still reads READY, so the
        # scheduler picks it (tie on outstanding) and submit() raises the
        # typed error INSIDE the router's dispatch attempt
        reps[0]._batcher.begin_drain()
        with pytest.raises(ServerDraining):  # the bare-replica behaviour
            reps[0].submit(np.ones(4, np.float32))
        out = router.predict("drace", np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)
        assert profiling.counter("router.drace.failover") >= 1


def test_router_swap_incompatible_model_fails_before_cutover():
    with Router(replicas=2, max_batch=8, max_wait_ms=1) as router:
        reps = router.serve("swbad", _EchoModel(n_cols=4))
        with pytest.raises(ValueError, match="n_cols 4 -> 6"):
            router.swap("swbad", _EchoModel(n_cols=6))
        # the set is untouched: same replica objects, still serving
        assert router.replicas("swbad") == reps
        out = router.predict("swbad", np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)
        assert profiling.counter("router.swbad.replica_swaps") == 0


# -- health rollup + Prometheus families --------------------------------------


def test_router_health_rollup_is_capacity_aware(monkeypatch):
    with Router(replicas=2, max_batch=8, max_wait_ms=1) as router:
        reps = router.serve("hrr", _EchoModel())
        h = router.health()
        assert h["state"] == READY
        m = h["models"]["hrr"]
        assert (m["replicas"], m["in_rotation"]) == (2, 2)
        assert set(m["models"]) == {"hrr-r0", "hrr-r1"}
        # one replica out: DEGRADED capacity, an alert — not an outage
        orig = ModelServer.effective_state
        monkeypatch.setattr(
            ModelServer,
            "effective_state",
            lambda self: UNHEALTHY if self is reps[0] else orig(self),
        )
        m = router.health()["models"]["hrr"]
        assert m["state"] == DEGRADED and m["in_rotation"] == 1
        # every replica out: the model is UNHEALTHY, and so is the plane
        monkeypatch.setattr(
            ModelServer, "effective_state", lambda self: UNHEALTHY
        )
        h = router.health()
        assert h["models"]["hrr"]["state"] == UNHEALTHY
        assert h["state"] == UNHEALTHY


def test_router_prometheus_families_round_trip(armed_faults):
    """The exposition round-trip for the new layer: router capacity gauges
    render as the srml_router family, per-REPLICA health (including
    restart counts — the restart-storm signal) as srml_health, and the
    router.<model>.* counters ride export_metrics/telemetry."""
    armed_faults("serving.dispatch:tag=prom-r1:call=1:action=kill")
    with Router(replicas=2, max_batch=4, max_wait_ms=2) as router:
        reps = router.serve("prom", _EchoModel())
        futs = [
            router.submit("prom", np.ones(4, np.float32)) for _ in range(6)
        ]
        for f in futs:
            f.result(timeout=30)  # r1's death rerouted, zero errors
        assert _wait(lambda: reps[1].state() == READY)
        assert _wait(
            lambda: router.health()["models"]["prom"]["restarts"] == 1
        )
        gauges = profiling.export_metrics()["gauges"]
        assert gauges["router.prom.replicas"] == 2.0
        assert gauges["router.prom.state_code"] >= 0.0
        assert "router.prom.in_rotation" in gauges
        assert "router.prom.fill" in gauges
        # per-replica health through the shared srml-watch flattening,
        # restart counts included
        assert gauges["health.prom-r1.restarts"] == 1.0
        assert "health.prom-r0.state_code" in gauges
        text = profiling.render_prometheus()
        assert 'srml_router{name="router.prom.replicas"} 2.0' in text
        assert 'srml_health{name="health.prom-r1.restarts"} 1.0' in text
        # router counters ride the telemetry snapshot surface
        snap = router.telemetry()
        assert snap.counters.get("router.prom.rerouted", 0) >= 1
        assert snap.counters.get("router.prom.admitted", 0) >= 6
        stats = router.stats()["prom"]
        assert set(stats["replicas"]) == {"prom-r0", "prom-r1"}
        assert stats["counters"]["router.prom.dispatched"] >= 6
    # shutdown unregisters the weak gauge provider
    assert not any(
        k.startswith("router.prom.")
        for k in profiling.export_metrics()["gauges"]
    )


def test_registry_health_gauges_include_restarts(model_zoo, armed_faults):
    """Satellite: the registry side of the shared flattening — a restarted
    registry server's restart count reaches the srml_health family."""
    from spark_rapids_ml_tpu.serving import ModelRegistry, ServerRecovering

    model, X = model_zoo("kmeans")
    reg = ModelRegistry(max_batch=16, max_wait_ms=2)
    try:
        reg.register("regkm", model)
        reg.get("regkm").predict(X[:2])
        armed_faults("serving.dispatch:tag=regkm:call=1:action=kill")
        with pytest.raises(ServerRecovering):
            reg.get("regkm").predict(X[:2])
        assert _wait(lambda: reg.get("regkm").state() == READY)
        assert reg.health()["models"]["regkm"]["restarts"] == 1
        assert reg.health()["restarts"] == 1
        gauges = profiling.export_metrics()["gauges"]
        assert gauges["health.regkm.restarts"] == 1.0
        text = profiling.render_prometheus()
        assert 'srml_health{name="health.regkm.restarts"} 1.0' in text
    finally:
        reg.shutdown(drain=False)


def test_health_gauges_flattening_rule():
    # the ONE rule shared by registry and router (watch.health_gauges)
    out = watch.health_gauges(
        {
            "m": {
                "state_code": 0,
                "attainment": 0.5,
                "burn": 0.5,
                "queued_rows": 3,
                "p99_ms": 12.5,
                "restarts": 2,
            },
            "bare": {"state_code": 4},
        }
    )
    assert out == {
        "health.m.state_code": 0.0,
        "health.m.attainment": 0.5,
        "health.m.burn": 0.5,
        "health.m.queued_rows": 3.0,
        "health.m.p99_ms": 12.5,
        "health.m.restarts": 2.0,
        "health.bare.state_code": 4.0,
    }
