# LogisticRegression correctness vs sklearn (binary/multinomial, L2/L1/EN) +
# single-pass fitMultiple + transform-evaluate (strategy modeled on the
# reference's test_logistic_regression.py).
import numpy as np
import pytest

from spark_rapids_ml_tpu import LogisticRegression, LogisticRegressionModel
from spark_rapids_ml_tpu.core import load
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator


def _cls_data(n=500, d=8, k=2, seed=0, sep=2.0):
    rng = np.random.default_rng(seed)
    centers = sep * rng.normal(size=(k, d))
    y = rng.integers(0, k, size=n)
    X = centers[y] + rng.normal(size=(n, d))
    return X.astype(np.float64), y.astype(np.float64)


def _df(X, y, parts=4):
    return DataFrame.from_numpy(X, y=y, num_partitions=parts)


def test_default_params():
    lr = LogisticRegression()
    assert lr.tpu_params["penalty"] == "none"  # regParam default 0
    assert lr.tpu_params["C"] == 0.0
    lr = LogisticRegression(regParam=0.5)
    assert lr.tpu_params["penalty"] == "l2"
    assert lr.tpu_params["C"] == 2.0
    lr = LogisticRegression(regParam=0.5, elasticNetParam=1.0)
    assert lr.tpu_params["penalty"] == "l1"
    lr = LogisticRegression(regParam=0.5, elasticNetParam=0.4)
    assert lr.tpu_params["penalty"] == "elasticnet"
    assert lr.tpu_params["l1_ratio"] == 0.4


def test_unsupported_params():
    with pytest.raises(ValueError):
        LogisticRegression(threshold=0.7)
    with pytest.raises(ValueError):
        LogisticRegression(weightCol="w")
    # ignored params accepted
    lr = LogisticRegression(standardization=False, family="binomial")
    assert "standardization" not in lr.tpu_params


def test_binary_l2_matches_sklearn():
    from sklearn.linear_model import LogisticRegression as SkLR

    X, y = _cls_data()
    reg = 0.1
    model = LogisticRegression(regParam=reg, maxIter=500, tol=1e-10).fit(_df(X, y))
    # spark objective: (1/n)sum logloss + reg*||w||^2/2 == sklearn C=1/(reg*n)
    sk = SkLR(C=1.0 / (reg * len(y)), max_iter=5000, tol=1e-12).fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_[0], atol=2e-2)
    assert abs(model.intercept - sk.intercept_[0]) < 2e-2
    assert model.numClasses == 2
    assert model.coef_.shape == (1, 8)


def test_binary_transform_accuracy():
    X, y = _cls_data(n=400, sep=3.0)
    df = _df(X, y)
    model = LogisticRegression(regParam=0.01, maxIter=200).fit(df)
    out = model.transform(df).toPandas()
    acc = (out["prediction"].to_numpy() == y).mean()
    assert acc > 0.95
    probs = np.stack(out["probability"].to_numpy())
    assert probs.shape == (400, 2)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    raw = np.stack(out["rawPrediction"].to_numpy())
    assert raw.shape == (400, 2)
    np.testing.assert_allclose(raw[:, 0], -raw[:, 1], atol=1e-6)


def test_multinomial_matches_sklearn():
    from sklearn.linear_model import LogisticRegression as SkLR

    X, y = _cls_data(n=600, d=6, k=4)
    reg = 0.05
    model = LogisticRegression(regParam=reg, maxIter=500, tol=1e-10).fit(_df(X, y))
    assert model.numClasses == 4
    assert model.coefficientMatrix.shape == (4, 6)
    sk = SkLR(C=1.0 / (reg * len(y)), max_iter=5000, tol=1e-12).fit(X, y)
    df = _df(X, y)
    ours = model.transform(df).toPandas()["prediction"].to_numpy()
    theirs = sk.predict(X)
    assert (ours == theirs).mean() > 0.98


def test_l1_sparsity():
    X, y = _cls_data(n=400, d=20)
    # only first 3 features informative
    X[:, 3:] = np.random.default_rng(1).normal(size=(400, 17))
    model = LogisticRegression(regParam=0.1, elasticNetParam=1.0, maxIter=500).fit(
        _df(X, y)
    )
    coef = np.asarray(model.coefficients)
    # OWL-QN must produce exact zeros on noise features
    assert (coef == 0.0).sum() >= 10
    # signal features survive (center draw can leave one near-zero)
    assert (np.abs(coef[:3]) > 0).sum() >= 2


def test_noncontiguous_labels():
    X, y = _cls_data(n=300, k=2)
    y = np.where(y == 0, 3.0, 7.0)
    df = _df(X, y)
    model = LogisticRegression(maxIter=100).fit(df)
    np.testing.assert_array_equal(model.classes_, [3.0, 7.0])
    preds = model.transform(df).toPandas()["prediction"].unique()
    assert set(preds) <= {3.0, 7.0}


def test_fit_multiple_single_pass():
    X, y = _cls_data()
    df = _df(X, y)
    est = LogisticRegression(maxIter=200)
    pmaps = [
        {LogisticRegression.regParam: 0.01},
        {LogisticRegression.regParam: 1.0},
    ]
    models = [m for _, m in est.fitMultiple(df, pmaps)]
    assert len(models) == 2
    for pm, m in zip(pmaps, models):
        solo = est.copy(pm).fit(df)
        np.testing.assert_allclose(
            np.asarray(m.coefficients), np.asarray(solo.coefficients), atol=1e-4
        )
    # heavier regularization shrinks coefficients
    assert np.linalg.norm(models[1].coefficients) < np.linalg.norm(models[0].coefficients)


def test_combine_and_transform_evaluate():
    X, y = _cls_data(n=400)
    df = _df(X, y)
    est = LogisticRegression(maxIter=200)
    m0 = est.copy({LogisticRegression.regParam: 0.001}).fit(df)
    m1 = est.copy({LogisticRegression.regParam: 100.0}).fit(df)
    combined = LogisticRegressionModel._combine([m0, m1])
    for metric in ("accuracy", "f1", "logLoss"):
        ev = MulticlassClassificationEvaluator(metricName=metric)
        scores = combined._transformEvaluate(df, ev)
        assert len(scores) == 2
        direct = ev.evaluate(m0.transform(df))
        assert abs(scores[0] - direct) < 1e-9, metric
    # near-unregularized beats heavily-regularized on train accuracy
    ev = MulticlassClassificationEvaluator(metricName="accuracy")
    s = combined._transformEvaluate(df, ev)
    assert s[0] >= s[1]


def test_persistence(tmp_path):
    X, y = _cls_data(n=200)
    df = _df(X, y)
    model = LogisticRegression(regParam=0.1).fit(df)
    model.save(str(tmp_path / "m"))
    loaded = load(str(tmp_path / "m"))
    assert isinstance(loaded, LogisticRegressionModel)
    np.testing.assert_allclose(loaded.coef_, model.coef_)
    np.testing.assert_array_equal(loaded.classes_, model.classes_)
    p1 = model.transform(df).toPandas()["prediction"]
    p2 = loaded.transform(df).toPandas()["prediction"]
    assert (p1 == p2).all()


def test_predict_single():
    X, y = _cls_data(n=200, sep=4.0)
    model = LogisticRegression(maxIter=100).fit(_df(X, y))
    pred = model.predict(X[0])
    assert pred in (0.0, 1.0)
    probs = model.predictProbability(X[0])
    assert probs.shape == (2,)
    np.testing.assert_allclose(probs.sum(), 1.0, atol=1e-5)


def test_float64_warns_and_ignores():
    X, y = _cls_data(n=100)
    lr = LogisticRegression(float32_inputs=False)
    assert lr._float32_inputs is True
    model = lr.fit(_df(X, y))
    assert model.dtype == "float32"
