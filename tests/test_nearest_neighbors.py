# Exact kNN correctness vs sklearn (strategy modeled on the reference's
# test_nearest_neighbors.py).
import jax
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import NearestNeighbors
from spark_rapids_ml_tpu.dataframe import DataFrame


def _data(n_items=200, n_queries=30, d=6, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n_items, d)), rng.normal(size=(n_queries, d))


def test_kneighbors_matches_sklearn():
    from sklearn.neighbors import NearestNeighbors as SkNN

    items, queries = _data()
    item_df = DataFrame.from_numpy(items, num_partitions=4)
    query_df = DataFrame.from_numpy(queries, num_partitions=2)
    model = NearestNeighbors(k=7).fit(item_df)
    item_out, query_out, knn_df = model.kneighbors(query_df)
    pdf = knn_df.toPandas().sort_values("query_unique_id").reset_index(drop=True)
    got_idx = np.stack(pdf["indices"].to_numpy())
    got_dist = np.stack(pdf["distances"].to_numpy())

    sk = SkNN(n_neighbors=7).fit(items.astype(np.float32))
    exp_dist, exp_idx = sk.kneighbors(queries.astype(np.float32))
    np.testing.assert_array_equal(got_idx, exp_idx)
    np.testing.assert_allclose(got_dist, exp_dist, atol=1e-4)
    # distances ascending
    assert (np.diff(got_dist, axis=1) >= -1e-6).all()


def test_kneighbors_custom_id_col():
    items, queries = _data(n_items=50, n_queries=5)
    ids = np.arange(100, 150)
    pdf = pd.DataFrame({"features": list(items), "my_id": ids})
    item_df = DataFrame.from_pandas(pdf, 3)
    model = NearestNeighbors(k=3).setIdCol("my_id").fit(item_df)
    qdf = pd.DataFrame({"features": list(queries), "my_id": np.arange(5)})
    _, _, knn_df = model.kneighbors(DataFrame.from_pandas(qdf, 1))
    out = knn_df.toPandas()
    assert "query_my_id" in out.columns
    all_ids = np.concatenate(out["indices"].to_numpy())
    assert all_ids.min() >= 100 and all_ids.max() < 150


def test_k_larger_than_items():
    items, queries = _data(n_items=4, n_queries=3)
    model = NearestNeighbors(k=10).fit(DataFrame.from_numpy(items))
    _, _, knn_df = model.kneighbors(DataFrame.from_numpy(queries))
    assert len(knn_df.toPandas()["indices"].iloc[0]) == 4


def test_exact_nearest_neighbors_join():
    items, queries = _data(n_items=40, n_queries=6)
    model = NearestNeighbors(k=2).fit(DataFrame.from_numpy(items, num_partitions=2))
    join_df = model.exactNearestNeighborsJoin(
        DataFrame.from_numpy(queries), distCol="dist"
    )
    pdf = join_df.toPandas()
    assert len(pdf) == 6 * 2
    assert set(pdf.columns) == {"item_df", "query_df", "dist"}
    row = pdf.iloc[0]
    assert "features" in row["item_df"] and "features" in row["query_df"]
    # generated id column dropped from structs (reference knn.py:663-670)
    assert "unique_id" not in row["item_df"]


def test_no_persistence():
    items, _ = _data(n_items=20)
    nn = NearestNeighbors(k=2)
    with pytest.raises(NotImplementedError):
        nn.write()
    model = nn.fit(DataFrame.from_numpy(items))
    with pytest.raises(NotImplementedError):
        model.write()


def test_param_mapping():
    nn = NearestNeighbors(k=9)
    assert nn.tpu_params["n_neighbors"] == 9
    nn = NearestNeighbors(n_neighbors=4)
    assert nn.getK() == 4


def test_int64_ids_survive():
    # ids above 2**31 (e.g. Spark monotonically_increasing_id) must not be
    # truncated by the device path, which only ever sees int32 positions
    items, queries = _data(n_items=30, n_queries=4)
    big = np.int64(1) << 40
    ids = big + np.arange(30, dtype=np.int64) * (np.int64(1) << 33)
    item_pdf = pd.DataFrame({"features": list(items), "my_id": ids})
    item_df = DataFrame([item_pdf])
    model = NearestNeighbors(k=3)
    model.setIdCol("my_id")
    model = model.fit(item_df)
    _, _, knn_df = model.kneighbors(DataFrame.from_numpy(queries))
    got = np.stack(knn_df.toPandas()["indices"].to_numpy())
    assert got.min() >= big
    from sklearn.neighbors import NearestNeighbors as SkNN

    _, exp_idx = SkNN(n_neighbors=3).fit(items.astype(np.float32)).kneighbors(
        queries.astype(np.float32)
    )
    np.testing.assert_array_equal(got, ids[exp_idx])


def test_knn_merge_branches_multi_chunk(monkeypatch):
    # shrink the tile budget so shards scan MANY chunks, and run both merge
    # strategies (COLLECT and RUNNING) — each must stay exact vs sklearn
    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from sklearn.neighbors import NearestNeighbors as SkNN

    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    rng = np.random.default_rng(11)
    X = rng.normal(size=(4100, 16)).astype(np.float32)
    Q = rng.normal(size=(137, 16)).astype(np.float32)
    ids = np.arange(4100, dtype=np.int64)
    ds, isk = SkNN(n_neighbors=7).fit(X).kneighbors(Q)
    mesh = get_mesh(8)

    # tiny tile budget -> chunk=512 -> multiple chunks per shard
    monkeypatch.setattr(knn_mod, "_TILE_BUDGET", 1)
    d1, i1 = knn_mod.knn_search_prepared(
        knn_mod.prepare_items(X, ids, mesh), Q, 7, mesh
    )
    np.testing.assert_allclose(np.sort(d1, axis=1), ds, atol=2e-3)
    assert (np.sort(i1, axis=1) == np.sort(isk, axis=1)).all()

    # force the RUNNING merge branch as well
    monkeypatch.setattr(knn_mod, "_COLLECT_MERGE_BUDGET", 0)
    d2, i2 = knn_mod.knn_search_prepared(
        knn_mod.prepare_items(X, ids, mesh), Q, 7, mesh
    )
    np.testing.assert_allclose(np.sort(d2, axis=1), ds, atol=2e-3)
    assert (np.sort(i2, axis=1) == np.sort(isk, axis=1)).all()


def test_topk_approx_verified_exact():
    """_topk_approx_verified must return the exact top-k (values and a
    permutation-equivalent index set) — the verification pass + fallback
    guarantees it even when approx_max_k under-recalls.  On CPU
    approx_max_k lowers to exact top_k, so this exercises the verification
    wiring; the under-recall fallback is the same lax.cond branch."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.knn import (
        _grouped_topk_exact,
        _topk_approx_verified,
    )

    rng = np.random.default_rng(3)
    vals = jnp.asarray(rng.normal(size=(7, 4096)).astype(np.float32))
    k = 50
    av, ai = _topk_approx_verified(vals, k)
    ev, ei = _grouped_topk_exact(vals, k)
    np.testing.assert_allclose(np.asarray(av), np.asarray(ev))
    # same index SET per row (order among ties may differ); fetch once —
    # per-row np.asarray in the loop would sync per iteration (graftlint R1)
    ai_h, ei_h = jax.device_get((ai, ei))
    for r in range(vals.shape[0]):
        assert set(ai_h[r].tolist()) == set(ei_h[r].tolist())


def test_topk_approx_verified_ties():
    """Tie-tolerant verification: duplicate values at rank k must neither
    break exactness (value multiset equals the true top-k) nor the shape
    contract."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.knn import _topk_approx_verified

    rng = np.random.default_rng(4)
    base = rng.integers(0, 40, size=(5, 4096)).astype(np.float32)  # heavy ties
    k = 37
    av, ai = _topk_approx_verified(jnp.asarray(base), k)
    av = np.asarray(av)
    want = np.sort(base, axis=1)[:, ::-1][:, :k]
    np.testing.assert_allclose(np.sort(av, axis=1)[:, ::-1], want)
    # indices must address entries carrying the returned values
    got_vals = np.take_along_axis(base, np.asarray(ai), axis=1)
    np.testing.assert_allclose(np.sort(got_vals, 1), np.sort(av, 1))


def test_kneighbors_streams_item_partitions(monkeypatch):
    """kneighbors with item data >> one partition must stream bounded item
    blocks to the device — never concatenate the full item set on the driver
    (VERDICT round 2, item 3; reference keeps partitions worker-resident,
    knn.py:452-560) — and must keep the query partitioning in the result."""
    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from sklearn.neighbors import NearestNeighbors as SkNN

    rng = np.random.default_rng(11)
    n_items, n_query, d, k = 3000, 200, 16, 7
    X = rng.standard_normal((n_items, d)).astype(np.float32)
    Q = rng.standard_normal((n_query, d)).astype(np.float32)

    # tiny HBM budget: with 8 virtual devices, block bytes = budget * 8;
    # pick it so the item set splits into several blocks
    block_rows_target = 512
    monkeypatch.setenv(
        "SRML_KNN_HBM_BUDGET", str(block_rows_target * d * 4 // 8)
    )
    seen_blocks = []
    real_prepare = knn_mod.prepare_items

    def spy_prepare(items, ids, mesh, dtype=np.float32):
        seen_blocks.append(items.shape[0])
        return real_prepare(items, ids, mesh, dtype)

    monkeypatch.setattr(knn_mod, "prepare_items", spy_prepare)

    item_df = DataFrame.from_pandas(
        pd.DataFrame({"features": list(X)}), num_partitions=6
    )
    query_df = DataFrame.from_pandas(
        pd.DataFrame({"features": list(Q)}), num_partitions=3
    )
    # an EMPTY query partition must survive with an empty result partition
    # (partition-for-partition alignment with the query frame)
    query_df.partitions.insert(1, query_df.partitions[0].iloc[:0].copy())
    model = NearestNeighbors(k=k).fit(item_df)
    _, qdf_withid, knn_df = model.kneighbors(query_df)

    # streaming happened: multiple bounded blocks, never the full item set
    assert len(seen_blocks) >= 4
    assert max(seen_blocks) < n_items
    # result keeps the query partitioning, empty partition included
    assert knn_df.num_partitions == query_df.num_partitions == 4
    assert len(knn_df.partitions[1]) == 0
    # and the streamed result is exact
    knn_pdf = knn_df.toPandas()
    order = np.argsort(knn_pdf["query_unique_id"].to_numpy())
    got_ids = np.stack(knn_pdf["indices"].to_numpy()[order])
    got_d = np.stack(knn_pdf["distances"].to_numpy()[order])
    sk_d, sk_i = SkNN(n_neighbors=k).fit(X).kneighbors(Q)
    np.testing.assert_allclose(got_d, sk_d, rtol=1e-4, atol=1e-4)
    # ids may differ on exact distance ties; compare distances + majority ids
    assert (got_ids == sk_i).mean() > 0.99


def test_knn_block_adaptive_exact_small_mesh():
    """Adaptive approx-verify-fallback block search (ops/knn.py) must be
    exact on the multi-device CPU mesh, ragged chunk tails included (the
    prototype bug class: items past the last full chunk silently skipped by
    BOTH the candidate and the verification scan)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.knn import knn_block_adaptive, prepare_items
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh
    from sklearn.neighbors import NearestNeighbors as SkNN

    rng = np.random.default_rng(4)
    n, d, q_n, k = 1000, 24, 96, 9
    X = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q_n, d)).astype(np.float32)
    mesh = get_mesh()
    prepared = prepare_items(X, np.arange(n, dtype=np.int64), mesh)
    # chunk=64 with 1000/n_dev rows per shard -> ragged tail exercised
    d_out, p_out = knn_block_adaptive(
        prepared.items, prepared.norm, prepared.pos, prepared.valid,
        Q, mesh, k, chunk=64,
    )
    sk_d, sk_i = SkNN(n_neighbors=k).fit(X).kneighbors(Q)
    np.testing.assert_allclose(d_out, sk_d, rtol=1e-4, atol=1e-4)
    ids = prepared.ids[p_out]
    assert (ids == sk_i).mean() > 0.99  # ties only


def test_knn_block_adaptive_fallback_rescues_corrupted_merge(monkeypatch):
    """AUDIT route (SRML_KNN_AUDIT_COUNT=1): force a merge 'miss' by
    corrupting one row's merged candidate list.  The global
    count-verification must flag exactly that row and the exact fallback
    must restore the correct answer."""
    import jax.numpy as jnp

    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh
    from sklearn.neighbors import NearestNeighbors as SkNN

    monkeypatch.setenv("SRML_KNN_AUDIT_COUNT", "1")
    rng = np.random.default_rng(5)
    n, d, q_n, k = 768, 16, 64, 7
    X = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q_n, d)).astype(np.float32)
    mesh = get_mesh()
    prepared = knn_mod.prepare_items(X, np.arange(n, dtype=np.int64), mesh)

    real_merge = knn_mod._adaptive_merge
    flagged = {}

    def corrupt_merge(cand_v, cand_i, kk):
        fv, fpos, td, sg = real_merge(cand_v, cand_i, kk)
        fv, fpos = np.array(fv), np.array(fpos)
        # drop row 3's best entry: shift in its (k+1)-th best via a worse
        # duplicate of the 2nd entry — row 3 is now WRONG and its returned
        # list no longer accounts for every entry above the threshold
        fv[3, 0] = fv[3, -1] - 1.0
        fpos[3, 0] = fpos[3, 1]
        fv = np.sort(fv, axis=1)[:, ::-1].copy()
        t = fv[:, -1]
        td = t + (np.abs(t) * 1e-6 + 1e-30)
        sg = (fv > td[:, None]).sum(axis=1)
        flagged["called"] = True
        return (
            jnp.asarray(fv), jnp.asarray(fpos),
            jnp.asarray(td), jnp.asarray(sg),
        )

    monkeypatch.setattr(knn_mod, "_adaptive_merge", corrupt_merge)
    d_out, p_out = knn_mod.knn_block_adaptive(
        prepared.items, prepared.norm, prepared.pos, prepared.valid,
        Q, mesh, k, chunk=64,
    )
    assert flagged.get("called")
    sk_d, _ = SkNN(n_neighbors=k).fit(X).kneighbors(Q)
    np.testing.assert_allclose(d_out, sk_d, rtol=1e-4, atol=1e-4)


def test_knn_adaptive_selfverify_flags_genuine_overflow(monkeypatch):
    """The pool-resident verification (_adaptive_merge_self, the default
    route) must catch the production failure mode it exists for: a group
    holding MORE of the true top-k than the per-group candidate budget m.
    Force it by shrinking m to 2 and clustering the entire top-k of every
    query inside one item group — the merged list is then provably wrong
    for every query, the group's m-th kept value beats the global kth
    threshold, and the per-row exact fallback must restore sklearn
    parity."""
    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh
    from sklearn.neighbors import NearestNeighbors as SkNN

    rng = np.random.default_rng(11)
    n, d, q_n, k, chunk = 640, 12, 64, 5, 32
    # far background + a tight cluster of k*2 items at the FRONT of the row
    # order (one 32-wide group on the first shard after row-sharding)
    X = rng.standard_normal((n, d)).astype(np.float32) * 10.0
    X[: 2 * k] = rng.standard_normal((2 * k, d)).astype(np.float32) * 1e-2
    Q = (rng.standard_normal((q_n, d)) * 1e-2).astype(np.float32)
    mesh = get_mesh()
    # shuffle=False: the deterministic prepare-time shuffle exists exactly
    # to break up clusters like this one — keep it off so the overflow the
    # test constructs survives into the scan
    prepared = knn_mod.prepare_items(
        X, np.arange(n, dtype=np.int64), mesh, shuffle=False
    )

    monkeypatch.setattr(knn_mod, "_select_m", lambda kk, G, n_loc: 2)
    real_self = knn_mod._adaptive_merge_self
    seen = {}

    def spy(cand_v, cand_i, k, m):
        out = real_self(cand_v, cand_i, k, m=m)
        seen["flags"] = np.asarray(out[2])
        return out

    monkeypatch.setattr(knn_mod, "_adaptive_merge_self", spy)
    d_out, p_out = knn_mod.knn_block_adaptive(
        prepared.items, prepared.norm, prepared.pos, prepared.valid,
        Q, mesh, k, chunk=chunk,
    )
    assert seen["flags"].any(), "overflow went undetected"
    sk_d, _ = SkNN(n_neighbors=k).fit(X).kneighbors(Q)
    np.testing.assert_allclose(d_out, sk_d, rtol=1e-4, atol=1e-4)


def test_knn_adaptive_selfverify_matches_count_audit():
    """On ordinary shuffled data the pool-resident flag and the audit
    count-verify must agree that nothing failed, and both routes must
    return identical results (same pool, same exact merge)."""
    import os

    import jax.numpy as jnp

    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    rng = np.random.default_rng(12)
    n, d, q_n, k = 1024, 24, 96, 9
    X = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q_n, d)).astype(np.float32)
    mesh = get_mesh()
    prepared = knn_mod.prepare_items(X, np.arange(n, dtype=np.int64), mesh)
    args = (
        prepared.items, prepared.norm, prepared.pos, prepared.valid,
        jnp.asarray(Q), mesh, k,
    )
    fv_s, fp_s, flags, zeros = knn_mod.knn_block_adaptive_dispatch(
        *args, chunk=128
    )
    assert not np.asarray(flags).any() and not np.asarray(zeros).any()
    os.environ["SRML_KNN_AUDIT_COUNT"] = "1"
    try:
        fv_a, fp_a, sg, sa = knn_mod.knn_block_adaptive_dispatch(
            *args, chunk=128
        )
    finally:
        del os.environ["SRML_KNN_AUDIT_COUNT"]
    np.testing.assert_array_equal(np.asarray(sg), np.asarray(sa))
    np.testing.assert_array_equal(np.asarray(fv_s), np.asarray(fv_a))
    np.testing.assert_array_equal(np.asarray(fp_s), np.asarray(fp_a))


def test_seed_staging_hits_even_with_aligned_prepared_columns(monkeypatch):
    """seed_staging must install a key that the kneighbors lookup MATCHES —
    including when prepare_items tile-aligned the prepared columns wider
    than the frame's feature dim (regression: the key was derived from
    prepared.items.shape[1], silently defeating the cache and rebuilding
    the index from the frame on every call)."""
    import numpy as np

    from spark_rapids_ml_tpu import NearestNeighbors
    from spark_rapids_ml_tpu.dataframe import DataFrame
    from spark_rapids_ml_tpu.models.knn import NearestNeighborsModel
    from spark_rapids_ml_tpu.ops.knn import prepare_items
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    rng = np.random.default_rng(3)
    X = rng.standard_normal((300, 12)).astype(np.float32)
    Q = rng.standard_normal((40, 12)).astype(np.float32)
    mesh = get_mesh(None)
    model = NearestNeighbors(k=4).fit(DataFrame.from_numpy(X))
    # simulate column tile-alignment: prepared carries 64 extra zero cols
    Xal = np.pad(X, ((0, 0), (0, 64)))
    prepared = prepare_items(
        Xal, np.arange(300, dtype=np.int64), mesh, shuffle=False
    )
    model.seed_staging(prepared, mesh=mesh)

    def _boom(*a, **kw):
        raise AssertionError(
            "kneighbors rebuilt the index: seeded staging key missed"
        )

    monkeypatch.setattr(
        NearestNeighborsModel, "_iter_item_blocks", _boom
    )
    _, _, knn = model.kneighbors(DataFrame.from_numpy(Q))
    d = np.stack(knn.toPandas()["distances"].to_numpy())
    d2 = ((Q[:, None, :] - X[None]) ** 2).sum(-1)
    want = np.sort(np.sqrt(d2), axis=1)[:, :4]
    np.testing.assert_allclose(d, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("force_adaptive", [False, True])
def test_pipelined_dispatch_overlaps_collect(monkeypatch, force_adaptive):
    """The pipelined query engine must issue device dispatch for block i+1
    BEFORE block i's host collection completes (asserted on the profiling
    event log, not wall-clock), on BOTH routes — the exact chunk-scan
    default and the adaptive grouped-select path (forced here, since its
    profitability gate is TPU-shaped but its exactness is not) — while
    staying exact vs the unpipelined sklearn reference."""
    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from spark_rapids_ml_tpu import profiling
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh
    from sklearn.neighbors import NearestNeighbors as SkNN

    if force_adaptive:
        monkeypatch.setenv("SRML_KNN_FORCE_ADAPTIVE", "1")
    rng = np.random.default_rng(17)
    n, d, q_n, k = 1024, 16, 600, 5
    X = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q_n, d)).astype(np.float32)
    mesh = get_mesh()
    prepared = knn_mod.prepare_items(X, np.arange(n, dtype=np.int64), mesh)
    profiling.reset_events()
    d_out, i_out = knn_mod.knn_search_prepared(
        prepared, Q, k, mesh, query_block=64
    )
    ev = profiling.events("knn.")
    n_blocks = -(-q_n // 64)
    dispatch_at = {
        m["block"]: i for i, (name, m) in enumerate(ev) if name == "knn.dispatch"
    }
    collect_at = {
        m["block"]: i for i, (name, m) in enumerate(ev) if name == "knn.collect"
    }
    assert sorted(dispatch_at) == sorted(collect_at) == list(range(n_blocks))
    # the overlap property: block i+1's dispatch precedes block i's collect
    for b in range(n_blocks - 1):
        assert dispatch_at[b + 1] < collect_at[b], (
            f"block {b + 1} dispatched only after block {b} was collected "
            "(pipeline serialized)"
        )
    # and the pipelined result is exact vs the unpipelined reference
    sk_d, sk_i = SkNN(n_neighbors=k).fit(X).kneighbors(Q)
    np.testing.assert_allclose(d_out, sk_d, rtol=1e-4, atol=1e-4)
    assert (i_out == sk_i).mean() > 0.99  # ties only


def test_pipelined_fallback_rewrites_readonly_block(monkeypatch):
    """ADVICE high (ops/knn.py _collect_a): device_get returns READ-ONLY
    views, so the deferred exact-fallback write `out_d[bi][fr] = ...` used
    to raise 'assignment destination is read-only' precisely when a
    verification flag fired inside knn_search_prepared.  Force a genuine
    self-verify flag through the PIPELINED path (shrunken per-group budget
    + a front-clustered unshuffled item set) and require sklearn parity."""
    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh
    from sklearn.neighbors import NearestNeighbors as SkNN

    monkeypatch.setenv("SRML_KNN_FORCE_ADAPTIVE", "1")
    rng = np.random.default_rng(23)
    n, d, q_n, k = 640, 12, 96, 5
    X = rng.standard_normal((n, d)).astype(np.float32) * 10.0
    X[: 2 * k] = rng.standard_normal((2 * k, d)).astype(np.float32) * 1e-2
    Q = (rng.standard_normal((q_n, d)) * 1e-2).astype(np.float32)
    mesh = get_mesh()
    prepared = knn_mod.prepare_items(
        X, np.arange(n, dtype=np.int64), mesh, shuffle=False
    )
    monkeypatch.setattr(knn_mod, "_select_m", lambda kk, G, n_loc: 2)
    real_self = knn_mod._adaptive_merge_self
    seen = {}

    def spy(cand_v, cand_i, k, m):
        out = real_self(cand_v, cand_i, k, m=m)
        if np.asarray(out[2]).any():
            seen["flagged"] = True
        return out

    monkeypatch.setattr(knn_mod, "_adaptive_merge_self", spy)
    d_out, i_out = knn_mod.knn_search_prepared(
        prepared, Q, k, mesh, query_block=64
    )
    assert seen.get("flagged"), "no verification flag fired; test is vacuous"
    sk_d, _ = SkNN(n_neighbors=k).fit(X).kneighbors(Q)
    np.testing.assert_allclose(d_out, sk_d, rtol=1e-4, atol=1e-4)


def test_adaptive_rejects_unevenly_sharded_items():
    """ADVICE low (ops/knn.py merge-stride derivation): item rows that do
    not divide over the mesh shards must raise instead of silently deriving
    an unsound per-shard stride."""
    import jax.numpy as jnp

    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    mesh = get_mesh()
    n_dev = mesh.devices.size
    if n_dev == 1:
        pytest.skip("needs a multi-shard mesh")
    n = n_dev * 8 + 1  # NOT a device multiple
    items = jnp.zeros((n, 8), jnp.float32)
    norm = jnp.zeros((n,), jnp.float32)
    pos = jnp.arange(n, dtype=jnp.int32)
    valid = jnp.ones((n,), bool)
    qd = jnp.zeros((64, 8), jnp.float32)
    with pytest.raises(ValueError, match="evenly sharded"):
        knn_mod.knn_block_adaptive_dispatch(
            items, norm, pos, valid, qd, mesh, 3
        )
