# Stage-level scheduling decision table (spark/adapter.py), tested against a
# dict-backed conf the way the reference tests it with a synthetic SparkConf
# (test_common_estimator.py:526-580).  No pyspark needed: the decision
# function takes (version, conf_get).
import pytest

from spark_rapids_ml_tpu.spark.adapter import (
    TPU_RESOURCE_NAME,
    skip_stage_level_scheduling,
)

GOOD_CONF = {
    "spark.master": "spark://host:7077",
    "spark.executor.cores": "8",
    f"spark.executor.resource.{TPU_RESOURCE_NAME}.amount": "1",
}


def _get(conf):
    return conf.get


def test_enabled_on_good_conf():
    assert skip_stage_level_scheduling("3.4.0", _get(GOOD_CONF)) == ""
    assert skip_stage_level_scheduling("3.5.1", _get(GOOD_CONF)) == ""


def test_old_spark_skips():
    assert "3.4.0" in skip_stage_level_scheduling("3.3.2", _get(GOOD_CONF))


@pytest.mark.parametrize("master", ["yarn", "k8s://x", "local[4]", ""])
def test_non_standalone_skips(master):
    conf = {**GOOD_CONF, "spark.master": master}
    assert "standalone" in skip_stage_level_scheduling("3.4.0", _get(conf))


def test_local_cluster_allowed():
    conf = {**GOOD_CONF, "spark.master": "local-cluster[2,4,1024]"}
    assert skip_stage_level_scheduling("3.4.0", _get(conf)) == ""


@pytest.mark.parametrize(
    "missing", ["spark.executor.cores", f"spark.executor.resource.{TPU_RESOURCE_NAME}.amount"]
)
def test_missing_resource_confs_skip(missing):
    conf = {k: v for k, v in GOOD_CONF.items() if k != missing}
    assert "requires" in skip_stage_level_scheduling("3.4.0", _get(conf))


def test_single_core_executor_skips():
    conf = {**GOOD_CONF, "spark.executor.cores": "1"}
    assert "cores" in skip_stage_level_scheduling("3.4.0", _get(conf))


def test_multi_tpu_executor_skips():
    conf = {**GOOD_CONF, f"spark.executor.resource.{TPU_RESOURCE_NAME}.amount": "2"}
    assert "user-managed" in skip_stage_level_scheduling("3.4.0", _get(conf))


def test_task_amount_unset_enables():
    assert skip_stage_level_scheduling("3.4.0", _get(GOOD_CONF)) == ""


def test_task_claims_whole_resource_skips():
    conf = {**GOOD_CONF, f"spark.task.resource.{TPU_RESOURCE_NAME}.amount": "1"}
    assert "whole executor" in skip_stage_level_scheduling("3.4.0", _get(conf))


def test_fractional_task_amount_enables():
    conf = {**GOOD_CONF, f"spark.task.resource.{TPU_RESOURCE_NAME}.amount": "0.5"}
    assert skip_stage_level_scheduling("3.4.0", _get(conf)) == ""
