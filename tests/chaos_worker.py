# One rank of the srml-shield chaos matrix: a real OS process doing
# control-plane gather rounds over a FileControlPlane while SRML_FAULTS
# (inherited from the driver test's environment) kills / aborts one of the
# cohort mid-round.  Exit codes are the protocol:
#
#    0  clean run (all rounds completed, teardown clean)
#    7  survivor: raised RemoteRankError naming a dead/aborted peer
#    9  victim of action=raise: published its abort marker and exited
#   17  victim of action=die (faults.DIE_EXIT_CODE): os._exit, no teardown
#
# Survivors print one machine-readable line:
#   SHIELD rank=<me> culprit=<rank> dt=<seconds-to-detect> span=<span> etype=<t>
# where dt measures entry-into-the-failing-gather -> RemoteRankError — the
# abort-latency the ISSUE bounds at < 10 s (vs the 300 s round timeout).
#
# Invoked as: python chaos_worker.py <rank> <nranks> <jobdir> [rounds]
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from spark_rapids_ml_tpu.parallel.context import RemoteRankError  # noqa: E402
from spark_rapids_ml_tpu.parallel.faults import FaultInjected  # noqa: E402
from spark_rapids_ml_tpu.parallel.runner import FileControlPlane  # noqa: E402


def main() -> None:
    rank, nranks, root = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    rounds = int(sys.argv[4]) if len(sys.argv) > 4 else 4
    cp = FileControlPlane(
        os.path.join(root, "cp"), rank, nranks, timeout=120, poll=0.02
    )
    t0 = time.monotonic()
    try:
        for r in range(rounds):
            t0 = time.monotonic()
            got = cp.allGather(f"rank{rank}:round{r}")
            assert len(got) == nranks, got
    except RemoteRankError as exc:
        dt = time.monotonic() - t0
        print(
            f"SHIELD rank={rank} culprit={exc.rank} dt={dt:.3f} "
            f"span={exc.span} etype={exc.etype}",
            flush=True,
        )
        cp.close()
        sys.exit(7)
    except FaultInjected as exc:
        # the orderly victim: publish the abort marker the way
        # TpuContext.__exit__ does on the exception path, then leave
        import json

        cp.abort(json.dumps({
            "rank": rank,
            "etype": type(exc).__name__,
            "message": str(exc),
            "span": "chaos.gather",
        }))
        cp.close()
        sys.exit(9)
    print(f"SHIELD rank={rank} clean", flush=True)
    cp.close()
    sys.exit(0)


if __name__ == "__main__":
    main()
