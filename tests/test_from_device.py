#
# DataFrame.from_device: jax-native ingest — fits consume a device-resident
# (optionally mesh-sharded) feature array directly, skipping host
# materialization and upload (the TPU analog of the reference riding
# spark-rapids' GPU-resident columnar cache).  Fits must match the
# host-ingest path bit-for-bit-ish on the same data.
#
import numpy as np
import pytest

import jax

from spark_rapids_ml_tpu import (
    KMeans,
    LinearRegression,
    PCA,
    RandomForestRegressor,
)
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.parallel.mesh import data_sharding, get_mesh


def _device_df(X, y=None, mesh=None):
    mesh = mesh or get_mesh(None)
    n_dev = mesh.devices.size
    n_pad = X.shape[0] + (-X.shape[0]) % n_dev
    Xp = np.zeros((n_pad, X.shape[1]), X.dtype)
    Xp[: X.shape[0]] = X
    Xs = jax.device_put(Xp, data_sharding(mesh))
    return DataFrame.from_device(Xs, y=y, n_rows=X.shape[0])


def _data(n=500, d=12, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d) + 0.1 * rng.standard_normal(n)).astype(
        np.float32
    )
    return X, y


def test_kmeans_from_device_matches_host():
    X, _ = _data()
    a = KMeans(k=3, maxIter=12, seed=7).fit(_device_df(X))
    b = KMeans(k=3, maxIter=12, seed=7).fit(DataFrame.from_numpy(X))
    np.testing.assert_allclose(
        np.asarray(a.cluster_centers_), np.asarray(b.cluster_centers_),
        rtol=1e-5, atol=1e-5,
    )


def test_pca_from_device_matches_host():
    X, _ = _data(n=640)
    a = PCA(k=3).fit(_device_df(X))
    b = PCA(k=3).fit(DataFrame.from_numpy(X))
    np.testing.assert_allclose(
        np.asarray(a.components_), np.asarray(b.components_),
        rtol=1e-4, atol=1e-4,
    )


def test_linreg_from_device_matches_host_with_padding():
    X, y = _data(n=501)  # pads on the 8-device mesh
    a = LinearRegression(maxIter=20).fit(_device_df(X, y))
    b = LinearRegression(maxIter=20).fit(DataFrame.from_numpy(X, y))
    np.testing.assert_allclose(
        np.asarray(a.coef_), np.asarray(b.coef_), rtol=1e-4, atol=1e-5
    )


def test_rf_from_device_trains():
    X, y = _data(n=400, d=10)
    model = RandomForestRegressor(
        numTrees=6, maxDepth=4, maxBins=16, seed=2
    ).fit(_device_df(X, y))
    preds = model.transform(DataFrame.from_numpy(X)).toPandas()["prediction"]
    resid = np.asarray(preds, np.float64) - y
    assert float(np.sqrt((resid**2).mean())) < 0.8 * float(y.std())


def test_from_device_transform_raises():
    X, _ = _data(n=64)
    df = _device_df(X)
    model = KMeans(k=2, maxIter=5, seed=1).fit(df)
    with pytest.raises(NotImplementedError, match="fit-input only"):
        model.transform(df)


def test_from_device_knn_fit_raises():
    X, _ = _data(n=64)
    df = _device_df(X)
    from spark_rapids_ml_tpu import NearestNeighbors

    with pytest.raises(NotImplementedError, match="seed_staging"):
        NearestNeighbors(k=3).fit(df)
