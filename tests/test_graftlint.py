# graftlint rule tests: every rule R1-R5 must FIRE on a minimal bad snippet
# and stay SILENT on the corrected version, pragmas must suppress, the
# baseline must demote, and the real tree must lint clean (the zero-findings
# gate that keeps the pass trustworthy — a linter the tree itself violates
# trains everyone to ignore it).
import os
import textwrap

import pytest

from tools.graftlint import (
    apply_baseline,
    collect_pragmas,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src: str, path: str = "pkg/mod.py", rules=None):
    return lint_source(textwrap.dedent(src), path=path, rules=rules)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# -- R1: host sync in hot path ------------------------------------------------

R1_BAD_LOOP = """
    import jax
    import jax.numpy as jnp

    def fit(a, n):
        total = 0.0
        for i in range(n):
            x = jnp.sum(a) * i
            total += float(x)
        return total
"""

R1_GOOD_LOOP = """
    import jax
    import jax.numpy as jnp

    def fit(a, n):
        parts = []
        for i in range(n):
            parts.append(jnp.sum(a) * i)
        return sum(float(v) for v in jax.device_get(parts))
"""


def test_r1_fires_on_float_in_loop():
    findings = _lint(R1_BAD_LOOP)
    assert _rules_of(findings) == ["R1"]
    assert "device->host" in findings[0].message


def test_r1_silent_on_batched_fetch():
    assert _lint(R1_GOOD_LOOP) == []


def test_r1_fires_on_asarray_in_jitted_body():
    findings = _lint(
        """
        import numpy as np
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            y = jnp.sum(x)
            return np.asarray(y)
        """
    )
    assert "R1" in _rules_of(findings)


def test_r1_fires_on_device_get_inside_loop():
    findings = _lint(
        """
        import jax
        import jax.numpy as jnp

        def fit(a, n):
            out = []
            for i in range(n):
                out.append(jax.device_get(jnp.sum(a) * i))
            return out
        """
    )
    assert _rules_of(findings) == ["R1"]


def test_r1_untaints_through_shape_and_range():
    # vals.shape[0] / range() yield host ints: the loop variable must not
    # count as device data (regression: r taint via `range(vals.shape[0])`)
    assert (
        _lint(
            """
            import numpy as np
            import jax
            import jax.numpy as jnp

            def check(vals):
                ai = jnp.argsort(vals)
                ai_h = jax.device_get(ai)
                for r in range(vals.shape[0]):
                    print(ai_h[r].tolist())
            """
        )
        == []
    )


def test_r1_ignores_plain_numpy_loops():
    assert (
        _lint(
            """
            import numpy as np

            def ingest(parts):
                out = []
                for p in parts:
                    out.append(np.asarray(p, dtype=np.float32))
                return np.concatenate(out)
            """
        )
        == []
    )


# -- R2: recompile risk -------------------------------------------------------

R2_BAD_PARAM = """
    import jax

    @jax.jit
    def solve(x, n_iter):
        return x * n_iter
"""

R2_GOOD_PARAM = """
    from functools import partial
    import jax

    @partial(jax.jit, static_argnames=("n_iter",))
    def solve(x, n_iter):
        return x * n_iter
"""


def test_r2_fires_on_unmarked_shape_param():
    findings = _lint(R2_BAD_PARAM)
    assert _rules_of(findings) == ["R2"]
    assert "static_argnames" in findings[0].message


def test_r2_silent_with_static_argnames():
    assert _lint(R2_GOOD_PARAM) == []


def test_r2_fires_on_python_if_over_tracer():
    findings = _lint(
        """
        import jax

        @jax.jit
        def pick(x, flag):
            if flag:
                return x
            return -x
        """
    )
    assert _rules_of(findings) == ["R2"]
    assert "lax.cond" in findings[0].message


def test_r2_allows_static_shape_and_structure_tests():
    assert (
        _lint(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def pad(q, items):
                if q.shape[1] != items.shape[1]:
                    q = jnp.pad(q, ((0, 0), (0, items.shape[1] - q.shape[1])))
                if q.ndim == 1:
                    q = q[None, :]
                return q
            """
        )
        == []
    )


# -- R3: axis names bound through parallel/mesh -------------------------------


def test_r3_fires_on_string_literal_axis():
    findings = _lint(
        """
        import jax

        def agg(x):
            return jax.lax.psum(x, "data")
        """
    )
    assert _rules_of(findings) == ["R3"]
    assert "parallel/mesh" in findings[0].message


def test_r3_fires_on_module_local_axis_string():
    findings = _lint(
        """
        import jax

        AXIS = "data"

        def agg(x):
            return jax.lax.psum(x, AXIS)
        """
    )
    assert _rules_of(findings) == ["R3"]


def test_r3_counts_nested_constructor_literal_once():
    # P("data") nested in NamedSharding must be ONE finding, not two — a
    # double count would also corrupt --baseline budgets
    findings = _lint(
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        def shard(mesh):
            return NamedSharding(mesh, P("data"))
        """
    )
    assert _rules_of(findings) == ["R3"]
    assert len(findings) == 1


def test_r3_silent_on_mesh_bound_axis():
    assert (
        _lint(
            """
            import jax
            from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

            def agg(x):
                return jax.lax.psum(x, DATA_AXIS)
            """
        )
        == []
    )


def test_r3_fires_on_partition_spec_literal():
    findings = _lint(
        """
        from jax.sharding import PartitionSpec as P

        def spec():
            return P("data")
        """
    )
    assert _rules_of(findings) == ["R3"]


# -- R4: nondeterminism -------------------------------------------------------


def test_r4_fires_on_legacy_global_rng():
    findings = _lint(
        """
        import numpy as np

        def sample(n):
            return np.random.normal(size=n)
        """
    )
    assert _rules_of(findings) == ["R4"]
    assert "GLOBAL RNG" in findings[0].message


def test_r4_fires_on_unseeded_default_rng():
    findings = _lint(
        """
        import numpy as np

        def sample(n):
            rng = np.random.default_rng()
            return rng.normal(size=n)
        """
    )
    assert _rules_of(findings) == ["R4"]


def test_r4_fires_on_module_scope_rng():
    findings = _lint(
        """
        import jax

        _KEY = jax.random.PRNGKey(0)
        """
    )
    assert _rules_of(findings) == ["R4"]
    assert "module scope" in findings[0].message


def test_r4_fires_on_set_iteration():
    findings = _lint(
        """
        def merge(items):
            out = []
            for x in set(items):
                out.append(x)
            return out
        """
    )
    assert _rules_of(findings) == ["R4"]


def test_r4_silent_on_seeded_rng_and_sorted_set():
    assert (
        _lint(
            """
            import numpy as np

            def sample(n, seed):
                rng = np.random.default_rng(seed)
                vals = rng.normal(size=n)
                return [v for v in sorted(set(vals.tolist()))]
            """
        )
        == []
    )


# -- R5: float64 discipline in ops/ -------------------------------------------

R5_BAD = """
    import numpy as np
    import jax.numpy as jnp

    def kernel(x):
        return jnp.zeros(x.shape, dtype=np.float64)
"""


def test_r5_fires_on_float64_in_ops():
    findings = _lint(R5_BAD, path="spark_rapids_ml_tpu/ops/fake.py")
    assert _rules_of(findings) == ["R5"]
    assert "f64" in findings[0].message or "float64" in findings[0].message


def test_r5_scoped_to_ops_dirs():
    # the same snippet outside ops/ is not R5's business
    assert _lint(R5_BAD, path="spark_rapids_ml_tpu/models/fake.py") == []


def test_r5_fires_on_dtype_string_and_builtin_float():
    findings = _lint(
        """
        import numpy as np

        def kernel(x):
            a = np.zeros(3, dtype="float64")
            b = np.zeros(3, dtype=float)
            return a, b
        """,
        path="benchmark/ops/fake.py",
    )
    assert len(findings) == 2
    assert _rules_of(findings) == ["R5"]


def test_r5_silent_on_float32():
    assert (
        _lint(
            """
            import numpy as np
            import jax.numpy as jnp

            def kernel(x):
                return jnp.zeros(x.shape, dtype=np.float32)
            """,
            path="spark_rapids_ml_tpu/ops/fake.py",
        )
        == []
    )


# -- pragmas, baseline, rule selection ---------------------------------------


def test_pragma_suppresses_on_line_and_line_above():
    src = """
        import numpy as np

        def sample(n):
            return np.random.normal(size=n)  # graftlint: disable=R4 (test fixture)
    """
    assert _lint(src) == []
    src_above = """
        import numpy as np

        def sample(n):
            # graftlint: disable=R4 (test fixture)
            return np.random.normal(size=n)
    """
    assert _lint(src_above) == []


def test_pragma_is_rule_specific():
    src = """
        import numpy as np

        def sample(n):
            return np.random.normal(size=n)  # graftlint: disable=R1 (wrong rule)
    """
    assert _rules_of(_lint(src)) == ["R4"]


def test_pragma_reason_parses():
    pragmas = collect_pragmas(
        "x = 1  # graftlint: disable=R1, R5 (host-side math)\n"
    )
    assert pragmas == {1: {"R1", "R5"}}


def test_rule_selection():
    both = """
        import numpy as np
        import jax

        def f(x, n):
            np.random.seed(0)
            for i in range(n):
                y = jax.numpy.sum(x)
                print(float(y))
    """
    assert _rules_of(_lint(both)) == ["R1", "R4"]
    assert _rules_of(_lint(both, rules=["R4"])) == ["R4"]


def test_baseline_demotes_then_catches_new(tmp_path):
    findings = _lint(R1_BAD_LOOP, path="pkg/mod.py")
    assert findings
    baseline_file = tmp_path / "baseline.json"
    ids = write_baseline(str(baseline_file), findings)
    assert len(ids) == len(findings)
    baseline = load_baseline(str(baseline_file))
    assert isinstance(baseline, set) and baseline == set(ids)
    errors, warnings = apply_baseline(findings, baseline)
    assert errors == [] and len(warnings) == len(findings)
    # a second occurrence of the same fingerprint gets a `~1` id the
    # baseline has never seen — an error again
    doubled = findings + findings
    errors, warnings = apply_baseline(doubled, baseline)
    assert len(errors) == len(findings) and len(warnings) == len(findings)


def test_baseline_v1_counts_still_apply():
    # legacy count-budget baselines (pre-v2 checkouts) keep working
    findings = _lint(R1_BAD_LOOP, path="pkg/mod.py")
    counts = {f"{f.path}::{f.rule}": len(findings) for f in findings}
    errors, warnings = apply_baseline(findings, counts)
    assert errors == [] and len(warnings) == len(findings)
    doubled = findings + findings
    errors, warnings = apply_baseline(doubled, counts)
    assert len(errors) == len(findings)


# -- R6: raw wall clocks outside srml-scope -----------------------------------

R6_BAD = """
    import time

    def _dispatch(self, batch):
        t0 = time.perf_counter()
        run(batch)
        return time.time() - t0
"""

R6_GOOD = """
    from .. import profiling

    def _dispatch(self, batch):
        t0 = profiling.now()
        with profiling.span("serve.dispatch"):
            run(batch)
        return profiling.now() - t0
"""

R6_MONOTONIC_OK = """
    import time

    def poll(deadline):
        while time.monotonic() < deadline:
            time.sleep(0.01)
"""


def test_r6_fires_on_raw_clock_in_package_module():
    findings = _lint(R6_BAD, path="spark_rapids_ml_tpu/serving/engine.py")
    assert _rules_of(findings) == ["R6"]
    assert len(findings) == 2  # perf_counter AND time.time
    assert "profiling.now()" in findings[0].message


def test_r6_scoped_to_the_package_and_exempts_profiling():
    # profiling.py is the clock's home
    assert _lint(R6_BAD, path="spark_rapids_ml_tpu/profiling.py") == []
    # benchmark/test harness code may time however it likes
    assert _lint(R6_BAD, path="benchmark/base.py") == []
    assert _lint(R6_BAD, path="tests/test_x.py") == []


def test_r6_silent_on_srml_scope_and_monotonic():
    assert _lint(R6_GOOD, path="spark_rapids_ml_tpu/serving/engine.py") == []
    # deadline polling (monotonic/sleep) is control flow, not observability
    assert (
        _lint(R6_MONOTONIC_OK, path="spark_rapids_ml_tpu/parallel/runner.py")
        == []
    )


def test_r6_pragma_escape():
    src = """
        import time

        def boot():
            t0 = time.perf_counter()  # graftlint: disable=R6 (pre-profiling bootstrap)
            return t0
    """
    assert _lint(src, path="spark_rapids_ml_tpu/x.py") == []


# -- R7: every thread must be named -------------------------------------------

R7_BAD = """
    import threading

    def start(fn):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        return t
"""

R7_BAD_FROM_IMPORT = """
    from threading import Thread, Timer

    def start(fn):
        Timer(1.0, fn).start()
        return Thread(target=fn)
"""

R7_GOOD = """
    import threading

    def start(fn, name):
        t = threading.Thread(target=fn, name=f"srml-x-{name}", daemon=True)
        t.start()
        return t
"""


def test_r7_fires_on_unnamed_thread_in_package_module():
    findings = _lint(R7_BAD, path="spark_rapids_ml_tpu/serving/engine.py")
    assert _rules_of(findings) == ["R7"]
    assert "name=" in findings[0].message


def test_r7_resolves_from_import_aliases_and_timer():
    findings = _lint(
        R7_BAD_FROM_IMPORT, path="spark_rapids_ml_tpu/watch.py"
    )
    assert _rules_of(findings) == ["R7"]
    assert len(findings) == 2  # Thread AND Timer


def test_r7_silent_on_named_threads_and_out_of_scope():
    assert _lint(R7_GOOD, path="spark_rapids_ml_tpu/serving/engine.py") == []
    # benchmark/test harness threads may stay anonymous
    assert _lint(R7_BAD, path="benchmark/bench_serving.py") == []
    assert _lint(R7_BAD, path="tests/test_x.py") == []


def test_r7_pragma_escape():
    src = """
        import threading

        def start(fn):
            return threading.Thread(target=fn)  # graftlint: disable=R7 (3p callback contract)
    """
    assert _lint(src, path="spark_rapids_ml_tpu/x.py") == []


# -- R8: remote-DMA confinement + paired start/wait ---------------------------

R8_REMOTE_OUTSIDE = """
    from jax.experimental.pallas import tpu as pltpu

    def ring_kernel(x_ref, o_ref, send_sem, recv_sem, dst):
        copy = pltpu.make_async_remote_copy(
            src_ref=x_ref, dst_ref=o_ref,
            send_sem=send_sem, recv_sem=recv_sem, device_id=(dst,),
        )
        copy.start()
        copy.wait()
"""

R8_UNPAIRED_START = """
    from jax.experimental.pallas import tpu as pltpu

    def kernel(hbm_ref, vmem_ref, sem):
        dma = pltpu.make_async_copy(hbm_ref, vmem_ref, sem)
        dma.start()
        vmem_ref[...] = vmem_ref[...] * 2.0
"""

R8_PAIRED_OK = """
    from jax.experimental.pallas import tpu as pltpu

    def kernel(hbm_ref, vmem_ref, sem):
        dma = pltpu.make_async_copy(hbm_ref, vmem_ref, sem)
        dma.start()
        dma.wait()
"""


def test_r8_fires_on_remote_copy_outside_exchange():
    findings = _lint(
        R8_REMOTE_OUTSIDE, path="spark_rapids_ml_tpu/ops/pallas_knn.py"
    )
    assert _rules_of(findings) == ["R8"]
    assert "parallel/exchange.py" in findings[0].message


def test_r8_remote_copy_allowed_in_exchange():
    # rules=["R8"]: the fixture's bare copy.wait() is R9 material at this
    # path (the real exchange.py pragmas it with the DMA-has-no-timeout
    # reason); this test is about R8 confinement only
    assert (
        _lint(
            R8_REMOTE_OUTSIDE,
            path="spark_rapids_ml_tpu/parallel/exchange.py",
            rules=["R8"],
        )
        == []
    )


def test_r8_fires_on_unpaired_start():
    findings = _lint(
        R8_UNPAIRED_START, path="spark_rapids_ml_tpu/ops/pallas_knn.py"
    )
    assert _rules_of(findings) == ["R8"]
    assert "wait()" in findings[0].message


def test_r8_silent_on_paired_start_wait_and_out_of_scope():
    assert (
        _lint(R8_PAIRED_OK, path="spark_rapids_ml_tpu/ops/pallas_knn.py")
        == []
    )
    # non-package code (docs snippets, tests) is out of scope
    assert _lint(R8_UNPAIRED_START, path="tests/test_x.py") == []


def test_r8_pragma_escape():
    src = """
        from jax.experimental.pallas import tpu as pltpu

        def kernel(hbm_ref, vmem_ref, sem):
            dma = pltpu.make_async_copy(hbm_ref, vmem_ref, sem)
            dma.start()  # graftlint: disable=R8 (waited by the out_shape semaphore)
            return dma
    """
    assert _lint(src, path="spark_rapids_ml_tpu/ops/x.py") == []


# -- R9: unbounded waits + silent teardown swallows ---------------------------

R9_BAD_WAITS = """
    def collect(fut, lock, worker):
        out = fut.result()
        lock.acquire()
        worker.join()
        return out
"""

R9_BAD_SWALLOW = """
    def teardown(ctx):
        try:
            ctx.shutdown()
        except Exception:
            pass
"""

R9_GOOD = """
    import logging

    log = logging.getLogger(__name__)

    def collect(fut, lock, worker, parts, cond, remaining):
        out = fut.result(timeout=30.0)
        lock.acquire(timeout=1.0)
        worker.join(5.0)
        cond.wait(remaining)      # a deadline variable bounds it
        joined = "".join(parts)   # str.join always takes its iterable
        return out, joined

    def teardown(ctx):
        try:
            ctx.shutdown()
        except Exception as exc:
            log.warning("shutdown failed: %s", exc)  # logged, not swallowed
        try:
            ctx.unlink()
        except OSError:
            pass  # narrow handler: deliberate, in scope of the except type
"""


def test_r9_fires_on_unbounded_waits_in_parallel_and_serving():
    for path in (
        "spark_rapids_ml_tpu/parallel/runner.py",
        "spark_rapids_ml_tpu/serving/engine.py",
    ):
        findings = _lint(R9_BAD_WAITS, path=path)
        assert _rules_of(findings) == ["R9"]
        assert len(findings) == 3  # result, acquire, join
        assert "timeout" in findings[0].message


def test_r9_fires_on_silent_broad_swallow():
    findings = _lint(R9_BAD_SWALLOW, path="spark_rapids_ml_tpu/parallel/context.py")
    assert _rules_of(findings) == ["R9"]
    assert "logged event" in findings[0].message


def test_r9_silent_on_bounded_waits_logged_handlers_and_narrow_types():
    assert _lint(R9_GOOD, path="spark_rapids_ml_tpu/serving/batcher.py") == []


def test_r9_scoped_to_parallel_and_serving():
    # solver/engine modules block only on the device runtime — out of scope
    assert _lint(R9_BAD_WAITS, path="spark_rapids_ml_tpu/ops/knn.py") == []
    assert _lint(R9_BAD_SWALLOW, path="spark_rapids_ml_tpu/watch.py") == []
    assert _lint(R9_BAD_WAITS, path="benchmark/bench_serving.py") == []


def test_r9_pragma_escape():
    src = """
        def hop(copy):
            copy.wait()  # graftlint: disable=R9 (DMA completion has no timeout)
    """
    assert _lint(src, path="spark_rapids_ml_tpu/parallel/exchange.py") == []


# -- R10: raw-socket confinement + bounded socket waits -----------------------

R10_SOCKET_OUTSIDE = """
    import socket

    def pick_port():
        with socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    def dial(addr):
        return socket.create_connection(addr, timeout=5.0)
"""

R10_UNBOUNDED_RECV = """
    def read_all(sock, conn_listener):
        conn, _ = conn_listener.accept()
        return sock.recv(4096)
"""

R10_BOUNDED_RECV = """
    def read_all(sock, conn_listener):
        conn_listener.settimeout(0.25)
        sock.settimeout(0.25)
        conn, _ = conn_listener.accept()
        return sock.recv(4096)
"""


def test_r10_fires_on_raw_sockets_outside_netplane():
    findings = _lint(
        R10_SOCKET_OUTSIDE, path="spark_rapids_ml_tpu/parallel/context.py"
    )
    assert _rules_of(findings) == ["R10"]
    assert len(findings) == 2  # socket.socket + socket.create_connection
    assert "parallel/netplane.py" in findings[0].message


def test_r10_constructors_allowed_inside_netplane():
    assert _lint(
        R10_SOCKET_OUTSIDE,
        path="spark_rapids_ml_tpu/parallel/netplane.py",
    ) == []


def test_r10_fires_on_unbounded_recv_accept_in_netplane():
    findings = _lint(
        R10_UNBOUNDED_RECV, path="spark_rapids_ml_tpu/parallel/netplane.py"
    )
    assert _rules_of(findings) == ["R10"]
    assert len(findings) == 2  # accept + recv, both timeout-less
    assert "settimeout" in findings[0].message


def test_r10_silent_when_settimeout_precedes_the_wait():
    assert _lint(
        R10_BOUNDED_RECV, path="spark_rapids_ml_tpu/parallel/netplane.py"
    ) == []


def test_r10_scoped_to_the_package():
    # tests/benchmarks may socket however they like; the recv discipline
    # applies only inside the confined module itself
    assert _lint(R10_SOCKET_OUTSIDE, path="tests/chaos_driver.py") == []
    assert _lint(
        R10_UNBOUNDED_RECV, path="spark_rapids_ml_tpu/serving/engine.py"
    ) == []


def test_r10_pragma_escape():
    src = """
        import socket

        def legacy_probe():
            s = socket.socket()  # graftlint: disable=R10 (pre-wire probe, bounded by caller)
            return s
    """
    assert _lint(src, path="spark_rapids_ml_tpu/utils.py") == []


# -- the gate: the real tree is clean -----------------------------------------


@pytest.mark.parametrize("pkg", ["spark_rapids_ml_tpu", "benchmark", "tests"])
def test_tree_is_graftlint_clean(pkg):
    findings = lint_paths([os.path.join(REPO, pkg)])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_reports_per_rule_counts(capsys):
    from tools.graftlint.__main__ import main

    rc = main([os.path.join(REPO, "spark_rapids_ml_tpu", "utils.py")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "R1[host-sync]=" in out and "clean" in out
