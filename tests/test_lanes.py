# srml-lanes: the shared candidate/variant lane engine (ops/lanes.py) —
# lane-bucket edge cases, duplicate-lane padding correctness, the
# pack_lane_subset packing helper every sweep site rides, serving-side
# lane stacking / paging primitives, and the compile-count gate proving
# that growing K across a pow2 bucket boundary triggers exactly ONE new
# compile (and zero within a bucket) — the PR 12 insight the whole
# multiplex subsystem is built on.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.ops import sweep as sweep_ops
from spark_rapids_ml_tpu.ops.lanes import (
    lane_bucket,
    pack_lane_subset,
    pad_lanes,
    stack_lanes,
    write_lane,
)


def test_lane_bucket_edges():
    assert lane_bucket(1) == 1  # K=1: a single lane is its own bucket
    assert lane_bucket(2) == 2
    assert lane_bucket(3) == 4  # non-pow2 rounds up
    assert lane_bucket(4) == 4
    assert lane_bucket(5) == 8
    assert lane_bucket(17) == 32
    assert lane_bucket(512) == 512
    assert lane_bucket(0) == 1  # floor 1: empty never keys a 0-wide kernel


def test_sweep_reexports_are_the_lane_engine():
    # ops/sweep re-exports the hoisted engine under its historical names;
    # call sites and docs that say candidate_bucket must hit the SAME code
    assert sweep_ops.candidate_bucket is lane_bucket
    assert sweep_ops.pad_lanes is pad_lanes
    assert sweep_ops.pack_lane_subset is pack_lane_subset


def test_pad_lanes_duplicates_first_value():
    out = pad_lanes([0.5, 0.25, 0.125], 4)
    assert out.shape == (4,) and out.dtype == np.float64
    np.testing.assert_array_equal(out[:3], [0.5, 0.25, 0.125])
    assert out[3] == 0.5  # pad lane duplicates lane 0, never injects zeros


def test_pad_lanes_exact_bucket_is_identity():
    out = pad_lanes([1.0, 2.0], 2)
    np.testing.assert_array_equal(out, [1.0, 2.0])


def test_pack_lane_subset_single_field():
    cand = [(0.1,), (0.2,), (0.3,), (0.4,), (0.5,)]
    bucket, (vec,) = pack_lane_subset(cand, [1, 3, 4])
    assert bucket == 4
    got = np.asarray(vec)
    np.testing.assert_allclose(got[:3], [0.2, 0.4, 0.5])
    np.testing.assert_allclose(got[3], 0.2)  # duplicate-lane padding


def test_pack_lane_subset_multi_field():
    cand = [(0.1, 0.9), (0.2, 0.8), (0.3, 0.7)]
    bucket, (a, b) = pack_lane_subset(cand, [0, 2], fields=(0, 1))
    assert bucket == 2
    np.testing.assert_allclose(np.asarray(a), [0.1, 0.3])
    np.testing.assert_allclose(np.asarray(b), [0.9, 0.7])


def test_pack_lane_subset_k1():
    bucket, (vec,) = pack_lane_subset([(7.0,)], [0])
    assert bucket == 1
    np.testing.assert_allclose(np.asarray(vec), [7.0])


# -- serving-side stacking / paging ------------------------------------------


def test_stack_lanes_shapes_and_padding():
    leaves = [
        (np.full((3, 2), float(k), np.float32), np.float32(k)) for k in range(3)
    ]
    st = stack_lanes(leaves, 4)
    assert [s.shape for s in st] == [(4, 3, 2), (4,)]
    m, b = np.asarray(st[0]), np.asarray(st[1])
    np.testing.assert_array_equal(b[:3], [0.0, 1.0, 2.0])
    assert b[3] == 0.0  # pad lane duplicates variant 0
    np.testing.assert_array_equal(m[3], m[0])


def test_stack_lanes_validation():
    with pytest.raises(ValueError, match="at least one"):
        stack_lanes([], 2)
    with pytest.raises(ValueError, match="bucket 1 < 2"):
        stack_lanes([(np.zeros(2),), (np.ones(2),)], 1)


def test_write_lane_is_immutable_page_in():
    leaves = [(np.full(3, float(k), np.float32), np.float32(k)) for k in range(4)]
    st = stack_lanes(leaves, 4)
    # page a new variant into lane 2; 0-d scalar leaves must survive the
    # round-trip exactly (ascontiguousarray's 0-d -> (1,) promotion is the
    # classic way this breaks)
    st2 = write_lane(st, 2, (np.full(3, 9.0, np.float32), np.float32(9.0)),
                     name="lanes.test")
    assert [s.shape for s in st2] == [(4, 3), (4,)]
    np.testing.assert_array_equal(np.asarray(st2[0])[2], [9.0, 9.0, 9.0])
    assert np.asarray(st2[1])[2] == 9.0
    # the OLD tuple is untouched: an in-flight dispatch holding it keeps
    # consistent values
    np.testing.assert_array_equal(np.asarray(st[0])[2], [2.0, 2.0, 2.0])
    assert np.asarray(st[1])[2] == 2.0
    # untouched lanes carry over
    for lane in (0, 1, 3):
        np.testing.assert_array_equal(
            np.asarray(st2[0])[lane], np.asarray(st[0])[lane]
        )


def test_write_lane_same_shape_is_zero_new_compiles():
    leaves = [(np.full(2, float(k), np.float32),) for k in range(4)]
    st = stack_lanes(leaves, 4)
    st = write_lane(st, 0, (np.zeros(2, np.float32),), name="lanes.gate")
    c0 = profiling.counters("precompile.")
    # every lane slot of a given buffer shape shares ONE executable: the
    # lane index is traced, so these three page-ins are all AOT hits
    for lane in (1, 2, 3):
        st = write_lane(
            st, lane, (np.full(2, 5.0 + lane, np.float32),), name="lanes.gate"
        )
    delta = profiling.counter_deltas(c0, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.fallback", 0) == 0, delta
    np.testing.assert_array_equal(
        np.asarray(st[0]), [[0.0, 0.0], [6.0, 6.0], [7.0, 7.0], [8.0, 8.0]]
    )


# -- the compile-count gate ---------------------------------------------------


@jax.jit
def _toy_lane_kernel(X, lanes_vec):
    # a representative lane kernel: per-row scale by its lane's value
    return X.sum(axis=1)[None, :] * lanes_vec[:, None]


def test_growing_k_compiles_once_per_pow2_boundary():
    """The PR 12 insight, gated: lane VALUES are traced runtime data — only
    the pow2 bucket SIZE keys the executable cache.  Growing K from 1..8
    crosses bucket boundaries at K=2, 3 and 5; every K inside a bucket is
    zero new compiles."""
    from spark_rapids_ml_tpu.ops.precompile import cached_kernel

    X = jnp.asarray(np.ones((4, 3), np.float32))
    expected_new = {1: 1, 2: 1, 3: 1, 4: 0, 5: 1, 6: 0, 7: 0, 8: 0}
    outs = {}
    for k in range(1, 9):
        bucket, (vec,) = pack_lane_subset(
            [(float(i + 1),) for i in range(k)], list(range(k))
        )
        c0 = profiling.counters("precompile.")
        out = cached_kernel(f"lanes.growK.b{bucket}", _toy_lane_kernel, X, vec)
        delta = profiling.counter_deltas(c0, "precompile.")
        assert delta.get("precompile.compile", 0) == expected_new[k], (k, delta)
        assert delta.get("precompile.fallback", 0) == 0, (k, delta)
        assert out.shape == (bucket, 4)
        outs[k] = out
    # ONE batched host fetch after the loop (graftlint R1), then check that
    # lane values really are traced: lane i computes with value i+1
    for k, got in jax.device_get(outs).items():
        np.testing.assert_allclose(got[:k, 0], np.arange(1, k + 1) * 3.0)
