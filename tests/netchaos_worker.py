# One rank of the srml-wire chaos matrix: a real OS process doing
# control-plane gather rounds over the plane SRML_CP selects (tcp for the
# wire matrix; the same worker drives the file plane for cross-plane
# comparisons) while SRML_FAULTS kills / partitions / corrupts one of the
# cohort mid-round — or the DRIVER SIGKILLs a whole process (rank 0 hosts
# the coordinator under SRML_CP=tcp, so killing rank 0 IS the
# "kill the coordinator" case).  Exit codes are the protocol:
#
#    0  clean run (all rounds completed, teardown clean)
#    7  survivor: raised RemoteRankError naming a dead/aborted/partitioned
#       peer
#    8  survivor of a lost CONTROL PLANE: CoordinatorLost (the coordinator
#       died or this host is partitioned from it) or StaleEpochError (this
#       process was fenced as a zombie)
#    9  victim of action=raise: published its abort marker and exited
#   17  victim of action=die (faults.DIE_EXIT_CODE): os._exit, no teardown
#
# Survivors print one machine-readable line:
#   SHIELD rank=<me> kind=<remote|plane> culprit=<rank|-1> dt=<s> \
#          span=<span> etype=<t>
# where dt measures entry-into-the-failing-gather -> typed error — the
# detection latency the ISSUE bounds at 2 heartbeat intervals.
#
# Invoked as: python netchaos_worker.py <rank> <nranks> <jobdir> [rounds]
# (rounds <= 0 means "loop until killed": the coordinator-kill case needs
# workers that outlive the driver's aim.)
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from spark_rapids_ml_tpu.parallel.context import RemoteRankError  # noqa: E402
from spark_rapids_ml_tpu.parallel.faults import FaultInjected  # noqa: E402
from spark_rapids_ml_tpu.parallel.netplane import (  # noqa: E402
    CoordinatorLost,
    StaleEpochError,
)
from spark_rapids_ml_tpu.parallel.runner import make_control_plane  # noqa: E402


def main() -> None:
    rank, nranks, root = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    rounds = int(sys.argv[4]) if len(sys.argv) > 4 else 4
    cp = make_control_plane(
        os.path.join(root, "cp"), rank, nranks, timeout=120
    )
    print(f"SHIELD rank={rank} joined", flush=True)
    t0 = time.monotonic()
    r = 0
    try:
        while rounds <= 0 or r < rounds:
            t0 = time.monotonic()
            got = cp.allGather(f"rank{rank}:round{r}")
            assert len(got) == nranks, got
            r += 1
            time.sleep(0.05)  # a window for the driver's SIGKILL to land
    except RemoteRankError as exc:
        dt = time.monotonic() - t0
        print(
            f"SHIELD rank={rank} kind=remote culprit={exc.rank} dt={dt:.3f} "
            f"span={exc.span} etype={exc.etype}",
            flush=True,
        )
        cp.close()
        sys.exit(7)
    except (CoordinatorLost, StaleEpochError) as exc:
        dt = time.monotonic() - t0
        print(
            f"SHIELD rank={rank} kind=plane culprit=-1 dt={dt:.3f} "
            f"span=None etype={type(exc).__name__}",
            flush=True,
        )
        cp.close()
        sys.exit(8)
    except FaultInjected as exc:
        # the orderly victim: publish the abort marker the way
        # TpuContext.__exit__ does on the exception path, then leave
        import json

        cp.abort(json.dumps({
            "rank": rank,
            "etype": type(exc).__name__,
            "message": str(exc),
            "span": "netchaos.gather",
        }))
        cp.close()
        sys.exit(9)
    print(f"SHIELD rank={rank} clean", flush=True)
    cp.close()
    sys.exit(0)


if __name__ == "__main__":
    main()
