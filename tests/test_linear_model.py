# LinearRegression correctness vs sklearn (OLS/Ridge/Lasso/ElasticNet) +
# fitMultiple single pass + transform-evaluate (strategy modeled on the
# reference's test_linear_model.py).
import numpy as np
import pytest

from spark_rapids_ml_tpu import LinearRegression, LinearRegressionModel
from spark_rapids_ml_tpu.core import load
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.evaluation import RegressionEvaluator


def _reg_data(n=400, d=10, seed=0, noise=0.1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    true_coef = rng.normal(size=d)
    y = X @ true_coef + 2.5 + noise * rng.normal(size=n)
    return X, y, true_coef


def _df(X, y, parts=4):
    return DataFrame.from_numpy(X, y=y, num_partitions=parts)


def test_default_params():
    lr = LinearRegression()
    assert lr.tpu_params["alpha"] == 0.0      # spark regParam default 0
    assert lr.tpu_params["l1_ratio"] == 0.0   # spark elasticNetParam default 0
    assert lr.tpu_params["normalize"] is True  # spark standardization default
    assert lr.tpu_params["solver"] == "eig"
    lr = LinearRegression(regParam=0.5, elasticNetParam=0.3)
    assert lr.tpu_params["alpha"] == 0.5
    assert lr.tpu_params["l1_ratio"] == 0.3


def test_unsupported_values():
    with pytest.raises(ValueError):
        LinearRegression(loss="huber")
    with pytest.raises(ValueError):
        LinearRegression(solver="l-bfgs")
    with pytest.raises(ValueError):
        LinearRegression(weightCol="w")


def test_ols_matches_sklearn():
    from sklearn.linear_model import LinearRegression as SkLR

    X, y, _ = _reg_data()
    model = LinearRegression(regParam=0.0, float32_inputs=False).fit(_df(X, y))
    sk = SkLR().fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_, atol=1e-5)
    assert abs(model.intercept - sk.intercept_) < 1e-5


def test_ols_no_intercept():
    from sklearn.linear_model import LinearRegression as SkLR

    X, y, _ = _reg_data()
    model = LinearRegression(fitIntercept=False, float32_inputs=False).fit(_df(X, y))
    sk = SkLR(fit_intercept=False).fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_, atol=1e-5)
    assert model.intercept == 0.0


def test_ridge_spark_alpha_scaling():
    # Spark-parity ridge: objective (1/2n)||y-Xb||^2 + (reg/2)||b||^2
    # == sklearn Ridge(alpha=reg*n). standardization off for direct compare.
    from sklearn.linear_model import Ridge

    X, y, _ = _reg_data()
    reg = 0.1
    model = LinearRegression(
        regParam=reg, elasticNetParam=0.0, standardization=False, float32_inputs=False
    ).fit(_df(X, y))
    sk = Ridge(alpha=reg * len(y)).fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_, atol=1e-4)
    assert abs(model.intercept - sk.intercept_) < 1e-4


def test_lasso_matches_sklearn():
    from sklearn.linear_model import Lasso

    X, y, _ = _reg_data(noise=0.5)
    reg = 0.1
    model = LinearRegression(
        regParam=reg, elasticNetParam=1.0, standardization=False,
        maxIter=2000, tol=1e-8, float32_inputs=False,
    ).fit(_df(X, y))
    sk = Lasso(alpha=reg, max_iter=10000, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_, atol=1e-3)
    assert abs(model.intercept - sk.intercept_) < 1e-3


def test_elasticnet_matches_sklearn():
    from sklearn.linear_model import ElasticNet

    X, y, _ = _reg_data(noise=0.5)
    reg, l1r = 0.2, 0.5
    model = LinearRegression(
        regParam=reg, elasticNetParam=l1r, standardization=False,
        maxIter=2000, tol=1e-8, float32_inputs=False,
    ).fit(_df(X, y))
    sk = ElasticNet(alpha=reg, l1_ratio=l1r, max_iter=10000, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_, atol=1e-3)
    assert abs(model.intercept - sk.intercept_) < 1e-3


def test_transform_and_predict():
    X, y, _ = _reg_data(n=200, d=5)
    df = _df(X, y)
    model = LinearRegression().fit(df)
    out = model.transform(df).toPandas()
    preds = out["prediction"].to_numpy()
    expect = X @ np.asarray(model.coefficients) + model.intercept
    np.testing.assert_allclose(preds, expect, rtol=1e-3, atol=1e-3)
    assert abs(model.predict(X[0]) - expect[0]) < 1e-2
    assert model.scale == 1.0


def test_fit_multiple_single_pass():
    X, y, _ = _reg_data()
    df = _df(X, y)
    est = LinearRegression(standardization=False, float32_inputs=False)
    pmaps = [
        {LinearRegression.regParam: 0.0},
        {LinearRegression.regParam: 0.1},
        {LinearRegression.regParam: 1.0},
    ]
    models = [m for _, m in est.fitMultiple(df, pmaps)]
    assert len(models) == 3
    # separate fits agree with the single-pass batch
    for pm, m in zip(pmaps, models):
        solo = est.copy(pm).fit(df)
        np.testing.assert_allclose(m.coefficients, solo.coefficients, atol=1e-6)
        assert m.getOrDefault("regParam") == pm[LinearRegression.regParam]
        assert m.tpu_params["alpha"] == pm[LinearRegression.regParam]


def test_combine_and_transform_evaluate():
    X, y, _ = _reg_data()
    df = _df(X, y)
    est = LinearRegression(standardization=False, float32_inputs=False)
    m0 = est.copy({LinearRegression.regParam: 0.0}).fit(df)
    m1 = est.copy({LinearRegression.regParam: 5.0}).fit(df)
    combined = LinearRegressionModel._combine([m0, m1])
    evaluator = RegressionEvaluator(metricName="rmse")
    scores = combined._transformEvaluate(df, evaluator)
    assert len(scores) == 2
    # unregularized fit must beat heavily-regularized on train rmse
    assert scores[0] < scores[1]
    # matches per-model evaluation via transform
    out0 = m0.transform(df)
    direct = evaluator.evaluate(out0)
    assert abs(scores[0] - direct) < 1e-6


def test_persistence(tmp_path):
    X, y, _ = _reg_data(n=100, d=4)
    df = _df(X, y)
    model = LinearRegression(regParam=0.1).fit(df)
    model.save(str(tmp_path / "m"))
    loaded = load(str(tmp_path / "m"))
    assert isinstance(loaded, LinearRegressionModel)
    np.testing.assert_allclose(loaded.coefficients, model.coefficients)
    assert abs(loaded.intercept - model.intercept) < 1e-12


def test_evaluator_metrics_match_sklearn():
    from sklearn.metrics import (
        mean_absolute_error,
        mean_squared_error,
        r2_score,
    )

    X, y, _ = _reg_data(n=300, d=6)
    df = _df(X, y)
    model = LinearRegression().fit(df)
    out = model.transform(df)
    preds = out.toPandas()["prediction"].to_numpy()
    for name, skfn in [
        ("mse", mean_squared_error),
        ("mae", mean_absolute_error),
        ("r2", r2_score),
    ]:
        got = RegressionEvaluator(metricName=name).evaluate(out)
        assert abs(got - skfn(y, preds)) < 1e-6, name
    rmse = RegressionEvaluator(metricName="rmse").evaluate(out)
    assert abs(rmse - np.sqrt(mean_squared_error(y, preds))) < 1e-6


def test_f64_fit_matches_sklearn_at_f64_only_tolerance():
    """float32_inputs=False on float64 data must genuinely compute in f64
    (VERDICT r1 item 5: device_put silently downcast to f32 before).  The
    1e-10 coefficient tolerance is unreachable in float32."""
    from sklearn.linear_model import LinearRegression as SkLinearRegression

    rng = np.random.default_rng(11)
    X = rng.standard_normal((400, 7))          # float64
    w = rng.standard_normal(7)
    y = X @ w + 0.01 * rng.standard_normal(400)
    assert X.dtype == np.float64
    df = DataFrame.from_numpy(X, y)
    est = LinearRegression(float32_inputs=False, standardization=False)
    model = est.fit(df)
    sk = SkLinearRegression().fit(X, y)
    np.testing.assert_allclose(np.asarray(model.coef_), sk.coef_, atol=1e-10)
    np.testing.assert_allclose(
        float(model.intercept_), float(sk.intercept_), atol=1e-10
    )
    # and the f32 path demonstrably CANNOT hit this tolerance
    m32 = LinearRegression(standardization=False).fit(df)
    assert np.abs(np.asarray(m32.coef_) - sk.coef_).max() > 1e-9
