# srml-elastic gates (docs/serving.md §srml-elastic): the SlicePool
# capacity ledger (disjoint group-aware leases, typed CapacityExhausted,
# explicit-only oversubscription), Router.scale_to / replace_replica
# actuation (retained-AOT warm, atomic admission, drain-then-release), and
# the Autoscaler policy loop (signal-driven hysteresis, decision journal,
# preemption repair).  Policy tests drive tick() manually for determinism;
# the preemption-storm chaos gate runs the real thread — "restored within
# bounded wall-clock" is the claim under test.
import threading
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.serving import (
    READY,
    UNHEALTHY,
    Autoscaler,
    AutoscalePolicy,
    CapacityExhausted,
    Router,
    ServingEntry,
    SlicePool,
)
from spark_rapids_ml_tpu.serving import scheduler


class _EchoModel:
    """Servable stub (test_router.py idiom): echoes row sums; optional
    delay holds a replica's worker busy to build backlog deterministically."""

    def __init__(self, n_cols=4, delay_s=0.0, out_col="echo"):
        self.n_cols = n_cols
        self.delay_s = delay_s
        self.out_col = out_col

    def _serving_entry(self, mesh=None):
        def call(batch):
            if self.delay_s:
                time.sleep(self.delay_s)
            return {self.out_col: batch.sum(axis=1)}

        return ServingEntry(
            name="serve.echo",
            n_cols=self.n_cols,
            dtype=np.dtype(np.float32),
            out_cols=[self.out_col],
            call=call,
            warm=lambda buckets: [],
        )


def _wait(pred, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _device_ids(lease):
    return {d.id for d in lease.devices}


# -- carve_device_slices: the group-aware fixed-granularity carve ------------


def test_carve_device_slices_group_aware(monkeypatch):
    """Simulated 2x4 topology, shuffled device list: every fixed-size
    slice lands inside ONE host group; a slice wider than a group falls
    back to the group-major contiguous carve (it must span DCN anyway);
    leftovers are stranded, never glued across the boundary."""
    import jax

    from spark_rapids_ml_tpu.parallel.mesh import carve_device_slices

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    monkeypatch.setenv("SRML_TOPO", "2:4")
    devs = list(jax.devices())
    shuf = [devs[j] for j in (3, 7, 0, 5, 2, 6, 1, 4)]
    two = carve_device_slices(shuf, 2)
    assert len(two) == 4
    assert all(len({d.id // 4 for d in s}) == 1 for s in two), two
    seen = [d.id for s in two for d in s]
    assert len(seen) == len(set(seen)) == 8  # disjoint, full coverage
    # 3-device slices in 4-device groups: one per group, 2 devices stranded
    three = carve_device_slices(shuf, 3)
    assert len(three) == 2
    assert all(len({d.id // 4 for d in s}) == 1 for s in three), three
    # wider than a group: group-major contiguous fallback, spans DCN
    assert len(carve_device_slices(shuf, 8)) == 1
    with pytest.raises(ValueError, match="slice_devices"):
        carve_device_slices(shuf, 0)


# -- SlicePool ledger --------------------------------------------------------


def test_slicepool_allocate_release_idempotent():
    pool = SlicePool(slice_devices=2)
    try:
        assert pool.capacity >= 2
        assert pool.free() == pool.capacity
        a = pool.allocate("m-r0")
        b = pool.allocate("m-r1")
        assert not a.shared and not b.shared
        assert _device_ids(a).isdisjoint(_device_ids(b))
        assert pool.free() == pool.capacity - 2
        assert pool.holders() == {"m-r0": 1, "m-r1": 1}
        pool.release(a)
        pool.release(a)  # idempotent: teardown paths may race
        assert pool.free() == pool.capacity - 1
        c = pool.allocate("m-r2")  # the freed slice is re-leasable
        assert _device_ids(c) == _device_ids(a)
        for lease in (b, c):
            lease.release()
        assert pool.free() == pool.capacity
    finally:
        pool.close()


def test_slicepool_capacity_exhausted_is_typed():
    """No free slice raises CapacityExhausted — a ValueError (deployment
    spec error) that is also retryable (capacity is dynamic), naming the
    allow_oversubscribe escape hatch and the current holders."""
    import jax

    pool = SlicePool(slice_devices=len(jax.devices()))
    try:
        assert pool.capacity == 1
        lease = pool.allocate("hog")
        with pytest.raises(CapacityExhausted, match="allow_oversubscribe"):
            pool.allocate("wants")
        assert issubclass(CapacityExhausted, ValueError)
        assert CapacityExhausted.retryable is True
        assert profiling.counter("slicepool.exhausted") >= 1
        pool.release(lease)
        pool.allocate("wants")  # a release frees real capacity
    finally:
        pool.close()


def test_slicepool_oversubscribe_only_by_policy():
    """Overflow leases exist only under the explicit flag, and degrade to
    SINGLE shared devices — single-device programs cannot deadlock the
    XLA:CPU cross-program rendezvous, they only contend."""
    import jax

    n = len(jax.devices())
    pool = SlicePool(slice_devices=n, allow_oversubscribe=True)
    try:
        first = pool.allocate("a")
        over = pool.allocate("b")  # pool policy admits the overflow
        assert over.shared and len(over.devices) == 1
        # per-call override beats pool policy in both directions
        with pytest.raises(CapacityExhausted):
            pool.allocate("c", oversubscribe=False)
        assert profiling.counter("slicepool.oversubscribed") >= 1
        pool.release(over)
        pool.release(first)
    finally:
        pool.close()


def test_slicepool_never_straddles_host_group(monkeypatch):
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    monkeypatch.setenv("SRML_TOPO", "2:4")
    devs = list(jax.devices())
    shuf = [devs[j] for j in (3, 7, 0, 5, 2, 6, 1, 4)]
    pool = SlicePool(slice_devices=2, devices=shuf)
    try:
        leases = [pool.allocate(f"m-r{i}") for i in range(pool.capacity)]
        for lease in leases:
            assert len({d.id // 4 for d in lease.devices}) == 1, lease
        for lease in leases:
            pool.release(lease)
    finally:
        pool.close()


def test_slicepool_concurrent_allocate_release():
    """The ledger under contention (CI re-runs this under
    SRML_SANITIZE=lockdep): hammering allocate/release from many threads
    never double-grants a slice and never leaks one."""
    pool = SlicePool(slice_devices=1)
    errors = []
    live_lock = threading.Lock()
    live = {}  # slice index -> owner, the double-grant detector

    def worker(tid):
        try:
            for _ in range(50):
                try:
                    lease = pool.allocate(f"w{tid}")
                except CapacityExhausted:
                    continue
                with live_lock:
                    if lease.index in live:
                        errors.append(
                            f"slice {lease.index} granted to w{tid} while "
                            f"held by {live[lease.index]}"
                        )
                    live[lease.index] = f"w{tid}"
                with live_lock:
                    live.pop(lease.index, None)
                pool.release(lease)
        except Exception as exc:  # noqa: BLE001 - surfaced via the errors list
            errors.append(repr(exc))

    threads = [
        threading.Thread(target=worker, args=(i,), name=f"pool-hammer-{i}")
        for i in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        assert not errors, errors
        assert pool.free() == pool.capacity  # nothing leaked
    finally:
        pool.close()


# -- router: pool-backed deployment ------------------------------------------


def test_router_shared_pool_keeps_models_disjoint():
    """The tentpole invariant: with a shared SlicePool, replicas of ALL
    served models sit on mutually disjoint device slices (the historical
    per-serve carve silently overlapped models)."""
    pool = SlicePool(slice_devices=2)
    with Router(pool=pool, max_batch=8, max_wait_ms=1) as router:
        router.serve("a", _EchoModel(), replicas=2)
        router.serve("b", _EchoModel(), replicas=2)
        held = []
        for name in ("a", "b"):
            for lease in router._sets[name].leases:
                held.append(_device_ids(lease))
        for i in range(len(held)):
            for j in range(i + 1, len(held)):
                assert held[i].isdisjoint(held[j]), (i, j, held)
        assert router.predict("a", np.ones(4, np.float32))["echo"][
            0
        ] == pytest.approx(4.0)
        assert router.predict("b", np.ones(4, np.float32))["echo"][
            0
        ] == pytest.approx(4.0)
    pool.close()


def test_router_serve_oversubscription_is_typed_not_silent():
    """More replicas than disjoint slices used to round-robin devices
    silently — the XLA:CPU cross_module rendezvous hazard.  Now it is the
    typed CapacityExhausted (a ValueError) unless explicitly allowed, in
    which case overflow replicas take single shared devices."""
    import jax

    n = jax.device_count()
    with Router(max_batch=8, max_wait_ms=1) as router:
        with pytest.raises(CapacityExhausted, match="allow_oversubscribe"):
            router.serve("big", _EchoModel(), replicas=n + 1)
        assert "big" not in router  # failed deploy leaves no reservation
        reps = router.serve(
            "big", _EchoModel(), replicas=n + 1, allow_oversubscribe=True
        )
        assert len(reps) == n + 1
        rs = router._sets["big"]
        assert sum(1 for lease in rs.leases if lease.shared) >= 1
        assert all(
            len(lease.devices) == 1 for lease in rs.leases if lease.shared
        )
        out = router.predict("big", np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)


# -- router: scale_to actuation ----------------------------------------------


def test_scale_to_grows_and_shrinks_with_lease_accounting():
    pool = SlicePool(slice_devices=1)
    with Router(pool=pool, max_batch=8, max_wait_ms=1) as router:
        router.serve("el", _EchoModel(), replicas=1)
        assert pool.free() == pool.capacity - 1
        reps = router.scale_to("el", 3)
        assert [r.name for r in reps] == ["el-r0", "el-r1", "el-r2"]
        assert pool.free() == pool.capacity - 3
        assert profiling.counter("router.el.scaled_up") >= 2
        out = router.predict("el", np.ones(4, np.float32))
        assert out["echo"][0] == pytest.approx(4.0)
        reps = router.scale_to("el", 1)
        assert [r.name for r in reps] == ["el-r0"]
        assert pool.free() == pool.capacity - 1  # drained slices returned
        assert profiling.counter("router.el.scaled_down") >= 2
        # scale_to is idempotent at the target; below 1 is a spec error
        assert len(router.scale_to("el", 1)) == 1
        with pytest.raises(ValueError, match="below 1"):
            router.scale_to("el", 0)
        # regrowth reuses the lowest free slots: names stay continuous
        reps = router.scale_to("el", 2)
        assert [r.name for r in reps] == ["el-r0", "el-r1"]
    pool.close()


def test_scale_up_is_warm_zero_new_compiles(model_zoo):
    """The scale-up compile gate on a REAL model: deploy at max (the
    compile bill is paid ONCE, at deploy), trim to 1, then grow back —
    the regrown replicas re-warm their slots from the retained AOT
    executable cache with ZERO new compiles, and predictions across every
    scale state are bitwise-identical to a fixed single-replica
    comparator."""
    model, X = model_zoo("kmeans")
    pool = SlicePool(slice_devices=1)
    with Router(pool=pool, max_batch=16, max_wait_ms=2) as router, Router(
        max_batch=16, max_wait_ms=2
    ) as fixed:
        fixed.serve("ckm", model, replicas=1)
        baseline = fixed.predict("ckm", X[:8])["prediction"]
        router.serve("ekm", model, replicas=3)  # deploy at max: bill paid
        assert np.array_equal(
            router.predict("ekm", X[:8])["prediction"], baseline
        )
        router.scale_to("ekm", 1)  # trim to the idle floor
        before = profiling.counters("precompile.")
        assert np.array_equal(
            router.predict("ekm", X[:8])["prediction"], baseline
        )
        router.scale_to("ekm", 3)  # burst capacity back, warm
        for r in router.replicas("ekm"):
            assert r.state() == READY
        futs = [router.submit("ekm", X[i : i + 4]) for i in range(8)]
        for f in futs:
            assert f.result(timeout=60)["prediction"].shape == (4,)
        assert np.array_equal(
            router.predict("ekm", X[:8])["prediction"], baseline
        )
        delta = profiling.counter_deltas(before, "precompile.")
        assert delta.get("precompile.compile", 0) == 0, delta
        assert delta.get("precompile.fallback", 0) == 0, delta
        for r in router.replicas("ekm"):
            r.drain()
            r.assert_steady_state()
    pool.close()


# -- the autoscaler policy loop ----------------------------------------------


def _tight_policy(**over):
    base = dict(
        min_replicas=1,
        max_replicas=3,
        window_s=0.3,
        down_window_s=0.6,
        up_fill=0.2,
        up_burn=0.5,
        down_fill=0.05,
        down_occupancy=0.2,
        up_cooldown_s=0.05,
        down_cooldown_s=0.2,
    )
    base.update(over)
    return AutoscalePolicy(**base)


def test_autoscaler_scales_up_on_load_and_down_on_idle():
    """The hysteresis gate: a paced load step drives the replica count up
    (fast, on fill) and back down (slow, on sustained idle), with the
    decision journal recording each transition's reason and the predictions
    staying identical to a fixed-replica comparator throughout."""
    pool = SlicePool(slice_devices=1)
    row = np.ones(4, np.float32)
    with Router(
        pool=pool, inflight_depth=1, max_batch=4, max_wait_ms=1,
        queue_depth=16,
    ) as router, Router(
        replicas=1, inflight_depth=1, max_batch=4, max_wait_ms=1
    ) as fixed:
        fixed.serve("echo", _EchoModel(delay_s=0.02))
        router.serve("echo", _EchoModel(delay_s=0.02), replicas=3)
        router.scale_to("echo", 1)  # trim: the autoscaler takes it from here
        autoscaler = Autoscaler(router, policy=_tight_policy())
        # -- load step: keep the single replica's queue full ----------------
        futs = []
        deadline = time.monotonic() + 10.0
        while (
            len(router.replicas("echo")) < 3 and time.monotonic() < deadline
        ):
            while sum(1 for f in futs if not f.done()) < 12:
                futs.append(router.submit("echo", row, timeout_ms=30000))
            autoscaler.tick()
            time.sleep(0.05)
        assert len(router.replicas("echo")) == 3, autoscaler.journal()
        assert profiling.counter("autoscale.echo.scale_up") >= 2
        ups = [e for e in autoscaler.journal() if e["decision"] == "scale_up"]
        assert ups and all(e["reason"] for e in ups)
        # every admitted request resolves, identical to the comparator
        expected = fixed.predict("echo", row)["echo"][0]
        for f in futs:
            assert f.result(timeout=60)["echo"][0] == expected
        # -- idle step: sustained quiet walks it back down ------------------
        deadline = time.monotonic() + 20.0
        while (
            len(router.replicas("echo")) > 1 and time.monotonic() < deadline
        ):
            autoscaler.tick()
            time.sleep(0.05)
        assert len(router.replicas("echo")) == 1, autoscaler.journal()
        assert profiling.counter("autoscale.echo.scale_down") >= 2
        downs = [
            e for e in autoscaler.journal() if e["decision"] == "scale_down"
        ]
        assert downs and all("idle" in e["reason"] for e in downs)
        assert profiling.counter("autoscale.echo.holds") >= 1
        assert np.asarray(
            router.predict("echo", row)["echo"]
        )[0] == expected
    pool.close()


def test_autoscaler_holds_on_cooldown_and_capacity(monkeypatch):
    """Pressured holds are journaled with their reasons: inside the
    up-cooldown, at max_replicas, and when the pool is out of slices
    (typed CapacityExhausted absorbed into a hold + counter)."""
    import jax

    pool = SlicePool(slice_devices=max(1, len(jax.devices()) // 2))
    with Router(pool=pool, max_batch=8, max_wait_ms=1) as router:
        router.serve("h", _EchoModel(), replicas=2)  # pool now exhausted
        autoscaler = Autoscaler(
            router, policy=_tight_policy(max_replicas=4, up_cooldown_s=0.0)
        )
        # the signal plane reads EXPORTED counters; a shed spike is the
        # fastest scale-up trigger and trivially injectable
        profiling.incr_counter("router.h.shed", 5)
        autoscaler.tick()  # watermark tick: deltas start at zero
        profiling.incr_counter("router.h.shed", 5)
        autoscaler.tick()
        assert profiling.counter("autoscale.h.capacity_exhausted") >= 1
        holds = [e for e in autoscaler.journal() if e["decision"] == "hold"]
        assert any("capacity exhausted" in e["reason"] for e in holds)
        assert len(router.replicas("h")) == 2  # held, not oversubscribed
    pool.close()


def test_preemption_storm_is_repaired_with_zero_client_errors(
    model_zoo, armed_faults, monkeypatch
):
    """The chaos acceptance gate, preemption as the common case: K=4
    replicas, restart budget ZERO (a killed worker is terminal — the
    preempted-slice model), kill ceil(K/2)=2 of them mid-burst.  Every
    admitted future resolves with a result (the router reroutes; zero
    client-visible errors), and the AUTOSCALER — not the in-place
    supervisor — restores the set: each terminal replica is re-sliced and
    re-warmed from the retained AOT cache (zero new compiles) under its
    old name, within bounded wall-clock."""
    model, X = model_zoo("kmeans")
    monkeypatch.setenv("SRML_SERVE_MAX_RESTARTS", "0")
    pool = SlicePool(slice_devices=1)
    with Router(pool=pool, max_batch=16, max_wait_ms=2) as router:
        reps = router.serve("skm", model, replicas=4)
        router.predict("skm", X[:3])  # healthy traffic, warm verified
        with Autoscaler(
            router,
            policy=_tight_policy(min_replicas=4, max_replicas=4),
            interval_s=0.05,
        ) as autoscaler:
            armed_faults(
                "serving.dispatch:tag=skm-r1:call=1:action=kill;"
                "serving.dispatch:tag=skm-r3:call=1:action=kill"
            )
            before = profiling.counters("precompile.")
            futs = [router.submit("skm", X[i : i + 2]) for i in range(16)]
            for f in futs:  # ZERO client-visible errors — the acceptance bar
                assert f.result(timeout=60)["prediction"].shape == (2,)
            dead = {reps[1], reps[3]}
            # bounded wall-clock restoration: 4 fresh READY replicas under
            # the original slot names, the dead objects replaced outright
            assert _wait(
                lambda: (
                    len(router.replicas("skm")) == 4
                    and not dead & set(router.replicas("skm"))
                    and all(
                        r.state() == READY for r in router.replicas("skm")
                    )
                ),
                timeout_s=30.0,
            ), [r.state() for r in router.replicas("skm")]
            assert sorted(r.name for r in router.replicas("skm")) == [
                "skm-r0", "skm-r1", "skm-r2", "skm-r3",
            ]
            assert profiling.counter("autoscale.skm.repairs") >= 2
            assert profiling.counter("router.skm.replicas_replaced") >= 2
            repairs = [
                e for e in autoscaler.journal() if e["decision"] == "repair"
            ]
            assert len(repairs) >= 2
            assert all("re-warmed" in e["reason"] for e in repairs)
            # post-repair traffic flows through the restored replicas
            out = router.predict("skm", X[:5])
            assert out["prediction"].shape == (5,)
            delta = profiling.counter_deltas(before, "precompile.")
            assert delta.get("precompile.compile", 0) == 0, delta
            assert delta.get("precompile.fallback", 0) == 0, delta
        assert pool.free() == pool.capacity - 4  # ledger intact after repair
    pool.close()


def test_autoscale_gauges_and_prometheus_families():
    """The satellite surface: router.<n>.fill_fraction / occupancy ride
    health() and the srml_router family; slicepool gauges ride
    srml_elastic."""
    pool = SlicePool(slice_devices=1)
    with Router(pool=pool, max_batch=8, max_wait_ms=1) as router:
        router.serve("g", _EchoModel(), replicas=2)
        m = router.health()["models"]["g"]
        assert 0.0 <= m["fill_fraction"] <= 1.0
        assert m["occupancy"] >= 0.0
        gauges = profiling.export_metrics()["gauges"]
        assert "router.g.fill_fraction" in gauges
        assert "router.g.occupancy" in gauges
        assert "slicepool.free" in gauges
        text = profiling.render_prometheus()
        assert 'srml_router{name="router.g.fill_fraction"}' in text
        assert 'srml_router{name="router.g.occupancy"}' in text
        assert 'srml_elastic{name="slicepool.free"}' in text
    pool.close()


def test_aggregate_occupancy_policy_unit():
    """Pure-function unit (scheduler idiom): occupancy counts in-flight
    work that fill cannot see, and an EMPTY set reads idle (0.0), unlike
    fill's defensive 1.0."""

    class _Stub:
        def __init__(self, depth, queued, outstanding):
            self._d, self._q, self._o = depth, queued, outstanding

        def queue_depth(self):
            return self._d

        def queued_rows(self):
            return self._q

        def outstanding(self):
            return self._o

    busy = _Stub(depth=8, queued=0, outstanding=6)
    assert scheduler.aggregate_fill([busy]) == 0.0  # fill is blind here
    assert scheduler.aggregate_occupancy([busy]) == pytest.approx(0.75)
    assert scheduler.aggregate_occupancy([]) == 0.0
    assert scheduler.aggregate_occupancy(
        [_Stub(8, 0, 6), _Stub(8, 0, 0)]
    ) == pytest.approx(0.375)
