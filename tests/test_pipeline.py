# Pipeline/PipelineModel composition + persistence (the reference composes
# via pyspark.ml.Pipeline — SURVEY.md L1; this framework ships its own
# equivalent surface).
import numpy as np

from spark_rapids_ml_tpu import (
    KMeans,
    LogisticRegression,
    PCA,
    Pipeline,
    PipelineModel,
)
from spark_rapids_ml_tpu.core import load
from spark_rapids_ml_tpu.dataframe import DataFrame


def _cls_df(n=200, d=10, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, size=n).astype(np.float64)
    X = rng.normal(size=(n, d)) + 3.0 * y[:, None]
    return X, y, DataFrame.from_numpy(X, y=y, num_partitions=3)


def test_pipeline_fit_transform():
    X, y, df = _cls_df()
    pca = PCA(k=4).setInputCol("features").setOutputCol("pca_features")
    lr = LogisticRegression(maxIter=100).setFeaturesCol("pca_features").setLabelCol("label")
    pm = Pipeline([pca, lr]).fit(df)
    assert isinstance(pm, PipelineModel)
    assert len(pm.stages) == 2
    out = pm.transform(df).toPandas()
    assert "pca_features" in out.columns and "prediction" in out.columns
    acc = (out["prediction"].to_numpy() == y).mean()
    assert acc > 0.9, acc


def test_pipeline_single_estimator_and_getstages():
    _, _, df = _cls_df(n=80)
    km = KMeans(k=2, maxIter=20, seed=1)
    p = Pipeline().setStages([km])
    assert len(p.getStages()) == 1
    pm = p.fit(df)
    out = pm.transform(df).toPandas()
    assert "prediction" in out.columns


def test_pipeline_persistence(tmp_path):
    X, y, df = _cls_df(n=120)
    pca = PCA(k=3).setInputCol("features").setOutputCol("pca_features")
    lr = LogisticRegression(maxIter=50).setFeaturesCol("pca_features").setLabelCol("label")
    pipe = Pipeline([pca, lr])

    # unfitted pipeline round trip (generic load resolves the class)
    pipe.save(str(tmp_path / "pipe"))
    p2 = load(str(tmp_path / "pipe"))
    assert isinstance(p2, Pipeline)
    assert [type(s).__name__ for s in p2.getStages()] == ["PCA", "LogisticRegression"]

    # fitted pipeline round trip preserves transform output
    pm = pipe.fit(df)
    pm.save(str(tmp_path / "pm"))
    pm2 = load(str(tmp_path / "pm"))
    assert isinstance(pm2, PipelineModel)
    o1 = pm.transform(df).toPandas()
    o2 = pm2.transform(df).toPandas()
    np.testing.assert_array_equal(
        o1["prediction"].to_numpy(), o2["prediction"].to_numpy()
    )


def test_pipeline_ambiguous_stage_fails_loudly_and_role_disambiguates():
    # a third-party stage exposing BOTH fit and transform (sklearn style) is
    # ambiguous: fitting it may clobber a pre-trained object, passing it
    # through may skip training.  Either silent choice is wrong for someone,
    # so the pipeline must raise — and honor an explicit srml_stage_role.
    import pytest

    _, _, df = _cls_df(n=40)

    class SklearnStyle:
        def __init__(self):
            self.fitted = False
            self.fit_calls = 0

        def fit(self, dataset):
            self.fitted = True
            self.fit_calls += 1
            return self

        def transform(self, dataset):
            assert self.fitted, "transform before fit"
            return dataset

    with pytest.raises(TypeError, match="Ambiguous pipeline stage"):
        Pipeline([SklearnStyle(), KMeans(k=2, maxIter=5, seed=1)]).fit(df)

    bad = SklearnStyle()
    bad.srml_stage_role = "Transformer"  # wrong case: must be named, not hidden
    with pytest.raises(TypeError, match="unrecognized srml_stage_role"):
        Pipeline([bad, KMeans(k=2, maxIter=5, seed=1)]).fit(df)

    # declared estimator: gets fit, then feeds the next stage
    est_stage = SklearnStyle()
    est_stage.srml_stage_role = "estimator"
    pm = Pipeline([est_stage, KMeans(k=2, maxIter=5, seed=1)]).fit(df)
    assert est_stage.fitted
    assert "prediction" in pm.transform(df).toPandas().columns

    # declared transformer: applied as-is, never refit
    tr_stage = SklearnStyle()
    tr_stage.fitted = True  # pre-trained elsewhere
    tr_stage.srml_stage_role = "transformer"
    Pipeline([tr_stage, KMeans(k=2, maxIter=5, seed=1)]).fit(df)
    assert tr_stage.fit_calls == 0
