#
# Distributed-runtime context tests — the analog of the reference's comms
# test (python/tests/test_ucx.py:35-99, which spins a barrier stage, builds a
# real CumlContext, and asserts the endpoint mesh came up).  Here the data
# plane is jax.distributed + mesh collectives: we check the coordinator
# handshake protocol over a fake control plane (the part the reference tests
# via BarrierTaskContext.allGather) and run a real psum/all_gather over the
# 8-device CPU mesh (the part test_ucx verifies by constructing comms).
#

import json
import os
import sys
from typing import List

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from spark_rapids_ml_tpu.parallel.context import (  # noqa: E402
    LocalControlPlane,
    TpuContext,
)
from spark_rapids_ml_tpu.parallel.netplane import (  # noqa: E402
    _free_port,
    _local_ip,
)
from spark_rapids_ml_tpu.parallel.mesh import (  # noqa: E402
    DATA_AXIS,
    data_sharding,
    get_mesh,
    shard_rows,
)
from spark_rapids_ml_tpu.parallel.partition import PartitionDescriptor  # noqa: E402


class FakeBarrierControlPlane:
    """Records every rank's allGather message like BarrierTaskContext would,
    releasing the gathered list once all ranks have posted."""

    def __init__(self, nranks: int):
        self.nranks = nranks
        self.messages: List[str] = []
        self.barriers = 0

    def allGather(self, message: str) -> List[str]:
        self.messages.append(message)
        assert len(self.messages) <= self.nranks
        return list(self.messages)

    def barrier(self) -> None:
        self.barriers += 1


class TestTpuContext:
    def test_single_rank_is_noop(self):
        with TpuContext(rank=0, nranks=1) as ctx:
            assert ctx.rank == 0 and ctx.nranks == 1
            assert not ctx._initialized_distributed  # no jax.distributed in-process

    def test_multi_rank_handshake(self, monkeypatch):
        calls = []

        def fake_initialize(coordinator_address, num_processes, process_id):
            calls.append((coordinator_address, num_processes, process_id))

        def fake_shutdown():
            calls.append("shutdown")

        monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
        monkeypatch.setattr(jax.distributed, "shutdown", fake_shutdown)
        # the real __enter__ would arm gloo collectives — with the FAKE
        # initialize there is never a distributed client, and a gloo flag
        # armed clientless breaks every later backend init in this process
        # (the standalone-run landmine memory/jax-0437 documents)
        from spark_rapids_ml_tpu import compat

        monkeypatch.setattr(compat, "ensure_cpu_collectives", lambda: False)

        # rank 0 first (it mints the coordinator address, like the NCCL uid
        # in cuml_context.py:75-103), then rank 1 sees it via the gather
        cp = FakeBarrierControlPlane(nranks=2)
        with TpuContext(rank=0, nranks=2, control_plane=cp):
            pass
        addr0 = json.loads(cp.messages[0])["addr"]
        assert addr0 and ":" in addr0
        with TpuContext(rank=1, nranks=2, control_plane=cp):
            pass
        assert calls[0] == (addr0, 2, 0)
        assert calls[1] == "shutdown"
        assert calls[2] == (addr0, 2, 1)

    def test_rank0_address_missing_raises(self, monkeypatch):
        monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: None)
        from spark_rapids_ml_tpu import compat

        monkeypatch.setattr(compat, "ensure_cpu_collectives", lambda: False)

        class EmptyCp:
            def allGather(self, message):
                return [json.dumps({"rank": 7, "addr": ""})]

            def barrier(self):
                pass

        with pytest.raises(AssertionError):
            TpuContext(rank=1, nranks=2, control_plane=EmptyCp()).__enter__()

    def test_local_ip_and_port_helpers(self):
        ip = _local_ip()
        assert ip.count(".") == 3
        port = _free_port()
        assert 0 < port < 65536


class TestMeshCollectives:
    def test_mesh_spans_devices(self):
        mesh = get_mesh()
        assert mesh.devices.size == len(jax.devices())
        assert DATA_AXIS in mesh.shape

    def test_psum_over_mesh_matches_numpy(self):
        from spark_rapids_ml_tpu.compat import shard_map

        mesh = get_mesh()
        X_host = np.arange(64, dtype=np.float32).reshape(16, 4)
        Xs, _ = shard_rows(X_host, mesh)

        def local_sum(x):
            return jax.lax.psum(x.sum(axis=0), DATA_AXIS)

        total = shard_map(
            local_sum, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(),
            check_vma=False,
        )(Xs)
        np.testing.assert_allclose(np.asarray(total), X_host.sum(axis=0))

    def test_all_gather_roundtrip(self):
        from spark_rapids_ml_tpu.compat import shard_map

        mesh = get_mesh()
        n_dev = mesh.devices.size
        X_host = np.arange(n_dev * 3, dtype=np.float32).reshape(n_dev, 3)
        Xs = jax.device_put(X_host, data_sharding(mesh))

        def gather(x):
            return jax.lax.all_gather(x, DATA_AXIS).reshape(-1, x.shape[-1])

        out = shard_map(
            gather, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(),
            check_vma=False,
        )(Xs)
        np.testing.assert_array_equal(np.asarray(out), X_host)


class TestPartitionDescriptor:
    def test_build(self):
        pd_ = PartitionDescriptor.build([5, 0, 7], 3)
        assert pd_.m == 12 and pd_.n == 3
        assert pd_.parts_rank_size == [(0, 5), (1, 0), (2, 7)]
