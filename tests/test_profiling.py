# srml-scope (SURVEY.md §5: NVTX-range analog via
# jax.profiler.TraceAnnotation + coarse phase logging, reference
# RapidsRowMatrix.scala:62,70 and core.py:583,617): flat phase timers,
# hierarchical spans + Chrome-trace export, mergeable telemetry snapshots,
# and the export surface.
import json
import os
import threading

import numpy as np
import pytest

from spark_rapids_ml_tpu import profiling


def test_phase_registry_accumulates():
    profiling.reset_phase_times()
    with profiling.phase("unit.a"):
        pass
    with profiling.phase("unit.a"):
        pass
    with profiling.phase("unit.b"):
        pass
    times = profiling.phase_times()
    assert set(times) == {"unit.a", "unit.b"}
    assert times["unit.a"] >= 0.0


def test_with_benchmark_returns_result_and_elapsed():
    result, elapsed = profiling.with_benchmark("unit", lambda: 42)
    assert result == 42
    assert elapsed >= 0.0


def test_fit_records_phase_times():
    from spark_rapids_ml_tpu import KMeans
    from spark_rapids_ml_tpu.dataframe import DataFrame

    rng = np.random.default_rng(0)
    X = rng.standard_normal((64, 8)).astype(np.float32)
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=2)
    est = KMeans(k=3, maxIter=2).setFeaturesCol("features")
    est.fit(df)
    times = est._last_fit_phase_times
    assert "srml.ingest" in times and "srml.fit" in times
    assert times["srml.fit"] > 0.0


def test_forest_fit_records_phase_set():
    """The forest engine's phase timers mirror the knn.*/umap.* sets:
    forest.bin (edges + binning), forest.hist (level-block dispatches),
    forest.route (per-block early-stop flag syncs — where each block's
    routing state resolves), forest.split (the single forest fetch)."""
    from spark_rapids_ml_tpu import RandomForestRegressor
    from spark_rapids_ml_tpu.dataframe import DataFrame

    rng = np.random.default_rng(3)
    X = rng.standard_normal((256, 6))
    y = X @ np.ones(6) + 0.1 * rng.standard_normal(256)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    est = RandomForestRegressor(numTrees=3, maxDepth=3, maxBins=8, seed=1)
    est.fit(df)
    times = est._last_fit_phase_times
    for name in ("forest.bin", "forest.hist", "forest.route", "forest.split"):
        assert name in times and times[name] >= 0.0, (name, times)
    # phase_times prefix filtering (the benchmark reporting idiom)
    profiling.reset_phase_times()
    with profiling.phase("forest.bin"):
        pass
    with profiling.phase("other.x"):
        pass
    assert set(profiling.phase_times("forest.")) == {"forest.bin"}


def test_maybe_trace_writes_profile(tmp_path, monkeypatch):
    # opt-in whole-fit xprof capture via SRML_PROFILE (NCCL_DEBUG analog)
    monkeypatch.setenv(profiling.PROFILE_ENV, str(tmp_path))
    with profiling.maybe_trace("unittrace"):
        np.zeros(4).sum()
    target = tmp_path / "unittrace"
    assert target.is_dir()
    # jax writes a plugins/profile subtree with at least one trace artifact
    contents = [str(p) for p in target.rglob("*") if p.is_file()]
    assert contents, "expected xprof trace files"


def test_maybe_trace_noop_without_env(monkeypatch):
    monkeypatch.delenv(profiling.PROFILE_ENV, raising=False)
    with profiling.maybe_trace("x"):
        pass


def test_counters_are_process_wide_and_resettable():
    import threading

    profiling.reset_counters("test.ctr")
    profiling.incr_counter("test.ctr.a")
    profiling.incr_counter("test.ctr.a", 2)

    # increments from another thread land in the same registry (the
    # precompile worker-pool contract)
    t = threading.Thread(target=lambda: profiling.incr_counter("test.ctr.b"))
    t.start()
    t.join()
    assert profiling.counter("test.ctr.a") == 3
    assert profiling.counters("test.ctr") == {
        "test.ctr.a": 3,
        "test.ctr.b": 1,
    }
    profiling.reset_counters("test.ctr")
    assert profiling.counters("test.ctr") == {}


def test_percentiles_over_recorded_durations():
    profiling.reset_durations("t.lat")
    for ms in range(1, 101):  # 1..100 ms
        profiling.record_duration("t.lat.a", ms / 1000.0)
    stats = profiling.percentiles("t.lat.a")
    assert stats["count"] == 100
    assert abs(stats["p50"] - 0.0505) < 1e-9  # numpy linear interpolation
    assert stats["p95"] <= stats["p99"] <= stats["max"] == 0.1
    assert abs(stats["mean"] - 0.0505) < 1e-9
    profiling.reset_durations("t.lat")
    assert profiling.percentiles("t.lat") == {}


def test_percentiles_merge_prefix_and_cross_thread():
    import threading

    profiling.reset_durations("t.merge")
    profiling.record_duration("t.merge.a", 1.0)
    # worker-thread samples land in the same process-wide registry (the
    # serving dispatch-thread contract)
    t = threading.Thread(target=lambda: profiling.record_duration("t.merge.b", 3.0))
    t.start()
    t.join()
    merged = profiling.percentiles("t.merge")
    assert merged["count"] == 2 and merged["p50"] == 2.0
    only_a = profiling.percentiles("t.merge.a")
    assert only_a["count"] == 1 and only_a["p50"] == 1.0
    assert profiling.durations("t.merge") == {
        "t.merge.a": [1.0],
        "t.merge.b": [3.0],
    }
    profiling.reset_durations("t.merge")


def test_metric_ttl_evicts_stale_series(monkeypatch):
    """SRML_METRIC_TTL_S: a series untouched for the TTL is evicted by the
    amortized sweep inside record_duration, so a long-lived serving process
    cycling through model names cannot leak series (default: off)."""
    monkeypatch.setenv(profiling.METRIC_TTL_ENV, "0.05")
    monkeypatch.setattr(profiling, "_TTL_SWEEP_EVERY", 2)
    profiling.reset_durations("t.ttl")
    profiling.record_duration("t.ttl.stale", 1.0)
    import time as _time

    _time.sleep(0.12)  # let t.ttl.stale age past the TTL
    for _ in range(4):  # enough records to cross the sweep cadence
        profiling.record_duration("t.ttl.live", 2.0)
    series = profiling.durations("t.ttl")
    assert "t.ttl.live" in series and "t.ttl.stale" not in series
    # TTL off (default): nothing is ever evicted
    monkeypatch.setenv(profiling.METRIC_TTL_ENV, "")
    profiling.record_duration("t.ttl.stale", 1.0)
    _time.sleep(0.06)
    for _ in range(4):
        profiling.record_duration("t.ttl.live", 2.0)
    assert "t.ttl.stale" in profiling.durations("t.ttl")
    profiling.reset_durations("t.ttl")


def test_series_stats_reports_registry_footprint():
    profiling.reset_durations("t.ss")
    for _ in range(3):
        profiling.record_duration("t.ss.a", 0.01)
    stats = profiling.series_stats()
    assert stats["series_count"] >= 1 and stats["ring_samples"] >= 3
    assert stats["est_bytes"] >= stats["ring_samples"] * 8
    a = stats["series"]["t.ss.a"]
    assert a["ring_samples"] == 3 and a["lifetime_count"] == 3
    profiling.reset_durations("t.ss")


def test_duration_cap_is_a_ring_buffer(monkeypatch):
    monkeypatch.setattr(profiling, "_DURATION_CAP", 4)
    profiling.reset_durations("t.ring")
    for i in range(6):
        profiling.record_duration("t.ring", float(i))
    series = profiling.durations("t.ring")["t.ring"]
    assert len(series) == 4  # capped
    assert sorted(series) == [2.0, 3.0, 4.0, 5.0]  # oldest overwritten
    profiling.reset_durations("t.ring")


# -- hierarchical spans / trace export ---------------------------------------


def test_span_nesting_and_thread_attribution():
    """Span records carry parent ids (per-thread stack) and the recording
    thread's ident/name — the hierarchy the Chrome-trace export renders."""
    profiling.reset_phase_times()
    with profiling.collect_spans():
        with profiling.span("t.outer"):
            with profiling.span("t.inner", block=7) as sp:
                sp.set(bytes=123)
        def worker():
            with profiling.span("t.worker"):
                pass
        th = threading.Thread(target=worker, name="unit-worker")
        th.start()
        th.join()
        recs = {r[0]: r for r in profiling.span_records()}
    assert set(recs) == {"t.outer", "t.inner", "t.worker"}
    outer, inner, worker_r = recs["t.outer"], recs["t.inner"], recs["t.worker"]
    # parent: inner's parent_id is outer's span_id; outer and worker are roots
    assert inner[6] == outer[5]
    assert outer[6] == 0 and worker_r[6] == 0
    # timestamps nest: outer contains inner
    assert outer[1] <= inner[1] <= inner[2] <= outer[2]
    # thread attribution: the worker span carries ITS thread, not ours
    assert worker_r[3] != outer[3]
    assert worker_r[4] == "unit-worker"
    # attached counters (attrs) survive, including mid-span set()
    assert inner[7] == {"block": 7, "bytes": 123}
    # the flat registry still accumulated (phase() compatibility)
    assert "t.inner" in profiling.phase_times()
    # buffer cleared once the last collection scope exits
    assert profiling.span_records() == []


def test_span_disabled_path_has_zero_overhead(monkeypatch):
    """Spans off => no span records, no per-thread stack, no counters, and
    the null handle (no attrs dict allocated) — the hard zero-cost rule."""
    monkeypatch.delenv(profiling.TRACE_ENV, raising=False)
    counters_before = profiling.counters()
    seen = {}

    def worker():  # a FRESH thread proves no thread-local stack appears
        with profiling.span("t.off", bytes=1) as sp:
            sp.set(rows=2)  # must be a silent no-op
        seen["handle_attrs"] = sp.attrs
        seen["has_stack"] = hasattr(profiling._tls, "span_stack")

    th = threading.Thread(target=worker)
    th.start()
    th.join()
    assert seen["handle_attrs"] is None  # null handle: nothing allocated
    assert seen["has_stack"] is False
    assert profiling.span_records() == []
    assert profiling.counters() == counters_before
    with profiling.trace_session("t-noop") as path:  # env unset -> no-op
        assert path is None


def test_trace_session_writes_valid_chrome_trace(tmp_path, monkeypatch):
    monkeypatch.setenv(profiling.TRACE_ENV, str(tmp_path))
    with profiling.trace_session("unit sess") as path:
        assert path is not None and str(tmp_path) in path
        with profiling.span("t.a", rows=4):
            with profiling.span("t.b"):
                pass
    with open(path) as f:
        doc = json.load(f)
    assert isinstance(doc["traceEvents"], list)
    complete = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in complete} == {"t.a", "t.b"}
    for e in complete:
        # the Chrome trace-event contract Perfetto loads: microsecond
        # ts/dur, pid/tid, name, args
        assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid", "args"}
        assert e["ts"] >= 0 and e["dur"] >= 0
    meta = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
    assert any(m["name"] == "thread_name" for m in meta)
    by = {e["name"]: e for e in complete}
    assert by["t.b"]["args"]["parent_id"] == by["t.a"]["args"]["span_id"]
    assert by["t.a"]["args"]["rows"] == 4
    # session tag is sanitized into the filename
    assert os.path.basename(path).startswith("unit-sess-")


# -- telemetry snapshots ------------------------------------------------------


def _snap(**kw):
    return profiling.TelemetrySnapshot(**kw)


def test_telemetry_merge_is_commutative_and_associative():
    a = _snap(
        phases={"f.x": {"count": 1, "total_s": 2.0}},
        counters={"c.a": 3},
        durations={"d.l": {"count": 2, "sum_s": 1.0, "min_s": 0.25, "max_s": 0.75}},
        meta={"ranks": [0]},
    )
    b = _snap(
        phases={"f.x": {"count": 2, "total_s": 1.5}, "f.y": {"count": 1, "total_s": 0.5}},
        counters={"c.a": 1, "c.b": 7},
        durations={"d.l": {"count": 1, "sum_s": 3.0, "min_s": 3.0, "max_s": 3.0}},
        meta={"ranks": [1]},
    )
    c = _snap(counters={"c.b": 2}, meta={"ranks": [2]})
    assert a.merge(b) == b.merge(a)
    assert a.merge(b).merge(c) == a.merge(b.merge(c))
    m = a.merge(b)
    assert m.phases["f.x"] == {"count": 3, "total_s": 3.5}
    assert m.counters == {"c.a": 4, "c.b": 7}
    assert m.durations["d.l"] == {
        "count": 3, "sum_s": 4.0, "min_s": 0.25, "max_s": 3.0,
    }
    assert m.meta["ranks"] == [0, 1]
    # wire round-trip (the Spark result path ships snapshots as JSON)
    rt = profiling.TelemetrySnapshot.from_dict(
        json.loads(json.dumps(m.to_dict()))
    )
    assert rt == m
    assert m.phase_seconds("f.") == {"f.x": 3.5, "f.y": 0.5}


def test_telemetry_capture_deltas_counters():
    profiling.reset_counters("t.cap")
    profiling.reset_phase_times()
    before = profiling.counters()
    with profiling.phase("t.cap.phase"):
        profiling.incr_counter("t.cap.n", 5)
    snap = profiling.TelemetrySnapshot.capture(before, rank=3)
    assert snap.counters.get("t.cap.n") == 5
    # counters that did not move during the window are absent (delta form)
    assert all(k.startswith("t.cap") or v != 0 for k, v in snap.counters.items())
    assert snap.phases["t.cap.phase"]["count"] == 1
    assert snap.meta["ranks"] == [3]
    profiling.reset_counters("t.cap")


def test_local_fit_attaches_telemetry():
    from spark_rapids_ml_tpu import KMeans
    from spark_rapids_ml_tpu.dataframe import DataFrame

    rng = np.random.default_rng(1)
    X = rng.standard_normal((96, 6)).astype(np.float32)
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=2)
    model = KMeans(k=2, maxIter=2).setFeaturesCol("features").fit(df)
    t = model.fit_telemetry()
    assert t is not None
    assert t.phases["srml.fit"]["count"] == 1
    assert t.phases["srml.fit"]["total_s"] > 0.0
    assert t.meta["ranks"] == [0]
    # the telemetry key never leaks into the model attribute dict
    from spark_rapids_ml_tpu.core import TELEMETRY_ATTR

    assert TELEMETRY_ATTR not in model._get_model_attributes()


# -- export surface -----------------------------------------------------------


def test_export_metrics_roundtrips_json():
    profiling.reset_durations("t.em")
    profiling.reset_counters("t.em")
    profiling.incr_counter("t.em.c", 2)
    for v in (0.01, 0.02, 0.03):
        profiling.record_duration("t.em.lat", v)
    m = profiling.export_metrics("t.em")
    assert json.loads(json.dumps(m)) == m
    assert m["schema"] == "srml-scope/v1"
    assert m["counters"]["t.em.c"] == 2
    assert m["durations"]["t.em.lat"]["count"] == 3
    profiling.reset_durations("t.em")
    profiling.reset_counters("t.em")


def test_render_prometheus_exposition():
    m = {
        "counters": {"pre.compile": 4},
        "phases": {"srml.fit": {"count": 1, "total_s": 2.5}},
        "durations": {
            "serve.m.latency": {
                "count": 10, "mean": 0.02, "p50": 0.01, "p95": 0.05,
                "p99": 0.09, "max": 0.1,
            }
        },
    }
    txt = profiling.render_prometheus(m)
    assert 'srml_counter{name="pre.compile"} 4' in txt
    assert 'srml_phase_seconds_total{name="srml.fit"} 2.5' in txt
    assert 'srml_duration_seconds{name="serve.m.latency",quantile="0.5"} 0.01' in txt
    assert 'srml_duration_seconds_count{name="serve.m.latency"} 10' in txt
    # every non-comment line is name{labels} value — the exposition shape
    for line in txt.strip().splitlines():
        if not line.startswith("#"):
            assert " " in line and line.startswith("srml_"), line


def test_now_is_monotonic():
    a = profiling.now()
    b = profiling.now()
    assert b >= a


def test_event_log_order_and_reset():
    profiling.reset_events()
    profiling.record_event("t.dispatch", block=0)
    profiling.record_event("t.dispatch", block=1)
    profiling.record_event("t.collect", block=0)
    ev = profiling.events("t.")
    assert ev == [
        ("t.dispatch", {"block": 0}),
        ("t.dispatch", {"block": 1}),
        ("t.collect", {"block": 0}),
    ]
    assert profiling.events("t.collect") == [("t.collect", {"block": 0})]
    profiling.reset_events()
    assert profiling.events() == []
