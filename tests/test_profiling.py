# Tracing/profiling hooks (SURVEY.md §5: NVTX-range analog via
# jax.profiler.TraceAnnotation + coarse phase logging, reference
# RapidsRowMatrix.scala:62,70 and core.py:583,617).
import os

import numpy as np
import pytest

from spark_rapids_ml_tpu import profiling


def test_phase_registry_accumulates():
    profiling.reset_phase_times()
    with profiling.phase("unit.a"):
        pass
    with profiling.phase("unit.a"):
        pass
    with profiling.phase("unit.b"):
        pass
    times = profiling.phase_times()
    assert set(times) == {"unit.a", "unit.b"}
    assert times["unit.a"] >= 0.0


def test_with_benchmark_returns_result_and_elapsed():
    result, elapsed = profiling.with_benchmark("unit", lambda: 42)
    assert result == 42
    assert elapsed >= 0.0


def test_fit_records_phase_times():
    from spark_rapids_ml_tpu import KMeans
    from spark_rapids_ml_tpu.dataframe import DataFrame

    rng = np.random.default_rng(0)
    X = rng.standard_normal((64, 8)).astype(np.float32)
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=2)
    est = KMeans(k=3, maxIter=2).setFeaturesCol("features")
    est.fit(df)
    times = est._last_fit_phase_times
    assert "srml.ingest" in times and "srml.fit" in times
    assert times["srml.fit"] > 0.0


def test_forest_fit_records_phase_set():
    """The forest engine's phase timers mirror the knn.*/umap.* sets:
    forest.bin (edges + binning), forest.hist (level-block dispatches),
    forest.route (per-block early-stop flag syncs — where each block's
    routing state resolves), forest.split (the single forest fetch)."""
    from spark_rapids_ml_tpu import RandomForestRegressor
    from spark_rapids_ml_tpu.dataframe import DataFrame

    rng = np.random.default_rng(3)
    X = rng.standard_normal((256, 6))
    y = X @ np.ones(6) + 0.1 * rng.standard_normal(256)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    est = RandomForestRegressor(numTrees=3, maxDepth=3, maxBins=8, seed=1)
    est.fit(df)
    times = est._last_fit_phase_times
    for name in ("forest.bin", "forest.hist", "forest.route", "forest.split"):
        assert name in times and times[name] >= 0.0, (name, times)
    # phase_times prefix filtering (the benchmark reporting idiom)
    profiling.reset_phase_times()
    with profiling.phase("forest.bin"):
        pass
    with profiling.phase("other.x"):
        pass
    assert set(profiling.phase_times("forest.")) == {"forest.bin"}


def test_maybe_trace_writes_profile(tmp_path, monkeypatch):
    # opt-in whole-fit xprof capture via SRML_PROFILE (NCCL_DEBUG analog)
    monkeypatch.setenv(profiling.PROFILE_ENV, str(tmp_path))
    with profiling.maybe_trace("unittrace"):
        np.zeros(4).sum()
    target = tmp_path / "unittrace"
    assert target.is_dir()
    # jax writes a plugins/profile subtree with at least one trace artifact
    contents = [str(p) for p in target.rglob("*") if p.is_file()]
    assert contents, "expected xprof trace files"


def test_maybe_trace_noop_without_env(monkeypatch):
    monkeypatch.delenv(profiling.PROFILE_ENV, raising=False)
    with profiling.maybe_trace("x"):
        pass


def test_counters_are_process_wide_and_resettable():
    import threading

    profiling.reset_counters("test.ctr")
    profiling.incr_counter("test.ctr.a")
    profiling.incr_counter("test.ctr.a", 2)

    # increments from another thread land in the same registry (the
    # precompile worker-pool contract)
    t = threading.Thread(target=lambda: profiling.incr_counter("test.ctr.b"))
    t.start()
    t.join()
    assert profiling.counter("test.ctr.a") == 3
    assert profiling.counters("test.ctr") == {
        "test.ctr.a": 3,
        "test.ctr.b": 1,
    }
    profiling.reset_counters("test.ctr")
    assert profiling.counters("test.ctr") == {}


def test_percentiles_over_recorded_durations():
    profiling.reset_durations("t.lat")
    for ms in range(1, 101):  # 1..100 ms
        profiling.record_duration("t.lat.a", ms / 1000.0)
    stats = profiling.percentiles("t.lat.a")
    assert stats["count"] == 100
    assert abs(stats["p50"] - 0.0505) < 1e-9  # numpy linear interpolation
    assert stats["p95"] <= stats["p99"] <= stats["max"] == 0.1
    assert abs(stats["mean"] - 0.0505) < 1e-9
    profiling.reset_durations("t.lat")
    assert profiling.percentiles("t.lat") == {}


def test_percentiles_merge_prefix_and_cross_thread():
    import threading

    profiling.reset_durations("t.merge")
    profiling.record_duration("t.merge.a", 1.0)
    # worker-thread samples land in the same process-wide registry (the
    # serving dispatch-thread contract)
    t = threading.Thread(target=lambda: profiling.record_duration("t.merge.b", 3.0))
    t.start()
    t.join()
    merged = profiling.percentiles("t.merge")
    assert merged["count"] == 2 and merged["p50"] == 2.0
    only_a = profiling.percentiles("t.merge.a")
    assert only_a["count"] == 1 and only_a["p50"] == 1.0
    assert profiling.durations("t.merge") == {
        "t.merge.a": [1.0],
        "t.merge.b": [3.0],
    }
    profiling.reset_durations("t.merge")


def test_duration_cap_is_a_ring_buffer(monkeypatch):
    monkeypatch.setattr(profiling, "_DURATION_CAP", 4)
    profiling.reset_durations("t.ring")
    for i in range(6):
        profiling.record_duration("t.ring", float(i))
    series = profiling.durations("t.ring")["t.ring"]
    assert len(series) == 4  # capped
    assert sorted(series) == [2.0, 3.0, 4.0, 5.0]  # oldest overwritten
    profiling.reset_durations("t.ring")


def test_event_log_order_and_reset():
    profiling.reset_events()
    profiling.record_event("t.dispatch", block=0)
    profiling.record_event("t.dispatch", block=1)
    profiling.record_event("t.collect", block=0)
    ev = profiling.events("t.")
    assert ev == [
        ("t.dispatch", {"block": 0}),
        ("t.dispatch", {"block": 1}),
        ("t.collect", {"block": 0}),
    ]
    assert profiling.events("t.collect") == [("t.collect", {"block": 0})]
    profiling.reset_events()
    assert profiling.events() == []
