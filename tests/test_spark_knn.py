#
# kneighbors / exactNearestNeighborsJoin on a live pyspark cluster must run
# inside a barrier stage — item partitions stay on the executors, only query
# blocks and (Q, k) candidate lists cross task boundaries, and NOTHING is
# collected to the driver (VERDICT round 3, item 1; reference knn.py:452-560
# keeps partitions worker-resident and exchanges p2p, 604-672 joins with
# Spark).  pyspark is not installable on this image, so the surfaces the
# executor path touches (select/withColumn/union/repartition/mapInPandas/
# rdd.barrier/createDataFrame/sort/join + BarrierTaskContext) are mocked
# faithfully with REAL concurrency: the barrier tasks run in threads whose
# allGather is a genuine rendezvous, so the two-round control-plane protocol
# of ops.knn.distributed_kneighbors executes for real at nranks > 1.
# spark_to_facade is patched to raise, PROVING the driver-collect path is
# never entered.  The OS-process equivalent lives in test_multicontroller.py.
#
import sys
import threading
import types

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import NearestNeighbors
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.spark.adapter import NUM_WORKERS_CONF

N_TASKS = 2


# -- expression sentinels for pyspark.sql.functions ---------------------------

class _Lit:
    def __init__(self, v):
        self.v = v


class _MonoId:
    pass


# -- threaded barrier context -------------------------------------------------

class _SharedBarrier:
    def __init__(self, n):
        self.n = n
        self.barrier = threading.Barrier(n, timeout=120)
        self.lock = threading.Lock()
        self.rounds = {}


class _FakeBarrierTaskContext:
    _tls = threading.local()

    def __init__(self, rank, shared):
        self._rank = rank
        self._shared = shared
        self._round = 0

    @classmethod
    def get(cls):
        return cls._tls.ctx

    def partitionId(self):
        return self._rank

    def allGather(self, message=""):
        sh = self._shared
        r = self._round
        self._round += 1
        with sh.lock:
            sh.rounds.setdefault(r, {})[self._rank] = message
        sh.barrier.wait()
        return [sh.rounds[r][i] for i in range(sh.n)]

    def barrier(self):
        self.allGather("")


# -- fake pyspark DataFrame ---------------------------------------------------

class _FakeField:
    def __init__(self, name, ddl):
        self.name = name
        self.dataType = types.SimpleNamespace(simpleString=lambda d=ddl: d)


def _parse_ddl(schema: str):
    """Top-level comma split of a DDL string, respecting <> nesting."""
    fields, depth, cur = [], 0, ""
    for ch in schema:
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        if ch == "," and depth == 0:
            fields.append(cur.strip())
            cur = ""
        else:
            cur += ch
    if cur.strip():
        fields.append(cur.strip())
    out = []
    for f in fields:
        name, _, ddl = f.partition(" ")
        out.append(_FakeField(name.strip("`"), ddl.strip()))
    return out


class _FakeRdd:
    def __init__(self, df):
        self._df = df
        self.barriered = False

    def barrier(self):
        self.barriered = True
        return self

    def mapPartitions(self, f):
        return self

    def withResources(self, profile):
        return self


class _FakeSparkSession:
    version = "3.5.0"

    def __init__(self, conf=None):
        conf = conf or {
            "spark.master": "local[2]",
            NUM_WORKERS_CONF: str(N_TASKS),
        }
        self.sparkContext = types.SimpleNamespace(
            getConf=lambda: types.SimpleNamespace(
                get=lambda k, d=None: conf.get(k, d)
            )
        )

    def createDataFrame(self, rdd, schema):
        df = rdd._df
        assert rdd.barriered and df._udf is not None, (
            "createDataFrame in this mock only consumes barrier mapInPandas"
        )
        parts = _run_barrier_tasks(df._src_parts, df._udf, len(df._src_parts))
        fields = _parse_ddl(schema)
        cols = [f.name for f in fields]
        parts = [
            p if len(p.columns) else pd.DataFrame({c: [] for c in cols})
            for p in parts
        ]
        return _FakeSparkDataFrame(parts, fields)


def _run_barrier_tasks(src_parts, udf, n_tasks):
    shared = _SharedBarrier(n_tasks)
    results = [None] * n_tasks
    errs = []

    def work(rank):
        _FakeBarrierTaskContext._tls.ctx = _FakeBarrierTaskContext(rank, shared)
        try:
            results[rank] = list(udf(iter([src_parts[rank]])))
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append((rank, e))
            shared.barrier.abort()
        finally:
            _FakeBarrierTaskContext._tls.ctx = None

    threads = [
        threading.Thread(target=work, args=(r,)) for r in range(n_tasks)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise errs[0][1]
    return [
        pd.concat(r, ignore_index=True) if r else pd.DataFrame()
        for r in results
    ]


class _FakeSparkDataFrame:
    """Eager pandas-backed stand-in for the pyspark surface the executor-side
    kNN path touches.  mapInPandas is LAZY: barrier consumption runs the UDF
    in concurrent threads (createDataFrame above); plain consumption (struct/
    explode frames feeding joins) runs it sequentially on materialization.
    Deliberately NO toPandas — a driver collect of any frame fails loudly."""

    def __init__(self, partitions, fields, udf=None):
        self._src_parts = partitions
        self._fields = fields
        self._udf = udf
        self.sparkSession = _FakeSparkSession()

    # -- materialization ------------------------------------------------
    def _parts(self):
        if self._udf is None:
            return self._src_parts
        out = []
        for p in self._src_parts:
            chunks = list(self._udf(iter([p])))
            out.append(
                pd.concat(chunks, ignore_index=True)
                if chunks
                else pd.DataFrame({f.name: [] for f in self._fields})
            )
        return out

    def _materialize(self):  # test helper, not pyspark surface
        parts = self._parts()
        return pd.concat(parts, ignore_index=True) if parts else pd.DataFrame()

    # -- pyspark surface ------------------------------------------------
    @property
    def schema(self):
        return types.SimpleNamespace(fields=list(self._fields))

    @property
    def columns(self):
        return [f.name for f in self._fields]

    @property
    def rdd(self):
        return _FakeRdd(self)

    def select(self, *cols):
        assert all(isinstance(c, str) for c in cols)
        fmap = {f.name: f for f in self._fields}
        return _FakeSparkDataFrame(
            [p[list(cols)] for p in self._parts()], [fmap[c] for c in cols]
        )

    def withColumn(self, name, expr):
        parts = []
        for pid, p in enumerate(self._parts()):
            p = p.copy()
            if isinstance(expr, _Lit):
                p[name] = expr.v
            elif isinstance(expr, _MonoId):
                # real monotonically_increasing_id packs the partition id in
                # the high bits — keeping that here proves int64 ids survive
                # the whole kneighbors pipeline
                p[name] = (np.int64(pid) << 33) + np.arange(len(p), dtype=np.int64)
            else:
                raise TypeError(f"unsupported expr {expr!r}")
            parts.append(p)
        ddl = "int" if isinstance(expr, _Lit) else "bigint"
        return _FakeSparkDataFrame(parts, self._fields + [_FakeField(name, ddl)])

    def union(self, other):
        assert self.columns == other.columns, "union requires aligned schemas"
        return _FakeSparkDataFrame(
            self._parts() + other._parts(), self._fields
        )

    def repartition(self, n):
        whole = self._materialize()
        idx = np.array_split(np.arange(len(whole)), n)
        return _FakeSparkDataFrame(
            [whole.iloc[ix].reset_index(drop=True) for ix in idx], self._fields
        )

    def mapInPandas(self, udf, schema=None):
        return _FakeSparkDataFrame(self._src_parts, _parse_ddl(schema), udf=udf)

    def sort(self, col):
        whole = self._materialize().sort_values(col).reset_index(drop=True)
        return _FakeSparkDataFrame([whole], self._fields)

    def join(self, other, on):
        merged = pd.merge(
            self._materialize(), other._materialize(), on=on, how="inner"
        )
        fmap = {f.name: f for f in list(self._fields) + list(other._fields)}
        return _FakeSparkDataFrame(
            [merged], [fmap[c] for c in merged.columns]
        )


_FakeSparkDataFrame.__module__ = "pyspark.sql.dataframe"


@pytest.fixture(autouse=True)
def fake_pyspark(monkeypatch):
    mod = types.ModuleType("pyspark")
    mod.BarrierTaskContext = _FakeBarrierTaskContext
    sqlmod = types.ModuleType("pyspark.sql")
    fmod = types.ModuleType("pyspark.sql.functions")
    fmod.lit = _Lit
    fmod.monotonically_increasing_id = lambda: _MonoId()
    fmod.col = lambda c: c
    mod.sql = sqlmod
    sqlmod.functions = fmod
    monkeypatch.setitem(sys.modules, "pyspark", mod)
    monkeypatch.setitem(sys.modules, "pyspark.sql", sqlmod)
    monkeypatch.setitem(sys.modules, "pyspark.sql.functions", fmod)
    monkeypatch.delenv("SRML_SPARK_COLLECT", raising=False)

    from spark_rapids_ml_tpu.spark import adapter

    def _boom(sdf):
        raise AssertionError("kNN collected a dataset to the driver")

    monkeypatch.setattr(adapter, "spark_to_facade", _boom)


def _data(n_items=500, n_query=120, d=8, seed=9):
    rng = np.random.default_rng(seed)
    items = rng.standard_normal((n_items, d)).astype(np.float32)
    queries = rng.standard_normal((n_query, d)).astype(np.float32)
    return items, queries


def _fake_sdf(X, ids=None, n_parts=3):
    fields = [_FakeField("features", "array<float>")]
    parts = []
    for ix in np.array_split(np.arange(len(X)), n_parts):
        pdf = pd.DataFrame({"features": list(X[ix])})
        if ids is not None:
            pdf["row"] = ids[ix]
        parts.append(pdf.reset_index(drop=True))
    if ids is not None:
        fields.append(_FakeField("row", "bigint"))
    return _FakeSparkDataFrame(parts, fields)


def _local_baseline(items, item_ids, queries, query_ids, k):
    """Driver-local facade path on the identical data/ids."""
    est = NearestNeighbors(k=k).setIdCol("row")
    model = est.fit(
        DataFrame.from_pandas(
            pd.DataFrame({"features": list(items), "row": item_ids}), 3
        )
    )
    _, _, knn = model.kneighbors(
        DataFrame.from_pandas(
            pd.DataFrame({"features": list(queries), "row": query_ids}), 3
        )
    )
    return knn.toPandas().sort_values("query_row").reset_index(drop=True)


def test_kneighbors_runs_in_barrier_stage():
    items, queries = _data()
    k = 7
    item_ids = np.arange(len(items), dtype=np.int64) * 3 + 11
    query_ids = np.arange(len(queries), dtype=np.int64) * 7 + 5
    est = NearestNeighbors(k=k).setIdCol("row")
    model = est.fit(_fake_sdf(items, item_ids))
    item_out, query_out, knn_df = model.kneighbors(_fake_sdf(queries, query_ids))
    assert isinstance(knn_df, _FakeSparkDataFrame)
    got = knn_df._materialize().sort_values("query_row").reset_index(drop=True)
    want = _local_baseline(items, item_ids, queries, query_ids, k)
    np.testing.assert_array_equal(
        got["query_row"].to_numpy(np.int64), want["query_row"].to_numpy(np.int64)
    )
    np.testing.assert_allclose(
        np.stack(got["distances"].to_numpy()),
        np.stack(want["distances"].to_numpy()),
        rtol=1e-5, atol=1e-6,
    )
    # neighbor ids may legitimately swap only on exact distance ties
    gi = np.stack(got["indices"].to_numpy()).astype(np.int64)
    wi = np.stack(want["indices"].to_numpy()).astype(np.int64)
    assert (gi == wi).mean() > 0.99


def test_generated_id_and_int64_partition_encoding():
    """Without setIdCol, ids come from monotonically_increasing_id — the
    mock packs the partition id in the high bits (like real Spark), so this
    also proves int64 ids survive the candidate exchange."""
    items, queries = _data(n_items=300, n_query=64)
    k = 5
    model = NearestNeighbors(k=k).fit(_fake_sdf(items))
    _, query_out, knn_df = model.kneighbors(_fake_sdf(queries))
    got = knn_df._materialize()
    assert len(got) == len(queries)
    assert set(got.columns) == {"query_unique_id", "indices", "distances"}
    # sorted query ids == original row order (partition-major mono ids)
    qids = got["query_unique_id"].to_numpy(np.int64)
    assert (np.sort(qids) == qids).all()
    assert qids.max() >= (np.int64(1) << 33)  # high-bit ids really exercised
    d = np.stack(got["distances"].to_numpy())
    assert (np.diff(d, axis=1) >= -1e-6).all()  # ascending per row
    # distances match an id-free local baseline row-for-row
    local = NearestNeighbors(k=k).fit(DataFrame.from_numpy(items))
    _, _, knn_local = local.kneighbors(DataFrame.from_numpy(queries))
    want = np.stack(knn_local.toPandas()["distances"].to_numpy())
    np.testing.assert_allclose(d, want, rtol=1e-5, atol=1e-6)


def test_exact_join_runs_spark_side():
    items, queries = _data(n_items=200, n_query=40)
    k = 4
    item_ids = np.arange(len(items), dtype=np.int64)
    query_ids = np.arange(len(queries), dtype=np.int64)
    est = NearestNeighbors(k=k).setIdCol("row")
    model = est.fit(_fake_sdf(items, item_ids))
    out = model.exactNearestNeighborsJoin(_fake_sdf(queries, query_ids), distCol="dc")
    got = out._materialize()
    assert set(got.columns) == {"item_df", "query_df", "dc"}
    assert len(got) == len(queries) * k
    # per-query neighbor id sets + distances match the local baseline
    want = _local_baseline(items, item_ids, queries, query_ids, k)
    want_map = {
        int(r["query_row"]): (set(map(int, r["indices"])), np.sort(r["distances"]))
        for _, r in want.iterrows()
    }
    got["qid"] = [int(s["row"]) for s in got["query_df"]]
    got["iid"] = [int(s["row"]) for s in got["item_df"]]
    for qid, grp in got.groupby("qid"):
        ids, dists = want_map[qid]
        assert set(grp["iid"]) == ids
        np.testing.assert_allclose(
            np.sort(grp["dc"].to_numpy(np.float32)), dists, rtol=1e-5, atol=1e-6
        )
    # structs carry the source columns (features survived the join)
    assert "features" in got["item_df"].iloc[0]


def test_join_drops_generated_id():
    items, queries = _data(n_items=120, n_query=16)
    model = NearestNeighbors(k=3).fit(_fake_sdf(items))
    got = model.exactNearestNeighborsJoin(_fake_sdf(queries))._materialize()
    assert len(got) == len(queries) * 3
    # the auto-generated unique_id must NOT leak into the structs
    assert "unique_id" not in got["item_df"].iloc[0]
    assert "unique_id" not in got["query_df"].iloc[0]


def test_collect_override_routes_driver_local(monkeypatch):
    monkeypatch.setenv("SRML_SPARK_COLLECT", "1")
    items, _ = _data(n_items=60, n_query=8)
    with pytest.raises(Exception):
        NearestNeighbors(k=3).fit(_fake_sdf(items))


def test_mixed_input_types_fail_loudly():
    items, queries = _data(n_items=60, n_query=8)
    model = NearestNeighbors(k=3).fit(_fake_sdf(items))
    with pytest.raises(TypeError, match="pyspark"):
        model.kneighbors(DataFrame.from_numpy(queries))


def test_kneighbors_empty_rank_and_k_beyond_items():
    """One barrier task ends up with zero item AND zero query rows (skewed
    repartition), and k exceeds the global item count: the empty rank must
    still join both control-plane rounds (bailing out would hang the
    barrier) and every result row gets min(k, n_items) columns."""
    import threading

    from spark_rapids_ml_tpu.ops.knn import distributed_kneighbors

    rng = np.random.default_rng(11)
    items = rng.standard_normal((12, 5)).astype(np.float32)
    queries = rng.standard_normal((7, 5)).astype(np.float32)
    shared = _SharedBarrier(3)
    res = {}
    errs = []

    def run(rank):
        ctx = _FakeBarrierTaskContext(rank, shared)
        if rank == 0:
            ip = [(items, np.arange(12, dtype=np.int64))]
            qp = []
        elif rank == 1:
            ip = []
            qp = [(queries, np.arange(7, dtype=np.int64))]
        else:  # rank 2: completely empty
            ip, qp = [], []
        try:
            res[rank] = distributed_kneighbors(ip, qp, 50, rank, 3, ctx)
        except Exception as e:  # noqa: BLE001 — re-raised below
            errs.append(e)
            shared.barrier.abort()  # free the other ranks immediately

    threads = [threading.Thread(target=run, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise errs[0]
    assert res[0] == [] and res[2] == []
    (d, i), = res[1]
    assert d.shape == (7, 12) and i.shape == (7, 12)  # k_eff = 12 items
    d2 = ((queries[:, None, :] - items[None]) ** 2).sum(-1)
    want = np.sort(np.sqrt(d2), axis=1)
    np.testing.assert_allclose(d, want, rtol=1e-4, atol=1e-5)
    # every item id appears exactly once per row (full ranking)
    assert all(set(row) == set(range(12)) for row in i)
