# Worker process for the multi-controller kneighbors test: one rank of a
# distributed_kneighbors exchange over a FileControlPlane (the stand-in for
# Spark's BarrierTaskContext — same role as mc_worker.py for fits).  No
# jax.distributed bootstrap is needed: the protocol moves query blocks and
# candidate lists over the control plane only; each rank computes on its own
# local device mesh, exactly as a Spark barrier task would.
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from spark_rapids_ml_tpu.ops.knn import distributed_kneighbors  # noqa: E402
from spark_rapids_ml_tpu.parallel.runner import make_control_plane  # noqa: E402


def main() -> None:
    rank, nranks, root = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    with open(os.path.join(root, "knn_job.json")) as f:
        job = json.load(f)
    data = np.load(os.path.join(root, f"knn_shard_{rank}.npz"))
    item_parts = [(data["item_X"], data["item_id"])]
    query_parts = (
        [(data["q_X"], data["q_id"])] if data["q_X"].shape[0] else []
    )
    cp = make_control_plane(os.path.join(root, "cp"), rank, nranks, timeout=180)
    results = distributed_kneighbors(
        item_parts, query_parts, job["k"], rank, nranks, cp
    )
    if results:
        d, i = results[0]
    else:
        d = np.zeros((0, job["k"]), np.float32)
        i = np.zeros((0, job["k"]), np.int64)
    np.savez(os.path.join(root, f"knn_out_{rank}.npz"), d=d, i=i)
    cp.close()  # srml-shield teardown: no orphan presence files


if __name__ == "__main__":
    main()
