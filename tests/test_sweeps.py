# Parameterized cross-shape sweeps vs sklearn (the reference's slow-sweep
# layer, e.g. test_pca.py:289-344, test_kmeans.py:230) plus weighted-fit
# semantics checks.  Kept small enough for CI; the full grids run under
# --runslow.
import numpy as np
import pytest
from sklearn.cluster import KMeans as SkKMeans
from sklearn.decomposition import PCA as SkPCA
from sklearn.linear_model import LinearRegression as SkLinReg
from sklearn.linear_model import LogisticRegression as SkLogReg

from spark_rapids_ml_tpu import (
    KMeans,
    LinearRegression,
    LogisticRegression,
    PCA,
)
from spark_rapids_ml_tpu.dataframe import DataFrame


def _blobs(rng, n, d, k, spread=0.15):
    centers = rng.uniform(-5, 5, size=(k, d)).astype(np.float32)
    assign = rng.integers(0, k, size=n)
    X = centers[assign] + spread * rng.standard_normal((n, d)).astype(np.float32)
    return X


@pytest.mark.parametrize("n,d,k", [(2000, 8, 4), (4000, 33, 7), (1500, 128, 3)])
def test_kmeans_sweep_quality(n, d, k):
    rng = np.random.default_rng(n + d)
    X = _blobs(rng, n, d, k)
    df = DataFrame.from_numpy(X, num_partitions=4)
    model = KMeans(k=k, maxIter=30, tol=1e-6, seed=5).fit(df)
    sk = SkKMeans(n_clusters=k, n_init=4, random_state=5).fit(X)
    # within 5% of sklearn's inertia on well-separated blobs
    assert model.inertia_ <= 1.05 * sk.inertia_


@pytest.mark.parametrize("n,d,k", [(1000, 12, 2), (3000, 64, 5), (800, 200, 4)])
def test_pca_sweep_matches_sklearn(n, d, k):
    rng = np.random.default_rng(d)
    # well-separated top-k variances so components are individually
    # comparable (near-degenerate eigenvalues make per-component cosines
    # meaningless for any implementation pair)
    scales = np.full(d, 0.3, np.float32)
    scales[: k + 2] = np.geomspace(10.0, 2.0, k + 2)
    X = rng.standard_normal((n, d)).astype(np.float32) * scales
    df = DataFrame.from_numpy(X, num_partitions=4)
    model = PCA(k=k).fit(df)
    sk = SkPCA(n_components=k).fit(X.astype(np.float64))
    np.testing.assert_allclose(
        np.asarray(model.explained_variance_ratio_),
        sk.explained_variance_ratio_,
        atol=1e-3,
    )
    # components match up to sign (both sign-flip deterministically but
    # differently); compare absolute cosine alignment
    for j in range(k):
        cos = abs(
            float(np.dot(np.asarray(model.components_)[j], sk.components_[j]))
            / (
                np.linalg.norm(np.asarray(model.components_)[j])
                * np.linalg.norm(sk.components_[j])
            )
        )
        assert cos > 0.99, (j, cos)


@pytest.mark.parametrize("n,d", [(2000, 5), (5000, 40), (1200, 150)])
def test_linreg_sweep_matches_sklearn(n, d):
    rng = np.random.default_rng(d)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal(d).astype(np.float32)
    y = X @ w + 0.7 + 0.05 * rng.standard_normal(n).astype(np.float32)
    df = DataFrame.from_numpy(X, y=y, num_partitions=4)
    model = LinearRegression(regParam=0.0).fit(df)
    sk = SkLinReg().fit(X, y)
    np.testing.assert_allclose(np.asarray(model.coef_), sk.coef_, atol=2e-3)
    np.testing.assert_allclose(model.intercept_, sk.intercept_, atol=2e-3)


@pytest.mark.parametrize("n,d,classes", [(3000, 10, 2), (4000, 24, 4)])
def test_logreg_sweep_matches_sklearn(n, d, classes):
    rng = np.random.default_rng(d + classes)
    X = rng.standard_normal((n, d)).astype(np.float32)
    W = rng.standard_normal((d, classes)).astype(np.float32)
    y = (X @ W).argmax(axis=1).astype(np.float32)
    df = DataFrame.from_numpy(X, y=y, num_partitions=4)
    model = LogisticRegression(regParam=1e-3, maxIter=300, tol=1e-10).fit(df)
    sk = SkLogReg(C=1.0 / (1e-3 * n), max_iter=2000).fit(X, y)
    ours = model.transform(df).toPandas()["prediction"].to_numpy()
    theirs = sk.predict(X)
    agreement = float((ours == theirs).mean())
    assert agreement > 0.98, agreement


def test_kmeans_weightcol_unsupported_parity():
    # reference parity: spark-rapids-ml KMeans rejects weightCol
    # (clustering.py setWeightCol raises)
    with pytest.raises(ValueError, match="weightCol"):
        KMeans(k=3).setWeightCol("weight")


def test_weightcol_unsupported_parity_all_estimators():
    # reference parity: weightCol maps to None (= unsupported, raises) for
    # every estimator family (params.py:97, regression.py:186,
    # classification.py:658, tree.py:84 in the reference)
    with pytest.raises(ValueError):
        LinearRegression(weightCol="w")
    with pytest.raises(ValueError):
        LogisticRegression(weightCol="w")


@pytest.mark.parametrize("algo", ["kmeans", "pca", "linreg"])
def test_float64_sweep(algo):
    rng = np.random.default_rng(17)
    X = _blobs(rng, 1000, 10, 3)
    y = (X @ rng.standard_normal(10).astype(np.float32)).astype(np.float32)
    if algo == "kmeans":
        df = DataFrame.from_numpy(X, num_partitions=2)
        m32 = KMeans(k=3, seed=1, maxIter=15).fit(df)
        m64 = KMeans(k=3, seed=1, maxIter=15, float32_inputs=False).fit(df)
        np.testing.assert_allclose(
            np.sort(np.asarray(m32.cluster_centers_), axis=0),
            np.sort(np.asarray(m64.cluster_centers_), axis=0),
            atol=1e-2,
        )
    elif algo == "pca":
        df = DataFrame.from_numpy(X, num_partitions=2)
        m32 = PCA(k=2).fit(df)
        m64 = PCA(k=2, float32_inputs=False).fit(df)
        np.testing.assert_allclose(
            np.abs(np.asarray(m32.components_)),
            np.abs(np.asarray(m64.components_)),
            atol=1e-2,
        )
    else:
        df = DataFrame.from_numpy(X, y=y, num_partitions=2)
        m32 = LinearRegression().fit(df)
        m64 = LinearRegression(float32_inputs=False).fit(df)
        np.testing.assert_allclose(
            np.asarray(m32.coef_), np.asarray(m64.coef_), atol=1e-3
        )
