# CPU-model interop (spark/interop.py).  pyspark is not installed on the TPU
# test image, so the py4j construction is exercised against a recording mock
# of the JVM gateway; the full pyspark path is covered by the compat suite on
# a Spark cluster (reference analog: test_random_forest.py cpu() tests).
from types import SimpleNamespace

import numpy as np
import pytest

from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.models.random_forest import (
    RandomForestClassifier,
    RandomForestRegressor,
)
from spark_rapids_ml_tpu.spark.interop import _build_java_tree


class _Recorder:
    """Mimics the py4j jvm attribute chain; every call returns a node record."""

    def __init__(self, path=""):
        self.path = path

    def __getattr__(self, name):
        return _Recorder(f"{self.path}.{name}" if self.path else name)

    def __call__(self, *args):
        return {"cls": self.path, "args": args}


class _Gateway:
    def new_array(self, cls, n):
        return [None] * n


def _mock_sc():
    return SimpleNamespace(_jvm=_Recorder(), _gateway=_Gateway())


def _fit_forest(classification):
    rng = np.random.default_rng(0)
    X = rng.standard_normal((300, 4)).astype(np.float32)
    if classification:
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
        est = RandomForestClassifier(numTrees=3, maxDepth=3, seed=7)
    else:
        y = (2 * X[:, 0] - X[:, 2]).astype(np.float32)
        est = RandomForestRegressor(numTrees=3, maxDepth=3, seed=7)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    return est.fit(df)


@pytest.mark.parametrize(
    "impurity", [pytest.param("gini", marks=pytest.mark.slow), "variance"]
)
def test_build_java_tree_structure(impurity):
    model = _fit_forest(classification=(impurity == "gini"))
    sc = _mock_sc()
    trees = model.trees_to_dicts()
    assert len(trees) == 3
    node = _build_java_tree(sc, impurity, trees[0])
    # root of a depth-3 fit on separable data must be an internal node
    assert node["cls"].endswith("ml.tree.InternalNode")
    pred, imp, gain, left, right, split, calc = node["args"]
    assert split["cls"].endswith("ml.tree.ContinuousSplit")
    feat, thr = split["args"]
    assert 0 <= feat < 4 and np.isfinite(thr)
    expected_calc = "GiniCalculator" if impurity == "gini" else "VarianceCalculator"
    assert calc["cls"].endswith(expected_calc)

    # walk to a leaf and check prediction semantics
    def find_leaf(n):
        if n["cls"].endswith("LeafNode"):
            return n
        return find_leaf(n["args"][3])  # left child

    leaf = find_leaf(node)
    leaf_pred = leaf["args"][0]
    if impurity == "gini":
        assert leaf_pred in (0.0, 1.0)  # class index, not probability
    else:
        assert np.isfinite(leaf_pred)


def test_entropy_calculator_selected():
    model = _fit_forest(classification=True)
    node = _build_java_tree(_mock_sc(), "entropy", model.trees_to_dicts()[0])

    def calcs(n, acc):
        acc.append(n["args"][-1]["cls"] if n["cls"].endswith("InternalNode") else n["args"][2]["cls"])
        if n["cls"].endswith("InternalNode"):
            calcs(n["args"][3], acc)
            calcs(n["args"][4], acc)
        return acc

    assert all(c.endswith("EntropyCalculator") for c in calcs(node, []))


def test_cpu_requires_pyspark():
    model = _fit_forest(classification=True)
    with pytest.raises((ImportError, RuntimeError)):
        model.cpu()


def test_unknown_impurity_rejected():
    model = _fit_forest(classification=True)
    with pytest.raises(ValueError):
        _build_java_tree(_mock_sc(), "bogus", model.trees_to_dicts()[0])
