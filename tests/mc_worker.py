#
# Barrier-task stand-in for the multi-controller tests: one OS process per
# rank (what a Spark barrier task would be on a real cluster,
# reference core.py:558-640), rendezvous over a FileControlPlane directory,
# data shard + estimators staged on disk by the test driver.
#
# Invoked as: python mc_worker.py <rank> <nranks> <jobdir>
# with env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=N
#
import json
import os
import sys


def main() -> None:
    rank, nranks, root = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    import numpy as np
    import pandas as pd

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from spark_rapids_ml_tpu.core import load
    from spark_rapids_ml_tpu.parallel.runner import (
        distributed_session,
        make_control_plane,
    )

    shard = np.load(os.path.join(root, f"shard_{rank}.npz"))
    part = pd.DataFrame({"features": list(shard["X"])})
    for key in shard.files:
        if key == "X":
            continue
        # "y" keeps its historical mapping to the default labelCol; any
        # other array rides under its own name (extra label columns for
        # the classification estimators)
        part["label" if key == "y" else key] = shard[key]

    with open(os.path.join(root, "estimators.json")) as f:
        names = json.load(f)

    # plane kind honors SRML_CP (file | tcp) — the whole fit matrix reruns
    # over the srml-wire socket plane by flipping the env var
    cp = make_control_plane(os.path.join(root, "cp"), rank, nranks)
    out = {}
    # one jax.distributed lifetime for every fit (the session amortizes the
    # bootstrap; each fit still barriers like the reference's per-fit NCCL)
    with distributed_session(rank, nranks, cp) as session:
        import jax

        meta = {
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "process_index": jax.process_index(),
        }
        for name in names:
            est = load(os.path.join(root, f"est_{name}"))
            out[name] = session.fit(est, [part])

    if rank == 0:
        with open(os.path.join(root, "attrs.json"), "w") as f:
            json.dump({"meta": meta, "results": out}, f)


if __name__ == "__main__":
    main()
