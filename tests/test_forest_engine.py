# Device-resident random-forest engine contracts (ops/forest.grow_forest
# rework): mesh-shape parity of the fitted forest (the CI 8-device gate),
# the scan-batched dispatch/transfer collapse (forest.* counters), the
# sharded+psum MXU histogram rule against the numpy oracle, reference
# equivalence against the per-tree grow_tree builder, AOT warm staging, and
# zero-recompile repeat fits.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu import (
    RandomForestClassifier,
    RandomForestRegressor,
    profiling,
)
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.ops.forest import (
    bin_features,
    compute_bin_edges,
    grow_forest,
    grow_tree,
    warm_forest_kernels,
)
from spark_rapids_ml_tpu.parallel.mesh import get_mesh


def _cls_df(n=512, d=10, k=3, seed=1):
    from sklearn.datasets import make_classification

    X, y = make_classification(
        n_samples=n, n_features=d, n_informative=min(6, d - 2), n_classes=k,
        random_state=seed,
    )
    return (
        DataFrame.from_numpy(
            X.astype(np.float64), y=y.astype(np.float64), num_partitions=2
        ),
        X,
        y,
    )


def _int_reg_df(n=512, d=8, seed=0):
    """Regression fixture with SMALL-INTEGER targets: every histogram stat
    (w, w*y, w*y^2) is an exact small integer in f32, so per-shard partial
    sums + psum equal the single-device sums BITWISE regardless of
    reduction order — the documented exactness basis of the parity gate."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float64)
    y = rng.integers(0, 8, size=n).astype(np.float64)
    return DataFrame.from_numpy(X, y=y, num_partitions=2), X, y


def test_mesh_parity_classifier():
    """The acceptance gate: a fixed seed must produce the IDENTICAL forest
    (features, thresholds, leaf values) on a 1-device and an 8-device mesh.
    Exactness argument: n = 512 rows divide every mesh size, so the padded
    row count — and with it every Poisson bootstrap draw and feature-subset
    draw — is mesh-independent; one-hot class stats times integer bootstrap
    weights are exact in f32, so the psum-combined shard histograms match
    the single-device histograms bitwise and every gain/argmax agrees."""
    df, X, y = _cls_df()
    kw = dict(numTrees=6, maxDepth=5, maxBins=16, seed=5)
    m1 = RandomForestClassifier(**kw, num_workers=1).fit(df)
    m8 = RandomForestClassifier(**kw, num_workers=None).fit(df)
    np.testing.assert_array_equal(m1.features_, m8.features_)
    np.testing.assert_array_equal(m1.thresholds_, m8.thresholds_)
    np.testing.assert_array_equal(m1.leaf_values_, m8.leaf_values_)
    np.testing.assert_array_equal(m1.node_counts_, m8.node_counts_)
    # and the forest actually learned something on either mesh
    acc = (
        m8.transform(df).toPandas()["prediction"].to_numpy() == y
    ).mean()
    assert acc > 0.85, acc


def test_mesh_parity_regressor_integer_targets():
    df, X, y = _int_reg_df()
    kw = dict(numTrees=4, maxDepth=5, maxBins=16, seed=2)
    m1 = RandomForestRegressor(**kw, num_workers=1).fit(df)
    m8 = RandomForestRegressor(**kw, num_workers=None).fit(df)
    np.testing.assert_array_equal(m1.features_, m8.features_)
    np.testing.assert_array_equal(m1.thresholds_, m8.thresholds_)
    np.testing.assert_array_equal(m1.leaf_values_, m8.leaf_values_)


def _grow_fixture(n=1024, d=6, B=16, T=3, seed=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d) > 0).astype(np.float32)
    edges = compute_bin_edges(X, B)
    Xb = bin_features(jnp.asarray(X), jnp.asarray(edges))
    stats = np.stack([1.0 - y, y], axis=1).astype(np.float32)
    stats_t = jnp.broadcast_to(jnp.asarray(stats)[None], (T, n, 2))
    return Xb, stats_t, edges


def test_dispatch_and_transfer_counters(monkeypatch):
    """The scan-batching acceptance bound: ceil(levels / LEVEL_BLOCK)
    engine dispatches, ONE early-stop flag sync per block, and ONE
    device_get for the whole forest."""
    monkeypatch.setenv("SRML_FOREST_LEVEL_BLOCK", "2")
    Xb, stats_t, edges = _grow_fixture()
    kw = dict(
        max_depth=5, n_bins=16, kind="gini", max_features=6,
        min_samples_leaf=1.0, min_impurity_decrease=0.0, seed=3,
    )
    c0 = profiling.counters("forest")
    grow_forest(Xb, stats_t, edges, **kw)
    d = profiling.counter_deltas(c0, "forest")
    assert d.get("forest.levels.dispatches", 0) == 3  # ceil(6 / 2)
    assert d.get("forest.level_syncs", 0) == 3
    assert d.get("forest.d2h_transfers", 0) == 1


def test_early_stop_skips_dead_level_blocks(monkeypatch):
    """Constant features leaf every tree at the root: the on-device
    any-split mask must stop the block loop after the FIRST dispatch
    instead of running all ceil(levels/block) blocks."""
    monkeypatch.setenv("SRML_FOREST_LEVEL_BLOCK", "2")
    n, T = 256, 2
    Xb = jnp.zeros((n, 4), jnp.int8)
    y = np.zeros(n, np.float32)
    y[::2] = 1.0
    stats = np.stack([1.0 - y, y], axis=1).astype(np.float32)
    stats_t = jnp.broadcast_to(jnp.asarray(stats)[None], (T, n, 2))
    edges = np.zeros((4, 7), np.float32)
    c0 = profiling.counters("forest")
    f, t, v, ns, imp = grow_forest(
        Xb, stats_t, edges, max_depth=5, n_bins=8, kind="gini",
        max_features=4, min_samples_leaf=1.0, min_impurity_decrease=0.0,
        seed=0,
    )
    d = profiling.counter_deltas(c0, "forest")
    assert d.get("forest.levels.dispatches", 0) == 1
    assert (f == -1).all()  # pure roots: no splits anywhere
    np.testing.assert_allclose(ns[:, 0], n)


def test_engine_matches_reference_grow_tree():
    """No bootstrap + all features: the engine and the kept per-tree
    reference builder (grow_tree) are deterministic on the same binned
    data and must grow IDENTICAL trees — on the 1-device mesh by identical
    ops, and on the full mesh because integer class stats make the
    psum-combined histograms bitwise equal to the single-pass sums."""
    Xb, stats_t, edges = _grow_fixture(T=2)
    kw = dict(
        max_depth=5, n_bins=16, kind="gini", max_features=6,
        min_samples_leaf=1.0, min_impurity_decrease=0.0,
    )
    ref = grow_tree(Xb, stats_t[0], edges, seed=11, **kw)
    for mesh in (get_mesh(1), get_mesh()):
        f, t, v, ns, imp = grow_forest(
            Xb, stats_t, edges, seed=11, mesh=mesh, **kw
        )
        for tree in range(2):
            np.testing.assert_array_equal(f[tree], np.asarray(ref.feature))
            np.testing.assert_allclose(t[tree], np.asarray(ref.threshold))
            np.testing.assert_allclose(
                v[tree], np.asarray(ref.leaf_value), atol=1e-6
            )
            np.testing.assert_allclose(
                ns[tree], np.asarray(ref.n_samples), atol=1e-4
            )


def test_sharded_histogram_rule_matches_oracle():
    """forest_hist.node_histograms_sharded (per-shard pallas pass + one
    psum) must reproduce the plain-numpy oracle on the 8-device mesh —
    the interpret-mode gate for the MXU path's sharding rule."""
    from spark_rapids_ml_tpu.ops.forest_hist import (
        _F_BLOCK,
        _ROW_TILE,
        node_histograms_reference,
        node_histograms_sharded,
    )

    mesh = get_mesh()
    n_dev = mesh.devices.size
    rng = np.random.default_rng(6)
    N = n_dev * _ROW_TILE
    T, nodes, S, B = 2, 4, 2, 16
    sub = rng.integers(0, B, (_F_BLOCK, N)).astype(np.int8)
    node_rel = rng.integers(0, nodes + 2, (T, N)).astype(np.int32)
    stats = rng.integers(0, 4, (T * S, N)).astype(np.float32)
    H = np.asarray(
        node_histograms_sharded(
            jnp.asarray(sub), jnp.asarray(node_rel), jnp.asarray(stats),
            mesh=mesh, t_pack=T, nodes=nodes, s_dim=S, n_bins=B,
            interpret=True,
        )
    )
    Href = node_histograms_reference(sub, node_rel, stats, T, nodes, S, B)
    # integer-valued stats: the bf16 one-hot matmuls and the psum are exact
    np.testing.assert_allclose(H, Href, rtol=2e-2, atol=1e-3)


def test_warm_forest_kernels_covers_the_fit():
    """warm_forest_kernels must enumerate the exact executables the engine
    dispatches: after warming (and draining the compile pool) a first-ever
    grow_forest at that geometry performs ZERO new compilations and never
    falls back to plain jit."""
    from spark_rapids_ml_tpu.ops.precompile import global_precompiler

    Xb, stats_t, edges = _grow_fixture(n=768, d=5, B=8, T=2, seed=9)
    mesh = get_mesh()
    kw = dict(
        max_depth=4, n_bins=8, kind="gini", max_features=5,
        min_samples_leaf=1.0, min_impurity_decrease=0.0,
    )
    keys = warm_forest_kernels(768, 5, 2, 2, mesh=mesh, dtype=np.float32, **kw)
    assert keys
    global_precompiler().wait(keys)
    c0 = profiling.counters("precompile")
    grow_forest(Xb, stats_t, edges, seed=1, mesh=mesh, **kw)
    d = profiling.counter_deltas(c0, "precompile")
    assert d.get("precompile.compile", 0) == 0, d
    assert d.get("precompile.fallback", 0) == 0, d
    assert d.get("precompile.aot_hit", 0) >= len(keys) - 1  # early stop may skip blocks


def test_repeat_fit_zero_new_compiles():
    """The acceptance smoke mirroring test_umap_engine: a second same-shape
    RandomForest fit performs ZERO new compilations — every engine kernel
    lands on a cached AOT executable — and grows the identical forest."""
    df, X, y = _cls_df(n=256, d=6, seed=3)
    est = RandomForestClassifier(numTrees=4, maxDepth=4, maxBins=8, seed=7)
    m1 = est.fit(df)
    c0 = profiling.counters("precompile")
    m2 = est.fit(df)
    d = profiling.counter_deltas(c0, "precompile")
    assert d.get("precompile.compile", 0) == 0, d
    assert d.get("precompile.fallback", 0) == 0, d
    assert d.get("precompile.aot_hit", 0) > 0, d
    np.testing.assert_array_equal(m1.features_, m2.features_)
    np.testing.assert_array_equal(m1.leaf_values_, m2.leaf_values_)


def test_repeat_transform_zero_new_compiles():
    """Prediction rides the same executable cache (power-of-two row
    buckets): a repeat transform at the same partition shape compiles
    nothing new."""
    df, X, y = _cls_df(n=256, d=6, seed=3)
    model = RandomForestClassifier(numTrees=4, maxDepth=4, maxBins=8, seed=7).fit(df)
    p1 = model.transform(df).toPandas()["prediction"].to_numpy()
    c0 = profiling.counters("precompile")
    p2 = model.transform(df).toPandas()["prediction"].to_numpy()
    d = profiling.counter_deltas(c0, "precompile")
    assert d.get("precompile.compile", 0) == 0, d
    np.testing.assert_array_equal(p1, p2)


def test_engine_min_samples_and_depth_gates():
    """The engine must honor min_samples_leaf and the depth cap exactly as
    the split gate documents: split nodes carry >= 2*min samples and the
    bottom level never splits."""
    Xb, stats_t, edges = _grow_fixture(n=512, T=2, seed=12)
    f, t, v, ns, imp = grow_forest(
        Xb, stats_t, edges, max_depth=3, n_bins=16, kind="gini",
        max_features=6, min_samples_leaf=40.0, min_impurity_decrease=0.0,
        seed=5, mesh=get_mesh(),
    )
    split = f >= 0
    assert ns[split].min() >= 2 * 40.0
    assert not split[:, 7:].any()  # nodes at the depth cap are leaves
