# srml-watch: the always-on health plane (docs/observability.md §7).
# Gates, in ISSUE order:
#   - induced-hang: a fit task blocking one mocked rank produces a watchdog
#     report naming the stalled RANK and its innermost open SPAN
#   - induced-exception: a failing fit dumps a Perfetto-loadable flight
#     recording whose FINAL event is the exception, naming the failing span
#   - overhead: always-on flight recording adds <2% to a warm kmeans fit
#   - memory accounting: per-phase peak-delta attribution merges through
#     TelemetrySnapshot; watermark gauges + serving health round-trip
#     through export_metrics()/render_prometheus()
import glob
import json
import threading
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu import profiling, watch


@pytest.fixture
def fresh_recorder():
    """A private FlightRecorder installed as the profiling hook for one
    test (restoring the process recorder after), so ring/thread/memory
    assertions never race the rest of the suite's events."""
    prev = profiling._flight
    rec = watch.FlightRecorder(cap=64)
    profiling._flight = rec
    try:
        yield rec
    finally:
        profiling._flight = prev


# -- flight recorder ----------------------------------------------------------


def test_flight_recorder_always_on_without_any_session(fresh_recorder):
    """Span closes and counter increments land in the ring with NO trace
    session open — the whole point: nobody plans a crash."""
    rec = fresh_recorder
    with profiling.span("w.outer"):
        with profiling.span("w.inner"):
            profiling.incr_counter("w.ctr", 3)
    kinds = [r[0] for r in rec.records()]
    assert kinds == ["ctr", "span", "span"]
    ctr = rec.records()[0]
    assert ctr[1] == "w.ctr" and ctr[2] == 3
    inner, outer = rec.records()[1], rec.records()[2]
    assert inner[1] == "w.inner" and inner[6] == 1  # depth under outer
    assert outer[1] == "w.outer" and outer[6] == 0
    assert not inner[7] and not outer[7]  # no error flag


def test_flight_ring_is_bounded(fresh_recorder):
    rec = fresh_recorder
    for i in range(rec.cap * 2):
        profiling.incr_counter("w.ring", 1)
    recs = rec.records()
    assert len(recs) == rec.cap  # bounded
    assert rec.event_count() == rec.cap * 2  # lifetime count keeps going
    # oldest half overwritten: the surviving totals are the most recent
    assert recs[0][3] == rec.cap + 1 and recs[-1][3] == rec.cap * 2


def test_open_spans_and_innermost_cross_thread(fresh_recorder):
    """The recorder answers 'where is thread X right now' — the question a
    hang poses — from any other thread."""
    rec = fresh_recorder
    entered, release = threading.Event(), threading.Event()

    def wedged():
        with profiling.span("w.fit"):
            with profiling.span("w.fit.collective"):
                entered.set()
                release.wait(10.0)

    th = threading.Thread(target=wedged, name="w-wedged")
    th.start()
    try:
        assert entered.wait(10.0)
        spans = {name: stack for name, stack in rec.open_spans().values()}
        assert spans.get("w-wedged") == ["w.fit", "w.fit.collective"]
        assert rec.innermost(th.ident) == "w.fit.collective"
        assert rec.progress(th.ident) == 0  # nothing closed: wedged
    finally:
        release.set()
        th.join()
    assert rec.progress(th.ident) == 2


def test_ring_cap_clamps_to_one_never_crashes():
    """A zero/negative SRML_WATCH_RING must degrade to a tiny ring, never
    to IndexError inside the spans/counters the recorder watches."""
    rec = watch.FlightRecorder(cap=0)
    assert rec.cap == 1
    prev = profiling._flight
    profiling._flight = rec
    try:
        with profiling.span("w.tiny"):
            profiling.incr_counter("w.tiny.ctr")
    finally:
        profiling._flight = prev
    assert rec.event_count() == 2 and len(rec.records()) == 1


def test_recorder_installs_regardless_of_import_order():
    """Importing watch BEFORE profiling (a monitoring sidecar's natural
    first touch) must still leave the recorder installed — the circular
    bootstrap degrades on the partial module, and watch's own bottom
    install() covers it."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-c",
         "import spark_rapids_ml_tpu.watch as w; "
         "from spark_rapids_ml_tpu import profiling; "
         "assert w.recorder() is not None; "
         "assert profiling._flight is w.recorder(); "
         "print('installed')"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "installed" in out.stdout


def test_disabled_recorder_restores_the_zero_hook_path(monkeypatch):
    monkeypatch.setattr(profiling, "_flight", None)
    with profiling.span("w.off"):
        profiling.incr_counter("w.off.ctr")
    # nothing to assert beyond "no crash": with _flight None the span path
    # is byte-for-byte the pre-watch branch (see also the overhead gate)
    assert profiling._flight is None


# -- induced exception: flight dump -------------------------------------------


def test_induced_exception_dumps_flight_with_failing_span_last(
    tmp_path, monkeypatch
):
    """A fit task that raises must leave a Perfetto-loadable flight dump
    whose final event is the exception instant naming the innermost
    failing span (the ISSUE acceptance gate)."""
    from spark_rapids_ml_tpu import KMeans
    from spark_rapids_ml_tpu.dataframe import DataFrame

    monkeypatch.setenv(profiling.TRACE_ENV, str(tmp_path))

    def failing_fit(inputs, params):
        with profiling.span("fit.prep"):
            pass
        with profiling.span("fit.boom"):
            raise ValueError("induced failure")

    X = np.random.default_rng(0).standard_normal((64, 4)).astype(np.float32)
    est = KMeans(k=2, maxIter=2).setFeaturesCol("features")
    est._get_tpu_fit_func = lambda df, extra_params=None: failing_fit
    with pytest.raises(ValueError, match="induced failure"):
        est.fit(DataFrame.from_numpy(X, feature_layout="array"))

    dumps = glob.glob(str(tmp_path / "flight-fit-KMeans-*.json"))
    assert dumps, "no flight dump written"
    doc = json.load(open(dumps[0]))
    events = doc["traceEvents"]
    # Perfetto-loadable: complete events carry the ts/dur/pid/tid contract
    complete = [e for e in events if e.get("ph") == "X"]
    assert complete
    for e in complete:
        assert set(e) >= {"name", "ts", "dur", "pid", "tid", "args"}
    names = {e["name"] for e in complete}
    assert {"fit.prep", "fit.boom", "srml.fit"} <= names
    errored = {e["name"] for e in complete if e["args"].get("error")}
    assert "fit.boom" in errored and "fit.prep" not in errored
    # the FINAL event is the exception, naming the innermost failing span
    last = events[-1]
    assert last["ph"] == "i" and last["name"] == "exception"
    assert last["args"]["failing_span"] == "fit.boom"
    assert last["args"]["type"] == "ValueError"


def test_flight_dump_noop_without_trace_dir(monkeypatch):
    monkeypatch.delenv(profiling.TRACE_ENV, raising=False)
    assert watch.dump("nowhere") is None


# -- induced hang: heartbeats + stall watchdog --------------------------------


def _rank_plane(root, rank, nranks=2):
    from spark_rapids_ml_tpu.parallel.runner import FileControlPlane

    return FileControlPlane(str(root), rank, nranks, timeout=30)


def test_control_plane_health_surface_is_non_collective(tmp_path):
    """publish_health/read_health never block and never consume gather
    rounds — rank 1 can read rank 0's payload without rank 0 waiting."""
    cp0 = _rank_plane(tmp_path, 0)
    cp1 = _rank_plane(tmp_path, 1)
    cp0.publish_health('{"rank": 0, "progress": 7}')
    assert json.loads(cp1.read_health()[0])["progress"] == 7
    assert 1 not in cp1.read_health()  # rank 1 never published
    cp0.publish_health('{"rank": 0, "progress": 8}')  # overwrite, not append
    assert json.loads(cp1.read_health()[0])["progress"] == 8


def test_local_control_plane_health_surface():
    from spark_rapids_ml_tpu.parallel.context import LocalControlPlane

    cp = LocalControlPlane()
    cp.publish_health(json.dumps({"rank": 0, "progress": 1}))
    assert json.loads(cp.read_health()[0])["progress"] == 1


def test_induced_hang_watchdog_names_stuck_rank_and_innermost_span(tmp_path):
    """Two thread-mocked ranks fit over a FileControlPlane; rank 1 wedges
    inside a span.  The watchdog must report rank 1 BY NAME with the
    innermost open span it is stuck in — and must NOT flag rank 0, whose
    fit keeps making progress (the ISSUE acceptance gate)."""
    assert watch.recorder() is not None, "flight recorder must be on"
    done, blocked_entered, release = (
        threading.Event(), threading.Event(), threading.Event(),
    )

    def rank0():
        cp = _rank_plane(tmp_path, 0)
        hb = watch.HeartbeatPublisher(cp, 0, interval_s=0.05)
        try:
            while not done.wait(0.01):  # keeps closing spans: alive
                with profiling.span("fit.work"):
                    pass
        finally:
            hb.stop()

    def rank1():
        cp = _rank_plane(tmp_path, 1)
        hb = watch.HeartbeatPublisher(cp, 1, interval_s=0.05)
        try:
            with profiling.span("runner.fit"):
                with profiling.span("fit.wedge.block"):
                    blocked_entered.set()
                    release.wait(30.0)  # the induced hang
        finally:
            hb.stop()

    threads = [
        threading.Thread(target=rank0, name="w-rank0"),
        threading.Thread(target=rank1, name="w-rank1"),
    ]
    for t in threads:
        t.start()
    dog = None
    try:
        assert blocked_entered.wait(10.0)
        reports = []
        dog = watch.StallWatchdog(
            _rank_plane(tmp_path, 0), nranks=2, stall_s=0.5, poll_s=0.1,
            on_stall=reports.append,
        )
        deadline = time.monotonic() + 15.0
        while not reports and time.monotonic() < deadline:
            time.sleep(0.05)
        assert reports, "watchdog never fired on the wedged rank"
        assert reports[0]["rank"] == 1
        assert reports[0]["span"] == "fit.wedge.block"
        assert reports[0]["reason"] == "progress frozen"
        # rank 0 keeps progressing: one stall episode, one report
        time.sleep(0.4)
        assert all(r["rank"] == 1 for r in dog.reports), dog.reports
        assert profiling.counter("watch.stalls") >= 1
    finally:
        if dog is not None:
            dog.stop()
        done.set()
        release.set()
        for t in threads:
            t.join(timeout=10.0)


def test_start_fit_health_noops_when_unsupported():
    class GatherOnlyPlane:  # live Spark's BarrierTaskContext shape
        def allGather(self, message):
            return [message]

        def barrier(self):
            return None

    h = watch.start_fit_health(GatherOnlyPlane(), rank=0, nranks=2)
    assert h.publisher is None and h.watchdog is None
    h.stop()  # must be safe
    h1 = watch.start_fit_health(object(), rank=0, nranks=1)
    assert h1.publisher is None
    h1.stop()


# -- overhead guard -----------------------------------------------------------


def test_always_on_recording_overhead_under_2pct_of_warm_fit():
    """The <2% gate, measured structurally: (per-event recorder cost) x
    (events a warm kmeans fit generates) must stay under 2% of the warm
    fit's wall clock.  Per-event cost is the on-vs-off difference of a
    span microbenchmark — this bounds the recorder's ADDED cost without
    racing two full fits against wall-clock noise."""
    from spark_rapids_ml_tpu import KMeans
    from spark_rapids_ml_tpu.dataframe import DataFrame

    rec = watch.recorder()
    assert rec is not None

    N = 20000

    def span_bench():
        t0 = profiling.now()
        for _ in range(N):
            with profiling.span("w.ovh"):
                pass
        return (profiling.now() - t0) / N

    on = min(span_bench() for _ in range(3))
    try:
        watch.disable()
        off = min(span_bench() for _ in range(3))
    finally:
        watch.enable()
    per_event = max(on - off, 0.0)

    X = np.random.default_rng(1).standard_normal((256, 8)).astype(np.float32)
    df = DataFrame.from_numpy(X, feature_layout="array")
    est = KMeans(k=3, maxIter=4, seed=1).setFeaturesCol("features")
    est.fit(df)  # warm-up: compiles + staging out of the clock
    events0 = watch.recorder().event_count()
    t0 = profiling.now()
    est.fit(df)
    fit_s = profiling.now() - t0
    events = watch.recorder().event_count() - events0
    assert events > 0, "a fit must feed the flight ring"
    added = events * per_event
    assert added < 0.02 * fit_s, (
        f"always-on recording adds {added * 1e3:.3f} ms over {events} events "
        f"to a {fit_s * 1e3:.1f} ms warm fit "
        f"({100 * added / fit_s:.2f}% > 2%)"
    )


# -- device-memory accounting -------------------------------------------------


def test_phase_memory_attribution_with_injected_sampler(fresh_recorder):
    rec = fresh_recorder
    # fake backend: in_use grows inside the span, peak follows
    samples = iter([(100.0, 100.0), (150.0, 400.0)])
    rec.set_memory_sampler(lambda: next(samples, (150.0, 400.0)))
    with profiling.span("w.mem.phase"):
        pass
    mem = rec.phase_memory()
    assert mem["w.mem.phase"]["count"] == 1
    assert mem["w.mem.phase"]["peak_bytes"] == 400.0
    assert mem["w.mem.phase"]["sum_delta_bytes"] == 300.0  # peak - entry
    telem = rec.telemetry_memory()
    assert telem["mem.phase.w.mem.phase"]["peak_bytes"] == 400.0
    assert "mem.host" in telem  # RSS watermark always available


def test_telemetry_snapshot_carries_and_merges_memory(fresh_recorder):
    rec = fresh_recorder
    rec.set_memory_sampler(lambda: (10.0, 20.0))
    with profiling.span("w.mem.fit"):
        pass
    profiling.reset_phase_times()
    snap = profiling.TelemetrySnapshot.capture(rank=0)
    assert "mem.phase.w.mem.fit" in snap.memory
    a = profiling.TelemetrySnapshot(
        memory={"mem.hbm": {"count": 1, "peak_bytes": 70.0,
                            "sum_delta_bytes": 30.0}},
        meta={"ranks": [0]},
    )
    b = profiling.TelemetrySnapshot(
        memory={"mem.hbm": {"count": 2, "peak_bytes": 50.0,
                            "sum_delta_bytes": 25.0}},
        meta={"ranks": [1]},
    )
    m = a.merge(b)
    # watermark algebra: counts sum, peaks MAX (worst rank), deltas sum
    assert m.memory["mem.hbm"] == {
        "count": 3, "peak_bytes": 70.0, "sum_delta_bytes": 55.0,
    }
    assert a.merge(b) == b.merge(a)
    rt = profiling.TelemetrySnapshot.from_dict(
        json.loads(json.dumps(m.to_dict()))
    )
    assert rt == m  # memory survives the Spark wire


def test_executable_cache_stats_shape():
    from spark_rapids_ml_tpu.ops import precompile

    stats = precompile.executable_cache_stats()
    assert set(stats) == {"entries", "in_flight", "est_code_bytes", "kernels"}
    assert stats["entries"] >= 0
    for name, k in stats["kernels"].items():
        assert isinstance(name, str)
        assert k["entries"] >= 1
        assert isinstance(k["bucket_geometries"], list)


# -- health surface: serving states + SLO + gauges ----------------------------


def test_server_lifecycle_states_and_slo_health(model_zoo, monkeypatch):
    from spark_rapids_ml_tpu.serving import DRAINING, READY, ModelServer

    model, X = model_zoo("kmeans")
    with ModelServer("w_km", model, max_batch=16, max_wait_ms=1) as srv:
        assert srv.state() == READY
        for i in range(8):
            srv.predict(X[i])
        # generous SLO: everything attains
        monkeypatch.setenv("SRML_SERVE_SLO_MS", "60000")
        h = srv.health()
        assert h["state"] == READY
        assert h["attainment"] == 1.0 and h["burn"] == 0.0
        assert h["window_count"] >= 8 and h["p99_ms"] is not None
        # impossible SLO: full burn -> DEGRADED (state stays READY inside;
        # DEGRADED is an SLO verdict, not a lifecycle transition)
        monkeypatch.setenv("SRML_SERVE_SLO_MS", "0.000001")
        h = srv.health()
        assert h["state"] == "DEGRADED" and h["burn"] > 0.9
        # no SLO configured: vacuous attainment
        monkeypatch.delenv("SRML_SERVE_SLO_MS")
        assert srv.health()["attainment"] == 1.0
        srv.drain()
        assert srv.state() == DRAINING


def test_wedged_server_flips_unhealthy_and_sheds_then_recovers(
    model_zoo, monkeypatch
):
    """The PASSIVE wedge contract (pre-shield behavior, still the policy
    when the restart budget is zero): UNHEALTHY + shed while wedged, lazy
    recovery when the blocked dispatch finally returns.
    SRML_SERVE_MAX_RESTARTS=0 pins it; the ACTING watchdog (supersede +
    supervised restart) is gated in test_serving.py."""
    from spark_rapids_ml_tpu.serving import (
        READY,
        UNHEALTHY,
        ModelServer,
        ServerUnhealthy,
    )

    monkeypatch.setenv("SRML_SERVE_MAX_RESTARTS", "0")
    model, X = model_zoo("kmeans")
    srv = ModelServer("w_wedge", model, max_batch=16, max_wait_ms=1)
    try:
        release = threading.Event()
        real_call = srv._entry.call

        def wedged_call(batch):
            release.wait(30.0)
            return real_call(batch)

        srv._entry.call = wedged_call
        monkeypatch.setenv("SRML_WATCH_STALL_S", "0.2")
        fut = srv.submit(X[0])  # the worker blocks inside this dispatch
        deadline = time.monotonic() + 10.0
        while srv.state() != UNHEALTHY and time.monotonic() < deadline:
            time.sleep(0.05)
        assert srv.state() == UNHEALTHY
        with pytest.raises(ServerUnhealthy):  # shed, don't queue
            srv.submit(X[1])
        assert profiling.counter("serving.w_wedge.unhealthy") >= 1
        release.set()  # the dispatch comes back: recover
        assert fut.result(timeout=30.0)
        deadline = time.monotonic() + 10.0
        while srv.state() != READY and time.monotonic() < deadline:
            time.sleep(0.05)
        assert srv.state() == READY
        assert profiling.counter("serving.w_wedge.recovered") >= 1
    finally:
        release.set()
        monkeypatch.setenv("SRML_WATCH_STALL_S", "0")
        srv.shutdown(drain=False)


def test_registry_health_rolls_up_worst_state(model_zoo):
    from spark_rapids_ml_tpu.serving import ModelRegistry

    model, X = model_zoo("kmeans")
    with ModelRegistry(max_batch=16, max_wait_ms=1) as reg:
        reg.register("w_a", model)
        reg.get("w_a").predict(X[0])
        h = reg.health()
        assert h["state"] == "READY"
        assert h["models"]["w_a"]["state"] == "READY"
        assert h["models"]["w_a"]["attainment"] >= 0
    assert ModelRegistry().health()["state"] == "WARMING"  # empty = idle


def test_health_and_memory_round_trip_export_and_prometheus(model_zoo):
    """The CI acceptance gate in unit form: ModelRegistry.health() + memory
    watermarks flow through export_metrics() (JSON round-trip) and
    render_prometheus() (srml_health / srml_memory_bytes families)."""
    from spark_rapids_ml_tpu.serving import ModelRegistry

    model, X = model_zoo("kmeans")
    with ModelRegistry(max_batch=16, max_wait_ms=1) as reg:
        reg.register("w_rt", model)
        reg.get("w_rt").predict(X[0])
        m = profiling.export_metrics()
        assert json.loads(json.dumps(m)) == m
        g = m["gauges"]
        assert g["health.w_rt.state_code"] == 1.0  # READY
        assert g["health.w_rt.attainment"] >= 0.0
        assert any(k.startswith("mem.host.") for k in g)
        txt = profiling.render_prometheus(m)
        assert "# TYPE srml_health gauge" in txt
        assert "# TYPE srml_memory_bytes gauge" in txt
        assert 'srml_health{name="health.w_rt.state_code"} 1.0' in txt
    # shutdown unregisters the provider: the registry's gauges disappear
    assert not any(
        k.startswith("health.w_rt.")
        for k in profiling.export_metrics()["gauges"]
    )


def test_ring_stats_self_description():
    stats = watch.ring_stats()
    assert stats["enabled"] is True
    assert stats["capacity"] > 0 and stats["events"] >= 0
    assert isinstance(stats["open_spans"], dict)
