# srml-pq IVF-PQ engine contracts (ann/pq.py + ops/pallas_pq.py + the
# ApproximateNearestNeighbors ivfpq tier): the ADC LUT-accumulation kernel
# EXACT against a numpy oracle in interpret mode, the encode/decode
# round-trip against a numpy argmin/reconstruction oracle (error monotone
# in m_sub), refined recall@10 >= 0.9 vs exact kneighbors at the documented
# defaults (the acceptance gate), BITWISE 1-dev-vs-8-dev parity of probed
# AND refined results, zero-new-compile repeat/warmed searches, the
# k>n / empty-list / -1-sentinel edges the IVF-Flat suite gates, and the
# ivfpq model param surface.
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu import ApproximateNearestNeighbors, profiling
from spark_rapids_ml_tpu.ann.ivfflat import recall_at_k
from spark_rapids_ml_tpu.ann.pq import (
    DEFAULT_N_BITS,
    build_ivfpq_packed,
    default_m_sub,
    index_from_packed_pq,
    ivfpq_search_prepared,
    pq_geometry,
    reconstruct,
    warm_pq_probe_kernels,
)
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.ops.knn import knn_search_prepared, prepare_items
from spark_rapids_ml_tpu.ops.pallas_pq import (
    _lut_accumulate_pallas,
    lut_accumulate,
)
from spark_rapids_ml_tpu.parallel.mesh import get_mesh


def _clustered(n=2500, d=16, n_blobs=24, seed=0):
    rng = np.random.default_rng(seed)
    centers = 20.0 * rng.normal(size=(n_blobs, d))
    lab = rng.integers(0, n_blobs, size=n)
    X = (centers[lab] + rng.normal(size=(n, d))).astype(np.float32)
    ids = np.arange(n, dtype=np.int64) * 7 + 3  # non-contiguous user ids
    return X, ids


@pytest.fixture(scope="module")
def pq_setup():
    """ONE shared build at the DOCUMENTED defaults (default_m_sub,
    n_bits=8, default nlist) on clustered data — the recall, parity, and
    zero-compile gates all score the same index, so the m_sub*ksub
    codebook training cost is paid once per test session."""
    from spark_rapids_ml_tpu.ann.ivfflat import default_nlist

    X, ids = _clustered()
    nlist = default_nlist(X.shape[0])  # 50 at n=2500
    packed = build_ivfpq_packed(
        X, ids, nlist, m_sub=default_m_sub(X.shape[1]),
        n_bits=DEFAULT_N_BITS, seed=1,
    )
    return X, ids, packed


# -- the ADC LUT kernel (interpret mode, exact) -------------------------------


def test_lut_kernel_matches_numpy_adc_oracle():
    """out[b, r] = sum_j T[b, j, codes[b, r, j]] with SEQUENTIAL f32
    accumulation over j — the kernel's select-sum gather is exact (one
    nonzero lane per compare tile), so interpret mode must equal the
    oracle bit for bit, on aligned and ragged row counts and at sub-256
    table widths (n_bits < 8)."""
    rng = np.random.default_rng(5)
    cases = []
    for B, R, m_sub, ksub in [(3, 700, 4, 16), (1, 512, 2, 256), (2, 33, 8, 5)]:
        T = rng.standard_normal((B, m_sub, ksub)).astype(np.float32)
        C = rng.integers(0, ksub, size=(B, R, m_sub)).astype(np.uint8)
        want = np.zeros((B, R), np.float32)
        for j in range(m_sub):
            want += np.take_along_axis(
                T[:, j, :], C[:, :, j].astype(np.int64), axis=1
            )
        cases.append(
            (
                (B, R, m_sub, ksub),
                want,
                _lut_accumulate_pallas(
                    jnp.asarray(T), jnp.asarray(C), interpret=True
                ),
                # the routed entry (XLA on this backend) computes the same
                # sum to float tolerance — the route is per-backend, never
                # per-mesh, so this is a formulation check, not parity
                lut_accumulate(jnp.asarray(T), jnp.asarray(C)),
            )
        )
    fetched = jax.device_get([(p, x) for *_a, p, x in cases])  # ONE fetch
    for (shape, want, *_h), (got, got_xla) in zip(cases, fetched):
        np.testing.assert_array_equal(got, want, err_msg=f"{shape}")
        np.testing.assert_allclose(got_xla, want, rtol=1e-6, atol=1e-6)


# -- encode / decode round-trip -----------------------------------------------


def test_encode_matches_numpy_argmin_oracle():
    """Per-subspace codes must pick each residual's nearest codeword (the
    fused distance+argmin kernel vs a numpy expanded-form oracle; a >=
    99.9%% match bar absorbs low-bit argmin ties on near-equidistant
    codewords, which both sides resolve arbitrarily)."""
    X, ids = _clustered(n=600, d=8, n_blobs=8, seed=3)
    packed = build_ivfpq_packed(X, ids, 8, m_sub=2, n_bits=4, seed=2)
    m_sub, dsub, d_pad = pq_geometry(packed.dim, packed.m_sub)
    # residuals of the PACKED (list-sorted) items against their coarse cell
    row_list = np.repeat(np.arange(packed.counts.shape[0]), packed.counts)
    cpad = np.zeros((packed.centroids.shape[0], d_pad), np.float32)
    cpad[:, : packed.dim] = packed.centroids
    res = np.zeros((packed.items.shape[0], d_pad), np.float32)
    res[:, : packed.dim] = packed.items
    res -= cpad[row_list]
    match = 0
    for j in range(m_sub):
        rj = res[:, j * dsub : (j + 1) * dsub]
        cb = packed.codebooks[j]
        d2 = (
            (rj**2).sum(1)[:, None]
            - 2.0 * rj @ cb.T
            + (cb**2).sum(1)[None, :]
        )
        match += (np.argmin(d2, axis=1) == packed.codes[:, j]).sum()
    assert match / (res.shape[0] * m_sub) >= 0.999


def test_reconstruction_error_monotone_in_m_sub():
    """Decode round-trip: reconstruction MSE must shrink as m_sub grows
    (more codes per item = finer residual quantization) and always beat
    the coarse-only reconstruction."""
    X, ids = _clustered(n=800, d=8, n_blobs=6, seed=4)
    errs = []
    for m_sub in (1, 2, 4):
        packed = build_ivfpq_packed(X, ids, 6, m_sub=m_sub, n_bits=4, seed=5)
        rec = reconstruct(packed)
        errs.append(float(np.mean((rec - packed.items) ** 2)))
        # coarse-only error: residual variance around the assigned centroid
        row_list = np.repeat(np.arange(packed.counts.shape[0]), packed.counts)
        coarse = float(
            np.mean((packed.items - packed.centroids[row_list]) ** 2)
        )
        assert errs[-1] < coarse, (m_sub, errs[-1], coarse)
    assert errs[0] > errs[1] > errs[2], errs


# -- the acceptance gates ------------------------------------------------------


def test_refined_recall_at_10(pq_setup):
    """Acceptance: refined recall@10 >= 0.9 vs the exact kneighbors path at
    the DOCUMENTED defaults (default_m_sub, n_bits=8, nprobe=nlist/4,
    refine_ratio=4) on clustered data; raw ADC recall is reported-but-lower
    (quantization error), refine must not lose recall."""
    from spark_rapids_ml_tpu.ann.ivfflat import default_nprobe

    X, ids, packed = pq_setup
    mesh = get_mesh()
    nprobe = default_nprobe(packed.counts.shape[0])
    index = index_from_packed_pq(packed, mesh)
    Q = X[:512]
    _, i_raw = ivfpq_search_prepared(index, Q, 10, nprobe, mesh)
    d_ref, i_ref = ivfpq_search_prepared(
        index, Q, 10, nprobe, mesh, refine_items=packed.items, refine_ratio=4
    )
    prepared = prepare_items(X, ids, mesh)
    _, i_exact = knn_search_prepared(prepared, Q, 10, mesh)
    r_raw = recall_at_k(i_raw, i_exact)
    r_ref = recall_at_k(i_ref, i_exact)
    assert r_ref >= 0.9, (r_ref, r_raw)
    assert r_ref >= r_raw, (r_ref, r_raw)
    # refined distances are true f32 euclidean: ascending, self leads
    assert np.all(np.diff(d_ref, axis=1) >= 0)
    assert np.mean(i_ref[:, 0] == ids[:512]) >= 0.95


def test_mesh_parity_bitwise(pq_setup):
    """Acceptance: probed ADC results AND refined results are BITWISE
    identical on a 1-device and an 8-device mesh (the flat kernel's
    lex/merge helpers are reused verbatim; refine is deterministic host
    math over the already-identical candidate set)."""
    X, ids, packed = pq_setup
    Q = X[:300]
    out = {}
    for name, mesh in (("one", get_mesh(1)), ("all", get_mesh())):
        index = index_from_packed_pq(packed, mesh)
        out[name] = (
            ivfpq_search_prepared(index, Q, 10, 6, mesh),
            ivfpq_search_prepared(
                index, Q, 10, 6, mesh,
                refine_items=packed.items, refine_ratio=3,
            ),
        )
    for arm in (0, 1):
        d1, i1 = out["one"][arm]
        d8, i8 = out["all"][arm]
        np.testing.assert_array_equal(i1, i8)
        np.testing.assert_array_equal(
            d1.astype(np.float32).view(np.uint32),
            d8.astype(np.float32).view(np.uint32),
        )


def test_repeat_and_warm_zero_new_compiles(pq_setup):
    """Acceptance: a repeat same-shape probed PQ search performs ZERO new
    compilations, and warm_pq_probe_kernels submits the EXACT executable
    the dispatch looks up (fresh query-block geometry, straight aot_hit)."""
    from spark_rapids_ml_tpu.ops.precompile import global_precompiler

    X, ids, packed = pq_setup
    mesh = get_mesh()
    index = index_from_packed_pq(packed, mesh)
    kw = dict(refine_items=packed.items, refine_ratio=2)
    ivfpq_search_prepared(index, X[:200], 5, 4, mesh, **kw)  # compiles once
    before = profiling.counters("precompile.")
    d1, i1 = ivfpq_search_prepared(index, X[:200], 5, 4, mesh, **kw)
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.fallback", 0) == 0, delta
    assert delta.get("precompile.aot_hit", 0) >= 1, delta
    d2, i2 = ivfpq_search_prepared(index, X[:200], 5, 4, mesh, **kw)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_array_equal(d1, d2)
    # warm at a geometry no search has touched (k=7 > any dispatched k)
    keys = warm_pq_probe_kernels(
        index, 7, 4, mesh, n_queries=200, refine=True, refine_ratio=2
    )
    assert keys
    global_precompiler().wait(keys)
    before = profiling.counters("precompile.")
    ivfpq_search_prepared(index, X[:200], 7, 4, mesh, **kw)
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.aot_miss", 0) == 0, delta


def test_compression_vs_flat_index(pq_setup):
    """The memory headline: at this geometry the staged PQ index must sit
    far below the flat index per item (>= 8x is the CI bar at d >= 256;
    even at d=16 the code layout wins by ~2x, asserted here so the
    device_bytes accounting itself is gated in tier-1)."""
    from spark_rapids_ml_tpu.ann.ivfflat import (
        build_ivfflat_packed,
        index_from_packed,
    )

    X, ids, packed = pq_setup
    mesh = get_mesh()
    pq_bytes = index_from_packed_pq(packed, mesh).device_bytes()
    flat = build_ivfflat_packed(X, ids, packed.counts.shape[0], seed=1)
    flat_bytes = index_from_packed(flat, mesh).device_bytes()
    n = packed.n_items
    assert pq_bytes / n < (flat_bytes / n) / 2.0, (pq_bytes / n, flat_bytes / n)


# -- edges ---------------------------------------------------------------------


def test_unfillable_slots_and_empty_lists():
    """k beyond the probed pool yields the -1/inf sentinel contract on BOTH
    the raw and the refined route; empty coarse lists (nlist > occupied
    cells) and k > n_items are absorbed the same way the flat engine's
    suite gates."""
    rng = np.random.default_rng(0)
    X = np.concatenate(
        [
            rng.normal(size=(16, 4)).astype(np.float32),
            (100.0 + rng.normal(size=(16, 4))).astype(np.float32),
        ]
    )
    ids = np.arange(32, dtype=np.int64)
    mesh = get_mesh()
    # nlist=8 over two far blobs leaves most lists nearly/fully empty
    packed = build_ivfpq_packed(X, ids, 8, m_sub=2, n_bits=4, seed=5)
    index = index_from_packed_pq(packed, mesh)
    for kw in (
        {},
        {"refine_items": packed.items, "refine_ratio": 2},
    ):
        d, i = ivfpq_search_prepared(index, X[:4], 30, 1, mesh, **kw)
        assert d.shape == (4, 30) and i.shape == (4, 30)
        assert (i == -1).any()
        assert np.all(np.isinf(d[i == -1]))
        assert np.all(i[:, 0] >= 0)
    # k > n_items clamps to k_eff, full coverage probing everything
    d, i = ivfpq_search_prepared(
        index, X[:4], 64, index.nlist_pad, mesh,
        refine_items=packed.items, refine_ratio=2,
    )
    assert d.shape == (4, 32) and i.shape == (4, 32)
    assert np.all(i >= 0)


# -- model surface -------------------------------------------------------------


def test_model_pq_param_surface():
    X, _ = _clustered(n=120, d=6, n_blobs=4, seed=7)
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=1)
    with pytest.raises(ValueError, match="unknown algoParams"):
        ApproximateNearestNeighbors(
            algorithm="ivfpq", algoParams={"M": 2, "nbits": 4}
        ).setFeaturesCol("features").fit(df)
    with pytest.raises(ValueError, match="n_bits"):
        ApproximateNearestNeighbors(
            algorithm="ivfpq", algoParams={"n_bits": 11}
        ).setFeaturesCol("features").fit(df)
    # M is an ivfpq-only key: the flat tier must reject it loudly
    with pytest.raises(ValueError, match="unknown algoParams"):
        ApproximateNearestNeighbors(
            algorithm="ivfflat", algoParams={"M": 2}
        ).setFeaturesCol("features").fit(df)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        model = ApproximateNearestNeighbors(
            k=3,
            algorithm="ivfpq",
            algoParams={
                "nlist": 4, "nprobe": 4, "M": 2, "n_bits": 4,
                "usePrecomputedTables": True,
            },
        ).setFeaturesCol("features").fit(df)
        assert any(
            "usePrecomputedTables" in str(w.message) for w in caught
        ), [str(w.message) for w in caught]
    _, _, knn_df = model.kneighbors(
        DataFrame.from_numpy(X[:5], num_partitions=1)
    )
    ids = np.concatenate(
        [np.asarray(list(p["indices"])) for p in knn_df.partitions if len(p)]
    )
    assert ids.shape == (5, 3)
    # probed self-match leads every row after refine
    np.testing.assert_array_equal(ids[:, 0], np.arange(5))
    # a flat-fit model has no PQ payload to stage
    flat = ApproximateNearestNeighbors(
        k=3, algoParams={"nlist": 4, "nprobe": 4}
    ).setFeaturesCol("features").fit(df)
    with pytest.raises(ValueError, match="no PQ payload"):
        flat._packed_pq()


def test_default_m_sub_geometry():
    assert default_m_sub(256) == 32   # the ~32x operating point
    assert default_m_sub(3000) == 64  # clamped
    assert default_m_sub(5) == 4
    assert pq_geometry(5, 4) == (4, 2, 8)    # pow2-padded subspaces
    assert pq_geometry(256, 32) == (32, 8, 256)
    assert pq_geometry(16, 64) == (16, 1, 16)  # m_sub clamped to dim
