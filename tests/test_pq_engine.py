# srml-pq IVF-PQ engine contracts (ann/pq.py + ops/pallas_pq.py + the
# ApproximateNearestNeighbors ivfpq tier): the ADC LUT-accumulation kernel
# EXACT against a numpy oracle in interpret mode, the encode/decode
# round-trip against a numpy argmin/reconstruction oracle (error monotone
# in m_sub), refined recall@10 >= 0.9 vs exact kneighbors at the documented
# defaults (the acceptance gate), BITWISE 1-dev-vs-8-dev parity of probed
# AND refined results, zero-new-compile repeat/warmed searches, the
# k>n / empty-list / -1-sentinel edges the IVF-Flat suite gates, and the
# ivfpq model param surface.
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu import ApproximateNearestNeighbors, profiling
from spark_rapids_ml_tpu.ann.ivfflat import recall_at_k
from spark_rapids_ml_tpu.ann.pq import (
    DEFAULT_N_BITS,
    build_ivfpq_packed,
    default_m_sub,
    index_from_packed_pq,
    ivfpq_search_prepared,
    pq_geometry,
    reconstruct,
    warm_pq_probe_kernels,
)
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.ops.knn import knn_search_prepared, prepare_items
from spark_rapids_ml_tpu.ops.pallas_pq import (
    _lut_accumulate_pallas,
    lut_accumulate,
)
from spark_rapids_ml_tpu.parallel.mesh import get_mesh


def _clustered(n=2500, d=16, n_blobs=24, seed=0):
    rng = np.random.default_rng(seed)
    centers = 20.0 * rng.normal(size=(n_blobs, d))
    lab = rng.integers(0, n_blobs, size=n)
    X = (centers[lab] + rng.normal(size=(n, d))).astype(np.float32)
    ids = np.arange(n, dtype=np.int64) * 7 + 3  # non-contiguous user ids
    return X, ids


@pytest.fixture(scope="module")
def pq_setup():
    """ONE shared build at the DOCUMENTED defaults (default_m_sub,
    n_bits=8, default nlist) on clustered data — the recall, parity, and
    zero-compile gates all score the same index, so the m_sub*ksub
    codebook training cost is paid once per test session."""
    from spark_rapids_ml_tpu.ann.ivfflat import default_nlist

    X, ids = _clustered()
    nlist = default_nlist(X.shape[0])  # 50 at n=2500
    packed = build_ivfpq_packed(
        X, ids, nlist, m_sub=default_m_sub(X.shape[1]),
        n_bits=DEFAULT_N_BITS, seed=1,
    )
    return X, ids, packed


# -- the ADC LUT kernel (interpret mode, exact) -------------------------------


def test_lut_kernel_matches_numpy_adc_oracle():
    """out[b, r] = sum_j T[b, j, codes[b, r, j]] with SEQUENTIAL f32
    accumulation over j — the kernel's select-sum gather is exact (one
    nonzero lane per compare tile), so interpret mode must equal the
    oracle bit for bit, on aligned and ragged row counts and at sub-256
    table widths (n_bits < 8)."""
    rng = np.random.default_rng(5)
    cases = []
    for B, R, m_sub, ksub in [(3, 700, 4, 16), (1, 512, 2, 256), (2, 33, 8, 5)]:
        T = rng.standard_normal((B, m_sub, ksub)).astype(np.float32)
        C = rng.integers(0, ksub, size=(B, R, m_sub)).astype(np.uint8)
        want = np.zeros((B, R), np.float32)
        for j in range(m_sub):
            want += np.take_along_axis(
                T[:, j, :], C[:, :, j].astype(np.int64), axis=1
            )
        cases.append(
            (
                (B, R, m_sub, ksub),
                want,
                _lut_accumulate_pallas(
                    jnp.asarray(T), jnp.asarray(C), interpret=True
                ),
                # the routed entry (XLA on this backend) computes the same
                # sum to float tolerance — the route is per-backend, never
                # per-mesh, so this is a formulation check, not parity
                lut_accumulate(jnp.asarray(T), jnp.asarray(C)),
            )
        )
    fetched = jax.device_get([(p, x) for *_a, p, x in cases])  # ONE fetch
    for (shape, want, *_h), (got, got_xla) in zip(cases, fetched):
        np.testing.assert_array_equal(got, want, err_msg=f"{shape}")
        np.testing.assert_allclose(got_xla, want, rtol=1e-6, atol=1e-6)


# -- encode / decode round-trip -----------------------------------------------


def test_encode_matches_numpy_argmin_oracle():
    """Per-subspace codes must pick each residual's nearest codeword (the
    fused distance+argmin kernel vs a numpy expanded-form oracle; a >=
    99.9%% match bar absorbs low-bit argmin ties on near-equidistant
    codewords, which both sides resolve arbitrarily)."""
    X, ids = _clustered(n=600, d=8, n_blobs=8, seed=3)
    packed = build_ivfpq_packed(X, ids, 8, m_sub=2, n_bits=4, seed=2)
    m_sub, dsub, d_pad = pq_geometry(packed.dim, packed.m_sub)
    # residuals of the PACKED (list-sorted) items against their coarse cell
    row_list = np.repeat(np.arange(packed.counts.shape[0]), packed.counts)
    cpad = np.zeros((packed.centroids.shape[0], d_pad), np.float32)
    cpad[:, : packed.dim] = packed.centroids
    res = np.zeros((packed.items.shape[0], d_pad), np.float32)
    res[:, : packed.dim] = packed.items
    res -= cpad[row_list]
    match = 0
    for j in range(m_sub):
        rj = res[:, j * dsub : (j + 1) * dsub]
        cb = packed.codebooks[j]
        d2 = (
            (rj**2).sum(1)[:, None]
            - 2.0 * rj @ cb.T
            + (cb**2).sum(1)[None, :]
        )
        match += (np.argmin(d2, axis=1) == packed.codes[:, j]).sum()
    assert match / (res.shape[0] * m_sub) >= 0.999


def test_reconstruction_error_monotone_in_m_sub():
    """Decode round-trip: reconstruction MSE must shrink as m_sub grows
    (more codes per item = finer residual quantization) and always beat
    the coarse-only reconstruction."""
    X, ids = _clustered(n=800, d=8, n_blobs=6, seed=4)
    errs = []
    for m_sub in (1, 2, 4):
        packed = build_ivfpq_packed(X, ids, 6, m_sub=m_sub, n_bits=4, seed=5)
        rec = reconstruct(packed)
        errs.append(float(np.mean((rec - packed.items) ** 2)))
        # coarse-only error: residual variance around the assigned centroid
        row_list = np.repeat(np.arange(packed.counts.shape[0]), packed.counts)
        coarse = float(
            np.mean((packed.items - packed.centroids[row_list]) ** 2)
        )
        assert errs[-1] < coarse, (m_sub, errs[-1], coarse)
    assert errs[0] > errs[1] > errs[2], errs


# -- the acceptance gates ------------------------------------------------------


def test_refined_recall_at_10(pq_setup):
    """Acceptance: refined recall@10 >= 0.9 vs the exact kneighbors path at
    the DOCUMENTED defaults (default_m_sub, n_bits=8, nprobe=nlist/4,
    refine_ratio=4) on clustered data; raw ADC recall is reported-but-lower
    (quantization error), refine must not lose recall."""
    from spark_rapids_ml_tpu.ann.ivfflat import default_nprobe

    X, ids, packed = pq_setup
    mesh = get_mesh()
    nprobe = default_nprobe(packed.counts.shape[0])
    index = index_from_packed_pq(packed, mesh)
    Q = X[:512]
    _, i_raw = ivfpq_search_prepared(index, Q, 10, nprobe, mesh)
    d_ref, i_ref = ivfpq_search_prepared(
        index, Q, 10, nprobe, mesh, refine_items=packed.items, refine_ratio=4
    )
    prepared = prepare_items(X, ids, mesh)
    _, i_exact = knn_search_prepared(prepared, Q, 10, mesh)
    r_raw = recall_at_k(i_raw, i_exact)
    r_ref = recall_at_k(i_ref, i_exact)
    assert r_ref >= 0.9, (r_ref, r_raw)
    assert r_ref >= r_raw, (r_ref, r_raw)
    # refined distances are true f32 euclidean: ascending, self leads
    assert np.all(np.diff(d_ref, axis=1) >= 0)
    assert np.mean(i_ref[:, 0] == ids[:512]) >= 0.95


def test_mesh_parity_bitwise(pq_setup):
    """Acceptance: probed ADC results AND refined results are BITWISE
    identical on a 1-device and an 8-device mesh (the flat kernel's
    lex/merge helpers are reused verbatim; refine is deterministic host
    math over the already-identical candidate set)."""
    X, ids, packed = pq_setup
    Q = X[:300]
    out = {}
    for name, mesh in (("one", get_mesh(1)), ("all", get_mesh())):
        index = index_from_packed_pq(packed, mesh)
        out[name] = (
            ivfpq_search_prepared(index, Q, 10, 6, mesh),
            ivfpq_search_prepared(
                index, Q, 10, 6, mesh,
                refine_items=packed.items, refine_ratio=3,
            ),
        )
    for arm in (0, 1):
        d1, i1 = out["one"][arm]
        d8, i8 = out["all"][arm]
        np.testing.assert_array_equal(i1, i8)
        np.testing.assert_array_equal(
            d1.astype(np.float32).view(np.uint32),
            d8.astype(np.float32).view(np.uint32),
        )


def test_repeat_and_warm_zero_new_compiles(pq_setup):
    """Acceptance: a repeat same-shape probed PQ search performs ZERO new
    compilations, and warm_pq_probe_kernels submits the EXACT executable
    the dispatch looks up (fresh query-block geometry, straight aot_hit)."""
    from spark_rapids_ml_tpu.ops.precompile import global_precompiler

    X, ids, packed = pq_setup
    mesh = get_mesh()
    index = index_from_packed_pq(packed, mesh)
    kw = dict(refine_items=packed.items, refine_ratio=2)
    ivfpq_search_prepared(index, X[:200], 5, 4, mesh, **kw)  # compiles once
    before = profiling.counters("precompile.")
    d1, i1 = ivfpq_search_prepared(index, X[:200], 5, 4, mesh, **kw)
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.fallback", 0) == 0, delta
    assert delta.get("precompile.aot_hit", 0) >= 1, delta
    d2, i2 = ivfpq_search_prepared(index, X[:200], 5, 4, mesh, **kw)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_array_equal(d1, d2)
    # warm at a geometry no search has touched (k=7 > any dispatched k)
    keys = warm_pq_probe_kernels(
        index, 7, 4, mesh, n_queries=200, refine=True, refine_ratio=2
    )
    assert keys
    global_precompiler().wait(keys)
    before = profiling.counters("precompile.")
    ivfpq_search_prepared(index, X[:200], 7, 4, mesh, **kw)
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.aot_miss", 0) == 0, delta


def test_compression_vs_flat_index(pq_setup):
    """The memory headline: at this geometry the staged PQ index must sit
    far below the flat index per item (>= 8x is the CI bar at d >= 256;
    even at d=16 the code layout wins by ~2x, asserted here so the
    device_bytes accounting itself is gated in tier-1)."""
    from spark_rapids_ml_tpu.ann.ivfflat import (
        build_ivfflat_packed,
        index_from_packed,
    )

    X, ids, packed = pq_setup
    mesh = get_mesh()
    pq_bytes = index_from_packed_pq(packed, mesh).device_bytes()
    flat = build_ivfflat_packed(X, ids, packed.counts.shape[0], seed=1)
    flat_bytes = index_from_packed(flat, mesh).device_bytes()
    n = packed.n_items
    assert pq_bytes / n < (flat_bytes / n) / 2.0, (pq_bytes / n, flat_bytes / n)


# -- edges ---------------------------------------------------------------------


def test_unfillable_slots_and_empty_lists():
    """k beyond the probed pool yields the -1/inf sentinel contract on BOTH
    the raw and the refined route; empty coarse lists (nlist > occupied
    cells) and k > n_items are absorbed the same way the flat engine's
    suite gates."""
    rng = np.random.default_rng(0)
    X = np.concatenate(
        [
            rng.normal(size=(16, 4)).astype(np.float32),
            (100.0 + rng.normal(size=(16, 4))).astype(np.float32),
        ]
    )
    ids = np.arange(32, dtype=np.int64)
    mesh = get_mesh()
    # nlist=8 over two far blobs leaves most lists nearly/fully empty
    packed = build_ivfpq_packed(X, ids, 8, m_sub=2, n_bits=4, seed=5)
    index = index_from_packed_pq(packed, mesh)
    for kw in (
        {},
        {"refine_items": packed.items, "refine_ratio": 2},
    ):
        d, i = ivfpq_search_prepared(index, X[:4], 30, 1, mesh, **kw)
        assert d.shape == (4, 30) and i.shape == (4, 30)
        assert (i == -1).any()
        assert np.all(np.isinf(d[i == -1]))
        assert np.all(i[:, 0] >= 0)
    # k > n_items clamps to k_eff, full coverage probing everything
    d, i = ivfpq_search_prepared(
        index, X[:4], 64, index.nlist_pad, mesh,
        refine_items=packed.items, refine_ratio=2,
    )
    assert d.shape == (4, 32) and i.shape == (4, 32)
    assert np.all(i >= 0)


# -- model surface -------------------------------------------------------------


def test_model_pq_param_surface():
    X, _ = _clustered(n=120, d=6, n_blobs=4, seed=7)
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=1)
    with pytest.raises(ValueError, match="unknown algoParams"):
        ApproximateNearestNeighbors(
            algorithm="ivfpq", algoParams={"M": 2, "nbits": 4}
        ).setFeaturesCol("features").fit(df)
    with pytest.raises(ValueError, match="n_bits"):
        ApproximateNearestNeighbors(
            algorithm="ivfpq", algoParams={"n_bits": 11}
        ).setFeaturesCol("features").fit(df)
    # M is an ivfpq-only key: the flat tier must reject it loudly
    with pytest.raises(ValueError, match="unknown algoParams"):
        ApproximateNearestNeighbors(
            algorithm="ivfflat", algoParams={"M": 2}
        ).setFeaturesCol("features").fit(df)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        model = ApproximateNearestNeighbors(
            k=3,
            algorithm="ivfpq",
            algoParams={
                "nlist": 4, "nprobe": 4, "M": 2, "n_bits": 4,
                "usePrecomputedTables": True,
            },
        ).setFeaturesCol("features").fit(df)
        assert any(
            "usePrecomputedTables" in str(w.message) for w in caught
        ), [str(w.message) for w in caught]
    _, _, knn_df = model.kneighbors(
        DataFrame.from_numpy(X[:5], num_partitions=1)
    )
    ids = np.concatenate(
        [np.asarray(list(p["indices"])) for p in knn_df.partitions if len(p)]
    )
    assert ids.shape == (5, 3)
    # probed self-match leads every row after refine
    np.testing.assert_array_equal(ids[:, 0], np.arange(5))
    # a flat-fit model has no PQ payload to stage
    flat = ApproximateNearestNeighbors(
        k=3, algoParams={"nlist": 4, "nprobe": 4}
    ).setFeaturesCol("features").fit(df)
    with pytest.raises(ValueError, match="no PQ payload"):
        flat._packed_pq()


def test_default_m_sub_geometry():
    assert default_m_sub(256) == 32   # the ~32x operating point
    assert default_m_sub(3000) == 64  # clamped
    assert default_m_sub(5) == 4
    assert pq_geometry(5, 4) == (4, 2, 8)    # pow2-padded subspaces
    assert pq_geometry(256, 32) == (32, 8, 256)
    assert pq_geometry(16, 64) == (16, 1, 16)  # m_sub clamped to dim


# -- 4-bit fast-scan ----------------------------------------------------------


def test_fastscan_kernel_matches_numpy_adc_oracle():
    """out[b, r] = sum_j T[b, j, nibble_j(packed[b, r])] with SEQUENTIAL
    f32 accumulation in subspace order — the packed two-codes-per-byte
    kernel must equal the oracle bit for bit in interpret mode, on ragged
    row counts and sub-16 table widths, and the routed XLA unpack path
    must compute the identical sum."""
    from spark_rapids_ml_tpu.ops.pallas_pq import (
        _fastscan_pallas,
        fastscan_lut_accumulate,
        pack_codes4,
        unpack_codes4,
    )

    rng = np.random.default_rng(11)
    cases = [(3, 700, 4, 16), (1, 512, 2, 16), (2, 33, 8, 5)]
    wants, outs = [], []   # device outputs batched; ONE fetch after the loop
    for B, R, m_sub, ksub in cases:
        T = rng.standard_normal((B, m_sub, ksub)).astype(np.float32)
        C = rng.integers(0, ksub, size=(B, R, m_sub)).astype(np.uint8)
        packed = np.stack([pack_codes4(C[b]) for b in range(B)])
        want = np.zeros((B, R), np.float32)
        for j in range(m_sub):  # sequential j — the accumulation contract
            want += np.take_along_axis(
                T[:, j, :], C[:, :, j].astype(np.int64), axis=1
            )
        wants.append((want, C))
        outs.append((
            _fastscan_pallas(jnp.asarray(T), jnp.asarray(packed), interpret=True),
            fastscan_lut_accumulate(jnp.asarray(T), jnp.asarray(packed)),
            unpack_codes4(jnp.asarray(packed)),
        ))
    for case, (want, C), (got, got_routed, unpacked) in zip(
        cases, wants, jax.device_get(outs)
    ):
        np.testing.assert_array_equal(got, want, err_msg=f"{case}")
        np.testing.assert_allclose(got_routed, want, rtol=1e-6, atol=1e-6)
        # the unpack round-trip is lossless (nibble order: low = even j)
        np.testing.assert_array_equal(unpacked, C)


def test_fastscan_typed_rejections():
    """Odd m_sub cannot pack two codes per byte and a 4-bit nibble cannot
    address ksub > 16 — both are typed errors at the packing/kernel
    layer.  The ROUTE derivation keeps odd-m_sub payloads off the packed
    layout entirely (they build and search on the unpacked byte-per-code
    route, the pre-fast-scan behavior), so the typed errors guard the
    kernel's contract, not the user's geometry choice."""
    from spark_rapids_ml_tpu.ann.pq import (
        index_from_packed_pq,
        pq_fastscan,
    )
    from spark_rapids_ml_tpu.ops.pallas_pq import pack_codes4

    with pytest.raises(ValueError, match="even"):
        pack_codes4(np.zeros((4, 3), np.uint8))
    with pytest.raises(ValueError, match="16"):
        pack_codes4(np.full((4, 2), 16, np.uint8))
    assert pq_fastscan(4, 2) and not pq_fastscan(8, 2)
    assert not pq_fastscan(4, 3)  # odd m_sub: unpacked route, no error
    X, ids = _clustered(n=200, d=8, n_blobs=4, seed=9)
    packed = build_ivfpq_packed(X, ids, 4, m_sub=3, n_bits=4, seed=0)
    index = index_from_packed_pq(packed, get_mesh())
    assert not index.fastscan
    _, i = ivfpq_search_prepared(index, X[:8], 3, 4, get_mesh())
    np.testing.assert_array_equal(np.asarray(i)[:, 0], ids[:8])


# -- OPQ ----------------------------------------------------------------------


def test_opq_recall_at_equal_index_bytes():
    """The OPQ acceptance gate, at EQUAL device code bytes per item (4-bit
    at M vs 8-bit at M/2 — both M/2 bytes of codes): the OPQ+4-bit
    PIPELINE (refined against the host-side f32 payload, which costs zero
    HBM) must reach at least the recall@10 of the raw ADC-only 8-bit arm
    on the clustered bench shape.  Rotation must also strictly help the
    4-bit arm's own raw ADC recall — that is the part OPQ buys.  (An
    ADC-vs-ADC flip at equal rate is NOT gated: a joint 256-word codebook
    over 2*dsub dims is structurally at least as expressive as the product
    of two 16-word codebooks, so 8-bit raw ADC >= 4-bit raw ADC at equal
    bytes always — docs/ann_engine.md carries the measured table.)"""
    X, ids = _clustered(n=2000, d=16, n_blobs=24, seed=13)
    mesh = get_mesh()
    nlist, nprobe, M = 16, 8, 8
    prepared = prepare_items(X, ids, mesh)
    Q = X[:256]
    _, i_exact = knn_search_prepared(prepared, Q, 10, mesh)
    arms = {
        "raw8_halfM": (M // 2, 8, False),
        "opq4": (M, 4, True),
        "raw4": (M, 4, False),
    }
    raw, ref = {}, {}
    for label, (m_sub, n_bits, opq) in arms.items():
        packed = build_ivfpq_packed(
            X, ids, nlist, m_sub=m_sub, n_bits=n_bits, seed=1, opq=opq
        )
        index = index_from_packed_pq(packed, mesh)
        _, i_raw = ivfpq_search_prepared(index, Q, 10, nprobe, mesh)
        raw[label] = recall_at_k(i_raw, i_exact)
        _, i_ref = ivfpq_search_prepared(
            index, Q, 10, nprobe, mesh,
            refine_items=packed.items, refine_ratio=8,
        )
        ref[label] = recall_at_k(i_ref, i_exact)
    # the equal-HBM-bytes headline: refined opq4 >= raw 8-bit at half M
    assert ref["opq4"] >= raw["raw8_halfM"], (ref, raw)
    assert ref["opq4"] >= 0.9, ref
    # the rotation itself must pay for its training loop
    assert raw["opq4"] > raw["raw4"], raw


def test_opq_reduces_reconstruction_error():
    """The rotation exists to cut quantization error: OPQ reconstruction
    MSE must not exceed the unrotated build's at the same geometry, and
    reconstruct() must un-rotate (error far below residual variance)."""
    X, ids = _clustered(n=800, d=8, n_blobs=6, seed=4)
    errs = {}
    for opq in (False, True):
        packed = build_ivfpq_packed(X, ids, 6, m_sub=2, n_bits=4, seed=5, opq=opq)
        rec = reconstruct(packed)
        errs[opq] = float(np.mean((rec - packed.items) ** 2))
    assert errs[True] <= errs[False] * 1.001, errs
    packed = build_ivfpq_packed(X, ids, 6, m_sub=2, n_bits=4, seed=5, opq=True)
    assert packed.rotation is not None
    R = packed.rotation.astype(np.float64)
    np.testing.assert_allclose(R @ R.T, np.eye(R.shape[0]), atol=1e-5)


def test_opq_and_fastscan_mesh_parity_bitwise():
    """Acceptance: probed AND refined results BITWISE identical on 1-dev
    and 8-dev meshes for the opq arm and the 4-bit fast-scan arm (and
    their composition)."""
    X, ids = _clustered(n=800, d=16, n_blobs=12, seed=17)
    Q = X[:200]
    for n_bits, opq in ((8, True), (4, False), (4, True)):
        packed = build_ivfpq_packed(
            X, ids, 8, m_sub=4, n_bits=n_bits, seed=2, opq=opq
        )
        out = {}
        for name, mesh in (("one", get_mesh(1)), ("all", get_mesh())):
            index = index_from_packed_pq(packed, mesh)
            out[name] = (
                ivfpq_search_prepared(index, Q, 10, 4, mesh),
                ivfpq_search_prepared(
                    index, Q, 10, 4, mesh,
                    refine_items=packed.items, refine_ratio=3,
                ),
            )
        for arm in (0, 1):
            d1, i1 = out["one"][arm]
            d8, i8 = out["all"][arm]
            np.testing.assert_array_equal(i1, i8, err_msg=f"{n_bits}/{opq}")
            np.testing.assert_array_equal(
                d1.astype(np.float32).view(np.uint32),
                d8.astype(np.float32).view(np.uint32),
                err_msg=f"{n_bits}/{opq}",
            )


# -- tiered residency ---------------------------------------------------------


def test_tiered_matches_resident_bitwise(pq_setup):
    """Acceptance: a hot_fraction=0.25 tiered index answers the SAME
    probed+refined search BITWISE identically to the all-resident staging
    (the tiered kernel is the resident body plus one slot indirection),
    and the cold->warm page-in sweep performs ZERO new compilations after
    the first block geometry."""
    from spark_rapids_ml_tpu.ann.pq import tiered_index_from_packed_pq

    X, ids, packed = pq_setup
    mesh = get_mesh()
    resident = index_from_packed_pq(packed, mesh)
    tiered = tiered_index_from_packed_pq(packed, mesh, hot_fraction=0.25)
    kw = dict(refine_items=packed.items, refine_ratio=3)
    Q = X[:192]
    d_r, i_r = ivfpq_search_prepared(resident, Q, 10, 6, mesh, **kw)
    d_t, i_t = ivfpq_search_prepared(tiered, Q, 10, 6, mesh, **kw)
    np.testing.assert_array_equal(i_r, i_t)
    np.testing.assert_array_equal(
        np.asarray(d_r, np.float32).view(np.uint32),
        np.asarray(d_t, np.float32).view(np.uint32),
    )
    # cold->warm sweep: disjoint query slices probe different lists, so
    # the pager keeps paging — but never compiles anew at this geometry
    before = profiling.counters("precompile.")
    t0 = profiling.counter("ann.tier.hits") + profiling.counter("ann.tier.misses")
    for lo in range(192, 2112, 192):
        ivfpq_search_prepared(tiered, X[lo:lo + 192], 10, 6, mesh, **kw)
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.fallback", 0) == 0, delta
    # the pager actually worked (counters are the observability surface)
    assert (
        profiling.counter("ann.tier.hits")
        + profiling.counter("ann.tier.misses")
    ) > t0
    assert profiling.counter("ann.tier.stage_bytes") > 0


def test_tiered_tombstone_interaction():
    """Tiered + live mutation: lists paged in from host AFTER a delete
    must honor the tombstone bitmap — the tier's host planes are views of
    the holder's mirrors and delete_items refreshes resident slots, so a
    tombstoned id must never resurface from ANY list, hot, resident-warm,
    or paged-in-later cold."""
    from spark_rapids_ml_tpu.ann.ivfflat import build_ivfflat_packed
    from spark_rapids_ml_tpu.ann.mutable import MutableIVFIndex

    rng = np.random.default_rng(23)
    X = rng.standard_normal((1200, 16)).astype(np.float32)
    ids = np.arange(1200, dtype=np.int64)
    mesh = get_mesh()
    packed = build_ivfflat_packed(X, ids, 16, seed=0)
    holder = MutableIVFIndex(packed, mesh, hot_fraction=0.25)
    # warm only a few lists so most stay cold on host
    holder.search(X[:16], 5, 2)
    victims = ids[:48]
    holder.delete_items(victims)
    # nprobe = nlist forces EVERY list through the pager, including cold
    # lists first touched after the delete
    d, i = holder.search(X[:128], 10, 16)
    assert not np.isin(i, victims).any()
    assert holder.stats()["tombstoned"] == 48
    # the paged-in rows carry live neighbors, not garbage
    live = ids[48:]
    hits = i[i >= 0]
    assert np.isin(hits, live).all()


def test_model_refine_ratio_edge_semantics():
    """Satellite regression: refine_ratio=0 used to pass the `>= 0` guard
    and silently behave like 1 (the refine gate keys off `> 1`); it is now
    a typed error, while refine_ratio=1 is the documented "ADC only, no
    refine" mode and must equal the engine's raw probed route."""
    X, _ = _clustered(n=300, d=8, n_blobs=6, seed=29)
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=1)
    for bad in (0, -2):
        with pytest.raises(ValueError, match="refine_ratio"):
            ApproximateNearestNeighbors(
                algorithm="ivfpq",
                algoParams={"nlist": 4, "nprobe": 4, "M": 2, "refine_ratio": bad},
            ).setFeaturesCol("features").fit(df)
    base = {"nlist": 4, "nprobe": 4, "M": 2, "n_bits": 8}
    model = ApproximateNearestNeighbors(
        k=5, algorithm="ivfpq", algoParams={**base, "refine_ratio": 1},
    ).setFeaturesCol("features").fit(df)
    _, _, knn_df = model.kneighbors(DataFrame.from_numpy(X[:8], num_partitions=1))
    got = np.concatenate(
        [np.asarray(list(p["indices"])) for p in knn_df.partitions if len(p)]
    )
    mesh = get_mesh(model.num_workers)
    index = model._ensure_staged_pq(mesh)
    _, want = ivfpq_search_prepared(index, X[:8], 5, 4, mesh)  # raw ADC
    np.testing.assert_array_equal(got, want)


def test_model_hot_fraction_param_surface():
    X, _ = _clustered(n=200, d=8, n_blobs=4, seed=31)
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=1)
    with pytest.raises(ValueError, match="hot_fraction"):
        ApproximateNearestNeighbors(
            algoParams={"nlist": 4, "hot_fraction": 1.5}
        ).setFeaturesCol("features").fit(df)
    model = ApproximateNearestNeighbors(
        k=3, algoParams={"nlist": 4, "nprobe": 4, "hot_fraction": 0.5},
    ).setFeaturesCol("features").fit(df)
    _, _, knn_df = model.kneighbors(DataFrame.from_numpy(X[:6], num_partitions=1))
    got = np.concatenate(
        [np.asarray(list(p["indices"])) for p in knn_df.partitions if len(p)]
    )
    np.testing.assert_array_equal(got[:, 0], np.arange(6))
    res = model.index_residency()
    assert res["hbm_bytes_per_item"] > 0
    assert res["host_bytes_per_item"] > 0
    assert res["items_per_device"] >= 1
