# CrossValidator single-pass multi-model CV tests (strategy modeled on the
# reference's test_tuning.py / per-algo test_crossvalidator tests).
import numpy as np
import pytest

from spark_rapids_ml_tpu import LinearRegression, LogisticRegression
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.evaluation import (
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
)


def _reg_df(n=300, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = X @ rng.normal(size=d) + 0.5 * rng.normal(size=n)
    return DataFrame.from_numpy(X, y=y, num_partitions=4), X, y


def test_param_grid_builder():
    grid = (
        ParamGridBuilder()
        .addGrid(LinearRegression.regParam, [0.0, 0.1])
        .addGrid(LinearRegression.elasticNetParam, [0.0, 0.5, 1.0])
        .build()
    )
    assert len(grid) == 6
    assert all(LinearRegression.regParam in pm for pm in grid)


def test_cv_regression_single_pass():
    df, X, y = _reg_df()
    est = LinearRegression(standardization=False)
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.0, 10.0]).build()
    eva = RegressionEvaluator(metricName="rmse")
    assert est._supportsTransformEvaluate(eva)
    cv = CrossValidator(
        estimator=est, estimatorParamMaps=grid, evaluator=eva, numFolds=3, seed=5
    )
    cv_model = cv.fit(df)
    assert len(cv_model.avgMetrics) == 2
    # regParam=0 must beat absurd regParam=10 on rmse
    assert cv_model.avgMetrics[0] < cv_model.avgMetrics[1]
    assert cv_model.bestModel.getOrDefault("regParam") == 0.0
    out = cv_model.transform(df)
    assert "prediction" in out.columns


def test_cv_classification_single_pass():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(300, 5))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    df = DataFrame.from_numpy(X, y=y, num_partitions=3)
    est = LogisticRegression(maxIter=100)
    grid = ParamGridBuilder().addGrid(LogisticRegression.regParam, [0.01, 50.0]).build()
    eva = MulticlassClassificationEvaluator(metricName="accuracy")
    cv = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=eva, numFolds=3)
    cv_model = cv.fit(df)
    assert cv_model.avgMetrics[0] > cv_model.avgMetrics[1]
    assert cv_model.bestModel.getOrDefault("regParam") == 0.01


def test_cv_parallel_folds_match_serial():
    df, _, _ = _reg_df()
    est = LinearRegression(standardization=False)
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.0, 1.0]).build()
    eva = RegressionEvaluator()
    m1 = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=eva, seed=3).fit(df)
    m2 = CrossValidator(
        estimator=est, estimatorParamMaps=grid, evaluator=eva, seed=3, parallelism=3
    ).fit(df)
    np.testing.assert_allclose(m1.avgMetrics, m2.avgMetrics, rtol=1e-6)


def test_cv_collect_sub_models():
    df, _, _ = _reg_df(n=200)
    est = LinearRegression(standardization=False)
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.0, 0.5]).build()
    cv = CrossValidator(
        estimator=est,
        estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(),
        numFolds=2,
        collectSubModels=True,
    )
    cv_model = cv.fit(df)
    assert cv_model.subModels is not None
    assert len(cv_model.subModels) == 2
    assert len(cv_model.subModels[0]) == 2


def test_cv_model_persistence(tmp_path):
    df, _, _ = _reg_df(n=150)
    est = LinearRegression()
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.0, 0.1]).build()
    cv = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=RegressionEvaluator())
    cv_model = cv.fit(df)
    path = str(tmp_path / "cv")
    cv_model.save(path)
    loaded = CrossValidatorModel.load(path)
    np.testing.assert_allclose(loaded.avgMetrics, cv_model.avgMetrics)
    p1 = cv_model.transform(df).toPandas()["prediction"]
    p2 = loaded.transform(df).toPandas()["prediction"]
    np.testing.assert_allclose(p1, p2, atol=1e-7)


@pytest.mark.slow
def test_cv_random_forest_classifier_single_pass():
    from spark_rapids_ml_tpu import RandomForestClassifier

    rng = np.random.default_rng(4)
    X = rng.normal(size=(240, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    df = DataFrame.from_numpy(X, y=y, num_partitions=3)
    est = RandomForestClassifier(numTrees=5, seed=9)
    assert est._supportsTransformEvaluate(
        MulticlassClassificationEvaluator(metricName="accuracy")
    )
    # grid varies BOTH tree count and depth: _combine must concatenate
    # differing dense layouts
    grid = (
        ParamGridBuilder()
        .addGrid(RandomForestClassifier.maxDepth, [1, 6])
        .build()
    )
    eva = MulticlassClassificationEvaluator(metricName="accuracy")
    cv = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=eva, numFolds=3)
    cv_model = cv.fit(df)
    assert len(cv_model.avgMetrics) == 2
    # depth-6 forest must beat decision stumps on this 2-feature interaction
    assert cv_model.avgMetrics[1] > cv_model.avgMetrics[0]
    assert cv_model.bestModel.getOrDefault("maxDepth") == 6


@pytest.mark.slow
def test_cv_random_forest_regressor_single_pass():
    from spark_rapids_ml_tpu import RandomForestRegressor

    df, X, y = _reg_df(n=240)
    est = RandomForestRegressor(numTrees=5, seed=9)
    eva = RegressionEvaluator(metricName="rmse")
    assert est._supportsTransformEvaluate(eva)
    grid = ParamGridBuilder().addGrid(RandomForestRegressor.maxDepth, [1, 7]).build()
    cv = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=eva, numFolds=3)
    cv_model = cv.fit(df)
    assert cv_model.avgMetrics[1] < cv_model.avgMetrics[0]  # rmse: deeper wins
    assert cv_model.bestModel.getOrDefault("maxDepth") == 7


def test_rf_combined_multi_model_matches_per_model_eval():
    from spark_rapids_ml_tpu import RandomForestClassifier

    rng = np.random.default_rng(6)
    X = rng.normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    train = DataFrame.from_numpy(X, y=y, num_partitions=2)
    est = RandomForestClassifier(numTrees=4, seed=3)
    pm = [
        {est.getParam("maxDepth"): 2},
        {est.getParam("maxDepth"): 5},
    ]
    models = [m for _, m in est.fitMultiple(train, pm)]
    combined = models[0]._combine(models)
    assert combined._num_models == 2
    eva = MulticlassClassificationEvaluator(metricName="accuracy")
    single = [eva.evaluate(m.transform(train)) for m in models]
    fused = combined._transformEvaluate(train, eva)
    np.testing.assert_allclose(fused, single, atol=1e-12)
    # combined models refuse plain transform (ambiguous tree averaging)
    with pytest.raises(AssertionError):
        combined.transform(train)
