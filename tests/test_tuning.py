# CrossValidator single-pass multi-model CV tests (strategy modeled on the
# reference's test_tuning.py / per-algo test_crossvalidator tests), plus the
# srml-sweep batched-engine gates: batched-vs-sequential EXACT equality on
# 1/2/8-device meshes, the one-staged-dataset transfer contract, and the
# zero-new-compiles repeat-sweep contract (docs/tuning_engine.md).
import numpy as np
import pytest

from spark_rapids_ml_tpu import LinearRegression, LogisticRegression, profiling
from spark_rapids_ml_tpu.core import clear_fit_cache
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.evaluation import (
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
)


def _int_reg_df(n=300, d=6, seed=0, num_partitions=4):
    """Integer-valued float32 regression data: every sum in the
    sufficient-statistics pass is exactly representable, so summation ORDER
    is irrelevant and the masked-fold batched route can be gated BITWISE
    against the restaged sequential route (float addition is associative on
    exact integers; see docs/tuning_engine.md §equality contract)."""
    rng = np.random.default_rng(seed)
    X = rng.integers(-3, 4, size=(n, d)).astype(np.float32)
    c = rng.integers(-2, 3, size=d).astype(np.float32)
    y = (X @ c + rng.integers(-2, 3, size=n)).astype(np.float32)
    return DataFrame.from_numpy(X, y=y, num_partitions=num_partitions)


def _int_cls_df(n=300, d=6, seed=1, num_partitions=3):
    """Integer-valued, margin-separated binary data: integer scores X@c are
    either 0 or at least 1 in magnitude, and the 0-score rows are dropped,
    so every row carries a true margin >= 1 — the last-bit solver-path
    differences the batched L-BFGS is allowed cannot flip a prediction,
    which is what makes the ACCURACY equality gate exact."""
    rng = np.random.default_rng(seed)
    X = rng.integers(-3, 4, size=(int(n * 1.5), d)).astype(np.float32)
    c = rng.integers(-2, 3, size=d).astype(np.float32)
    X = X[X @ c != 0][:n]
    assert len(X) == n
    y = (X @ c > 0).astype(np.float32)
    return DataFrame.from_numpy(X, y=y, num_partitions=num_partitions)


def _run_cv(df, est, grid, eva, batched, monkeypatch, **cv_kwargs):
    monkeypatch.setenv("SRML_SWEEP_BATCH", "1" if batched else "0")
    clear_fit_cache()
    cv = CrossValidator(
        estimator=est, estimatorParamMaps=grid, evaluator=eva, **cv_kwargs
    )
    c0 = profiling.counters("ingest.")
    model = cv.fit(df)
    return model, profiling.counter_deltas(c0, "ingest.")


def _reg_df(n=300, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = X @ rng.normal(size=d) + 0.5 * rng.normal(size=n)
    return DataFrame.from_numpy(X, y=y, num_partitions=4), X, y


def test_param_grid_builder():
    grid = (
        ParamGridBuilder()
        .addGrid(LinearRegression.regParam, [0.0, 0.1])
        .addGrid(LinearRegression.elasticNetParam, [0.0, 0.5, 1.0])
        .build()
    )
    assert len(grid) == 6
    assert all(LinearRegression.regParam in pm for pm in grid)


def test_cv_regression_single_pass():
    df, X, y = _reg_df()
    est = LinearRegression(standardization=False)
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.0, 10.0]).build()
    eva = RegressionEvaluator(metricName="rmse")
    assert est._supportsTransformEvaluate(eva)
    cv = CrossValidator(
        estimator=est, estimatorParamMaps=grid, evaluator=eva, numFolds=3, seed=5
    )
    cv_model = cv.fit(df)
    assert len(cv_model.avgMetrics) == 2
    # regParam=0 must beat absurd regParam=10 on rmse
    assert cv_model.avgMetrics[0] < cv_model.avgMetrics[1]
    assert cv_model.bestModel.getOrDefault("regParam") == 0.0
    out = cv_model.transform(df)
    assert "prediction" in out.columns


def test_cv_classification_single_pass():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(300, 5))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    df = DataFrame.from_numpy(X, y=y, num_partitions=3)
    est = LogisticRegression(maxIter=100)
    grid = ParamGridBuilder().addGrid(LogisticRegression.regParam, [0.01, 50.0]).build()
    eva = MulticlassClassificationEvaluator(metricName="accuracy")
    cv = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=eva, numFolds=3)
    cv_model = cv.fit(df)
    assert cv_model.avgMetrics[0] > cv_model.avgMetrics[1]
    assert cv_model.bestModel.getOrDefault("regParam") == 0.01


def test_cv_parallel_folds_match_serial():
    df, _, _ = _reg_df()
    est = LinearRegression(standardization=False)
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.0, 1.0]).build()
    eva = RegressionEvaluator()
    m1 = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=eva, seed=3).fit(df)
    m2 = CrossValidator(
        estimator=est, estimatorParamMaps=grid, evaluator=eva, seed=3, parallelism=3
    ).fit(df)
    np.testing.assert_allclose(m1.avgMetrics, m2.avgMetrics, rtol=1e-6)


def test_cv_collect_sub_models():
    df, _, _ = _reg_df(n=200)
    est = LinearRegression(standardization=False)
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.0, 0.5]).build()
    cv = CrossValidator(
        estimator=est,
        estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(),
        numFolds=2,
        collectSubModels=True,
    )
    cv_model = cv.fit(df)
    assert cv_model.subModels is not None
    assert len(cv_model.subModels) == 2
    assert len(cv_model.subModels[0]) == 2


def test_cv_model_persistence(tmp_path):
    df, _, _ = _reg_df(n=150)
    est = LinearRegression()
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.0, 0.1]).build()
    cv = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=RegressionEvaluator())
    cv_model = cv.fit(df)
    path = str(tmp_path / "cv")
    cv_model.save(path)
    loaded = CrossValidatorModel.load(path)
    np.testing.assert_allclose(loaded.avgMetrics, cv_model.avgMetrics)
    p1 = cv_model.transform(df).toPandas()["prediction"]
    p2 = loaded.transform(df).toPandas()["prediction"]
    np.testing.assert_allclose(p1, p2, atol=1e-7)


@pytest.mark.slow
def test_cv_random_forest_classifier_single_pass():
    from spark_rapids_ml_tpu import RandomForestClassifier

    rng = np.random.default_rng(4)
    X = rng.normal(size=(240, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    df = DataFrame.from_numpy(X, y=y, num_partitions=3)
    est = RandomForestClassifier(numTrees=5, seed=9)
    assert est._supportsTransformEvaluate(
        MulticlassClassificationEvaluator(metricName="accuracy")
    )
    # grid varies BOTH tree count and depth: _combine must concatenate
    # differing dense layouts
    grid = (
        ParamGridBuilder()
        .addGrid(RandomForestClassifier.maxDepth, [1, 6])
        .build()
    )
    eva = MulticlassClassificationEvaluator(metricName="accuracy")
    cv = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=eva, numFolds=3)
    cv_model = cv.fit(df)
    assert len(cv_model.avgMetrics) == 2
    # depth-6 forest must beat decision stumps on this 2-feature interaction
    assert cv_model.avgMetrics[1] > cv_model.avgMetrics[0]
    assert cv_model.bestModel.getOrDefault("maxDepth") == 6


@pytest.mark.slow
def test_cv_random_forest_regressor_single_pass():
    from spark_rapids_ml_tpu import RandomForestRegressor

    df, X, y = _reg_df(n=240)
    est = RandomForestRegressor(numTrees=5, seed=9)
    eva = RegressionEvaluator(metricName="rmse")
    assert est._supportsTransformEvaluate(eva)
    grid = ParamGridBuilder().addGrid(RandomForestRegressor.maxDepth, [1, 7]).build()
    cv = CrossValidator(estimator=est, estimatorParamMaps=grid, evaluator=eva, numFolds=3)
    cv_model = cv.fit(df)
    assert cv_model.avgMetrics[1] < cv_model.avgMetrics[0]  # rmse: deeper wins
    assert cv_model.bestModel.getOrDefault("maxDepth") == 7


# -- srml-sweep: batched one-dispatch CV gates -------------------------------


@pytest.mark.parametrize("num_workers", [1, 2, 8])
def test_batched_sweep_exact_equality_linreg(num_workers, monkeypatch):
    """Acceptance: the batched CV route produces EXACTLY the sequential
    route's avgMetrics/stdMetrics/best_index and sub-model coefficients —
    bitwise, not allclose — on a mixed closed-form + coordinate-descent
    grid, on 1/2/8-device meshes."""
    df = _int_reg_df()
    grid = (
        ParamGridBuilder()
        .addGrid(LinearRegression.regParam, [0.0, 0.1])
        .addGrid(LinearRegression.elasticNetParam, [0.0, 0.5])
        .build()
    )

    def run(batched):
        est = LinearRegression(standardization=False, num_workers=num_workers)
        return _run_cv(
            df, est, grid, RegressionEvaluator(metricName="rmse"),
            batched, monkeypatch, numFolds=3, seed=5, collectSubModels=True,
        )

    m_seq, _ = run(False)
    m_bat, d_bat = run(True)
    # EXACT equality: compare raw float64 payloads, no tolerance
    assert m_bat.avgMetrics == m_seq.avgMetrics
    assert m_bat.stdMetrics == m_seq.stdMetrics
    assert (
        m_bat.bestModel.getOrDefault("regParam")
        == m_seq.bestModel.getOrDefault("regParam")
    )
    for f in range(3):
        for i in range(len(grid)):
            s, b = m_seq.subModels[f][i], m_bat.subModels[f][i]
            np.testing.assert_array_equal(
                np.asarray(s.coef_), np.asarray(b.coef_)
            )
            assert float(s.intercept_) == float(b.intercept_)
    np.testing.assert_array_equal(
        np.asarray(m_seq.bestModel.coef_), np.asarray(m_bat.bestModel.coef_)
    )
    # transfer contract: the whole batched CV staged the dataset ONCE (the
    # sweep); the best-model refit rode the device-input cache
    assert d_bat.get("ingest.staged", 0) == 1, d_bat


@pytest.mark.parametrize("num_workers", [1, 2, 8])
def test_batched_sweep_exact_equality_logreg(num_workers, monkeypatch):
    """Logreg sweep gate: EXACT avgMetrics (accuracy is a ratio of integer
    counts, and the margin-separated data forbids prediction flips) and
    best_index vs the sequential path on 1/2/8-device meshes; coefficients
    agree to the documented L-BFGS trajectory tolerance (the fused lane
    contraction reduces across a different geometry than the solo fit —
    docs/tuning_engine.md §equality contract)."""
    df = _int_cls_df()
    grid = (
        ParamGridBuilder()
        .addGrid(LogisticRegression.regParam, [0.01, 1.0])
        .addGrid(LogisticRegression.elasticNetParam, [0.0, 0.5])
        .build()
    )

    def run(batched):
        est = LogisticRegression(maxIter=200, num_workers=num_workers)
        return _run_cv(
            df, est, grid,
            MulticlassClassificationEvaluator(metricName="accuracy"),
            batched, monkeypatch, numFolds=3, seed=7, collectSubModels=True,
        )

    m_seq, _ = run(False)
    m_bat, d_bat = run(True)
    assert m_bat.avgMetrics == m_seq.avgMetrics
    assert m_bat.stdMetrics == m_seq.stdMetrics
    assert int(np.argmax(m_bat.avgMetrics)) == int(np.argmax(m_seq.avgMetrics))
    for f in range(3):
        for i in range(len(grid)):
            np.testing.assert_allclose(
                np.asarray(m_bat.subModels[f][i].coef_),
                np.asarray(m_seq.subModels[f][i].coef_),
                atol=5e-3,
            )
    assert d_bat.get("ingest.staged", 0) == 1, d_bat


def test_batched_sweep_repeat_is_deterministic(monkeypatch):
    """Two identical batched sweeps produce bitwise-identical sub-model
    coefficients and metrics (no set-order / thread-order nondeterminism
    anywhere in the engine)."""
    df = _int_cls_df(n=240, seed=4)
    grid = ParamGridBuilder().addGrid(
        LogisticRegression.regParam, [0.01, 0.5, 2.0]
    ).build()

    def run():
        est = LogisticRegression(maxIter=100)
        return _run_cv(
            df, est, grid,
            MulticlassClassificationEvaluator(metricName="accuracy"),
            True, monkeypatch, numFolds=2, seed=3, collectSubModels=True,
        )[0]

    m1, m2 = run(), run()
    assert m1.avgMetrics == m2.avgMetrics
    for f in range(2):
        for i in range(len(grid)):
            np.testing.assert_array_equal(
                np.asarray(m1.subModels[f][i].coef_),
                np.asarray(m2.subModels[f][i].coef_),
            )


def test_batched_sweep_zero_new_compiles_on_repeat(monkeypatch):
    """Acceptance: a repeat sweep at the same shapes — even with DIFFERENT
    grid values (the reg/l1 lanes are traced, not baked) — performs ZERO
    new kernel compilations: precompile.compile/fallback frozen, aot_hit
    moving (the candidate-bucket AOT cache key contract)."""
    df = _int_reg_df(n=256, seed=9)
    eva = RegressionEvaluator()

    def run(alphas):
        est = LinearRegression(standardization=False)
        grid = ParamGridBuilder().addGrid(
            LinearRegression.regParam, alphas
        ).build()
        return _run_cv(
            df, est, grid, eva, True, monkeypatch, numFolds=3, seed=2
        )

    run([0.0, 0.1, 1.0])  # cold: compiles the sweep kernels
    before = profiling.counters("precompile.")
    run([0.0, 0.5, 2.0])  # same shapes, same 3->4 candidate bucket
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.fallback", 0) == 0, delta
    assert delta.get("precompile.aot_hit", 0) >= 2, delta  # stats + solve


def test_batched_sweep_single_candidate_grid(monkeypatch):
    """m=1 must still route through the batched engine (tuning.candidates
    moves) and equal the sequential path exactly."""
    df = _int_reg_df(n=200, seed=11)
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.1]).build()
    eva = RegressionEvaluator()

    def run(batched):
        c0 = profiling.counter("tuning.candidates")
        est = LinearRegression(standardization=False)
        model, _ = _run_cv(
            df, est, grid, eva, batched, monkeypatch, numFolds=3, seed=6
        )
        return model, profiling.counter("tuning.candidates") - c0

    m_seq, routed_seq = run(False)
    m_bat, routed_bat = run(True)
    assert routed_seq == 0 and routed_bat == 1
    assert m_bat.avgMetrics == m_seq.avgMetrics
    np.testing.assert_array_equal(
        np.asarray(m_seq.bestModel.coef_), np.asarray(m_bat.bestModel.coef_)
    )


def test_batched_sweep_many_small_folds_edge(monkeypatch):
    """numFolds greater than the rows-per-fold count (24 rows, 8 folds —
    3-row validation folds, near-rank-deficient trains): the masked-fold
    formulation must still match the restaged sequential path exactly."""
    df = _int_reg_df(n=24, d=4, seed=13, num_partitions=2)
    grid = ParamGridBuilder().addGrid(
        LinearRegression.regParam, [0.0, 1.0]
    ).build()

    def run(batched):
        est = LinearRegression(standardization=False)
        return _run_cv(
            df, est, grid, RegressionEvaluator(), batched, monkeypatch,
            numFolds=8, seed=1,
        )[0]

    m_seq, m_bat = run(False), run(True)
    assert m_bat.avgMetrics == m_seq.avgMetrics
    assert m_bat.stdMetrics == m_seq.stdMetrics


def test_batched_sweep_kill_switch_and_fallbacks(monkeypatch):
    """SRML_SWEEP_BATCH=0 forces the legacy loop; a grid over a
    non-lane-batchable param (fitIntercept) falls back to it on its own;
    sparse CSR input keeps the legacy loop (documented non-goal)."""
    import scipy.sparse as sp

    df = _int_reg_df(n=120, seed=8)
    eva = RegressionEvaluator()

    def candidates_delta(df_, grid, batched):
        c0 = profiling.counter("tuning.candidates")
        est = LinearRegression(standardization=False)
        _run_cv(df_, est, grid, eva, batched, monkeypatch, numFolds=2, seed=4)
        return profiling.counter("tuning.candidates") - c0

    plain = ParamGridBuilder().addGrid(
        LinearRegression.regParam, [0.0, 0.1]
    ).build()
    assert candidates_delta(df, plain, batched=False) == 0  # kill switch
    mixed = (
        ParamGridBuilder()
        .addGrid(LinearRegression.regParam, [0.0, 0.1])
        .addGrid(LinearRegression.fitIntercept, [True, False])
        .build()
    )
    assert candidates_delta(df, mixed, batched=True) == 0  # non-lane param
    # sparse CSR frames: the batched hook must decline (masked-fold ELL
    # stats are a documented non-goal; CV over sparse frames keeps whatever
    # the legacy route does with them)
    rng = np.random.default_rng(0)
    Xs = sp.random(150, 8, density=0.3, random_state=1, dtype=np.float32).tocsr()
    ys = np.asarray(Xs @ rng.standard_normal(8), dtype=np.float32)
    sparse_df = DataFrame.from_numpy(Xs, ys, num_partitions=2)
    assert not LinearRegression()._supportsBatchedSweep(sparse_df, plain, eva)
    assert LinearRegression()._supportsBatchedSweep(df, plain, eva)


def test_batched_sweep_telemetry_spans_and_counters(monkeypatch):
    """The sweep emits the documented tuning.sweep.{stats,solve,score}
    spans and tuning.candidates/tuning.folds counters, and the sub-models
    carry the sweep's mergeable telemetry snapshot."""
    df = _int_reg_df(n=160, seed=14)
    grid = ParamGridBuilder().addGrid(
        LinearRegression.regParam, [0.0, 0.1, 1.0]
    ).build()
    c0 = profiling.counters("tuning.")
    est = LinearRegression(standardization=False)
    model, _ = _run_cv(
        df, est, grid, RegressionEvaluator(), True, monkeypatch,
        numFolds=3, seed=5, collectSubModels=True,
    )
    delta = profiling.counter_deltas(c0, "tuning.")
    assert delta.get("tuning.candidates", 0) == 3, delta
    assert delta.get("tuning.folds", 0) == 3, delta
    snap = model.subModels[0][0].fit_telemetry()
    assert snap is not None
    phases = snap.phases
    for name in ("tuning.sweep", "tuning.sweep.stats", "tuning.sweep.solve",
                 "tuning.sweep.score"):
        assert name in phases and phases[name]["count"] >= 1, phases.keys()
    assert snap.counters.get("tuning.candidates") == 3


def test_cv_copy_carries_bookkeeping():
    """CrossValidator.copy must carry (not alias) the estimator/evaluator/
    param-map bookkeeping CrossValidatorModel relies on — the old override
    was a dead pass-through."""
    est = LinearRegression()
    grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.0, 0.1]).build()
    eva = RegressionEvaluator()
    cv = CrossValidator(
        estimator=est, estimatorParamMaps=grid, evaluator=eva, numFolds=4
    )
    cp = cv.copy()
    assert cp.getNumFolds() == 4
    assert cp.getEstimator() is not None and cp.getEstimator() is not est
    assert cp.getEvaluator() is not None and cp.getEvaluator() is not eva
    assert cp.getEstimatorParamMaps() == grid
    assert cp.getEstimatorParamMaps() is not cv.getEstimatorParamMaps()
    # the copy still fits end to end
    df = _int_reg_df(n=120, seed=2)
    model = cp.fit(df)
    assert len(model.avgMetrics) == 2


def test_rf_combined_multi_model_matches_per_model_eval():
    from spark_rapids_ml_tpu import RandomForestClassifier

    rng = np.random.default_rng(6)
    X = rng.normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    train = DataFrame.from_numpy(X, y=y, num_partitions=2)
    est = RandomForestClassifier(numTrees=4, seed=3)
    pm = [
        {est.getParam("maxDepth"): 2},
        {est.getParam("maxDepth"): 5},
    ]
    models = [m for _, m in est.fitMultiple(train, pm)]
    combined = models[0]._combine(models)
    assert combined._num_models == 2
    eva = MulticlassClassificationEvaluator(metricName="accuracy")
    single = [eva.evaluate(m.transform(train)) for m in models]
    fused = combined._transformEvaluate(train, eva)
    np.testing.assert_allclose(fused, single, atol=1e-12)
    # combined models refuse plain transform (ambiguous tree averaging)
    with pytest.raises(AssertionError):
        combined.transform(train)
