# Sharded UMAP engine contracts (ops/umap.py rework): on-device graph
# assembly vs the host reference, mesh-shape determinism (the CI parity gate
# runs this file on the 8-device CPU mesh), scan-batched dispatch counting,
# single-upload accounting, quality parity against the single-device
# reference layout, and zero-recompile repeat fits.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu import UMAP, profiling
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.ops import umap as uops
from spark_rapids_ml_tpu.parallel.mesh import get_mesh, padded_row_count


def _blob_graph(n=320, d=8, k=12, seed=0):
    """Deterministic tier-1 parity fixture: blob data + its exact kNN graph."""
    rng = np.random.default_rng(seed)
    centers = 10.0 * rng.normal(size=(3, d))
    labels = rng.integers(0, 3, size=n)
    X = (centers[labels] + rng.normal(size=(n, d))).astype(np.float32)
    from sklearn.neighbors import NearestNeighbors as SkNN

    dists, ids = SkNN(n_neighbors=k).fit(X).kneighbors(X)
    return X, ids.astype(np.int64), dists.astype(np.float32)


def _fit_kwargs(n_epochs=120, seed=7):
    return dict(
        n_components=2,
        a=1.577,
        b=0.895,
        n_epochs=n_epochs,
        learning_rate=1.0,
        init="spectral",
        set_op_mix_ratio=1.0,
        local_connectivity=1.0,
        repulsion_strength=1.0,
        negative_sample_rate=5,
        seed=seed,
    )


def _neighbor_preservation(X, emb, k=15):
    """Mean fraction of each point's k high-dim neighbors preserved among
    its k embedding neighbors (the acceptance metric)."""
    from sklearn.neighbors import NearestNeighbors as SkNN

    _, hi = SkNN(n_neighbors=k + 1).fit(X).kneighbors(X)
    _, lo = SkNN(n_neighbors=k + 1).fit(emb).kneighbors(emb)
    keep = 0.0
    for a, b in zip(hi[:, 1:], lo[:, 1:]):
        keep += len(set(a) & set(b)) / float(k)
    return keep / len(X)


def test_device_assembly_matches_host_reference():
    """build_head_layout_device must produce the same padded head layout as
    the host dedupe_undirected + padded_head_layout reference (same pad
    width, and per node the same truncated edge set with the same
    normalized weights)."""
    X, ids, dists = _blob_graph(n=200, k=10, seed=3)
    n = ids.shape[0]
    n_epochs = 150
    W = uops._calibrated_weights(
        jnp.asarray(ids.astype(np.int32)), jnp.asarray(dists), 1.0, 1.0
    )
    # host reference: dedupe -> prune -> pad -> normalize
    Wh = np.asarray(W)
    wmax = Wh.max()
    ii, jj, ww = uops.dedupe_undirected(ids, Wh)
    keep = ww / max(wmax, 1e-12) >= 1.0 / n_epochs
    tails_h, w_h = uops.padded_head_layout(ii[keep], jj[keep], ww[keep], n)
    w_h = w_h / max(wmax, 1e-12)
    # device path (padding rows beyond n are 0-weight self-loops)
    n_pad = padded_row_count(n)
    tails_d, w_d = uops.build_head_layout_device(
        jnp.asarray(ids.astype(np.int32)), W, n_pad, n_epochs
    )
    tails_d, w_d = np.asarray(tails_d), np.asarray(w_d)
    assert tails_d.shape == (n_pad, tails_h.shape[1])
    assert np.all(w_d[n:] == 0.0)
    assert np.all(tails_d[n:] == np.arange(n, n_pad)[:, None])
    for i in range(n):
        host_edges = {
            (int(t), round(float(w), 5))
            for t, w in zip(tails_h[i], w_h[i])
            if w > 0
        }
        dev_edges = {
            (int(t), round(float(w), 5))
            for t, w in zip(tails_d[i], w_d[i])
            if w > 0
        }
        assert host_edges == dev_edges, i


def test_mesh_shape_parity_and_quality():
    """The CI multi-device gate: a fixed seed must give the same embedding
    on a 1-device and an 8-device mesh (counter-based threefry draws index
    global positions, so sharding cannot change them), and the k=15
    neighbor-preservation score must stay within 1% of the single-device
    REFERENCE layout implementation (optimize_layout_padded)."""
    X, ids, dists = _blob_graph()
    n = ids.shape[0]
    kwargs = _fit_kwargs()
    emb_multi = uops.umap_fit_embedding(
        ids, dists, mesh=get_mesh(), **kwargs
    )
    emb_single = uops.umap_fit_embedding(
        ids, dists, mesh=get_mesh(1), **kwargs
    )
    assert emb_multi.shape == (n, 2)
    np.testing.assert_allclose(emb_multi, emb_single, atol=1e-4)

    # quality guard vs the pre-sharding reference: same graph + same init,
    # epochs run through the old single-device fori layout
    W = uops._calibrated_weights(
        jnp.asarray(ids.astype(np.int32)), jnp.asarray(dists), 1.0, 1.0
    )
    n_pad = padded_row_count(n, get_mesh())
    tails_pad, w_pad = uops.build_head_layout_device(
        jnp.asarray(ids.astype(np.int32)), W, n_pad, kwargs["n_epochs"]
    )
    key = jax.random.PRNGKey(kwargs["seed"])
    init = uops._spectral_scale_noise(
        uops._laplacian_eigenmap_kernel(
            tails_pad, w_pad, key, jnp.int32(n), c=2
        ),
        jax.random.fold_in(key, 0x5CA1E),
    )
    emb_ref = np.asarray(
        uops.optimize_layout_padded(
            init,
            tails_pad,
            w_pad,
            kwargs["a"],
            kwargs["b"],
            kwargs["n_epochs"],
            kwargs["learning_rate"],
            kwargs["repulsion_strength"],
            kwargs["negative_sample_rate"],
            kwargs["seed"],
        )
    )[:n]
    s_new = _neighbor_preservation(X, emb_multi)
    s_ref = _neighbor_preservation(X, emb_ref)
    assert abs(s_new - s_ref) < 0.01, (s_new, s_ref)


def test_layout_dispatch_count_is_epoch_blocks(monkeypatch):
    """The epoch loop must issue exactly ceil(n_epochs / EPOCH_BLOCK)
    device dispatches (the scan-batching acceptance bound)."""
    monkeypatch.setenv("SRML_UMAP_EPOCH_BLOCK", "40")
    X, ids, dists = _blob_graph(n=128, k=8, seed=5)
    c0 = profiling.counters("umap.layout")
    uops.umap_fit_embedding(
        ids, dists, mesh=get_mesh(), **_fit_kwargs(n_epochs=100)
    )
    delta = profiling.counter_deltas(c0, "umap.layout")
    assert delta.get("umap.layout.dispatches", 0) == 3  # ceil(100 / 40)


def test_fit_uploads_graph_once():
    """Single-upload contract: a host-array fit moves exactly the (n, k)
    ids + dists over the host link — the graph never round-trips back up
    (the duplicate tails_pad/w_pad upload this engine removed), and the
    supervised path adds only the label-code vector."""
    X, ids, dists = _blob_graph(n=128, k=8, seed=9)
    c0 = profiling.counters("umap.h2d")
    uops.umap_fit_embedding(
        ids, dists, mesh=get_mesh(), **_fit_kwargs(n_epochs=20)
    )
    d1 = profiling.counter_deltas(c0, "umap.h2d")
    assert d1.get("umap.h2d_transfers", 0) == 2
    assert d1.get("umap.h2d_bytes", 0) == ids.size * 4 + dists.size * 4

    y = np.random.default_rng(0).integers(0, 3, size=len(X)).astype(np.float64)
    c1 = profiling.counters("umap.h2d")
    uops.umap_fit_embedding(
        ids, dists, y=y, mesh=get_mesh(), **_fit_kwargs(n_epochs=20)
    )
    d2 = profiling.counter_deltas(c1, "umap.h2d")
    assert d2.get("umap.h2d_transfers", 0) == 3  # + the label codes


def test_repeat_fit_zero_new_compiles():
    """The acceptance smoke mirroring tests/test_precompile.py for kNN: a
    second same-shape UMAP.fit performs ZERO new compilations — every
    engine kernel (graph assembly, layout steps, knn search) lands on a
    cached AOT executable."""
    X, _, _ = _blob_graph(n=256, k=10, seed=11)
    df = DataFrame.from_numpy(X.astype(np.float64), num_partitions=2)
    est = UMAP(n_neighbors=10, random_state=0, n_epochs=80)
    m1 = est.fit(df)
    c0 = profiling.counters("precompile")
    m2 = est.fit(df)
    c1 = profiling.counters("precompile")
    assert c1.get("precompile.compile", 0) == c0.get("precompile.compile", 0)
    assert c1.get("precompile.fallback", 0) == c0.get("precompile.fallback", 0)
    assert c1.get("precompile.aot_hit", 0) > c0.get("precompile.aot_hit", 0)
    np.testing.assert_allclose(m1.embedding_, m2.embedding_, atol=1e-5)


def test_transform_device_path_deterministic_and_blocked(monkeypatch):
    """The transform refinement must be scan-batched (ceil(epochs/block)
    dispatches), deterministic across repeat calls, and bucket-padded so
    the padding rows never leak into results."""
    monkeypatch.setenv("SRML_UMAP_EPOCH_BLOCK", "16")
    rng = np.random.default_rng(2)
    nr, nq, k, c = 300, 100, 8, 2
    train_emb = rng.normal(size=(nr, c)).astype(np.float32)
    q_ids = rng.integers(0, nr, size=(nq, k))
    q_dists = np.sort(rng.random(size=(nq, k)).astype(np.float32) + 0.05, axis=1)
    kwargs = dict(
        local_connectivity=1.0, a=1.577, b=0.895, n_epochs=96, seed=5
    )  # 96 // 3 = 32 refinement epochs -> 2 blocks of 16
    c0 = profiling.counters("umap.transform")
    e1 = uops.umap_transform_embedding(q_ids, q_dists, train_emb, **kwargs)
    d1 = profiling.counter_deltas(c0, "umap.transform")
    assert d1.get("umap.transform.dispatches", 0) == 2
    e2 = uops.umap_transform_embedding(q_ids, q_dists, train_emb, **kwargs)
    assert e1.shape == (nq, c)
    np.testing.assert_allclose(e1, e2, atol=1e-6)
    assert np.all(np.isfinite(e1))


def test_ann_graph_knob_preserves_quality(monkeypatch):
    """SRML_UMAP_ANN=ivfflat routes the graph phase's kNN self-join through
    the srml-ann IVF-Flat engine (models/umap._ann_self_join).  Gate: the
    k=15 neighbor-preservation score of the ANN-graph layout stays within
    the established 1% tolerance of the exact-graph layout at the same
    seed (the same bar the sharded engine itself was accepted against).
    n=640: the preservation metric's run-to-run sensitivity to ulp-level
    graph perturbations shrinks with n (measured 0.027 at n=320 vs 0.001
    at n=640 for the SAME recall-1.0 graph), so the gate measures the
    knob, not SGD chaos."""
    rng = np.random.default_rng(0)
    centers = 10.0 * rng.normal(size=(4, 8))
    labels = rng.integers(0, 4, size=640)
    X = (centers[labels] + rng.normal(size=(640, 8))).astype(np.float32)
    df = DataFrame.from_numpy(X, num_partitions=2)
    est = UMAP(n_neighbors=12, n_epochs=120, random_state=7)
    emb_exact = est.fit(df).embedding_
    monkeypatch.setenv("SRML_UMAP_ANN", "ivfflat")
    emb_ann = est.fit(df).embedding_
    s_exact = _neighbor_preservation(X, emb_exact)
    s_ann = _neighbor_preservation(X, emb_ann)
    assert abs(s_ann - s_exact) < 0.01, (s_ann, s_exact)


def test_ann_graph_knob_rejects_unknown_mode(monkeypatch):
    monkeypatch.setenv("SRML_UMAP_ANN", "hnsw")
    from spark_rapids_ml_tpu.models.umap import _umap_ann_mode

    with pytest.raises(ValueError, match="not supported"):
        _umap_ann_mode()
