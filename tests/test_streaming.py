# srml-stream gates (docs/streaming.md is the contract):
#
#   1. Streamed-fit EQUALITY: partial_fit over k chunks vs batch fit on the
#      union — BITWISE for the closed-form engines (linreg coefficients,
#      sign-canonicalized PCA components) on the exact-arithmetic data
#      family (small-integer features, pow2 row count: every chunk partial
#      is an exact f32 sum, the f64 host fold is exact, finalize shares the
#      batch solver kernels), quality-gated for the online approximations
#      (kmeans inertia, logreg accuracy) — across 1/2/8-device batch
#      meshes (streamed states are mesh-independent data by construction).
#   2. ZERO-COMPILE steady ingest: after the first chunk of a bucket,
#      further same-bucket chunks move precompile.aot_hit and never
#      precompile.compile.
#   3. Merge algebra: associative/commutative state merge, wire round
#      trip, control-plane allgather fold, identity-anchor mismatch fails
#      loudly.
#   4. Live IVF mutation: add/delete/repack on a serving index with
#      recall@10 >= 0.95 at every step, tombstoned ids never returned,
#      zero steady-state compiles across a warm-covered repack.
#   5. Train-while-serve: StreamingSession.refresh() through the router
#      under concurrent load — zero client-visible errors, zero new
#      compiles at a same-shape refresh.
import json

import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    ApproximateNearestNeighbors,
    KMeans,
    LinearRegression,
    LogisticRegression,
    PCA,
    profiling,
)
from spark_rapids_ml_tpu.dataframe import DataFrame, stream_chunk_ids
from spark_rapids_ml_tpu.stream import (
    StreamingSession,
    StreamState,
    allgather_merge,
    merge_all,
    streaming_fit,
)

CHUNK = 128


@pytest.fixture(scope="module")
def exact_data():
    """The exact-arithmetic family: small-integer features, pow2 rows —
    every f32 sum in both the batch moment passes and the streamed chunk
    partials is exact, so bitwise streamed==batch is a mathematical
    identity, not a tolerance (same basis as the srml-sweep bitwise gates,
    docs/tuning_engine.md)."""
    rng = np.random.default_rng(3)
    n, d = 512, 8
    X = rng.integers(-4, 5, size=(n, d)).astype(np.float32)
    y = (X @ np.arange(1.0, d + 1.0)).astype(np.float64)
    cid = stream_chunk_ids(n, CHUNK, seed=5)
    return X, y, cid


@pytest.fixture(scope="module")
def clustered_data():
    rng = np.random.default_rng(11)
    n, d, k = 1024, 8, 4
    centers = rng.standard_normal((k, d)) * 8
    X = (centers[rng.integers(0, k, n)] + rng.standard_normal((n, d))).astype(
        np.float32
    )
    cid = stream_chunk_ids(n, 256, seed=7)
    return X, cid, k


def _stream(engine, X, cid, y=None):
    for c in range(int(cid.max()) + 1):
        m = cid == c
        engine.partial_fit(X[m], y=None if y is None else y[m])
    return engine


# -- 1. streamed == batch equality -------------------------------------------


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_streamed_linreg_bitwise_equals_batch(exact_data, n_dev):
    X, y, cid = exact_data
    batch = LinearRegression(maxIter=20, num_workers=n_dev).fit(
        DataFrame.from_numpy(X, y=y, num_partitions=2)
    )
    streamed = _stream(
        LinearRegression(maxIter=20).streaming(), X, cid, y=y
    ).finalize()
    np.testing.assert_array_equal(streamed.coef_, batch.coef_)
    assert streamed.intercept_ == batch.intercept_
    assert streamed.n_cols == batch.n_cols and streamed.dtype == batch.dtype


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_streamed_pca_bitwise_equals_batch(exact_data, n_dev):
    X, _y, cid = exact_data
    batch = (
        PCA(k=3, num_workers=n_dev)
        .setInputCol("features")
        .fit(DataFrame.from_numpy(X, feature_layout="array", num_partitions=2))
    )
    streamed = _stream(
        PCA(k=3).setInputCol("features").streaming(), X, cid
    ).finalize()
    # components are sign-canonicalized by the shared sign_flip inside
    # _pca_from_moments on BOTH routes — bitwise is the bar
    np.testing.assert_array_equal(streamed.components_, batch.components_)
    np.testing.assert_array_equal(streamed.mean_, batch.mean_)
    np.testing.assert_array_equal(
        streamed.explained_variance_, batch.explained_variance_
    )
    np.testing.assert_array_equal(
        streamed.singular_values_, batch.singular_values_
    )


def _inertia(centers, X):
    d2 = ((X[:, None, :] - centers[None]) ** 2).sum(-1)
    return float(d2.min(axis=1).sum())


def test_streamed_kmeans_inertia_quality(clustered_data):
    X, cid, k = clustered_data
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=2)
    batch = KMeans(k=k, maxIter=20, seed=1).setFeaturesCol("features").fit(df)
    streamed = _stream(
        KMeans(k=k, maxIter=20, seed=1).setFeaturesCol("features").streaming(),
        X, cid,
    ).finalize()
    bi = _inertia(np.asarray(batch.cluster_centers_), X)
    si = _inertia(np.asarray(streamed.cluster_centers_), X)
    # one-pass mini-batch Lloyd on clustered data: within 10% of batch
    assert si <= 1.10 * bi, (si, bi)
    assert streamed.n_cols == batch.n_cols
    # the model predicts like any batch model
    assert streamed.predict(X[0]) in range(k)


def test_streamed_logreg_metric_quality(clustered_data):
    X, cid, _k = clustered_data
    rng = np.random.default_rng(5)
    w = rng.standard_normal(X.shape[1])
    margin = X @ w
    y = (margin > np.median(margin)).astype(np.float64)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)

    def acc(model):
        out = model.transform(df)
        preds = np.concatenate(
            [np.asarray(p["prediction"]) for p in out.partitions if len(p)]
        )
        return float((preds == y).mean())

    batch = LogisticRegression(maxIter=30).fit(df)
    streamed = _stream(
        LogisticRegression(maxIter=30).streaming(), X, cid, y=y
    ).finalize()
    assert acc(streamed) >= acc(batch) - 0.03, (acc(streamed), acc(batch))
    np.testing.assert_array_equal(streamed.classes_, batch.classes_)


# -- 2. zero-compile steady ingest -------------------------------------------


def test_steady_ingest_zero_new_compiles(exact_data):
    X, y, cid = exact_data
    eng = LinearRegression(maxIter=20).streaming()
    eng.partial_fit(X[cid == 0], y=y[cid == 0])  # bucket's first chunk
    before = profiling.counters("precompile.")
    for c in range(1, int(cid.max()) + 1):
        m = cid == c
        eng.partial_fit(X[m], y=y[m])
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.fallback", 0) == 0, delta
    assert delta.get("precompile.aot_hit", 0) >= int(cid.max()), delta


def test_ingest_counters_and_frame_chunks(exact_data):
    """Frame chunks route through utils.materialize_feature_block (the
    shared ingest path) and ingest volume lands on the
    stream.h2d_transfers/stream.bytes counter pair."""
    X, y, cid = exact_data
    m = cid == 0
    before = profiling.counters("stream.")
    eng_np = LinearRegression(maxIter=20).streaming()
    eng_np.partial_fit(X[m], y=y[m])
    eng_df = LinearRegression(maxIter=20).streaming()
    eng_df.partial_fit(DataFrame.from_numpy(X[m], y=y[m], num_partitions=2))
    delta = profiling.counter_deltas(before, "stream.")
    assert delta.get("stream.h2d_transfers", 0) >= 6, delta  # 3 buffers x 2
    assert delta.get("stream.bytes", 0) > 0, delta
    assert delta.get("stream.rows", 0) == 2 * int(m.sum()), delta
    # identical chunk membership => identical accumulated state
    assert eng_np.state == eng_df.state
    with pytest.raises(ValueError, match="y/weight only with numpy"):
        eng_df.partial_fit(
            DataFrame.from_numpy(X[m], y=y[m]), y=y[m]
        )


# -- 3. merge algebra --------------------------------------------------------


def test_state_merge_commutative_associative_and_wire(exact_data):
    X, y, cid = exact_data
    engines = []
    for c in range(3):
        m = cid == c
        engines.append(
            _stream(
                LinearRegression(maxIter=20).streaming(),
                X[m], np.zeros(int(m.sum()), np.int32), y=y[m],
            )
        )
    a, b, c3 = (e.state for e in engines)
    # exact data => merge order cannot change a single bit
    ab_c = a.merge(b).merge(c3)
    a_bc = a.merge(b.merge(c3))
    ba_c = b.merge(a).merge(c3)
    assert ab_c == a_bc == ba_c
    # wire round trip through the JSON form is lossless
    assert StreamState.from_dict(json.loads(json.dumps(ab_c.to_dict()))) == ab_c
    assert merge_all([a, b, c3]) == ab_c


def test_two_rank_merge_equals_single_stream(exact_data):
    """Rank 0 streams chunks {0,1}, rank 1 streams {2,3}; the merged
    engine finalizes BIT-IDENTICALLY to one engine that saw all four —
    the multi-rank scale-out contract."""
    X, y, cid = exact_data
    r0 = LinearRegression(maxIter=20).streaming()
    r1 = LinearRegression(maxIter=20).streaming()
    for c in range(int(cid.max()) + 1):
        m = cid == c
        (r0 if c < 2 else r1).partial_fit(X[m], y=y[m])
    solo = _stream(LinearRegression(maxIter=20).streaming(), X, cid, y=y)
    merged = r0.merge(r1.state_dict())  # wire-form merge, as ranks would
    np.testing.assert_array_equal(
        merged.finalize().coef_, solo.finalize().coef_
    )


def test_fresh_engine_adopts_peer_state(exact_data):
    """A rank whose partition was empty (zero chunks ingested) must still
    fold peer states — it adopts the gathered state wholesale, anchors
    included, and finalizes identically to the peer."""
    X, y, cid = exact_data
    peer = _stream(LinearRegression(maxIter=20).streaming(), X, cid, y=y)
    fresh = LinearRegression(maxIter=20).streaming()
    fresh.merge(peer.state_dict())
    np.testing.assert_array_equal(fresh.finalize().coef_, peer.finalize().coef_)
    # logreg: the classes anchor must come across too
    yl = (X[:, 0] > 0).astype(np.float64)
    lpeer = _stream(LogisticRegression(maxIter=10).streaming(), X, cid, y=yl)
    lfresh = LogisticRegression(maxIter=10).streaming()
    lfresh.merge(lpeer.state)
    np.testing.assert_array_equal(
        lfresh.finalize().classes_, lpeer.finalize().classes_
    )


def test_chunk_label_length_mismatch_fails_loudly(exact_data):
    X, y, _cid = exact_data
    eng = LinearRegression(maxIter=20).streaming()
    with pytest.raises(ValueError, match="chunk y has 50 rows but X has 100"):
        eng.partial_fit(X[:100], y=y[:50])
    with pytest.raises(ValueError, match="chunk weight has"):
        eng.partial_fit(X[:100], y=y[:100], weight=np.ones(99))
    # frame chunks cannot even CONSTRUCT the mismatch: the facade rejects
    # partitions with differing columns (the frame-branch length check in
    # _chunk_arrays is defensive depth behind this constructor guard)
    import pandas as pd

    from spark_rapids_ml_tpu.dataframe import DataFrame as Facade

    p0 = pd.DataFrame({"features": list(X[:8]), "label": y[:8]})
    p1 = pd.DataFrame({"features": list(X[8:16])})
    with pytest.raises(ValueError, match="same columns"):
        Facade([p0, p1])


def test_allgather_merge_over_control_plane(exact_data):
    from spark_rapids_ml_tpu.parallel.context import LocalControlPlane

    X, y, cid = exact_data
    eng = _stream(LinearRegression(maxIter=20).streaming(), X, cid, y=y)
    merged = allgather_merge(LocalControlPlane(), eng.state)
    assert merged == eng.state  # single-controller: identity fold


def test_merge_anchor_mismatch_fails_loudly(clustered_data):
    X, cid, k = clustered_data
    a = KMeans(k=k, maxIter=5, seed=1).setFeaturesCol("features").streaming()
    b = KMeans(k=k, maxIter=5, seed=2).setFeaturesCol("features").streaming()
    a.partial_fit(X[cid == 0])
    b.partial_fit(X[cid == 1])  # different seed => different init anchor
    with pytest.raises(ValueError, match="init_centers"):
        a.merge(b)
    with pytest.raises(ValueError, match="kind"):
        a.state.merge(
            _stream(
                PCA(k=2).setInputCol("features").streaming(),
                X, np.zeros(len(X), np.int32),
            ).state
        )


def test_logreg_unseen_label_fails_loudly(clustered_data):
    X, cid, _k = clustered_data
    eng = LogisticRegression(maxIter=5).streaming()
    m0 = cid == 0
    y0 = (X[m0, 0] > 0).astype(np.float64)
    eng.partial_fit(X[m0], y=y0)
    m1 = cid == 1
    with pytest.raises(ValueError, match="outside the stream's class set"):
        eng.partial_fit(X[m1], y=np.full(int(m1.sum()), 7.0))


# -- stream_chunk_ids (dataframe satellite) ----------------------------------


def test_stream_chunk_ids_deterministic_and_partitioning():
    ids = stream_chunk_ids(1000, 256, seed=9)
    replay = stream_chunk_ids(1000, 256, seed=9)
    np.testing.assert_array_equal(ids, replay)  # replayed stream: identical
    assert ids.shape == (1000,) and ids.dtype == np.int32
    sizes = np.bincount(ids)
    # EXACT integer cuts: every chunk is chunk_rows except the short tail
    # (never chunk_rows+1 — a drifted row would cross a pow2 bucket
    # boundary and compile mid-stream)
    np.testing.assert_array_equal(sizes, [256, 256, 256, 232])
    for n, c in ((22, 3), (513, 256), (97, 10)):
        s = np.bincount(stream_chunk_ids(n, c, seed=1))
        assert s[:-1].tolist() == [c] * (len(s) - 1) and 0 < s[-1] <= c, (n, c, s)
    assert not np.array_equal(ids, stream_chunk_ids(1000, 256, seed=10))
    assert stream_chunk_ids(0, 256).size == 0
    with pytest.raises(ValueError, match="chunk_rows"):
        stream_chunk_ids(10, 0)


# -- 4. live IVF mutation ----------------------------------------------------


@pytest.fixture(scope="module")
def live_index():
    """A fitted IVF-Flat model + its mutable holder + clustered item/query
    sets (module-scoped: the mutation tests form one ordered story via
    fresh holders per test on a shared model class)."""
    rng = np.random.default_rng(17)
    n, d = 1500, 16
    centers = rng.standard_normal((8, d)) * 6
    X = (centers[rng.integers(0, 8, n)] + rng.standard_normal((n, d))).astype(
        np.float32
    )
    Q = (centers[rng.integers(0, 8, 48)] + rng.standard_normal((48, d))).astype(
        np.float32
    )
    extra = (
        centers[rng.integers(0, 8, 300)] + rng.standard_normal((300, d))
    ).astype(np.float32)
    return X, Q, extra, centers


def _fit_ann(X):
    return (
        ApproximateNearestNeighbors(k=10, algoParams={"nlist": 16, "nprobe": 8})
        .setFeaturesCol("features")
        .fit(DataFrame.from_numpy(X, feature_layout="array"))
    )


def _exact_ids(items, ids, Q, k=10):
    d2 = ((Q[:, None, :].astype(np.float64) - items[None].astype(np.float64)) ** 2).sum(-1)
    return np.asarray(ids)[np.argsort(d2, axis=1)[:, :k]]


def test_live_index_add_delete_repack_recall(live_index):
    from spark_rapids_ml_tpu.ann import recall_at_k

    X, Q, extra, _ = live_index
    n = X.shape[0]
    model = _fit_ann(X)
    holder = model.mutable_index()
    _, ids0 = holder.search(Q, 10, 8)
    assert recall_at_k(ids0, _exact_ids(X, np.arange(n), Q)) >= 0.95

    # add
    holder.add_items(extra, np.arange(n, n + len(extra)))
    items = np.concatenate([X, extra])
    all_ids = np.arange(n + len(extra))
    _, ids1 = holder.search(Q, 10, 8)
    assert recall_at_k(ids1, _exact_ids(items, all_ids, Q)) >= 0.95

    # delete: tombstoned ids must NEVER come back
    dele = np.arange(0, 300)
    assert holder.delete_items(dele) == 300
    assert holder.delete_items(dele) == 0  # idempotent
    keep = np.ones(len(all_ids), bool)
    keep[dele] = False
    _, ids2 = holder.search(Q, 10, 8)
    assert not np.isin(ids2, dele).any()
    assert recall_at_k(ids2, _exact_ids(items[keep], all_ids[keep], Q)) >= 0.95
    st = holder.stats()
    assert st["tombstoned"] == 300 and st["n_items"] == len(all_ids) - 300
    # the packed tombstone bitmap surface covers every slot
    bitmap = holder.tombstone_bitmap()
    assert bitmap.dtype == np.uint8
    assert int(np.unpackbits(bitmap, axis=1).sum()) == 300

    # repack reclaims the tombstones; results stay recall-clean
    holder.repack()
    st = holder.stats()
    assert st["tombstoned"] == 0 and st["repacks"] == 1
    _, ids3 = holder.search(Q, 10, 8)
    assert not np.isin(ids3, dele).any()
    assert recall_at_k(ids3, _exact_ids(items[keep], all_ids[keep], Q)) >= 0.95


def test_live_index_overflow_repack_zero_steady_compiles(live_index):
    """Warm-before-swap across a bucket-growing repack: a burst add that
    overflows L_pad migrates to the next pow2 bucket; because the holder
    re-warms every noted probe geometry before swapping, the next search
    performs ZERO new compilations."""
    from spark_rapids_ml_tpu.ann import recall_at_k

    X, Q, _extra, centers = live_index
    rng = np.random.default_rng(23)
    model = _fit_ann(X)
    holder = model.mutable_index()
    holder.search(Q, 10, 8)  # notes the probe geometry for re-warm
    l_pad0 = holder.stats()["l_pad"]
    burst = (
        centers[0] + 0.5 * rng.standard_normal((4 * l_pad0, X.shape[1]))
    ).astype(np.float32)
    holder.add_items(burst, np.arange(50_000, 50_000 + len(burst)))
    st = holder.stats()
    assert st["l_pad"] > l_pad0 and st["repacks"] == 1
    before = profiling.counters("precompile.")
    _, ids = holder.search(Q, 10, 8)
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    items = np.concatenate([X, burst])
    all_ids = np.concatenate(
        [np.arange(len(X)), np.arange(50_000, 50_000 + len(burst))]
    )
    assert recall_at_k(ids, _exact_ids(items, all_ids, Q)) >= 0.95


def test_snapshot_isolated_from_later_mutations(live_index):
    """A search holding an index snapshot must see the WHOLE old state: a
    later delete/add mutates the holder's mirrors, never the snapshot's
    host id table (device buffers are immutable uploads already)."""
    X, _Q, extra, _ = live_index
    model = _fit_ann(X)
    holder = model.mutable_index()
    snap = holder.index
    victim = 7
    pos = holder._pos_of_id[victim]
    assert snap.ids[pos] == victim
    holder.delete_items(np.array([victim]))
    assert snap.ids[pos] == victim  # old snapshot untouched
    assert holder.index.ids[pos] == -1  # new snapshot sees the delete
    holder.add_items(extra[:1], np.array([77_000]))
    assert 77_000 not in snap.ids  # adds invisible to the old snapshot too


def test_search_never_blocks_on_mutator_lock(live_index):
    """The lock-free reader contract, structurally: a search issued while
    another thread HOLDS the mutator lock (as a repack's staging+warm
    would) completes instead of queuing behind it."""
    import threading

    X, Q, _extra, _ = live_index
    model = _fit_ann(X)
    holder = model.mutable_index()
    holder.search(Q, 10, 8)  # warm the probe path first
    done = threading.Event()
    out = {}

    def probe():
        out["ids"] = holder.search(Q, 10, 8)[1]
        done.set()

    with holder._lock:  # simulate an in-flight mutation holding the lock
        t = threading.Thread(target=probe, name="stream-test-probe")
        t.start()
        finished = done.wait(timeout=30)
    t.join(timeout=30)
    assert finished, "search blocked behind the mutator lock"
    assert out["ids"].shape == (len(Q), 10)


def test_exact_search_rejected_while_mutable(live_index):
    """kneighbors(exactSearch=True) reads the persistable packed payload,
    which live mutations do not touch until freeze — serving it would
    return tombstoned ids.  It must refuse, typed, until freeze."""
    X, Q, extra, _ = live_index
    model = _fit_ann(X)
    holder = model.mutable_index()
    holder.add_items(extra[:10], np.arange(90_000, 90_010))
    model.setExactSearch(True)
    try:
        with pytest.raises(ValueError, match="freeze"):
            model.kneighbors(DataFrame.from_numpy(Q[:4], num_partitions=1))
        model.freeze_mutations()
        _, _, knn = model.kneighbors(
            DataFrame.from_numpy(Q[:4], num_partitions=1)
        )
        ids = np.concatenate(
            [np.stack(list(p["indices"])) for p in knn.partitions if len(p)]
        )
        assert ids.shape == (4, 10)  # frozen payload serves the exact route
    finally:
        model.setExactSearch(False)


def test_live_index_validation_errors(live_index):
    X, _Q, extra, _ = live_index
    model = _fit_ann(X)
    holder = model.mutable_index()
    with pytest.raises(ValueError, match="duplicate ids"):
        holder.add_items(extra[:2], np.array([99_000, 99_000]))
    with pytest.raises(ValueError, match="already present"):
        holder.add_items(extra[:1], np.array([0]))
    with pytest.raises(ValueError, match="items must be"):
        holder.add_items(extra[:, :4], np.array([99_001, 99_002])[: len(extra)])
    with pytest.raises(ValueError, match="items vs"):
        holder.add_items(extra[:3], np.array([99_003]))
    pq_model = ApproximateNearestNeighbors(
        k=4, algorithm="ivfpq",
        algoParams={"nlist": 4, "nprobe": 4, "M": 2, "n_bits": 4},
    ).setFeaturesCol("features").fit(
        DataFrame.from_numpy(X[:200, :16], feature_layout="array")
    )
    with pytest.raises(ValueError, match="IVF-Flat-only"):
        pq_model.mutable_index()


def test_served_ann_absorbs_mutations(live_index):
    """The live-index serving gate: an index serving through serve.ann
    absorbs add/delete/repack — every served batch reflects the mutation
    state at dispatch, recall holds at every step, tombstoned ids never
    surface, and the serving plane sees zero errors."""
    from spark_rapids_ml_tpu.ann import recall_at_k
    from spark_rapids_ml_tpu.serving import ModelRegistry

    X, Q, extra, _ = live_index
    n = X.shape[0]
    model = _fit_ann(X)
    holder = model.mutable_index()
    reg = ModelRegistry(max_batch=64, max_wait_ms=2)
    try:
        reg.register("live_ann", model)
        server = reg.get("live_ann")
        out0 = server.predict(Q)
        assert recall_at_k(
            out0["indices"], _exact_ids(X, np.arange(n), Q)
        ) >= 0.95

        holder.add_items(extra, np.arange(n, n + len(extra)))
        items = np.concatenate([X, extra])
        all_ids = np.arange(n + len(extra))
        out1 = server.predict(Q)
        assert recall_at_k(out1["indices"], _exact_ids(items, all_ids, Q)) >= 0.95
        # the added ids are genuinely reachable through serving
        assert np.isin(out1["indices"], np.arange(n, n + len(extra))).any()

        dele = np.arange(0, 200)
        holder.delete_items(dele)
        keep = np.ones(len(all_ids), bool)
        keep[dele] = False
        out2 = server.predict(Q)
        assert not np.isin(out2["indices"], dele).any()
        assert recall_at_k(
            out2["indices"], _exact_ids(items[keep], all_ids[keep], Q)
        ) >= 0.95

        holder.repack()
        before = profiling.counters("precompile.")
        out3 = server.predict(Q)
        delta = profiling.counter_deltas(before, "precompile.")
        assert delta.get("precompile.compile", 0) == 0, delta
        assert recall_at_k(
            out3["indices"], _exact_ids(items[keep], all_ids[keep], Q)
        ) >= 0.95
    finally:
        reg.shutdown(drain=False)


def test_mutable_freeze_persist_roundtrip(live_index, tmp_path):
    from spark_rapids_ml_tpu.core import load as core_load

    X, Q, extra, _ = live_index
    n = X.shape[0]
    model = _fit_ann(X)
    holder = model.mutable_index()
    holder.add_items(extra, np.arange(n, n + len(extra)))
    holder.delete_items(np.arange(0, 100))
    d_live, i_live = holder.search(Q, 10, 8)
    model.freeze_mutations()
    assert model.n_items == n + len(extra) - 100
    path = str(tmp_path / "mutated_ann")
    model.save(path)
    loaded = core_load(path)
    _, _, knn = loaded.kneighbors(DataFrame.from_numpy(Q, num_partitions=1))
    ids = np.concatenate(
        [np.stack(list(p["indices"])) for p in knn.partitions if len(p)]
    )
    # the persisted artifact reflects the mutations: no deleted ids, added
    # ids reachable, and the result set matches the live holder's ID SET
    # row for row (the repacked layout reorders positions, so distances
    # agree but tie order may differ — the id set is the contract)
    assert not np.isin(ids, np.arange(0, 100)).any()
    overlap = [
        np.intersect1d(a, b).size / a.shape[0] for a, b in zip(ids, i_live)
    ]
    assert float(np.mean(overlap)) >= 0.95, float(np.mean(overlap))


# -- 5. train-while-serve ----------------------------------------------------


def test_session_staleness_and_refresh_accounting(clustered_data):
    X, cid, k = clustered_data
    eng = KMeans(k=k, maxIter=5, seed=1).setFeaturesCol("features").streaming()
    session = StreamingSession(eng)
    session.partial_fit(X[cid == 0])
    assert session.staleness_rows == int((cid == 0).sum())
    assert session.staleness_seconds is None  # never refreshed
    model = session.refresh()  # no serving plane: snapshot + clock reset
    assert model.cluster_centers_ is not None
    assert session.staleness_rows == 0 and session.stats()["refreshes"] == 1
    session.partial_fit(X[cid == 1])
    assert session.staleness_rows == int((cid == 1).sum())
    assert session.staleness_seconds is not None
    with pytest.raises(ValueError, match="model name"):
        StreamingSession(eng, registry=object())


def test_session_ingest_refresh_every_rows(clustered_data):
    X, cid, k = clustered_data
    eng = KMeans(k=k, maxIter=5, seed=1).setFeaturesCol("features").streaming()
    session = StreamingSession(eng)
    chunks = [X[cid == c] for c in range(int(cid.max()) + 1)]
    session.ingest(iter(chunks), refresh_every_rows=512)
    assert session.stats()["refreshes"] >= 1
    assert session.rows_ingested == len(X)


def test_session_refresh_through_registry_swap(clustered_data):
    from spark_rapids_ml_tpu.serving import ModelRegistry

    X, cid, k = clustered_data
    eng = KMeans(k=k, maxIter=5, seed=1).setFeaturesCol("features").streaming()
    reg = ModelRegistry(max_batch=16, max_wait_ms=2)
    try:
        session = StreamingSession(eng, name="stream_km", registry=reg)
        session.partial_fit(X[cid == 0])
        session.refresh()  # first refresh registers
        assert "stream_km" in reg
        out = reg.get("stream_km").predict(X[:4])
        assert out["prediction"].shape == (4,)
        session.partial_fit(X[cid == 1])
        before = profiling.counters("precompile.")
        session.refresh()  # same-shape successor: swap from retained cache
        delta = profiling.counter_deltas(before, "precompile.")
        assert delta.get("precompile.compile", 0) == 0, delta
        assert profiling.counter("serving.stream_km.swaps") >= 1
        out = reg.get("stream_km").predict(X[:4])
        assert out["prediction"].shape == (4,)
    finally:
        reg.shutdown(drain=False)


def test_session_refresh_under_router_load_zero_client_errors(clustered_data):
    """The train-while-serve gate: a router serving a streamed model keeps
    answering a concurrent request burst across refresh() — every future
    resolves, zero client-visible errors, zero new compiles at the
    same-shape cut-over (the PR 11 swap guarantees, driven by the
    streaming plane)."""
    import threading

    from spark_rapids_ml_tpu.serving import Router

    X, cid, k = clustered_data
    eng = KMeans(k=k, maxIter=5, seed=1).setFeaturesCol("features").streaming()
    router = Router(max_batch=32, max_wait_ms=2)
    try:
        session = StreamingSession(
            eng, name="stream_rt", router=router, replicas=2
        )
        session.partial_fit(X[cid == 0])
        session.refresh()  # serve
        router.predict("stream_rt", X[:4])  # warm client path
        session.partial_fit(X[cid == 1])

        futures, submit_errors = [], []
        stop = threading.Event()

        def pump():
            import time

            i = 0
            while not stop.is_set() and len(futures) < 512:
                try:
                    futures.append(router.submit("stream_rt", X[i % 64 : i % 64 + 4]))
                except Exception as exc:  # typed shed/overload still counts as error here
                    submit_errors.append(exc)
                i += 4
                time.sleep(0.002)  # paced open loop: the gate is swap
                # correctness under live traffic, not an overload probe

        t = threading.Thread(target=pump, name="stream-load-pump")
        t.start()
        try:
            before = profiling.counters("precompile.")
            session.refresh()  # rolling swap under live load
            delta = profiling.counter_deltas(before, "precompile.")
        finally:
            stop.set()
            t.join(timeout=30)
        assert not t.is_alive()
        assert delta.get("precompile.compile", 0) == 0, delta
        assert not submit_errors, submit_errors[:3]
        assert futures
        for f in futures:
            out = f.result(timeout=60)  # every admitted request resolves
            assert out["prediction"].shape[0] > 0
    finally:
        router.shutdown(drain=False)
