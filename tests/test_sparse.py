# Sparse (CSR -> ELL) ingest + GLM kernels: conversion exactness, sufficient-
# stats parity with the dense pass, end-to-end LogisticRegression /
# LinearRegression fits on CSR DataFrames vs sklearn, transform parity, and
# the densify-with-warning fallback for estimators without a sparse path
# (strategy mirrors the reference's sparse logreg tests,
# test_logistic_regression.py sparse vector cases).
import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")

import jax.numpy as jnp

from spark_rapids_ml_tpu.compat import enable_x64
from spark_rapids_ml_tpu import (
    KMeans,
    LinearRegression,
    LogisticRegression,
)
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.ops.sparse import (
    EllMatrix,
    ell_device_from_scipy,
    ell_from_csr,
    ell_matmat,
    ell_matvec,
    ell_sufficient_stats,
)


def _random_csr(n=300, d=40, density=0.08, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    X = scipy_sparse.random(
        n, d, density=density, format="csr", random_state=rng, dtype=dtype
    )
    # ensure at least one row is empty and one is the max-nnz row
    X[0] = 0
    X.eliminate_zeros()
    return X.tocsr()


def test_ell_from_csr_roundtrip():
    X = _random_csr()
    idx, val = ell_from_csr(X.indptr, X.indices, X.data, X.shape[1], np.float64)
    dense = np.zeros(X.shape)
    np.add.at(dense, (np.arange(X.shape[0])[:, None], idx), val)
    np.testing.assert_array_equal(dense, X.toarray())


def test_ell_matvec_matmat():
    with enable_x64(True):  # the fit path's f64 scope (core._maybe_x64)
        X = _random_csr(seed=1)
        ell = ell_device_from_scipy(X, np.float64)
        b = np.random.default_rng(2).normal(size=X.shape[1])
        np.testing.assert_allclose(
            np.asarray(ell_matvec(ell, jnp.asarray(b))), X @ b, rtol=1e-12
        )
        B = np.random.default_rng(3).normal(size=(X.shape[1], 5))
        np.testing.assert_allclose(
            np.asarray(ell_matmat(ell, jnp.asarray(B))), X @ B, rtol=1e-12
        )


@pytest.mark.parametrize("use_mesh", [False, True])
def test_ell_sufficient_stats_parity(use_mesh):
    import jax

    from spark_rapids_ml_tpu.ops.glm import linreg_sufficient_stats
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh, shard_rows

    with enable_x64(True):  # the fit path's f64 scope (core._maybe_x64)
        X = _random_csr(n=256, seed=4)
        rng = np.random.default_rng(5)
        y = rng.normal(size=256)
        w = np.ones(256)
        mesh = get_mesh() if use_mesh else None
        ell = ell_device_from_scipy(X, np.float64, mesh=mesh)
        if use_mesh:
            y_s, _ = shard_rows(y, mesh)
            w_s, _ = shard_rows(w, mesh)
            stats = ell_sufficient_stats(
                ell, jnp.asarray(y_s), jnp.asarray(w_s), mesh=mesh, chunk=37
            )
        else:
            stats = ell_sufficient_stats(
                ell, jnp.asarray(y), jnp.asarray(w), mesh=None, chunk=37
            )
        ref = linreg_sufficient_stats(
            jnp.asarray(X.toarray()), jnp.asarray(y), jnp.asarray(w), mesh=None
        )
        # one batched fetch, then compare on host (graftlint R1: a per-field
        # np.asarray in the loop pays a device round-trip each)
        for got, want in zip(jax.device_get(tuple(stats)), jax.device_get(tuple(ref))):
            np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)


def _sparse_cls_data(n=2000, d=60, density=0.08, classes=2, seed=7):
    rng = np.random.default_rng(seed)
    X = scipy_sparse.random(
        n, d, density=density, format="csr", random_state=rng, dtype=np.float64
    )
    W = rng.normal(size=(d, classes))
    logits = X @ W
    y = np.argmax(logits + 0.3 * rng.normal(size=logits.shape), axis=1).astype(
        np.float64
    )
    return X.tocsr(), y


def test_logistic_sparse_binary_matches_sklearn():
    from sklearn.linear_model import LogisticRegression as SkLR

    X, y = _sparse_cls_data()
    df = DataFrame.from_numpy(X, y=y, num_partitions=4)
    model = LogisticRegression(
        regParam=0.01, maxIter=300, tol=1e-9, standardization=False,
        float32_inputs=False,
    ).fit(df)
    sk = SkLR(C=1.0 / (0.01 * X.shape[0]), max_iter=5000, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(
        np.asarray(model.coefficients), sk.coef_.ravel(), atol=2e-3
    )
    # accuracy parity on the training set
    pred = model.transform(df).toPandas()["prediction"].to_numpy()
    assert (pred == y).mean() >= (sk.predict(X) == y).mean() - 0.01


def test_logistic_sparse_multinomial_matches_sklearn():
    from sklearn.linear_model import LogisticRegression as SkLR

    X, y = _sparse_cls_data(classes=3, seed=8)
    df = DataFrame.from_numpy(X, y=y, num_partitions=3)
    model = LogisticRegression(
        regParam=0.02, maxIter=300, tol=1e-9, standardization=False,
        float32_inputs=False,
    ).fit(df)
    sk = SkLR(C=1.0 / (0.02 * X.shape[0]), max_iter=5000, tol=1e-10).fit(X, y)
    ours = (model.transform(df).toPandas()["prediction"].to_numpy() == y).mean()
    theirs = (sk.predict(X) == y).mean()
    assert ours >= theirs - 0.01


def test_linreg_sparse_matches_sklearn():
    from sklearn.linear_model import LinearRegression as SkLR, Ridge

    rng = np.random.default_rng(9)
    X = _random_csr(n=1500, d=50, density=0.1, seed=9)
    coef = rng.normal(size=50)
    y = X @ coef + 1.7 + 0.05 * rng.normal(size=1500)
    df = DataFrame.from_numpy(X, y=y, num_partitions=4)

    model = LinearRegression(regParam=0.0, float32_inputs=False).fit(df)
    sk = SkLR().fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_, atol=1e-6)
    assert abs(model.intercept - sk.intercept_) < 1e-6

    # Ridge (Spark alpha*n scaling; standardization off for direct compare)
    model_r = LinearRegression(
        regParam=0.1, standardization=False, float32_inputs=False
    ).fit(df)
    sk_r = Ridge(alpha=0.1 * X.shape[0]).fit(X, y)
    np.testing.assert_allclose(model_r.coefficients, sk_r.coef_, atol=1e-5)

    # sparse transform parity with the dense transform
    preds = model.transform(df).toPandas()["prediction"].to_numpy()
    df_dense = DataFrame.from_numpy(X.toarray(), y=y, num_partitions=4)
    preds_dense = model.transform(df_dense).toPandas()["prediction"].to_numpy()
    np.testing.assert_allclose(preds, preds_dense, atol=1e-5)


def test_sparse_fit_never_densifies(monkeypatch):
    """The GLM fit path must not call toarray() on the CSR input."""
    X, y = _sparse_cls_data(n=400, d=30)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    import scipy.sparse as sp

    calls = []
    orig = sp.csr_matrix.toarray

    def spy(self, *a, **k):
        calls.append(self.shape)
        return orig(self, *a, **k)

    monkeypatch.setattr(sp.csr_matrix, "toarray", spy)
    LogisticRegression(maxIter=20, float32_inputs=False).fit(df)
    assert calls == []


def test_sparse_fallback_densifies(monkeypatch):
    """Estimators without a sparse path densify partition-by-partition and
    still fit correctly (the package logger doesn't propagate, so the
    densification is asserted via a toarray spy)."""
    X = _random_csr(n=200, d=12, density=0.2, seed=11)
    df = DataFrame.from_numpy(X, num_partitions=2)
    import scipy.sparse as sp

    calls = []
    orig = sp.csr_matrix.toarray

    def spy(self, *a, **k):
        calls.append(self.shape)
        return orig(self, *a, **k)

    monkeypatch.setattr(sp.csr_matrix, "toarray", spy)
    model = KMeans(k=3, seed=1).fit(df)
    assert calls, "KMeans (no sparse path) should densify CSR partitions"
    assert model.cluster_centers_.shape == (3, 12)


def test_sparse_float32_default_dtype():
    X, y = _sparse_cls_data(n=300, d=20)
    df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    model = LogisticRegression(maxIter=30).fit(df)  # float32_inputs default
    assert model.dtype == "float32"
