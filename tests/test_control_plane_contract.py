# Control-plane CONFORMANCE suite (srml-wire satellite): ONE contract test
# module parameterized over every plane implementation, so the three can
# never drift.  The contract (parallel/context.ControlPlane + the
# srml-shield/srml-watch extensions):
#
#   - allGather returns messages INDEXED BY RANK (result[r] = rank r's
#     message) — exchange.py and the kneighbors protocol index positionally
#   - allGatherBytes moves raw binary frames (no utf-8 assumption)
#   - barrier completes when every rank arrives
#   - publish_health / read_health: non-collective, never blocks
#   - abort publishes a marker whose decoded shape carries rank / etype /
#     message / span; peers see it via check_abort and blocked gathers
#     raise RemoteRankError naming the origin
#   - a gather that runs out its round budget raises the TYPED
#     ControlPlaneTimeout (a TimeoutError) naming round + missing ranks +
#     the SRML_CP_ROUND_TIMEOUT_S knob
#   - close() is idempotent and leaves no presence files behind
#
# LocalControlPlane is the single-controller degenerate case: the same
# surface, collectives are identities, abort is a no-op (no peers).
import contextlib
import json
import os
import threading
import time

import pytest

from spark_rapids_ml_tpu.parallel.context import (
    ControlPlaneTimeout,
    LocalControlPlane,
    RemoteRankError,
)
from spark_rapids_ml_tpu.parallel.netplane import (
    CoordinatorServer,
    TcpControlPlane,
)
from spark_rapids_ml_tpu.parallel.runner import FileControlPlane

NRANKS = 3


class _PlaneHarness:
    """nranks plane instances over one rendezvous + their teardown."""

    def __init__(self, kind, tmp_path):
        self.kind = kind
        self.tmp_path = tmp_path
        self._server = None

    def build(self, timeout=30.0):
        if self.kind == "file":
            return [
                FileControlPlane(
                    str(self.tmp_path / "cp"), r, NRANKS, timeout=timeout
                )
                for r in range(NRANKS)
            ]
        self._server = CoordinatorServer(
            NRANKS, host="127.0.0.1", advertise_host="127.0.0.1", lease_s=5.0
        )
        addr = self._server.start()
        return [
            TcpControlPlane(addr, r, NRANKS, timeout=timeout)
            for r in range(NRANKS)
        ]

    def teardown(self, planes):
        for p in planes:
            with contextlib.suppress(Exception):
                p.close()
        if self._server is not None:
            self._server.stop(grace_s=0.2)
            self._server = None


@pytest.fixture(params=["file", "tcp"])
def harness(request, tmp_path):
    h = _PlaneHarness(request.param, tmp_path)
    built = []
    orig = h.build

    def build(**kw):
        planes = orig(**kw)
        built.extend(planes)
        return planes

    h.build = build
    yield h
    h.teardown(built)


def _run_ranks(fn, planes):
    """Run fn(rank, plane) on one thread per rank (the collective shape);
    returns {rank: result} and re-raises the first worker error."""
    results, errors = {}, {}

    def run(r):
        try:
            results[r] = fn(r, planes[r])
        except Exception as exc:  # noqa: BLE001 - relayed to the test
            errors[r] = exc

    threads = [
        threading.Thread(target=run, args=(r,), name=f"cpc-r{r}")
        for r in range(len(planes))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    if errors:
        raise next(iter(errors.values()))
    return results


# -- gather ordering + binary round-trip --------------------------------------


def test_allgather_is_rank_indexed(harness):
    planes = harness.build()
    results = _run_ranks(lambda r, p: p.allGather(f"msg-from-{r}"), planes)
    for r in range(NRANKS):
        assert results[r] == [f"msg-from-{i}" for i in range(NRANKS)], (
            f"{harness.kind}: rank {r} saw {results[r]} — allGather MUST "
            "index results by rank"
        )


def test_allgather_bytes_round_trips_raw_binary(harness):
    planes = harness.build()
    payloads = [bytes([r, 0x00, 0xFF, 0xFE]) + b"\x80raw" for r in range(NRANKS)]
    results = _run_ranks(
        lambda r, p: p.allGatherBytes(payloads[r]), planes
    )
    for r in range(NRANKS):
        assert results[r] == payloads, f"{harness.kind}: binary frames drifted"


def test_consecutive_rounds_stay_ordered(harness):
    planes = harness.build()

    def rounds(r, p):
        out = []
        for i in range(4):
            out.append(p.allGather(f"{r}:{i}"))
        p.barrier()
        return out

    results = _run_ranks(rounds, planes)
    for r in range(NRANKS):
        for i in range(4):
            assert results[r][i] == [f"{j}:{i}" for j in range(NRANKS)]


# -- health surface -----------------------------------------------------------


def test_health_publish_read_is_nonblocking(harness):
    planes = harness.build()

    def publish(r, p):
        p.publish_health(json.dumps({"rank": r, "progress": r * 10}))
        return True

    _run_ranks(publish, planes)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        health = planes[0].read_health()
        if set(health) == set(range(NRANKS)):
            break
        time.sleep(0.02)
    assert set(health) == set(range(NRANKS))
    for r, payload in health.items():
        assert json.loads(payload)["rank"] == r
    # republish overwrites (latest-wins, not append)
    planes[1].publish_health(json.dumps({"rank": 1, "progress": 99}))
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if json.loads(planes[0].read_health()[1])["progress"] == 99:
            break
        time.sleep(0.02)
    assert json.loads(planes[0].read_health()[1])["progress"] == 99


# -- abort marker shape -------------------------------------------------------


def test_abort_marker_shape_and_gather_interrupt(harness):
    planes = harness.build()
    marker = {
        "rank": 1, "etype": "ValueError",
        "message": "induced", "span": "solver.step",
    }
    errs = {}

    def waiter(rank):
        try:
            planes[rank].allGather("blocked")
        except RemoteRankError as exc:
            errs[rank] = exc

    threads = [
        threading.Thread(target=waiter, args=(r,), name=f"cpc-abort-r{r}")
        for r in (0, 2)
    ]
    for t in threads:
        t.start()
    time.sleep(0.2)
    planes[1].abort(json.dumps(marker))
    for t in threads:
        t.join(timeout=15.0)
    assert set(errs) == {0, 2}
    for exc in errs.values():
        assert (exc.rank, exc.etype, exc.span) == (1, "ValueError", "solver.step")
    # the non-blocking surface decodes the same shape
    info = planes[0].check_abort()
    assert info is not None and info["rank"] == 1
    assert info["etype"] == "ValueError" and info["span"] == "solver.step"


# -- typed round timeout ------------------------------------------------------


def test_round_timeout_typed_with_round_and_missing_ranks(harness):
    planes = harness.build(timeout=0.4)
    errs = {}

    def run(r, p):
        if r == 1:
            time.sleep(1.2)  # rank 1 never posts within the budget
            return None
        try:
            p.allGather("present")
        except ControlPlaneTimeout as exc:
            errs[r] = exc
        return None

    _run_ranks(run, planes)
    assert set(errs) == {0, 2}
    for exc in errs.values():
        assert isinstance(exc, TimeoutError)  # compatibility subclass
        assert exc.round_no == 0
        assert exc.missing_ranks == [1]
        assert exc.timeout_s == 0.4
        assert exc.knob == "SRML_CP_ROUND_TIMEOUT_S"
        assert "SRML_CP_ROUND_TIMEOUT_S" in str(exc)


# -- close idempotence --------------------------------------------------------


def test_close_is_idempotent_and_reaps_presence(harness, tmp_path):
    planes = harness.build()
    _run_ranks(lambda r, p: p.allGather(f"{r}"), planes)
    for p in planes:
        p.close()
        p.close()  # second close must be a no-op, never an error
    if harness.kind == "file":
        leftovers = [
            f for f in os.listdir(tmp_path / "cp")
            if f.startswith(("alive_", "health_"))
        ]
        assert leftovers == []


# -- the single-controller degenerate case ------------------------------------


def test_local_plane_satisfies_the_surface():
    cp = LocalControlPlane()
    assert cp.allGather("m") == ["m"]
    assert cp.allGatherBytes(b"\x00\xff") == [b"\x00\xff"]
    assert cp.barrier() is None
    cp.publish_health(json.dumps({"rank": 0, "progress": 1}))
    assert json.loads(cp.read_health()[0])["progress"] == 1
    cp.abort(json.dumps({"rank": 0}))  # no peers: a no-op, not an error
    assert cp.check_abort() is None
    cp.close()
    cp.close()
