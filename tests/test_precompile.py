# Precompile subsystem: AOT executable cache (cached_call), profiling
# counters, key helpers, and the persistent on-disk compilation-cache hookup.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu import profiling
from spark_rapids_ml_tpu.ops.precompile import (
    Precompiler,
    global_precompiler,
    initialize_persistent_cache,
    mesh_fingerprint,
    shape_bucket,
)


def test_shape_bucket_pow2():
    assert shape_bucket(1) == 64
    assert shape_bucket(64) == 64
    assert shape_bucket(65) == 128
    assert shape_bucket(137) == 256
    assert shape_bucket(8192) == 8192


def test_mesh_fingerprint_is_value_identity():
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    m1, m2 = get_mesh(), get_mesh()
    assert mesh_fingerprint(m1) == mesh_fingerprint(m2)
    assert mesh_fingerprint(None) == ()
    if m1.devices.size > 1:
        assert mesh_fingerprint(get_mesh(1)) != mesh_fingerprint(m1)


def test_cached_call_hits_without_new_compiles():
    pc = Precompiler(max_workers=2)

    @jax.jit
    def f(x):
        return (x * 3).sum(axis=1)

    x = jnp.asarray(np.ones((8, 4), np.float32))
    c0 = profiling.counters("precompile")

    def delta(name):
        return profiling.counter(name) - c0.get(name, 0)

    r1 = pc.cached_call(("f", x.shape), f, x)
    assert delta("precompile.aot_miss") == 1
    assert delta("precompile.compile") == 1
    r2 = pc.cached_call(("f", x.shape), f, x)
    assert delta("precompile.aot_hit") == 1
    assert delta("precompile.compile") == 1  # unchanged: zero new compiles
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))


def test_second_same_shape_search_zero_new_compiles():
    """The acceptance smoke: a second kNN search at the same shapes — with a
    FRESH mesh object, as repeat kneighbors calls produce — performs zero
    new compilations and runs entirely off aot_hit executables."""
    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    rng = np.random.default_rng(41)
    X = rng.standard_normal((1000, 16)).astype(np.float32)
    Q = rng.standard_normal((200, 16)).astype(np.float32)
    ids = np.arange(1000, dtype=np.int64)
    prepared = knn_mod.prepare_items(X, ids, get_mesh())
    d1, i1 = knn_mod.knn_search_prepared(prepared, Q, 7, get_mesh())
    c0 = profiling.counters("precompile")
    d2, i2 = knn_mod.knn_search_prepared(prepared, Q, 7, get_mesh())
    c1 = profiling.counters("precompile")
    assert c1.get("precompile.compile", 0) == c0.get("precompile.compile", 0)
    assert c1.get("precompile.fallback", 0) == c0.get("precompile.fallback", 0)
    assert c1.get("precompile.aot_hit", 0) > c0.get("precompile.aot_hit", 0)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(i1, i2)


@pytest.mark.parametrize("force_adaptive", [False, True])
def test_warm_search_kernels_covers_first_dispatch(monkeypatch, force_adaptive):
    """A warmed geometry must be the EXACT entry the later dispatch looks
    up: after warm_search_kernels, the first knn_search_prepared records no
    aot_miss (every kernel call lands on a submitted executable) — on the
    exact route AND the adaptive scan route (which dispatches TWO jits,
    candidates + merge; the merge warm was the review finding)."""
    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    if force_adaptive:
        monkeypatch.setenv("SRML_KNN_FORCE_ADAPTIVE", "1")
    rng = np.random.default_rng(43)
    n, d, q_n, k = 800, 24, 120, 6
    X = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q_n, d)).astype(np.float32)
    mesh = get_mesh()
    prepared = knn_mod.prepare_items(X, np.arange(n, dtype=np.int64), mesh)
    keys = knn_mod.warm_search_kernels(
        prepared, k, mesh, n_queries=q_n, d_query=d
    )
    assert keys, "warm path submitted nothing"
    c0 = profiling.counters("precompile")
    knn_mod.knn_search_prepared(prepared, Q, k, get_mesh())
    c1 = profiling.counters("precompile")
    assert c1.get("precompile.aot_miss", 0) == c0.get("precompile.aot_miss", 0)
    assert c1.get("precompile.aot_hit", 0) > c0.get("precompile.aot_hit", 0)
    # a warmed executable that REJECTS its inputs (sharding/placement skew)
    # would silently re-compile on the jit fallback — that is a warm-path
    # bug, not a cache hit (caught live: the merge warm compiled for
    # single-device placement while the sharded scan emits replicated pools)
    assert c1.get("precompile.fallback", 0) == c0.get("precompile.fallback", 0)


def test_cached_call_falls_back_on_plain_callable_and_compile_failure():
    pc = Precompiler(max_workers=1)

    @jax.jit
    def boom(x):
        raise RuntimeError("tracing failure")

    x = jnp.asarray(np.ones((4,), np.float32))
    with pytest.raises(RuntimeError, match="tracing failure"):
        # compile fails on the worker, fallback re-raises at the true site
        pc.cached_call(("boom",), boom, x)


def test_initialize_persistent_cache_respects_existing_config():
    """The test suite's conftest already configures jax's compilation cache
    — initialize_persistent_cache must adopt it (not clobber it) and be
    idempotent."""
    existing = jax.config.jax_compilation_cache_dir
    got = initialize_persistent_cache()
    if existing:
        assert got == existing
        assert jax.config.jax_compilation_cache_dir == existing
    assert initialize_persistent_cache() == got  # idempotent
