# One parametrized save -> load -> transform equivalence matrix over every
# persistable model class (persistence used to be asserted ad hoc per model
# file).  The loaded model must be the same class, carry the same param
# surface, and produce BIT-IDENTICAL transform output on the training
# features — both sides run the same device kernels on the same dtype, so
# exact equality is the right bar, not allclose.  The `model_zoo` fixture
# fitting these models is shared with the serving tests (the registry's
# model-loading path, tests/test_serving.py).
import numpy as np
import pytest

from spark_rapids_ml_tpu.core import load as core_load
from spark_rapids_ml_tpu.dataframe import DataFrame

MODEL_ARMS = ["kmeans", "pca", "linreg", "logreg", "rf_clf", "rf_reg", "umap"]


def _columns(df) -> dict:
    """{column: stacked np array} over all partitions of a facade frame."""
    out = {}
    for name in df.columns:
        vals = []
        for p in df.partitions:
            vals.extend(list(p[name]))
        out[name] = np.asarray(vals)
    return out


def _transform_outputs(model, X: np.ndarray) -> dict:
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=2)
    if model.hasParam("featuresCol"):
        model.setFeaturesCol("features")
    out = model.transform(df)
    return {k: v for k, v in _columns(out).items() if k != "features"}


@pytest.mark.parametrize("arm", MODEL_ARMS)
def test_save_load_transform_equivalence(arm, model_zoo, tmp_path):
    model, X = model_zoo(arm)
    path = str(tmp_path / arm)
    model.save(path)
    loaded = core_load(path)
    assert type(loaded) is type(model)
    # the param surface survives the round trip (outputs land in the same
    # columns)
    for p in ("predictionCol", "probabilityCol", "rawPredictionCol", "outputCol"):
        if model.hasParam(p) and model.isDefined(p):
            assert loaded.getOrDefault(p) == model.getOrDefault(p)
    before = _transform_outputs(model, X)
    after = _transform_outputs(loaded, X)
    assert sorted(before) == sorted(after)
    for col in before:
        assert np.array_equal(
            np.asarray(before[col]), np.asarray(after[col])
        ), f"{arm}: column {col!r} changed across save/load"


def test_ann_save_load_kneighbors_equivalence(model_zoo, tmp_path):
    """The ANN model has no transform — its persistence gate is
    save -> load -> kneighbors BIT-IDENTICAL to the in-memory model (the
    packed index layout is mesh-independent data, and the probed search is
    deterministic, so exact equality is the right bar here too)."""
    model, X = model_zoo("ann")
    path = str(tmp_path / "ann")
    model.save(path)
    loaded = core_load(path)
    assert type(loaded) is type(model)
    assert loaded.getK() == model.getK()
    assert loaded.getAlgoParams() == model.getAlgoParams()
    qdf = DataFrame.from_numpy(X[:20], num_partitions=2)
    _, _, before = model.kneighbors(qdf)
    _, _, after = loaded.kneighbors(qdf)
    for col in ("indices", "distances"):
        b = np.concatenate(
            [np.asarray(list(p[col])) for p in before.partitions if len(p)]
        )
        a = np.concatenate(
            [np.asarray(list(p[col])) for p in after.partitions if len(p)]
        )
        assert np.array_equal(a, b), f"ann: column {col!r} changed across save/load"


def test_loaded_model_attributes_round_trip(model_zoo, tmp_path):
    # spot-check the attribute payload itself (npz + json split): arrays
    # stay arrays, scalars stay scalars
    model, _X = model_zoo("kmeans")
    path = str(tmp_path / "kmeans_attrs")
    model.save(path)
    loaded = core_load(path)
    assert np.array_equal(loaded.cluster_centers_, model.cluster_centers_)
    assert loaded.n_cols == model.n_cols and loaded.dtype == model.dtype
