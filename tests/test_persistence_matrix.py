# One parametrized save -> load -> transform equivalence matrix over every
# persistable model class (persistence used to be asserted ad hoc per model
# file).  The loaded model must be the same class, carry the same param
# surface, and produce BIT-IDENTICAL transform output on the training
# features — both sides run the same device kernels on the same dtype, so
# exact equality is the right bar, not allclose.  The `model_zoo` fixture
# fitting these models is shared with the serving tests (the registry's
# model-loading path, tests/test_serving.py).
import numpy as np
import pytest

from spark_rapids_ml_tpu.core import load as core_load
from spark_rapids_ml_tpu.dataframe import DataFrame

MODEL_ARMS = ["kmeans", "pca", "linreg", "logreg", "rf_clf", "rf_reg", "umap"]


def _columns(df) -> dict:
    """{column: stacked np array} over all partitions of a facade frame."""
    out = {}
    for name in df.columns:
        vals = []
        for p in df.partitions:
            vals.extend(list(p[name]))
        out[name] = np.asarray(vals)
    return out


def _transform_outputs(model, X: np.ndarray) -> dict:
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=2)
    if model.hasParam("featuresCol"):
        model.setFeaturesCol("features")
    out = model.transform(df)
    return {k: v for k, v in _columns(out).items() if k != "features"}


@pytest.mark.parametrize("arm", MODEL_ARMS)
def test_save_load_transform_equivalence(arm, model_zoo, tmp_path):
    model, X = model_zoo(arm)
    path = str(tmp_path / arm)
    model.save(path)
    loaded = core_load(path)
    assert type(loaded) is type(model)
    # the param surface survives the round trip (outputs land in the same
    # columns)
    for p in ("predictionCol", "probabilityCol", "rawPredictionCol", "outputCol"):
        if model.hasParam(p) and model.isDefined(p):
            assert loaded.getOrDefault(p) == model.getOrDefault(p)
    before = _transform_outputs(model, X)
    after = _transform_outputs(loaded, X)
    assert sorted(before) == sorted(after)
    for col in before:
        assert np.array_equal(
            np.asarray(before[col]), np.asarray(after[col])
        ), f"{arm}: column {col!r} changed across save/load"


@pytest.mark.parametrize("arm", ["ann", "ivfpq", "ivfpq_opq"])
def test_ann_save_load_kneighbors_equivalence(arm, model_zoo, tmp_path):
    """The ANN models have no transform — their persistence gate is
    save -> load -> kneighbors BIT-IDENTICAL to the in-memory model (the
    packed index layout — raw lists for ivfflat, codes + ADC scalars +
    codebooks for ivfpq, plus the OPQ rotation and the packed 4-bit
    fast-scan layout on their arms — is mesh-independent data, and the
    probed search is deterministic, so exact equality is the right bar
    here too)."""
    model, X = model_zoo(arm)
    path = str(tmp_path / arm)
    model.save(path)
    loaded = core_load(path)
    assert type(loaded) is type(model)
    assert loaded.getK() == model.getK()
    assert loaded.getAlgoParams() == model.getAlgoParams()
    assert loaded.getAlgorithm() == model.getAlgorithm()
    qdf = DataFrame.from_numpy(X[:20], num_partitions=2)
    _, _, before = model.kneighbors(qdf)
    _, _, after = loaded.kneighbors(qdf)
    for col in ("indices", "distances"):
        b = np.concatenate(
            [np.asarray(list(p[col])) for p in before.partitions if len(p)]
        )
        a = np.concatenate(
            [np.asarray(list(p[col])) for p in after.partitions if len(p)]
        )
        assert np.array_equal(a, b), f"{arm}: column {col!r} changed across save/load"
    if arm.startswith("ivfpq"):
        # across mesh SHAPES too: the loaded payload staged on a 1-device
        # mesh must answer bit-identically to the default (8-device) mesh —
        # the engine parity gate re-asserted through the persisted artifact
        from spark_rapids_ml_tpu.ann.pq import (
            index_from_packed_pq,
            ivfpq_search_prepared,
        )
        from spark_rapids_ml_tpu.parallel.mesh import get_mesh

        packed = loaded._packed_pq()
        if arm == "ivfpq_opq":
            # the rotation is payload, not staging state: it must survive
            # the npz round trip exactly (codes decode against it)
            assert loaded.pq_rotation_ is not None
            np.testing.assert_array_equal(
                loaded.pq_rotation_, model.pq_rotation_
            )
            assert packed.rotation is not None
        out = {}
        for tag, mesh in (("one", get_mesh(1)), ("all", get_mesh())):
            idx = index_from_packed_pq(packed, mesh)
            out[tag] = ivfpq_search_prepared(
                idx, X[:16], 4, 4, mesh,
                refine_items=packed.items, refine_ratio=4,
            )
        np.testing.assert_array_equal(out["one"][1], out["all"][1])
        np.testing.assert_array_equal(
            out["one"][0].view(np.uint32), out["all"][0].view(np.uint32)
        )


# -- hot-swap persistence semantics (srml-router, docs/serving.md §router) ---
# swap() is the deployment story for persisted models: fit -> save on the
# training cluster, load -> swap on the serving one.  The gate is the same
# bit-identical bar as the save/load matrix: a swapped-in loaded model must
# serve EXACTLY what the in-memory model served.

SWAP_ARMS = ["kmeans", "pca", "linreg", "logreg", "rf_clf", "rf_reg"]


@pytest.mark.parametrize("arm", SWAP_ARMS)
def test_save_load_swap_serving_equivalence(arm, model_zoo, tmp_path):
    from spark_rapids_ml_tpu.serving import ModelRegistry

    model, X = model_zoo(arm)
    path = str(tmp_path / arm)
    model.save(path)
    loaded = core_load(path)
    reg = ModelRegistry(max_batch=16, max_wait_ms=2)
    try:
        reg.register(arm, model)
        before = reg.get(arm).predict(X[:5])
        incoming = reg.swap(arm, loaded)
        assert reg.get(arm) is incoming  # the name now serves the new gen
        after = reg.get(arm).predict(X[:5])
        assert sorted(before) == sorted(after)
        for col in before:
            assert np.array_equal(
                np.asarray(before[col]), np.asarray(after[col])
            ), f"{arm}: served column {col!r} changed across swap"
    finally:
        reg.shutdown(drain=False)


def test_swap_same_shape_is_zero_new_compiles(model_zoo, tmp_path):
    """The cut-over compile gate, registry side: a same-shape successor
    (save -> load of the SAME class/geometry) warms entirely from the
    retained AOT executable cache — zero new compiles while the old
    generation still serves, zero at cut-over."""
    from spark_rapids_ml_tpu import profiling
    from spark_rapids_ml_tpu.serving import ModelRegistry

    model, X = model_zoo("kmeans")
    path = str(tmp_path / "swap_km")
    model.save(path)
    loaded = core_load(path)
    reg = ModelRegistry(max_batch=16, max_wait_ms=2)
    try:
        reg.register("swap_km", model)
        reg.get("swap_km").predict(X[:4])
        before = profiling.counters("precompile.")
        reg.swap("swap_km", loaded)
        delta = profiling.counter_deltas(before, "precompile.")
        assert delta.get("precompile.compile", 0) == 0, delta
        assert delta.get("precompile.fallback", 0) == 0, delta
        assert profiling.counter("serving.swap_km.swaps") == 1
        out = reg.get("swap_km").predict(X[:4])
        assert out["prediction"].shape == (4,)
        reg.get("swap_km").drain()
        reg.get("swap_km").assert_steady_state()
    finally:
        reg.shutdown(drain=False)


def test_swap_drains_inflight_requests_on_old_generation(model_zoo):
    """swap-during-drain: requests admitted BEFORE the cut-over complete on
    the old generation (drained, not dropped) while the name already
    points at the successor — no request is lost across the swap."""
    from spark_rapids_ml_tpu.serving import ModelRegistry

    model, X = model_zoo("kmeans")
    reg = ModelRegistry(max_batch=16, max_wait_ms=25)
    try:
        reg.register("swap_drain", model)
        old = reg.get("swap_drain")
        old.predict(X[:2])
        # a burst still coalescing in the OLD generation's batcher when the
        # swap begins (25 ms window >> the swap's cut-over instant)
        futs = [old.submit(X[i : i + 1]) for i in range(6)]
        incoming = reg.swap("swap_drain", model)
        assert reg.get("swap_drain") is incoming
        for f in futs:  # drained on the old generation, every one resolved
            assert f.result(timeout=30)["prediction"].shape == (1,)
        assert incoming.predict(X[:3])["prediction"].shape == (3,)
    finally:
        reg.shutdown(drain=False)


def test_swap_incompatible_model_fails_clean(model_zoo):
    """swap-to-incompatible: a model whose serving signature differs
    (here: feature width) raises BEFORE any cut-over, and the old server
    keeps serving untouched.  Unknown names raise KeyError."""
    from spark_rapids_ml_tpu import KMeans
    from spark_rapids_ml_tpu.serving import ModelRegistry

    model, X = model_zoo("kmeans")
    narrow = (
        KMeans(k=2, maxIter=2, seed=1)
        .setFeaturesCol("features")
        .fit(DataFrame.from_numpy(X[:, :3], feature_layout="array"))
    )
    reg = ModelRegistry(max_batch=16, max_wait_ms=2)
    try:
        reg.register("swap_bad", model)
        old = reg.get("swap_bad")
        with pytest.raises(ValueError, match="n_cols 5 -> 3"):
            reg.swap("swap_bad", narrow)
        assert reg.get("swap_bad") is old  # untouched, still serving
        assert old.predict(X[:2])["prediction"].shape == (2,)
        with pytest.raises(KeyError, match="no served model"):
            reg.swap("no_such_model", model)
    finally:
        reg.shutdown(drain=False)


# -- streamed arms (srml-stream, docs/streaming.md) --------------------------
# A model built by partial_fit over chunks, then saved and loaded, must
# equal the BATCH-fit model on the concatenated data: bitwise for the
# closed-form engines (linreg coefficients, sign-canonicalized PCA
# components — on the exact-arithmetic integer/pow2-row data family the
# equality contract gates), quality-gated for the online approximations
# (kmeans inertia, logreg accuracy) — against batch fits on 1-device AND
# 8-device meshes (streamed states are mesh-independent data).

STREAM_ARMS = ["kmeans", "pca", "linreg", "logreg"]


@pytest.fixture(scope="module")
def stream_fixture():
    from spark_rapids_ml_tpu.dataframe import stream_chunk_ids

    rng = np.random.default_rng(13)
    n, d, k = 256, 6, 3
    centers = rng.integers(-2, 3, size=(k, d)) * 8
    assign = rng.integers(0, k, n)
    X = (centers[assign] + rng.integers(-2, 3, size=(n, d))).astype(np.float32)
    y_reg = (X @ np.arange(1.0, d + 1.0)).astype(np.float64)
    w = rng.standard_normal(d)
    margin = X @ w
    y_clf = (margin > np.median(margin)).astype(np.float64)
    cid = stream_chunk_ids(n, 64, seed=5)
    return X, y_reg, y_clf, cid, k


def _stream_pair(arm, fx, n_dev):
    """(streamed_model, batch_model_on_n_dev_mesh) for one arm."""
    from spark_rapids_ml_tpu import (
        KMeans,
        LinearRegression,
        LogisticRegression,
        PCA,
    )

    X, y_reg, y_clf, cid, k = fx

    def build(est_kw=None):
        kw = dict(est_kw or {})
        if arm == "kmeans":
            return KMeans(k=k, maxIter=10, seed=1, **kw).setFeaturesCol("features")
        if arm == "pca":
            return PCA(k=3, **kw).setInputCol("features")
        if arm == "linreg":
            return LinearRegression(maxIter=20, **kw)
        return LogisticRegression(maxIter=20, **kw)

    y = {"linreg": y_reg, "logreg": y_clf}.get(arm)
    if y is None:
        df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=2)
    else:
        df = DataFrame.from_numpy(X, y=y, num_partitions=2)
    batch = build({"num_workers": n_dev}).fit(df)
    eng = build().streaming()
    for c in range(int(cid.max()) + 1):
        m = cid == c
        eng.partial_fit(X[m], y=None if y is None else y[m])
    return eng.finalize(), batch


@pytest.mark.parametrize("arm", STREAM_ARMS)
@pytest.mark.parametrize("n_dev", [1, 8])
def test_streamed_save_load_equals_batch(arm, n_dev, stream_fixture, tmp_path):
    X, y_reg, y_clf, cid, k = stream_fixture
    streamed, batch = _stream_pair(arm, stream_fixture, n_dev)
    path = str(tmp_path / f"streamed_{arm}_{n_dev}")
    streamed.save(path)
    loaded = core_load(path)
    assert type(loaded) is type(batch)
    if arm == "linreg":
        np.testing.assert_array_equal(loaded.coef_, batch.coef_)
        assert loaded.intercept_ == batch.intercept_
    elif arm == "pca":
        np.testing.assert_array_equal(loaded.components_, batch.components_)
        np.testing.assert_array_equal(loaded.mean_, batch.mean_)
    elif arm == "kmeans":
        def inertia(C):
            d2 = ((X[:, None, :] - np.asarray(C)[None]) ** 2).sum(-1)
            return float(d2.min(axis=1).sum())

        assert inertia(loaded.cluster_centers_) <= 1.10 * inertia(
            batch.cluster_centers_
        )
    else:  # logreg: streamed accuracy within 3% of batch on the union
        df = DataFrame.from_numpy(X, y=y_clf, num_partitions=2)

        def acc(model):
            out = model.transform(df)
            preds = np.concatenate(
                [np.asarray(p["prediction"]) for p in out.partitions if len(p)]
            )
            return float((preds == y_clf).mean())

        assert acc(loaded) >= acc(batch) - 0.03
        np.testing.assert_array_equal(loaded.classes_, batch.classes_)
    # and the persistence bar itself: the loaded streamed model transforms
    # bit-identically to its in-memory twin
    before = _transform_outputs(streamed, X)
    after = _transform_outputs(loaded, X)
    assert sorted(before) == sorted(after)
    for col in before:
        assert np.array_equal(np.asarray(before[col]), np.asarray(after[col]))


def test_loaded_model_attributes_round_trip(model_zoo, tmp_path):
    # spot-check the attribute payload itself (npz + json split): arrays
    # stay arrays, scalars stay scalars
    model, _X = model_zoo("kmeans")
    path = str(tmp_path / "kmeans_attrs")
    model.save(path)
    loaded = core_load(path)
    assert np.array_equal(loaded.cluster_centers_, model.cluster_centers_)
    assert loaded.n_cols == model.n_cols and loaded.dtype == model.dtype
