#
# Data generator tests (reference python/benchmark/test_gen_data.py): shape,
# determinism, chunk invariants, and parquet round-trip of every generator.
#

import glob
import os
import sys

import numpy as np
import pandas as pd
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.gen_data import (  # noqa: E402
    BlobsDataGen,
    ClassificationDataGen,
    DefaultDataGen,
    LowRankMatrixDataGen,
    RegressionDataGen,
    _REGISTERED,
    main,
)

COMMON = ["--num_rows", "1000", "--num_cols", "8", "--output_dir", "ignored"]


def _collect(gen):
    parts = list(gen.gen_dataframes())
    return pd.concat(parts, ignore_index=True), parts


@pytest.mark.parametrize("name", sorted(_REGISTERED))
def test_shapes_and_chunking(name):
    gen = _REGISTERED[name](COMMON + ["--output_num_files", "4"])
    full, parts = _collect(gen)
    assert len(parts) == 4
    assert len(full) == 1000
    feat = full[gen.feature_cols].to_numpy()
    assert feat.shape == (1000, 8)
    assert feat.dtype == np.float32
    has_label = name in ("blobs", "regression", "classification")
    assert ("label" in full.columns) == has_label


def test_determinism_and_chunk_independence():
    gen_a = RegressionDataGen(COMMON + ["--output_num_files", "2"])
    gen_b = RegressionDataGen(COMMON + ["--output_num_files", "2"])
    full_a, _ = _collect(gen_a)
    full_b, _ = _collect(gen_b)
    pd.testing.assert_frame_equal(full_a, full_b)
    # different chunk counts draw from different per-chunk streams but the
    # same ground-truth coefficients: labels stay linearly explainable
    gen_c = RegressionDataGen(COMMON + ["--output_num_files", "5"])
    full_c, _ = _collect(gen_c)
    X = full_c[gen_c.feature_cols].to_numpy(dtype=np.float64)
    y = full_c["label"].to_numpy(dtype=np.float64)
    coef, *_ = np.linalg.lstsq(np.c_[X, np.ones(len(X))], y, rcond=None)
    resid = y - np.c_[X, np.ones(len(X))] @ coef
    assert np.std(resid) < 2.0  # noise=1.0 default


def test_blobs_share_centers_across_chunks():
    gen = BlobsDataGen(
        COMMON + ["--output_num_files", "3", "--n_clusters", "4", "--cluster_std", "0.1"]
    )
    full, parts = _collect(gen)
    # per-chunk cluster means must agree (chunks sample the same mixture)
    means = []
    for part in parts:
        X = part[gen.feature_cols].to_numpy(dtype=np.float64)
        lab = part["label"].to_numpy(dtype=np.int64)
        means.append(
            np.stack([X[lab == c].mean(axis=0) for c in range(4) if (lab == c).any()])
        )
    assert np.allclose(means[0], means[1], atol=0.2)


def test_low_rank_matrix_is_low_rank():
    gen = LowRankMatrixDataGen(
        COMMON + ["--effective_rank", "2", "--tail_strength", "0.01"]
    )
    full, _ = _collect(gen)
    X = full[gen.feature_cols].to_numpy(dtype=np.float64)
    s = np.linalg.svd(X, compute_uv=False)
    assert s[3] < 0.2 * s[0]  # spectrum decays fast past the effective rank


def test_classification_labels():
    gen = ClassificationDataGen(COMMON + ["--n_classes", "3"])
    full, _ = _collect(gen)
    assert set(np.unique(full["label"])) == {0.0, 1.0, 2.0}


def test_classification_chunks_are_distinct_points_same_problem():
    gen = ClassificationDataGen(COMMON + ["--output_num_files", "4"])
    full, parts = _collect(gen)
    feats = full[gen.feature_cols].to_numpy()
    assert len(np.unique(feats, axis=0)) == len(feats)  # no duplicated pool
    # same class geometry in every chunk: per-chunk class means agree
    m = []
    for part in parts[:2]:
        X = part[gen.feature_cols].to_numpy(dtype=np.float64)
        lab = part["label"].to_numpy(dtype=np.int64)
        m.append(np.stack([X[lab == c].mean(axis=0) for c in (0, 1)]))
    assert np.allclose(m[0], m[1], atol=0.8)


def test_low_rank_scale_invariant_to_file_count():
    stds = []
    for files in ("1", "10"):
        gen = LowRankMatrixDataGen(COMMON + ["--output_num_files", files])
        full, _ = _collect(gen)
        stds.append(full[gen.feature_cols].to_numpy(dtype=np.float64).std())
    assert abs(stds[0] - stds[1]) < 0.15 * stds[0]


def test_cli_writes_parquet(tmp_path):
    out = str(tmp_path / "data")
    main(
        [
            "default",
            "--num_rows",
            "100",
            "--num_cols",
            "4",
            "--output_dir",
            out,
            "--output_num_files",
            "3",
        ]
    )
    files = sorted(glob.glob(os.path.join(out, "*.parquet")))
    assert len(files) == 3
    total = sum(len(pd.read_parquet(f)) for f in files)
    assert total == 100
    with pytest.raises(RuntimeError):
        main(["default", "--num_rows", "10", "--num_cols", "2", "--output_dir", out])
    # --overwrite with fewer files must not leave stale parts behind
    main(
        [
            "default",
            "--num_rows",
            "100",
            "--num_cols",
            "4",
            "--output_dir",
            out,
            "--output_num_files",
            "2",
            "--overwrite",
        ]
    )
    assert len(glob.glob(os.path.join(out, "*.parquet"))) == 2


class _ThreadedSparkMock:
    """Minimal SparkSession mock for write_distributed: chunk-metadata
    partitions execute the generator UDF on concurrent THREADS (one per
    partition, like executor tasks), and collect returns only the status
    rows the UDF yields."""

    class _Frame:
        def __init__(self, parts, udf=None):
            self._parts = parts
            self._udf = udf

        def repartition(self, n):
            rows = pd.concat(self._parts, ignore_index=True)
            return _ThreadedSparkMock._Frame(
                [rows.iloc[i::n].reset_index(drop=True) for i in range(n)]
            )

        def mapInPandas(self, udf, schema=None):
            return _ThreadedSparkMock._Frame(self._parts, udf=udf)

        def collect(self):
            import threading

            out, errs = [], []

            def run(part):
                try:
                    for pdf in self._udf(iter([part])):
                        out.extend(pdf.to_dict("records"))
                except Exception as e:  # surfaced below
                    errs.append(e)

            ts = [threading.Thread(target=run, args=(p,)) for p in self._parts]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert not errs, errs
            return out

    def createDataFrame(self, pdf):
        return self._Frame([pdf])


@pytest.mark.parametrize("name", ["blobs", "regression", "classification"])
def test_distributed_write_matches_local_chunk_law(name, tmp_path):
    """--distributed must produce BYTE-IDENTICAL parquet parts to the
    local write: chunk content depends only on (random_state + i, size),
    never on which task generates it (the chunk law the reference's
    gen_data_distributed.py relies on)."""
    local_dir, dist_dir = str(tmp_path / "local"), str(tmp_path / "dist")
    args = [
        "--num_rows", "500", "--num_cols", "6", "--output_num_files", "4",
        "--random_state", "5",
    ]
    _REGISTERED[name](args + ["--output_dir", local_dir]).write()
    _REGISTERED[name](args + ["--output_dir", dist_dir]).write_distributed(
        _ThreadedSparkMock()
    )
    local_parts = sorted(os.listdir(local_dir))
    dist_parts = sorted(os.listdir(dist_dir))
    assert local_parts == dist_parts and len(local_parts) == 4
    for p in local_parts:
        a = pd.read_parquet(os.path.join(local_dir, p))
        b = pd.read_parquet(os.path.join(dist_dir, p))
        pd.testing.assert_frame_equal(a, b)
