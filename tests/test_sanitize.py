# Runtime sanitizer (SRML_SANITIZE=1): the transfer-guard + debug-nans scope
# must wrap solver invocations, the guarded fits must pass clean on the
# virtual 8-device mesh (locking the KMeans/LinearRegression hot paths
# transfer-free going forward), and NaN production inside the scope must
# raise instead of propagating into model attributes.
import numpy as np
import pandas as pd
import pytest

import jax

from spark_rapids_ml_tpu import KMeans, LinearRegression
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.sanitize import enabled, sanitize_scope


def _df(n=96, d=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    coef = rng.standard_normal(d).astype(np.float32)
    y = (X @ coef + 0.1 * rng.standard_normal(n)).astype(np.float32)
    pdf = pd.DataFrame({"features": list(X), "label": y})
    return DataFrame([pdf.iloc[: n // 2], pdf.iloc[n // 2 :]])


def test_scope_is_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("SRML_SANITIZE", raising=False)
    assert not enabled()
    before = jax.config.jax_transfer_guard_device_to_host
    with sanitize_scope():
        assert jax.config.jax_transfer_guard_device_to_host == before


def test_scope_installs_nan_check_on_cpu(monkeypatch):
    monkeypatch.setenv("SRML_SANITIZE", "1")
    assert enabled()
    assert jax.default_backend() == "cpu"
    # prior values, NOT literals: under a suite-wide SRML_SANITIZE=1 run the
    # conftest turns debug_nans on globally, and the scope must restore TO
    # that state, not to off
    nans_before = jax.config.jax_debug_nans
    with sanitize_scope():
        assert jax.config.jax_debug_nans
    assert jax.config.jax_debug_nans == nans_before


def test_scope_installs_guard_on_accelerators(monkeypatch):
    # the accelerator branch: transfer guard ON, debug_nans FORCED OFF —
    # debug_nans' posthook fetches every jitted output (an implicit d2h
    # transfer) and would trip the guard it shares a scope with
    monkeypatch.setenv("SRML_SANITIZE", "1")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    guard_before = jax.config.jax_transfer_guard_device_to_host
    with sanitize_scope():
        assert jax.config.jax_transfer_guard_device_to_host == "disallow"
        assert not jax.config.jax_debug_nans
    assert jax.config.jax_transfer_guard_device_to_host == guard_before


def test_scope_raises_on_nan(monkeypatch):
    monkeypatch.setenv("SRML_SANITIZE", "1")
    with pytest.raises(FloatingPointError):
        with sanitize_scope():
            jax.jit(lambda x: jax.numpy.log(x))(
                jax.numpy.zeros(4) - 1.0
            ).block_until_ready()


def test_kmeans_fit_clean_under_sanitizer(monkeypatch, n_devices):
    monkeypatch.setenv("SRML_SANITIZE", "1")
    model = KMeans(k=3, maxIter=8, seed=11).setFeaturesCol("features").fit(_df())
    centers = np.asarray(model.cluster_centers_)
    assert centers.shape == (3, 5)
    assert np.isfinite(centers).all()
    assert np.isfinite(model.inertia_)


def test_linreg_fit_clean_under_sanitizer(monkeypatch, n_devices):
    monkeypatch.setenv("SRML_SANITIZE", "1")
    model = (
        LinearRegression(regParam=0.0, standardization=False)
        .setFeaturesCol("features")
        .fit(_df(seed=3))
    )
    assert np.isfinite(np.asarray(model.coefficients)).all()
    assert np.isfinite(model.intercept)


def test_linreg_elasticnet_fit_clean_under_sanitizer(monkeypatch):
    # the CD solver is the other linreg hot path (while_loop + fori sweeps)
    monkeypatch.setenv("SRML_SANITIZE", "1")
    model = (
        LinearRegression(regParam=0.1, elasticNetParam=0.5)
        .setFeaturesCol("features")
        .fit(_df(seed=5))
    )
    assert np.isfinite(np.asarray(model.coefficients)).all()
