# Pallas TPU kernel correctness (interpreter mode on the CPU test mesh).
# The same kernel compiles with Mosaic on real TPU; the hardware-exactness
# A/B record (v5e, argmin mismatch 0 vs the XLA path) is quoted in the
# ops/pallas_tpu.py module header.  Set SRML_TPU_TESTS=1 to re-run this file
# against real TPU devices, where the kernel tests run the compiled Mosaic
# path (interpret=False) instead of the interpreter.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.pallas_tpu import (
    DISABLE_ENV,
    _min_dist_argmin_pallas,
    _min_dist_argmin_xla,
    min_dist_argmin,
    pallas_enabled,
)

# On a real TPU run the compiled Mosaic kernel; on the CPU mesh interpret.
ON_TPU = jax.devices()[0].platform == "tpu"
KERNEL_INTERPRET = not ON_TPU


@pytest.mark.parametrize(
    "n,d,k",
    [
        (300, 70, 33),     # nothing aligned
        (512, 256, 128),   # everything aligned
        (129, 1, 2),       # degenerate feature dim
        (64, 515, 700),    # k > n, unaligned d
    ],
)
def test_min_dist_argmin_matches_xla(n, d, k):
    rng = np.random.default_rng(n + d + k)
    X = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    C = jnp.asarray(rng.standard_normal((k, d)).astype(np.float32))
    xn = (X**2).sum(axis=1)
    cn = (C**2).sum(axis=1)
    md, am = _min_dist_argmin_pallas(X, C, xn, cn, interpret=KERNEL_INTERPRET)
    md_ref, am_ref = _min_dist_argmin_xla(X, C, xn, cn)
    assert md.shape == (n,) and am.shape == (n,)
    # padded center slots (norm=+inf) must never win
    assert int(np.asarray(am).max()) < k
    np.testing.assert_array_equal(np.asarray(am), np.asarray(am_ref))
    np.testing.assert_allclose(
        np.asarray(md), np.asarray(md_ref), rtol=1e-4, atol=1e-4
    )


def test_min_dist_argmin_precomputed_norms():
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((100, 40)).astype(np.float32))
    C = jnp.asarray(rng.standard_normal((7, 40)).astype(np.float32))
    xn = (X**2).sum(axis=1)
    cn = (C**2).sum(axis=1)
    md1, am1 = min_dist_argmin(X, C, xn, cn, interpret=KERNEL_INTERPRET)
    md2, am2 = min_dist_argmin(X, C, interpret=KERNEL_INTERPRET)
    np.testing.assert_array_equal(np.asarray(am1), np.asarray(am2))
    np.testing.assert_allclose(np.asarray(md1), np.asarray(md2), rtol=1e-5)


def test_pallas_disabled_by_env(monkeypatch):
    monkeypatch.setenv(DISABLE_ENV, "1")
    assert not pallas_enabled()


@pytest.mark.parametrize(
    "n,d,k,expect_pallas",
    [
        (4096, 64, 4096, True),    # low-d, large-k: memory-bound, pallas wins
        (4096, 64, 512, False),    # small k: distance matrix cheap
        (4096, 512, 4096, False),  # wide d: FLOPs dominate, XLA wins
        (256, 64, 4096, False),    # batch below one row tile
    ],
)
def test_min_dist_argmin_routing(monkeypatch, n, d, k, expect_pallas):
    # the heuristic itself, independent of backend: force pallas_enabled and
    # record which implementation min_dist_argmin dispatches to
    import spark_rapids_ml_tpu.ops.pallas_tpu as pt

    calls = []
    monkeypatch.setattr(pt, "pallas_enabled", lambda: True)
    monkeypatch.setattr(
        pt,
        "_min_dist_argmin_pallas",
        lambda *a, **kw: calls.append("pallas"),
    )
    monkeypatch.setattr(
        pt, "_min_dist_argmin_xla", lambda *a, **kw: calls.append("xla")
    )
    X = jnp.zeros((n, d), jnp.float32)
    C = jnp.zeros((k, d), jnp.float32)
    pt.min_dist_argmin(X, C)
    assert calls == (["pallas"] if expect_pallas else ["xla"])


def test_cpu_fallback_is_xla_path():
    # on the CPU test mesh, min_dist_argmin without interpret must route to
    # the XLA formulation and still be correct
    if jax.devices()[0].platform == "tpu":
        pytest.skip("CPU-only routing test")
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.standard_normal((50, 9)).astype(np.float32))
    C = jnp.asarray(rng.standard_normal((4, 9)).astype(np.float32))
    md, am = min_dist_argmin(X, C)
    brute = np.argmin(
        ((np.asarray(X)[:, None, :] - np.asarray(C)[None]) ** 2).sum(-1), axis=1
    )
    np.testing.assert_array_equal(np.asarray(am), brute)


# -- fused kNN distance + per-group top-m kernel (ops/pallas_knn.py) ---------

from spark_rapids_ml_tpu.ops.pallas_knn import knn_candidates_pallas
from spark_rapids_ml_tpu.ops.knn import _adaptive_merge_self, _select_m


def _knn_pool_topk(items, norms, valid, Q, k, m):
    """Run the pallas candidate kernel + the self-verified exact merge
    (the production route, including the pallas m_pad pool stride); return
    host (distances ascending, positions).  Asserts no overflow flag fired
    — with _select_m-sized (or >= k) budgets on these shapes the pool
    provably contains the exact top-k."""
    cv, ci = knn_candidates_pallas(
        jnp.asarray(items), jnp.asarray(norms), jnp.asarray(valid),
        jnp.asarray(Q), k, m, items.shape[0],
        interpret=KERNEL_INTERPRET,
    )
    fv, fpos, flags, _z = _adaptive_merge_self(cv, ci, k, m=m)
    assert not np.asarray(flags).any()
    return np.asarray(fv), np.asarray(fpos)  # fv is distances already


@pytest.mark.parametrize(
    "n,d,q,k",
    [
        (2048, 128, 256, 16),    # aligned everything
        (2100, 300, 256, 10),    # ragged N (last group) and ragged D tail
        (2560, 515, 384, 33),    # unaligned d, ragged N, q above one tile
        (1024, 64, 130, 7),      # q pads up to a tile
    ],
)
def test_knn_candidates_pool_contains_exact_topk(n, d, q, k):
    """The merged candidate pool must reproduce the exact top-k whenever no
    group overflowed m — with m from _select_m on shuffled data, overflow
    probability at these sizes is ~0, so the comparison is deterministic in
    practice; rows that would overflow are exactly what the count-verify
    phase catches in production."""
    rng = np.random.default_rng(n + d + k)
    items = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q, d)).astype(np.float32)
    norms = (items**2).sum(axis=1)
    valid = np.ones(n, bool)
    m = max(_select_m(k, 1024, n), k)  # small n: one group may hold all k
    dists, pos = _knn_pool_topk(items, norms, valid, Q, k, m)
    d2 = ((Q[:, None, :] - items[None]) ** 2).sum(-1)
    order = np.argsort(d2, axis=1, kind="stable")[:, :k]
    want = np.sqrt(np.take_along_axis(d2, order, axis=1))
    np.testing.assert_allclose(dists, want, rtol=1e-3, atol=1e-3)
    # positions agree except on genuine distance ties
    agree = (pos == order).mean()
    assert agree > 0.95, agree


def test_knn_candidates_masks_invalid_rows():
    rng = np.random.default_rng(5)
    n, d, q, k = 1536, 96, 128, 8
    items = rng.standard_normal((n, d)).astype(np.float32)
    Q = items[:q] + 1e-3  # near-duplicates force tight distances
    norms = (items**2).sum(axis=1)
    valid = np.ones(n, bool)
    valid[700:] = False  # half the set invalid (padding rows)
    m = max(_select_m(k, 1024, 700), k)
    dists, pos = _knn_pool_topk(items, norms, valid, Q, k, m)
    assert int(pos.max()) < 700, "an invalid row entered the top-k"
    assert np.isfinite(dists).all()


def test_knn_candidates_duplicate_distances_stay_distinct():
    """Position-masked selection: duplicated items must occupy separate
    candidate slots (value-masking would collapse them)."""
    rng = np.random.default_rng(9)
    n, d, k = 1024, 64, 6
    base = rng.standard_normal((n // 2, d)).astype(np.float32)
    items = np.concatenate([base, base])  # every item duplicated
    Q = base[:128]
    norms = (items**2).sum(axis=1)
    m = max(_select_m(k, 1024, n), k)
    dists, pos = _knn_pool_topk(items, norms, np.ones(n, bool), Q, k, m)
    # the query IS an item (distance 0), and its duplicate must also be in
    # the top-k with distance ~0.  The norm-expansion form cancels
    # catastrophically at zero distance (|d2| residual ~|q|^2 * 2^-19 under
    # 3-pass bf16 -> sqrt up to ~3e-2 at d=64, varying with fusion/rounding
    # across compiles) — the STRUCTURAL claim is what is exact: both
    # duplicate slots present, congruent positions.
    assert np.allclose(dists[:, 0], 0, atol=5e-2)
    assert np.allclose(dists[:, 1], 0, atol=5e-2)
    assert (pos[:, 0] % (n // 2) == pos[:, 1] % (n // 2)).all()


# -- fused merge epilogue (ops/pallas_knn.knn_fused_pallas) ------------------

from spark_rapids_ml_tpu.ops.pallas_knn import knn_fused_pallas


def _lex_oracle(items, Q, k):
    """numpy lexicographic (d2, pos) top-k oracle: unique total order, so
    the comparison against the fused kernel is EXACT on positions whenever
    d2 bits agree — and on crafted integer-valued data they do."""
    d2 = ((Q[:, None, :].astype(np.float64)
           - items[None].astype(np.float64)) ** 2).sum(-1)
    order = np.lexsort((np.arange(items.shape[0])[None].repeat(len(Q), 0),
                        d2), axis=1)[:, :k]
    return np.sqrt(np.take_along_axis(d2, order, axis=1)), order


@pytest.mark.parametrize(
    "n,d,q,k",
    [
        (2048, 128, 256, 16),   # aligned
        (2100, 300, 256, 10),   # ragged N and ragged D tail
        (1024, 64, 130, 7),     # q pads up to a tile
    ],
)
def test_knn_fused_epilogue_matches_merge_and_oracle(n, d, q, k):
    """The fused merge kernel must agree with the XLA merge route
    (identical pool in, identical distances out) AND with brute force."""
    rng = np.random.default_rng(n + d + k)
    items = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q, d)).astype(np.float32)
    norms = (items**2).sum(axis=1)
    valid = np.ones(n, bool)
    m = max(_select_m(k, 1024, n), k)
    dist, pos, flags, zeros = knn_fused_pallas(
        jnp.asarray(items), jnp.asarray(norms), jnp.asarray(valid),
        jnp.asarray(Q), k, m, n, interpret=KERNEL_INTERPRET,
    )
    assert not np.asarray(flags).any() and not np.asarray(zeros).any()
    # route parity: same pool -> same distances as the XLA merge
    fv_d, _fv_p = _knn_pool_topk(items, norms, valid, Q, k, m)
    np.testing.assert_allclose(np.asarray(dist), fv_d, rtol=1e-5, atol=1e-6)
    # ground truth
    d2 = ((Q[:, None, :] - items[None]) ** 2).sum(-1)
    order = np.argsort(d2, axis=1, kind="stable")[:, :k]
    want = np.sqrt(np.take_along_axis(d2, order, axis=1))
    np.testing.assert_allclose(np.asarray(dist), want, rtol=1e-3, atol=1e-3)
    assert (np.asarray(pos) == order).mean() > 0.95


def test_knn_fused_epilogue_lex_tie_contract():
    """The tie contract vs the numpy oracle: on integer-valued data with
    every item DUPLICATED, d2 values tie in pairs and the fused merge must
    return the lexicographically smaller position first — exact equality
    against np.lexsort, not a tolerance check."""
    rng = np.random.default_rng(11)
    n, d, q, k = 1024, 128, 128, 8
    base = rng.integers(-3, 4, size=(n // 2, d)).astype(np.float32)
    items = np.concatenate([base, base])     # every distance tied pairwise
    Q = base[:q].astype(np.float32)
    norms = (items**2).sum(axis=1)
    valid = np.ones(n, bool)
    m = max(_select_m(k, 1024, n), k)
    dist, pos, flags, _z = knn_fused_pallas(
        jnp.asarray(items), jnp.asarray(norms), jnp.asarray(valid),
        jnp.asarray(Q), k, m, n, interpret=KERNEL_INTERPRET,
    )
    assert not np.asarray(flags).any()
    want_d, want_pos = _lex_oracle(items, Q, k)
    # integer-valued inputs: the 3-pass bf16 dot is exact, so positions
    # must match the lex oracle EXACTLY — including which duplicate of
    # each tied pair comes first
    np.testing.assert_array_equal(np.asarray(pos), want_pos)
    np.testing.assert_allclose(np.asarray(dist), want_d, rtol=1e-5, atol=1e-5)


def test_knn_fused_epilogue_multi_kblock():
    """nb > 1 K-block geometry through the fused route: tile_d=128 at
    d=330 (d_pad=384 -> 3 K blocks) must keep the same results as the
    single-block default."""
    rng = np.random.default_rng(13)
    n, d, q, k = 1056, 330, 128, 6
    items = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q, d)).astype(np.float32)
    norms = (items**2).sum(axis=1)
    valid = np.ones(n, bool)
    m = max(_select_m(k, 1024, n), k)
    out_multi = knn_fused_pallas(
        jnp.asarray(items), jnp.asarray(norms), jnp.asarray(valid),
        jnp.asarray(Q), k, m, n, interpret=KERNEL_INTERPRET, tile_d=128,
    )
    d2 = ((Q[:, None, :] - items[None]) ** 2).sum(-1)
    order = np.argsort(d2, axis=1, kind="stable")[:, :k]
    want = np.sqrt(np.take_along_axis(d2, order, axis=1))
    np.testing.assert_allclose(
        np.asarray(out_multi[0]), want, rtol=1e-3, atol=1e-3
    )
    assert (np.asarray(out_multi[1]) == order).mean() > 0.95


def test_knn_fused_epilogue_flags_route_exact_fallback():
    """Forced self-verify failure through the fused path: an m far below
    the _select_m envelope with the whole true top-k packed into ONE item
    group must (a) raise the in-kernel overflow flag and (b) come back
    EXACT after knn_block_adaptive_collect's per-row rerun."""
    import jax

    from jax.sharding import Mesh
    from spark_rapids_ml_tpu.ops.knn import knn_block_adaptive_collect
    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    rng = np.random.default_rng(17)
    n, d, q, k, m = 2048, 128, 128, 10, 4
    items = rng.standard_normal((n, d)).astype(np.float32) + 50.0
    Q = rng.standard_normal((q, d)).astype(np.float32)
    # rows 0..k-1 live in group 0 and are the UNIQUE top-k of every query:
    # group 0 keeps only m=4 of them, so the merged list misses 6 and the
    # worst-kept-vs-threshold flag MUST fire
    items[:k] = Q[:k].mean(axis=0) + 0.01 * rng.standard_normal(
        (k, d)
    ).astype(np.float32)
    Q[:] = items[:k].mean(axis=0) + 0.01 * rng.standard_normal(
        (q, d)
    ).astype(np.float32)
    norms = (items**2).sum(axis=1)
    valid = np.ones(n, bool)
    handles = knn_fused_pallas(
        jnp.asarray(items), jnp.asarray(norms), jnp.asarray(valid),
        jnp.asarray(Q), k, m, n, interpret=KERNEL_INTERPRET,
    )
    flags = np.asarray(handles[2])
    assert flags.any(), "crafted overflow did not raise the fused flag"
    mesh = Mesh(np.array(jax.devices()[:1]), (DATA_AXIS,))
    d_out, p_out = knn_block_adaptive_collect(
        handles,
        jnp.asarray(items), jnp.asarray(norms),
        jnp.arange(n, dtype=jnp.int32), jnp.asarray(valid),
        jnp.asarray(Q), mesh, k,
    )
    d2 = ((Q[:, None, :].astype(np.float64) - items[None]) ** 2).sum(-1)
    order = np.argsort(d2, axis=1, kind="stable")[:, :k]
    want = np.sqrt(np.take_along_axis(d2, order, axis=1))
    np.testing.assert_allclose(d_out, want, rtol=1e-3, atol=1e-3)
    assert (p_out == order).mean() > 0.95


# -- fused feature binning kernel (ops/pallas_tpu.bin_features_fm_pallas) ----

from spark_rapids_ml_tpu.ops.pallas_tpu import bin_features_fm_pallas


@pytest.mark.parametrize(
    "n,d,b,n_pad",
    [
        (1024, 512, 128, 1024),   # aligned, max int8 bins
        (700, 300, 16, 1024),     # ragged rows+cols, padded target
        (513, 130, 64, 520),      # everything unaligned
    ],
)
def test_bin_features_pallas_matches_xla(n, d, b, n_pad):
    from spark_rapids_ml_tpu.ops.forest import _bin_chunk_t

    rng = np.random.default_rng(n + d + b)
    X = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    edges = jnp.asarray(
        np.sort(rng.standard_normal((d, b - 1)).astype(np.float32), axis=1)
    )
    got = np.asarray(
        bin_features_fm_pallas(X, edges, n_pad, interpret=KERNEL_INTERPRET)
    )
    want = np.asarray(_bin_chunk_t(X, edges))
    assert got.shape == (d, n_pad)
    np.testing.assert_array_equal(got[:, :n], want)
    assert (got[:, n:] == 0).all(), "padding rows must be bin 0"


def test_knn_audit_pair_runs_and_agrees():
    """The SRML_KNN_AUDIT_COUNT=1 route (legacy candidates kernel + count
    kernel, bitwise-paired) must still run — it is the ground-truth audit
    for the default self-verify route — and agree with it on clean data.
    Regression guard: the count kernel's _neg_d2 call broke when the
    helper moved to value inputs and no default-CI test exercised the
    pallas audit pairing."""
    import spark_rapids_ml_tpu.ops.knn as knn_mod
    from spark_rapids_ml_tpu.ops.pallas_knn import knn_count_pallas

    rng = np.random.default_rng(21)
    n, d, q, k = 1536, 128, 256, 9
    items = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q, d)).astype(np.float32)
    norms = (items**2).sum(axis=1)
    valid = np.ones(n, bool)
    m = max(_select_m(k, 1024, n), k)

    cv, ci = knn_candidates_pallas(
        jnp.asarray(items), jnp.asarray(norms), jnp.asarray(valid),
        jnp.asarray(Q), k, m, n, interpret=KERNEL_INTERPRET, legacy=True,
    )
    fv, fpos, tu, sg = knn_mod._adaptive_merge(cv, ci, k)
    sa = knn_count_pallas(
        jnp.asarray(items), jnp.asarray(norms), jnp.asarray(valid),
        jnp.asarray(Q), tu, n, interpret=KERNEL_INTERPRET,
    )
    np.testing.assert_array_equal(np.asarray(sg), np.asarray(sa))
    # and the audit merge agrees with the self-verify route's results
    fv_s, fpos_s, flags, _z = _adaptive_merge_self(cv, ci, k, m=m)
    assert not np.asarray(flags).any()
    np.testing.assert_allclose(
        np.asarray(fv_s), np.sqrt(np.maximum(-np.asarray(fv), 0)), rtol=1e-5
    )


def test_knn_candidates_qres_multi_kblock_matches_reference():
    """Multi-K-block query-resident kernel (nb > 1): tile_d=1024 at d=3100
    (d_pad=3200) forces several K blocks, the geometry whose previous
    (j, b, i) grid was undefined behavior (output blocks revisited with the
    revisiting dimension NOT innermost — ADVICE medium).  The restructured
    (j, i, b) grid must reproduce BOTH the XLA candidates-scan route and
    the brute-force ground truth through the unchanged self-verified
    merge."""
    import jax.numpy as jnp

    import spark_rapids_ml_tpu.ops.knn as knn_mod

    rng = np.random.default_rng(31)
    n, d, q, k = 1100, 3100, 128, 9
    items = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q, d)).astype(np.float32)
    norms = (items**2).sum(axis=1)
    valid = np.ones(n, bool)
    m = max(_select_m(k, 1024, n), k)

    cv, ci = knn_candidates_pallas(
        jnp.asarray(items), jnp.asarray(norms), jnp.asarray(valid),
        jnp.asarray(Q), k, m, n, interpret=KERNEL_INTERPRET, tile_d=1024,
    )
    fv, fpos, flags, _z = _adaptive_merge_self(cv, ci, k, m=m)
    assert not np.asarray(flags).any()

    # ground truth
    d2 = ((Q[:, None, :] - items[None]) ** 2).sum(-1)
    order = np.argsort(d2, axis=1, kind="stable")[:, :k]
    want = np.sqrt(np.take_along_axis(d2, order, axis=1))
    np.testing.assert_allclose(np.asarray(fv), want, rtol=1e-3, atol=1e-3)
    assert (np.asarray(fpos) == order).mean() > 0.95

    # XLA reference route: same pool contract, same merge
    chunk = min(knn_mod._ADAPTIVE_CHUNK, n)
    cv_x, ci_x = knn_mod._adaptive_candidates_single(
        jnp.asarray(items), jnp.asarray(norms),
        jnp.arange(n, dtype=jnp.int32), jnp.asarray(valid),
        jnp.asarray(Q), k=k, chunk=chunk,
    )
    G, m_x = knn_mod._scan_geometry(k, chunk, n)
    fv_x, fpos_x, flags_x, _zx = _adaptive_merge_self(cv_x, ci_x, k, m=m_x)
    assert not np.asarray(flags_x).any()
    np.testing.assert_allclose(
        np.asarray(fv), np.asarray(fv_x), rtol=1e-3, atol=1e-3
    )
    assert (np.asarray(fpos) == np.asarray(fpos_x)).mean() > 0.95


def test_knn_candidates_qres_multi_kblock_ragged_tail():
    """nb > 1 with a RAGGED D tail (d_pad > d): the qres route must keep
    the zero-padded columns exact no-ops across every K block."""
    import jax.numpy as jnp

    rng = np.random.default_rng(33)
    n, d, q, k = 1056, 330, 128, 6  # d_pad=384; tile_d=128 -> nb=3
    items = rng.standard_normal((n, d)).astype(np.float32)
    Q = rng.standard_normal((q, d)).astype(np.float32)
    norms = (items**2).sum(axis=1)
    valid = np.ones(n, bool)
    m = max(_select_m(k, 1024, n), k)
    cv, ci = knn_candidates_pallas(
        jnp.asarray(items), jnp.asarray(norms), jnp.asarray(valid),
        jnp.asarray(Q), k, m, n, interpret=KERNEL_INTERPRET, tile_d=128,
    )
    fv, fpos, flags, _z = _adaptive_merge_self(cv, ci, k, m=m)
    assert not np.asarray(flags).any()
    d2 = ((Q[:, None, :] - items[None]) ** 2).sum(-1)
    order = np.argsort(d2, axis=1, kind="stable")[:, :k]
    want = np.sqrt(np.take_along_axis(d2, order, axis=1))
    np.testing.assert_allclose(np.asarray(fv), want, rtol=1e-3, atol=1e-3)
