# Pallas TPU kernel correctness (interpreter mode on the CPU test mesh).
# The same kernel compiles with Mosaic on real TPU; the hardware-exactness
# A/B record (v5e, argmin mismatch 0 vs the XLA path) is quoted in the
# ops/pallas_tpu.py module header.  Set SRML_TPU_TESTS=1 to re-run this file
# against real TPU devices, where the kernel tests run the compiled Mosaic
# path (interpret=False) instead of the interpreter.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.pallas_tpu import (
    DISABLE_ENV,
    _min_dist_argmin_pallas,
    _min_dist_argmin_xla,
    min_dist_argmin,
    pallas_enabled,
)

# On a real TPU run the compiled Mosaic kernel; on the CPU mesh interpret.
ON_TPU = jax.devices()[0].platform == "tpu"
KERNEL_INTERPRET = not ON_TPU


@pytest.mark.parametrize(
    "n,d,k",
    [
        (300, 70, 33),     # nothing aligned
        (512, 256, 128),   # everything aligned
        (129, 1, 2),       # degenerate feature dim
        (64, 515, 700),    # k > n, unaligned d
    ],
)
def test_min_dist_argmin_matches_xla(n, d, k):
    rng = np.random.default_rng(n + d + k)
    X = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    C = jnp.asarray(rng.standard_normal((k, d)).astype(np.float32))
    xn = (X**2).sum(axis=1)
    cn = (C**2).sum(axis=1)
    md, am = _min_dist_argmin_pallas(X, C, xn, cn, interpret=KERNEL_INTERPRET)
    md_ref, am_ref = _min_dist_argmin_xla(X, C, xn, cn)
    assert md.shape == (n,) and am.shape == (n,)
    # padded center slots (norm=+inf) must never win
    assert int(np.asarray(am).max()) < k
    np.testing.assert_array_equal(np.asarray(am), np.asarray(am_ref))
    np.testing.assert_allclose(
        np.asarray(md), np.asarray(md_ref), rtol=1e-4, atol=1e-4
    )


def test_min_dist_argmin_precomputed_norms():
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((100, 40)).astype(np.float32))
    C = jnp.asarray(rng.standard_normal((7, 40)).astype(np.float32))
    xn = (X**2).sum(axis=1)
    cn = (C**2).sum(axis=1)
    md1, am1 = min_dist_argmin(X, C, xn, cn, interpret=KERNEL_INTERPRET)
    md2, am2 = min_dist_argmin(X, C, interpret=KERNEL_INTERPRET)
    np.testing.assert_array_equal(np.asarray(am1), np.asarray(am2))
    np.testing.assert_allclose(np.asarray(md1), np.asarray(md2), rtol=1e-5)


def test_pallas_disabled_by_env(monkeypatch):
    monkeypatch.setenv(DISABLE_ENV, "1")
    assert not pallas_enabled()


@pytest.mark.parametrize(
    "n,d,k,expect_pallas",
    [
        (4096, 64, 4096, True),    # low-d, large-k: memory-bound, pallas wins
        (4096, 64, 512, False),    # small k: distance matrix cheap
        (4096, 512, 4096, False),  # wide d: FLOPs dominate, XLA wins
        (256, 64, 4096, False),    # batch below one row tile
    ],
)
def test_min_dist_argmin_routing(monkeypatch, n, d, k, expect_pallas):
    # the heuristic itself, independent of backend: force pallas_enabled and
    # record which implementation min_dist_argmin dispatches to
    import spark_rapids_ml_tpu.ops.pallas_tpu as pt

    calls = []
    monkeypatch.setattr(pt, "pallas_enabled", lambda: True)
    monkeypatch.setattr(
        pt,
        "_min_dist_argmin_pallas",
        lambda *a, **kw: calls.append("pallas"),
    )
    monkeypatch.setattr(
        pt, "_min_dist_argmin_xla", lambda *a, **kw: calls.append("xla")
    )
    X = jnp.zeros((n, d), jnp.float32)
    C = jnp.zeros((k, d), jnp.float32)
    pt.min_dist_argmin(X, C)
    assert calls == (["pallas"] if expect_pallas else ["xla"])


def test_cpu_fallback_is_xla_path():
    # on the CPU test mesh, min_dist_argmin without interpret must route to
    # the XLA formulation and still be correct
    if jax.devices()[0].platform == "tpu":
        pytest.skip("CPU-only routing test")
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.standard_normal((50, 9)).astype(np.float32))
    C = jnp.asarray(rng.standard_normal((4, 9)).astype(np.float32))
    md, am = min_dist_argmin(X, C)
    brute = np.argmin(
        ((np.asarray(X)[:, None, :] - np.asarray(C)[None]) ** 2).sum(-1), axis=1
    )
    np.testing.assert_array_equal(np.asarray(am), brute)
