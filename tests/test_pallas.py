# Pallas TPU kernel correctness (interpreter mode on the CPU test mesh).
# The same kernel compiles with Mosaic on real TPU; the hardware-exactness
# A/B record (v5e, argmin mismatch 0 vs the XLA path) is quoted in the
# ops/pallas_tpu.py module header.  Set SRML_TPU_TESTS=1 to re-run this file
# against real TPU devices, where min_dist_argmin takes the compiled Mosaic
# path instead of the interpreter.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.pallas_tpu import (
    DISABLE_ENV,
    _min_dist_argmin_xla,
    min_dist_argmin,
    pallas_enabled,
)


@pytest.mark.parametrize(
    "n,d,k",
    [
        (300, 70, 33),     # nothing aligned
        (512, 256, 128),   # everything aligned
        (129, 1, 2),       # degenerate feature dim
        (64, 515, 700),    # k > n, unaligned d
    ],
)
def test_min_dist_argmin_matches_xla(n, d, k):
    rng = np.random.default_rng(n + d + k)
    X = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    C = jnp.asarray(rng.standard_normal((k, d)).astype(np.float32))
    md, am = min_dist_argmin(X, C, interpret=True)
    md_ref, am_ref = _min_dist_argmin_xla(
        X, C, (X**2).sum(axis=1), (C**2).sum(axis=1)
    )
    assert md.shape == (n,) and am.shape == (n,)
    # padded center slots (norm=+inf) must never win
    assert int(np.asarray(am).max()) < k
    np.testing.assert_array_equal(np.asarray(am), np.asarray(am_ref))
    np.testing.assert_allclose(
        np.asarray(md), np.asarray(md_ref), rtol=1e-4, atol=1e-4
    )


def test_min_dist_argmin_precomputed_norms():
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((100, 40)).astype(np.float32))
    C = jnp.asarray(rng.standard_normal((7, 40)).astype(np.float32))
    xn = (X**2).sum(axis=1)
    cn = (C**2).sum(axis=1)
    md1, am1 = min_dist_argmin(X, C, xn, cn, interpret=True)
    md2, am2 = min_dist_argmin(X, C, interpret=True)
    np.testing.assert_array_equal(np.asarray(am1), np.asarray(am2))
    np.testing.assert_allclose(np.asarray(md1), np.asarray(md2), rtol=1e-5)


def test_pallas_disabled_by_env(monkeypatch):
    monkeypatch.setenv(DISABLE_ENV, "1")
    assert not pallas_enabled()


def test_cpu_fallback_is_xla_path():
    # on the CPU test mesh, min_dist_argmin without interpret must route to
    # the XLA formulation and still be correct
    if jax.devices()[0].platform == "tpu":
        pytest.skip("CPU-only routing test")
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.standard_normal((50, 9)).astype(np.float32))
    C = jnp.asarray(rng.standard_normal((4, 9)).astype(np.float32))
    md, am = min_dist_argmin(X, C)
    brute = np.argmin(
        ((np.asarray(X)[:, None, :] - np.asarray(C)[None]) ** 2).sum(-1), axis=1
    )
    np.testing.assert_array_equal(np.asarray(am), brute)
