# srml-ann IVF-Flat engine contracts (ann/ivfflat.py + the
# ApproximateNearestNeighbors model): recall@10 >= 0.95 against the exact
# kneighbors path at the documented nprobe (the acceptance gate), BITWISE
# 1-device-vs-8-device mesh parity of probed results (extending the UMAP/RF
# parity matrix), zero-new-compile repeat probed searches (precompile
# counters, the PR2-5 idiom), the lexicographic selection core against a
# numpy oracle, the exactSearch fallback, and the model's param surface.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu import ApproximateNearestNeighbors, profiling
from spark_rapids_ml_tpu.ann.ivfflat import (
    _lex_topk,
    _POS_SENTINEL,
    build_ivfflat_packed,
    default_nlist,
    default_nprobe,
    index_from_packed,
    ivfflat_search_prepared,
    recall_at_k,
    warm_probe_kernels,
)
from spark_rapids_ml_tpu.dataframe import DataFrame
from spark_rapids_ml_tpu.ops.knn import knn_search_prepared, prepare_items
from spark_rapids_ml_tpu.parallel.mesh import get_mesh


def _clustered(n=4000, d=16, n_blobs=24, seed=0):
    """Clustered item set (the workload IVF-Flat exists for) + queries
    drawn from the same distribution."""
    rng = np.random.default_rng(seed)
    centers = 20.0 * rng.normal(size=(n_blobs, d))
    lab = rng.integers(0, n_blobs, size=n)
    X = (centers[lab] + rng.normal(size=(n, d))).astype(np.float32)
    ids = np.arange(n, dtype=np.int64) * 7 + 3  # non-contiguous user ids
    return X, ids


# -- selection core ------------------------------------------------------------


def test_lex_topk_matches_numpy_oracle():
    """The (d2, pos) lexicographic selection must equal np.lexsort's first
    k on every pool width (one-group, grouped, padded) — including value
    TIES, where pos decides (the mesh-parity basis)."""
    rng = np.random.default_rng(5)
    cases = []
    for Q, C, k in [(4, 17, 5), (3, 2100, 8), (2, 4096, 200), (5, 7, 10)]:
        d2 = rng.integers(0, 9, size=(Q, C)).astype(np.float32)  # many ties
        pos = rng.permutation(C * Q).reshape(Q, C).astype(np.int32)
        cases.append(
            (d2, pos, k, _lex_topk(jnp.asarray(d2), jnp.asarray(pos), k))
        )
    fetched = jax.device_get([h for *_x, h in cases])  # ONE batched fetch
    for (d2, pos, k, _h), (got_d, got_p) in zip(cases, fetched):
        Q, C = d2.shape
        for q in range(Q):
            order = np.lexsort((pos[q], d2[q]))[: min(k, C)]
            want_d, want_p = d2[q][order], pos[q][order]
            np.testing.assert_array_equal(got_d[q][: order.size], want_d)
            np.testing.assert_array_equal(got_p[q][: order.size], want_p)
            if order.size < k:  # unfillable slots carry the sentinels
                assert np.all(np.isinf(got_d[q][order.size :]))
                assert np.all(got_p[q][order.size :] == _POS_SENTINEL)


# -- build layout --------------------------------------------------------------


def test_packed_layout_is_a_list_partition():
    X, ids = _clustered(n=1000, d=8, n_blobs=6, seed=2)
    packed = build_ivfflat_packed(X, ids, n_lists=6, seed=1)
    assert packed.counts.sum() == 1000
    assert packed.n_items == 1000
    # list-sorted: every row keeps its (features, id) pairing
    lookup = {int(i): row for i, row in zip(ids, X)}
    for i, row in zip(packed.ids[:50], packed.items[:50]):
        np.testing.assert_array_equal(lookup[int(i)], row)
    # staging expands without losing rows, on either mesh
    for mesh in (get_mesh(1), get_mesh()):
        idx = index_from_packed(packed, mesh)
        assert idx.nlist_pad % mesh.shape["data"] == 0
        assert (idx.ids >= 0).sum() == 1000


# -- the acceptance gates ------------------------------------------------------


def test_recall_at_10_clustered_data():
    """Acceptance: recall@10 >= 0.95 vs the exact kneighbors path at the
    DOCUMENTED nprobe (docs/ann_engine.md: default_nprobe = nlist/4) on
    clustered data."""
    X, ids = _clustered()
    mesh = get_mesh()
    nlist = default_nlist(X.shape[0])  # 63 at n=4000
    nprobe = default_nprobe(nlist)
    packed = build_ivfflat_packed(X, ids, nlist, seed=1)
    index = index_from_packed(packed, mesh)
    Q = X[:512]
    d_ann, i_ann = ivfflat_search_prepared(index, Q, 10, nprobe, mesh)
    prepared = prepare_items(X, ids, mesh)
    _, i_exact = knn_search_prepared(prepared, Q, 10, mesh)
    r = recall_at_k(i_ann, i_exact)
    assert r >= 0.95, (r, nlist, nprobe)
    # distances ascending, self id (query == item row) leads each row
    assert np.all(np.diff(d_ann, axis=1) >= 0)
    np.testing.assert_array_equal(i_ann[:, 0], ids[:512])


def test_mesh_parity_bitwise():
    """Acceptance: a fixed seed gives BITWISE-identical probed results on a
    1-device and an 8-device mesh (lexicographic (d2, pos) selection is a
    total order, and each candidate's d2 is computed on an identically
    shaped tile on every mesh)."""
    X, ids = _clustered(n=2000, d=12, n_blobs=16, seed=3)
    packed = build_ivfflat_packed(X, ids, n_lists=16, seed=4)
    Q = X[:300]
    out = {}
    for name, mesh in (("one", get_mesh(1)), ("all", get_mesh())):
        index = index_from_packed(packed, mesh)
        out[name] = ivfflat_search_prepared(index, Q, 10, 5, mesh)
    d1, i1 = out["one"]
    d8, i8 = out["all"]
    np.testing.assert_array_equal(i1, i8)
    # bitwise, not allclose: compare the raw float32 payloads
    np.testing.assert_array_equal(
        d1.astype(np.float32).view(np.uint32),
        d8.astype(np.float32).view(np.uint32),
    )


def test_repeat_search_zero_new_compiles():
    """Acceptance: a repeat same-shape probed search performs ZERO new
    executable compilations (precompile compile/fallback counters frozen,
    aot_hit moving — the PR2-5 executable-cache contract)."""
    X, ids = _clustered(n=1500, d=10, n_blobs=12, seed=6)
    mesh = get_mesh()
    packed = build_ivfflat_packed(X, ids, 12, seed=2)
    index = index_from_packed(packed, mesh)
    ivfflat_search_prepared(index, X[:200], 5, 4, mesh)  # compiles once
    before = profiling.counters("precompile.")
    d1, i1 = ivfflat_search_prepared(index, X[:200], 5, 4, mesh)
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.fallback", 0) == 0, delta
    assert delta.get("precompile.aot_hit", 0) >= 1, delta
    # and the repeat is deterministic
    d2, i2 = ivfflat_search_prepared(index, X[:200], 5, 4, mesh)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_array_equal(d1, d2)


def test_warm_path_covers_the_dispatch_key():
    """warm_probe_kernels must submit the EXACT executable the later
    dispatch looks up: a search right after warm moves only aot_hit."""
    from spark_rapids_ml_tpu.ops.precompile import global_precompiler

    X, ids = _clustered(n=1200, d=8, n_blobs=8, seed=9)
    mesh = get_mesh()
    packed = build_ivfflat_packed(X, ids, 8, seed=7)
    index = index_from_packed(packed, mesh)
    keys = warm_probe_kernels(index, 6, 4, mesh, n_queries=250)
    assert keys
    global_precompiler().wait(keys)
    before = profiling.counters("precompile.")
    ivfflat_search_prepared(index, X[:250], 6, 4, mesh)
    delta = profiling.counter_deltas(before, "precompile.")
    assert delta.get("precompile.compile", 0) == 0, delta
    assert delta.get("precompile.aot_miss", 0) == 0, delta


def test_probe_all_lists_equals_exact_ids():
    """nprobe >= nlist visits every item exactly once: the probed ids must
    match the exact engine's (same id space, recall 1.0)."""
    X, ids = _clustered(n=900, d=8, n_blobs=8, seed=1)
    mesh = get_mesh()
    packed = build_ivfflat_packed(X, ids, 8, seed=0)
    index = index_from_packed(packed, mesh)
    Q = X[:128]
    _, i_ann = ivfflat_search_prepared(index, Q, 8, index.nlist_pad, mesh)
    prepared = prepare_items(X, ids, mesh)
    _, i_exact = knn_search_prepared(prepared, Q, 8, mesh)
    assert recall_at_k(i_ann, i_exact) == 1.0


def test_multi_chunk_scan_budget(monkeypatch):
    """A tiny tile budget forces the probe kernel's multi-chunk scan; the
    results must not change."""
    X, ids = _clustered(n=800, d=8, n_blobs=8, seed=4)
    mesh = get_mesh()
    packed = build_ivfflat_packed(X, ids, 8, seed=3)
    index = index_from_packed(packed, mesh)
    d_big, i_big = ivfflat_search_prepared(index, X[:100], 5, 4, mesh)
    monkeypatch.setenv("SRML_ANN_TILE_BUDGET", "65536")
    d_small, i_small = ivfflat_search_prepared(index, X[:100], 5, 4, mesh)
    np.testing.assert_array_equal(i_big, i_small)
    np.testing.assert_array_equal(d_big, d_small)


def test_unfillable_slots_carry_minus_one():
    """k beyond the probed candidate pool yields the -1 id / inf distance
    sentinel (the exact engine's contract)."""
    rng = np.random.default_rng(0)
    # two far blobs: probing ONE list cannot fill k=30 from a 16-row list
    X = np.concatenate(
        [
            rng.normal(size=(16, 4)).astype(np.float32),
            (100.0 + rng.normal(size=(16, 4))).astype(np.float32),
        ]
    )
    ids = np.arange(32, dtype=np.int64)
    mesh = get_mesh()
    packed = build_ivfflat_packed(X, ids, 2, seed=5)
    index = index_from_packed(packed, mesh)
    d, i = ivfflat_search_prepared(index, X[:4], 30, 1, mesh)
    assert (i == -1).any()
    assert np.all(np.isinf(d[i == -1]))
    assert np.all(i[:, :10] >= 0)


# -- model surface -------------------------------------------------------------


def _fit_model(n=800, d=8, k=4, nlist=8, nprobe=4, seed=1, **kw):
    X, _ = _clustered(n=n, d=d, n_blobs=nlist, seed=seed)
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=2)
    est = ApproximateNearestNeighbors(
        k=k, algoParams={"nlist": nlist, "nprobe": nprobe}, **kw
    ).setFeaturesCol("features")
    return est.fit(df), X


def _knn_arrays(knn_df):
    ids = np.concatenate(
        [np.asarray(list(p["indices"])) for p in knn_df.partitions if len(p)]
    )
    dists = np.concatenate(
        [np.asarray(list(p["distances"])) for p in knn_df.partitions if len(p)]
    )
    return ids, dists


def test_model_kneighbors_and_exact_search_fallback():
    model, X = _fit_model()
    qdf = DataFrame.from_numpy(X[:64], num_partitions=2)
    _, _, knn_df = model.kneighbors(qdf)
    i_ann, d_ann = _knn_arrays(knn_df)
    assert i_ann.shape == (64, 4) and d_ann.shape == (64, 4)
    model.setExactSearch(True)
    _, _, knn_exact = model.kneighbors(qdf)
    model.setExactSearch(False)
    i_exact, _ = _knn_arrays(knn_exact)
    assert recall_at_k(i_ann, i_exact) >= 0.95
    # default row ids: probed self-match leads every row
    np.testing.assert_array_equal(i_ann[:, 0], np.arange(64))


def test_model_param_validation():
    X, _ = _clustered(n=100, d=4, n_blobs=4, seed=0)
    df = DataFrame.from_numpy(X, feature_layout="array", num_partitions=1)
    with pytest.raises(ValueError, match="unknown algoParams"):
        ApproximateNearestNeighbors(
            algoParams={"nprobes": 3}
        ).setFeaturesCol("features").fit(df)
    with pytest.raises(ValueError, match="not supported"):
        ApproximateNearestNeighbors(algorithm="hnsw").setFeaturesCol(
            "features"
        ).fit(df)
    est = ApproximateNearestNeighbors(k=3)
    assert est.getAlgorithm() == "ivfflat"
    assert est.getAlgoParams() is None
    model = est.setFeaturesCol("features").fit(df)  # default nlist/nprobe
    _, _, knn_df = model.kneighbors(
        DataFrame.from_numpy(X[:5], num_partitions=1)
    )
    ids, _ = _knn_arrays(knn_df)
    assert ids.shape == (5, 3)


def test_model_empty_query_partition():
    model, X = _fit_model(n=200, nlist=4, nprobe=4)
    import pandas as pd

    qdf = DataFrame(
        [
            pd.DataFrame({"features": list(X[:6])}),
            pd.DataFrame({"features": []}),
        ]
    )
    _, _, knn_df = model.kneighbors(qdf)
    assert len(knn_df.partitions) == 2
    assert len(knn_df.partitions[1]) == 0
    ids, _ = _knn_arrays(knn_df)
    assert ids.shape == (6, 4)


def test_recall_harness_contract():
    assert recall_at_k([[1, 2, -1]], [[1, 2, 3]]) == pytest.approx(2 / 3)
    assert recall_at_k(np.zeros((0, 3)), np.zeros((0, 3))) == 1.0
    with pytest.raises(ValueError, match="row mismatch"):
        recall_at_k([[1]], [[1], [2]])
