# Binary array codec + bulk collectives (parallel/exchange.py) — the TPU
# stand-in for the reference's UCX data-plane frames (knn.py:452-560).
import threading

import numpy as np
import pytest

from spark_rapids_ml_tpu.parallel.exchange import (
    allgather_bytes,
    alltoall_bytes,
    pack_arrays,
    unpack_arrays,
)


class StringBarrier:
    """In-process mock of Spark's BarrierTaskContext.allGather: STRING-only
    frames (forces the base64 path), rank-ordered results, true barrier
    semantics via threading.Barrier."""

    def __init__(self, nranks):
        self.nranks = nranks
        self._barrier = threading.Barrier(nranks)
        self._slots = [None] * nranks
        self._lock = threading.Lock()
        self.wire_chars = 0  # total characters that crossed the wire

    def plane(self, rank):
        outer = self

        class _P:
            def allGather(self, message):
                assert isinstance(message, str)
                with outer._lock:
                    outer._slots[rank] = message
                    outer.wire_chars += len(message)
                outer._barrier.wait()
                out = list(outer._slots)
                outer._barrier.wait()
                return out

            def barrier(self):
                self.allGather("")

        return _P()


def _run_ranks(nranks, fn):
    results, errors = {}, {}

    def run(r):
        try:
            results[r] = fn(r)
        except Exception as e:  # surfaced below
            errors[r] = e

    ts = [threading.Thread(target=run, args=(r,)) for r in range(nranks)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    return results


# -- codec -------------------------------------------------------------------

@pytest.mark.parametrize(
    "arrays",
    [
        [np.arange(12, dtype=np.float32).reshape(3, 4)],
        [np.zeros((0, 7), np.float64), np.arange(5, dtype=np.int64)],
        [np.array(3.5, np.float32), np.ones((2, 3, 4), np.int8)],
        [np.array([], np.int32)],
    ],
)
def test_pack_unpack_roundtrip(arrays):
    out = unpack_arrays(pack_arrays(arrays))
    assert len(out) == len(arrays)
    for a, b in zip(arrays, out):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_unpack_rejects_garbage():
    with pytest.raises(ValueError):
        unpack_arrays(b"not a frame at all")


# -- alltoall ----------------------------------------------------------------

def test_alltoall_bytes_routes_per_destination():
    nranks = 4
    bar = StringBarrier(nranks)
    # dests[s][d]: distinct sizes to catch any offset slip, incl. empties
    payload = {
        (s, d): (bytes([65 + s]) * (100 * s + 37 * d) if (s + d) % 3 else b"")
        for s in range(nranks)
        for d in range(nranks)
    }

    def fn(rank):
        return alltoall_bytes(
            bar.plane(rank), rank, nranks,
            [payload[(rank, d)] for d in range(nranks)],
            chunk=64,
        )

    results = _run_ranks(nranks, fn)
    for d in range(nranks):
        for s in range(nranks):
            assert results[d][s] == payload[(s, d)], (s, d)


def test_alltoall_decode_volume_is_owner_only(monkeypatch):
    """The p2p-shape contract (reference knn.py:549-560): a receiver must
    only materialize (b64-decode + join) the bytes addressed to IT, not
    every rank's full result matrix.  Metered by instrumenting the decode
    hook per thread-rank."""
    import spark_rapids_ml_tpu.parallel.exchange as ex

    nranks = 4
    bar = StringBarrier(nranks)
    rows = [100, 0, 300, 50]  # rank d owns rows[d] query rows
    q_total = sum(rows)
    k = 16
    rng = np.random.default_rng(0)
    full = {
        s: (rng.normal(size=(q_total, k)).astype(np.float32),
            rng.integers(0, 1 << 40, size=(q_total, k)).astype(np.int64))
        for s in range(nranks)
    }
    offs = np.cumsum([0] + rows)

    real_recv = ex._recv
    decoded = {}  # thread ident -> bytes materialized

    def metered_recv(frame, use_bytes):
        out = real_recv(frame, use_bytes)
        tid = threading.get_ident()
        decoded[tid] = decoded.get(tid, 0) + len(out)
        return out

    monkeypatch.setattr(ex, "_recv", metered_recv)
    tid_of = {}

    def fn(rank):
        tid_of[rank] = threading.get_ident()
        d_mine, i_mine = full[rank]
        dests = [
            pack_arrays([d_mine[offs[r]:offs[r + 1]],
                         i_mine[offs[r]:offs[r + 1]]])
            for r in range(nranks)
        ]
        got = alltoall_bytes(bar.plane(rank), rank, nranks, dests, chunk=4096)
        return [unpack_arrays(fr) for fr in got]

    results = _run_ranks(nranks, fn)
    for d in range(nranks):
        got = results[d]
        # correctness: the owner got exactly its rows from every source
        for s in range(nranks):
            np.testing.assert_array_equal(
                got[s][0], full[s][0][offs[d]:offs[d + 1]]
            )
            np.testing.assert_array_equal(
                got[s][1], full[s][1][offs[d]:offs[d + 1]]
            )
        # decode volume: O(own_Q x k x nranks) + frame headers, NOT the
        # O(q_total x k x nranks) the full-matrix broadcast used to pay
        own_share = rows[d] * k * 12 * nranks  # 12B per (f32, i64) cell
        assert decoded[tid_of[d]] <= own_share + 1024 * nranks, (
            d, decoded[tid_of[d]], own_share
        )
    # sanity: the big owner really did materialize its share
    assert decoded[tid_of[2]] >= rows[2] * k * 12 * nranks


def test_alltoall_empty_rank_keeps_collective_shape():
    nranks = 3
    bar = StringBarrier(nranks)

    def fn(rank):
        dests = [b"" for _ in range(nranks)]
        if rank == 2:
            dests = [b"x" * 10, b"", b"yy"]
        return alltoall_bytes(bar.plane(rank), rank, nranks, dests, chunk=4)

    results = _run_ranks(nranks, fn)
    assert results[0][2] == b"x" * 10
    assert results[2][2] == b"yy"
    assert results[1] == [b"", b"", b""]


def test_allgather_bytes_string_plane_uses_base64():
    nranks = 2
    bar = StringBarrier(nranks)
    payloads = [b"\x00\xffbinary\x01" * 100, b"tiny"]

    def fn(rank):
        return allgather_bytes(bar.plane(rank), payloads[rank], chunk=128)

    results = _run_ranks(nranks, fn)
    for r in range(nranks):
        assert results[r] == payloads
    # wire carried ascii-safe frames only (base64), never raw bytes
    assert bar.wire_chars > 0


# -- section(): the ONE collective reporting wrapper --------------------------


def test_host_sections_report_uniform_byte_time_counters():
    """Every host collective reports exchange.<name>.bytes/time_ns/calls
    through section() — the uniform namespace of ROADMAP item 5."""
    from spark_rapids_ml_tpu import profiling

    profiling.reset_counters("exchange.")
    nranks = 2
    bar = StringBarrier(nranks)
    payloads = [b"a" * 300, b"b" * 50]

    def fn(rank):
        out = allgather_bytes(bar.plane(rank), payloads[rank], chunk=128)
        return alltoall_bytes(
            bar.plane(rank), rank, nranks, [b"x" * 10, b"y" * 20], chunk=16
        ) and out

    _run_ranks(nranks, fn)
    ctr = profiling.counters("exchange.")
    assert ctr["exchange.allgather.calls"] == nranks
    assert ctr["exchange.allgather.bytes"] == sum(len(p) for p in payloads)
    assert ctr["exchange.allgather.time_ns"] > 0
    assert ctr["exchange.alltoall.calls"] == nranks
    assert ctr["exchange.alltoall.bytes"] == nranks * 30
    assert ctr["exchange.alltoall.time_ns"] > 0
    # wall-clock also lands in the per-thread phase registry as before
    profiling.reset_counters("exchange.")


def test_device_sections_report_static_bytes_at_trace_time():
    """psum_parts/allgather_rows/psum_merge_parts report exchange.<name>
    bytes + trace counts through the same section namespace.  Device
    sections move counters at TRACE time (shapes are static; wall clock is
    meaningless inside a traced body) — a fresh jit trace moves them, a
    cached re-execution does not."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import profiling
    from spark_rapids_ml_tpu.compat import shard_map
    from spark_rapids_ml_tpu.parallel.exchange import (
        allgather_rows,
        psum_merge_parts,
        psum_parts,
    )
    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, get_mesh

    mesh = get_mesh()
    n_dev = mesh.devices.size
    profiling.reset_counters("exchange.")
    from jax.sharding import PartitionSpec as P

    @jax.jit
    def f(x):
        def body(xs):
            s = psum_parts(xs.sum())
            g = allgather_rows(xs)
            m = psum_merge_parts(xs)
            return (s + g.sum() + m.sum()).reshape(1)

        return shard_map(
            body, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS)
        )(x)

    x = jnp.arange(4 * n_dev, dtype=jnp.float32)
    f(x)
    ctr = profiling.counters("exchange.")
    per_shard_bytes = 4 * 4  # (4,) f32 per shard
    assert ctr["exchange.psum_parts.traces"] == 1
    assert ctr["exchange.allgather_rows.traces"] == 1
    assert ctr["exchange.psum_merge_parts.traces"] == 1
    assert ctr["exchange.allgather_rows.bytes"] == per_shard_bytes
    assert ctr["exchange.psum_merge_parts.bytes"] == per_shard_bytes
    assert ctr["exchange.psum_parts.bytes"] == 4  # scalar partial
    # cached re-execution: no new trace, counters frozen
    f(x)
    assert profiling.counters("exchange.") == ctr
    profiling.reset_counters("exchange.")


def test_distributed_kneighbors_binary_exchange_end_to_end():
    """4 thread-ranks over the string-only mock: the full kneighbors
    exchange (binary frames both rounds) must reproduce a single-process
    exact search, including an empty-query rank and k > one rank's items."""
    from sklearn.neighbors import NearestNeighbors as SkNN

    from spark_rapids_ml_tpu.ops.knn import distributed_kneighbors

    nranks = 4
    rng = np.random.default_rng(3)
    n, d, k = 700, 9, 11
    items = rng.normal(size=(n, d)).astype(np.float32)
    ids = np.arange(n, dtype=np.int64) * 7 + 3
    queries = rng.normal(size=(37, d)).astype(np.float32)
    item_split = np.array_split(np.arange(n), nranks)
    # rank 2 owns NO queries
    q_split = [np.arange(0, 20), np.arange(20, 30), np.arange(0, 0), np.arange(30, 37)]
    bar = StringBarrier(nranks)

    def fn(rank):
        ip = [(items[item_split[rank]], ids[item_split[rank]])]
        qp = [(queries[q_split[rank]], q_split[rank].astype(np.int64))]
        # no mesh arg: thread-mocked ranks get DISJOINT per-rank submeshes
        # (sharing one mesh across rank-threads deadlocks XLA:CPU's
        # collective rendezvous — see distributed_kneighbors)
        return distributed_kneighbors(
            ip, qp, k, rank, nranks, bar.plane(rank)
        )

    results = _run_ranks(nranks, fn)
    sk_d, sk_i = SkNN(n_neighbors=k).fit(items).kneighbors(queries)
    for rank in range(nranks):
        (d_out, i_out), = results[rank]
        rows = q_split[rank]
        assert d_out.shape == (len(rows), k)
        np.testing.assert_allclose(d_out, sk_d[rows], rtol=1e-4, atol=1e-4)
        if len(rows):
            assert (i_out == ids[sk_i[rows]]).mean() > 0.99
